"""CTEs (WITH … AS) and set operations (INTERSECT/EXCEPT [ALL]).

The reference inherits these from its forked DataFusion/sqlparser
(query_server/query/Cargo.toml:63-64); here the parser expands CTEs
inline into derived relations and the executor runs set-op chains with
SQL bag semantics (sql/parser.py parse_query / executor._set_op_cols).
"""
import numpy as np
import pytest

from cnosdb_tpu.errors import ParserError, QueryError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE cpu (v DOUBLE, TAGS(host, region))")
    ex.execute_one(
        "INSERT INTO cpu (time, host, region, v) VALUES "
        "(1, 'a', 'eu', 1.0), (2, 'b', 'eu', 2.0), "
        "(3, 'c', 'us', 3.0), (4, 'a', 'us', 4.0)")
    yield ex
    coord.close()


def q(ex, sql):
    rs = ex.execute_one(sql)
    return [tuple(c[i] if c.dtype == object else c[i].item()
                  for c in rs.columns) for i in range(rs.n_rows)]


# -- set operations ---------------------------------------------------------

def test_intersect_distinct(db):
    out = q(db, "SELECT host FROM cpu INTERSECT "
                "SELECT host FROM cpu WHERE v > 2.5 ORDER BY host")
    assert out == [("a",), ("c",)]


def test_except_distinct(db):
    out = q(db, "SELECT host FROM cpu EXCEPT "
                "SELECT host FROM cpu WHERE v > 2.5")
    assert out == [("b",)]


def test_except_all_bag_semantics(db):
    # left bag has 'a' twice; right (v>3.5) has it once → one 'a' survives
    out = q(db, "SELECT host FROM cpu EXCEPT ALL "
                "SELECT host FROM cpu WHERE v > 3.5 ORDER BY host")
    assert out == [("a",), ("b",), ("c",)]


def test_intersect_all_keeps_duplicates(db):
    out = q(db, "SELECT host FROM cpu INTERSECT ALL SELECT host FROM cpu "
                "ORDER BY host")
    assert out == [("a",), ("a",), ("b",), ("c",)]


def test_intersect_all_min_multiplicity(db):
    # left has 'a' twice, right once → INTERSECT ALL keeps min(2,1)=1
    out = q(db, "SELECT host FROM cpu INTERSECT ALL "
                "SELECT host FROM cpu WHERE v < 1.5")
    assert out == [("a",)]


def test_intersect_binds_tighter_than_union(db):
    # UNION (x INTERSECT y): the INTERSECT evaluates first.
    # hosts(v>1)={a,b,c}, hosts(v<3)={a,b} → intersect {a,b}, ∪ {'zz'}
    out = q(db, "SELECT 'zz' UNION SELECT host FROM cpu WHERE v > 1 "
                "INTERSECT SELECT host FROM cpu WHERE v < 3 ORDER BY 1")
    assert out == [("a",), ("b",), ("zz",)]


def test_setop_chain_left_associative(db):
    # ({a,b,c} EXCEPT {a}) EXCEPT {b} = {c}
    out = q(db, "SELECT host FROM cpu EXCEPT "
                "SELECT host FROM cpu WHERE v = 1.0 EXCEPT "
                "SELECT host FROM cpu WHERE v = 2.0")
    assert out == [("c",)]


def test_setop_nulls_not_distinct(db):
    # NULL matches NULL in set-op row comparison (SQL semantics)
    out = q(db, "SELECT CASE WHEN v > 10 THEN v END FROM cpu "
                "INTERSECT SELECT CASE WHEN v > 20 THEN v END FROM cpu")
    assert len(out) == 1  # single NULL row: NULL matches NULL
    v = out[0][0]
    assert v is None or v != v  # None (object col) or NaN (float col)


def test_setop_arity_mismatch_rejected(db):
    with pytest.raises(QueryError):
        q(db, "SELECT host, v FROM cpu INTERSECT SELECT host FROM cpu")


def test_setop_order_by_applies_to_whole_chain(db):
    out = q(db, "SELECT host FROM cpu WHERE v < 2 UNION ALL "
                "SELECT host FROM cpu WHERE v > 2.5 ORDER BY host DESC")
    assert out == [("c",), ("a",), ("a",)]


def test_order_by_only_on_last_branch(db):
    with pytest.raises(ParserError):
        q(db, "SELECT host FROM cpu ORDER BY host INTERSECT "
              "SELECT host FROM cpu")


# -- CTEs -------------------------------------------------------------------

def test_basic_cte(db):
    out = q(db, "WITH t AS (SELECT host, v FROM cpu WHERE v >= 2.0) "
                "SELECT host FROM t ORDER BY host")
    assert out == [("a",), ("b",), ("c",)]


def test_cte_column_list(db):
    out = q(db, "WITH t(h, val) AS (SELECT host, v FROM cpu) "
                "SELECT h, val FROM t WHERE val > 2 ORDER BY h")
    assert out == [("a", 4.0), ("c", 3.0)]


def test_cte_chained_references(db):
    out = q(db, "WITH a AS (SELECT host FROM cpu WHERE v < 2), "
                "b AS (SELECT host FROM a) SELECT host FROM b")
    assert out == [("a",)]


def test_cte_referenced_twice_in_join(db):
    out = q(db, "WITH t AS (SELECT host, v FROM cpu) "
                "SELECT t1.host FROM t t1 JOIN t t2 ON t1.host = t2.host "
                "WHERE t2.v > 3 ORDER BY t1.host")
    assert out == [("a",), ("a",)]


def test_cte_with_aggregate_body(db):
    out = q(db, "WITH s AS (SELECT host, sum(v) AS total FROM cpu "
                "GROUP BY host) SELECT host, total FROM s "
                "WHERE total > 1.5 ORDER BY host")
    assert out == [("a", 5.0), ("b", 2.0), ("c", 3.0)]


def test_cte_over_setop_body(db):
    out = q(db, "WITH t AS (SELECT host FROM cpu EXCEPT "
                "SELECT host FROM cpu WHERE v > 2.5) SELECT host FROM t")
    assert out == [("b",)]


def test_cte_in_subquery_expression(db):
    out = q(db, "WITH hi AS (SELECT max(v) AS m FROM cpu) "
                "SELECT host FROM cpu WHERE v = (SELECT m FROM hi)")
    assert out == [("a",)]


def test_cte_shadows_real_table(db):
    out = q(db, "WITH cpu AS (SELECT 'x' AS host) SELECT host FROM cpu")
    assert out == [("x",)]


def test_duplicate_cte_name_rejected(db):
    with pytest.raises(ParserError):
        q(db, "WITH t AS (SELECT 1), t AS (SELECT 2) SELECT * FROM t")


def test_cte_column_list_arity_rejected(db):
    with pytest.raises(ParserError):
        q(db, "WITH t(a, b) AS (SELECT host FROM cpu) SELECT a FROM t")


def test_cte_union_all_in_body(db):
    out = q(db, "WITH t AS (SELECT host FROM cpu WHERE v = 1.0 UNION ALL "
                "SELECT host FROM cpu WHERE v = 4.0) "
                "SELECT count(host) AS n FROM t")
    assert out == [(2,)]
