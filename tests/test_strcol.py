"""Dictionary-encoded string columns: DictArray invariants, codec v2
round-trips, string-field GROUP BY through the segment kernels, and the
vectorized string aggregation (reference parity: string columns in
tskv/src/tsm/codec/string.rs + DataFusion Utf8 group keys; here redesigned
as sorted-dictionary codes so the hot path is integer kernels)."""
import numpy as np
import pytest

from cnosdb_tpu.models.codec import Encoding
from cnosdb_tpu.models.schema import ValueType
from cnosdb_tpu.models.strcol import DictArray, unify_dictionaries
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage import codecs
from cnosdb_tpu.storage.engine import TsKv


# ---------------------------------------------------------------------------
# DictArray core
# ---------------------------------------------------------------------------
def test_from_objects_sorted_invariant():
    da = DictArray.from_objects(np.array(["b", "a", "c", "a", None], dtype=object))
    assert da.values.tolist() == sorted(set(da.values.tolist()))
    out = da.materialize()
    assert out[0] == "b" and out[1] == "a" and out[3] == "a"
    # code order == string order
    assert (np.argsort(da.values) == np.arange(len(da.values))).all()


def test_comparisons_on_codes():
    da = DictArray.from_objects(
        np.array(["x", "abc", "zz", "abc"], dtype=object))
    np.testing.assert_array_equal(da == "abc", [False, True, False, True])
    np.testing.assert_array_equal(da != "abc", [True, False, True, False])
    np.testing.assert_array_equal(da < "x", [False, True, False, True])
    np.testing.assert_array_equal(da >= "x", [True, False, True, False])
    np.testing.assert_array_equal(da.isin(["zz", "abc"]),
                                  [False, True, True, True])


def test_concat_and_unify():
    a = DictArray.from_objects(np.array(["a", "c"], dtype=object))
    b = DictArray.from_objects(np.array(["b", "c"], dtype=object))
    cat = DictArray.concat([a, b])
    assert cat.materialize().tolist() == ["a", "c", "b", "c"]
    union = unify_dictionaries([a, b])
    assert union.tolist() == ["a", "b", "c"]
    # non-mutating: originals still valid
    assert a.materialize().tolist() == ["a", "c"]


def test_map_values_per_unique():
    calls = []

    def f(s):
        calls.append(s)
        return s.upper()

    da = DictArray.from_objects(np.array(["q", "p", "q", "p", "q"], dtype=object))
    out = da.map_values(f)
    assert out.tolist() == ["Q", "P", "Q", "P", "Q"]
    assert len(calls) == 2  # once per unique, not per row


# ---------------------------------------------------------------------------
# codec v2 (dictionary pages) + v1 compat
# ---------------------------------------------------------------------------
def test_string_codec_roundtrip_dictionary():
    vals = np.array(["red", "green", "blue", "green", ""], dtype=object)
    for enc in (Encoding.ZSTD, Encoding.GZIP, Encoding.ZLIB, Encoding.BZIP,
                Encoding.SNAPPY, Encoding.NULL, Encoding.DEFAULT):
        blk = codecs.encode(vals, ValueType.STRING, enc)
        out = codecs.decode(blk, ValueType.STRING)
        assert isinstance(out, DictArray)
        assert out.materialize().tolist() == vals.tolist()


def test_string_codec_v1_pages_still_decode():
    vals = ["old", "page", "format", "old"]
    raw = codecs._unpack_strings(
        b"".join([np.uint32(4).tobytes(),
                  np.array([3, 4, 6, 3], dtype=np.uint32).tobytes(),
                  b"oldpageformatold"]))
    assert raw.materialize().tolist() == vals


def test_string_codec_empty_and_unicode():
    for vals in ([], ["héllo", "wörld", "héllo"], ["", "", ""]):
        arr = np.array(vals, dtype=object)
        blk = codecs.encode(arr, ValueType.STRING, Encoding.ZSTD)
        out = codecs.decode(blk, ValueType.STRING)
        assert out.materialize().tolist() == vals


# ---------------------------------------------------------------------------
# engine end-to-end
# ---------------------------------------------------------------------------
@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()


@pytest.fixture
def hits(db):
    """String-field table shaped like ClickBench hits (url is a FIELD)."""
    db.execute_one("CREATE TABLE hits (url STRING, latency DOUBLE, "
                   "TAGS(region))")
    urls = ["/home", "/search", "/cart", None, "/home"]
    rows = []
    for i in range(50):
        t = 1672531200000000000 + i * 1_000_000_000
        u = urls[i % 5]
        ustr = "NULL" if u is None else f"'{u}'"
        rows.append(f"({t}, 'r{i % 2}', {ustr}, {float(i)})")
    db.execute_one(
        "INSERT INTO hits (time, region, url, latency) VALUES "
        + ", ".join(rows))
    return db


def test_group_by_string_field(hits):
    rs = hits.execute_one(
        "SELECT url, count(latency) AS c, sum(latency) AS s FROM hits "
        "GROUP BY url ORDER BY url")
    got = {u: (int(c), float(s)) for u, c, s in
           zip(rs.columns[0], rs.columns[1], rs.columns[2])}
    # oracle
    want = {}
    urls = ["/home", "/search", "/cart", None, "/home"]
    for i in range(50):
        u = urls[i % 5]
        c, s = want.get(u, (0, 0.0))
        want[u] = (c + 1, s + float(i))
    assert got == want
    # NULL group key present exactly once
    assert sum(1 for u in rs.columns[0] if u is None) == 1


def test_group_by_string_field_and_tag_and_bucket(hits):
    rs = hits.execute_one(
        "SELECT date_bin(INTERVAL '10 seconds', time) AS t, region, url, "
        "avg(latency) AS a FROM hits GROUP BY t, region, url")
    assert rs.n_rows > 0
    cols = dict(zip(rs.names, rs.columns))
    # spot-check one cell against a scalar query
    i = 0
    t0, r0, u0 = cols["t"][i], cols["region"][i], cols["url"][i]
    if u0 is not None:
        rs2 = hits.execute_one(
            f"SELECT avg(latency) AS a FROM hits WHERE region = '{r0}' "
            f"AND url = '{u0}' AND time >= {int(t0)} "
            f"AND time < {int(t0) + 10_000_000_000}")
        np.testing.assert_allclose(cols["a"][i], rs2.columns[0][0])


def test_group_by_string_survives_flush(hits):
    # force the TSM path (dictionary pages), then group again
    for vn in hits.coord.engine.vnodes.values():
        vn.flush()
    rs = hits.execute_one(
        "SELECT url, count(latency) AS c FROM hits GROUP BY url ORDER BY url")
    got = dict(zip(rs.columns[0], (int(c) for c in rs.columns[1])))
    assert got[None if None in got else "/cart"] is not None
    assert got["/home"] == 20 and got["/search"] == 10 and got["/cart"] == 10


def test_string_min_max_first_last(hits):
    rs = hits.execute_one(
        "SELECT region, min(url) AS mn, max(url) AS mx, first(url) AS f, "
        "last(url) AS l FROM hits GROUP BY region ORDER BY region")
    cols = dict(zip(rs.names, rs.columns))
    # r0 rows: i even → urls cycle ['/home','/cart','/home','/search',None]
    r0_urls = [["/home", "/search", "/cart", None, "/home"][i % 5]
               for i in range(50) if i % 2 == 0]
    present = [u for u in r0_urls if u is not None]
    assert cols["mn"][0] == min(present)
    assert cols["mx"][0] == max(present)
    assert cols["f"][0] == present[0]
    assert cols["l"][0] == present[-1]


def test_like_and_cast_on_dictionary_column(hits):
    rs = hits.execute_one(
        "SELECT count(latency) AS c FROM hits WHERE url LIKE '/%a%'")
    # '/cart' and '/search' match
    assert int(rs.columns[0][0]) == 20
    rs = hits.execute_one(
        "SELECT upper(url) AS u FROM hits WHERE url = '/home' LIMIT 1")
    assert rs.columns[0][0] == "/HOME"


def test_string_filter_eq_on_scan(hits):
    rs = hits.execute_one(
        "SELECT count(latency) AS c FROM hits WHERE url = '/home'")
    assert int(rs.columns[0][0]) == 20
    rs = hits.execute_one(
        "SELECT count(latency) AS c FROM hits WHERE url != '/home'")
    # != excludes NULL url rows per 3VL
    assert int(rs.columns[0][0]) == 20


def test_numeric_field_group_by(db):
    """Numeric FIELD group keys ride the segment kernels too (per-batch
    factorization), including NULL keys as their own group."""
    db.execute_one("CREATE TABLE m (v DOUBLE, b BIGINT, TAGS(h))")
    db.execute_one(
        "INSERT INTO m (time, h, v, b) VALUES (1, 'a', 1.5, 2), "
        "(2, 'a', 2.5, 2), (3, 'b', 3.5, 4), (4, 'b', 0.5, NULL)")
    rs = db.execute_one("SELECT b, sum(v) AS s FROM m GROUP BY b ORDER BY b")
    got = {(None if k is None else int(k)): float(s)
           for k, s in zip(rs.columns[0], rs.columns[1])}
    assert got == {2: 4.0, 4: 3.5, None: 0.5}
    # float keys, NaN-safe: 0.0/0 rows group together
    rs = db.execute_one(
        "SELECT v, count(b) AS c FROM m GROUP BY v ORDER BY v")
    assert rs.n_rows == 4
    # combined with bucket + tag
    rs = db.execute_one(
        "SELECT date_bin(INTERVAL '10 seconds', time) AS t, h, b, "
        "count(v) AS c FROM m GROUP BY t, h, b")
    # one bucket; groups (a,2) (b,4) (b,NULL)
    got = {(h, None if b is None else int(b)): int(c) for h, b, c
           in zip(rs.columns[1], rs.columns[2], rs.columns[3])}
    assert got == {("a", 2): 2, ("b", 4): 1, ("b", None): 1}


def test_nan_group_merges_across_vnodes(tmp_path):
    """GROUP BY a float field whose value is NaN: ONE NaN group, even
    when partials merge across shards (NaN != NaN defeats naive tuple
    keys)."""
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    ex.execute_one("CREATE DATABASE sh WITH SHARD 4")
    from cnosdb_tpu.sql.executor import Session
    s = Session(database="sh")
    ex.execute_one("CREATE TABLE m (v DOUBLE, f DOUBLE, TAGS(h))", s)
    rows = ", ".join(f"({i}, 'h{i}', {i}.0, NaN)" for i in range(8))
    try:
        ex.execute_one(f"INSERT INTO m (time, h, v, f) VALUES {rows}", s)
    except Exception:
        import numpy as np
        from cnosdb_tpu.models.points import SeriesRows, WriteBatch
        from cnosdb_tpu.models.schema import ValueType
        from cnosdb_tpu.models.series import SeriesKey
        for i in range(8):
            wb = WriteBatch()
            wb.add_series("m", SeriesRows(
                SeriesKey("m", {"h": f"h{i}"}),
                np.array([i], dtype=np.int64),
                {"v": (int(ValueType.FLOAT), np.array([float(i)])),
                 "f": (int(ValueType.FLOAT), np.array([float("nan")]))}))
            ex.coord.write_points("cnosdb", "sh", wb)
    rs = ex.execute_one("SELECT f, count(v) AS c FROM m GROUP BY f", s)
    assert rs.n_rows == 1, rs.columns
    assert int(rs.columns[1][0]) == 8
    engine.close()


def test_field_group_with_host_merged_aggregates_falls_back(db):
    """median/stddev etc. merge host-side keyed on tags only — a field
    group key must route to the relational pipeline, not crash."""
    db.execute_one("CREATE TABLE fm (v DOUBLE, b BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO fm (time, h, v, b) VALUES "
                   "(1,'a',1.0,2),(2,'a',3.0,2),(3,'b',5.0,4)")
    rs = db.execute_one(
        "SELECT b, median(v) AS m FROM fm GROUP BY b ORDER BY b")
    got = {int(k): float(m) for k, m in zip(rs.columns[0], rs.columns[1])}
    assert got == {2: 2.0, 4: 5.0}
    rs = db.execute_one(
        "SELECT b, count(DISTINCT h) AS c FROM fm GROUP BY b ORDER BY b")
    assert [int(x) for x in rs.columns[1]] == [1, 1]
