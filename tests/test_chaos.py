"""Nemesis plane fast suite: history recorder semantics (torn tails,
restart-continued indexes), every consistency check catching a
deliberately violated synthetic history, seeded nemesis plan determinism,
the FAULT_POINTS registry contract, and the /metrics export shape.

Everything here is in-process and subprocess-free; the crash-point sweep
itself lives in test_chaos_sweep.py and the cluster nemesis mixes in
test_chaos_cluster.py.
"""
import json

import pytest

from cnosdb_tpu import chaos, faults
from cnosdb_tpu.chaos import nemesis
from cnosdb_tpu.chaos.checker import (
    book, check_checksum_convergence, check_matview_parity,
    check_monotonic_reads, check_no_lost_acked_writes,
    check_no_resurrection, check_read_your_writes, run_client_checks)
from cnosdb_tpu.chaos.history import History, HistoryRecorder


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    chaos.counters_reset()
    yield
    faults.reset()
    chaos.counters_reset()


# ------------------------------------------------------------- recorder
def test_recorder_roundtrip_and_join(tmp_path):
    p = str(tmp_path / "h.jsonl")
    r = HistoryRecorder(p)
    e0 = r.invoke("s1", "write", keys=["a", "b"])
    r.ok("s1", e0)
    e1 = r.invoke("s1", "read", durable=False, mono=True)
    r.ok("s1", e1, keys=["a", "b"])
    e2 = r.invoke("s2", "write", keys=["c"])
    r.fail("s2", e2, "boom")
    e3 = r.invoke("s2", "write", keys=["d"])   # crash before outcome
    r.close()
    h = History.load(p)
    assert [o.op for o in h.ops] == ["write", "read", "write", "write"]
    w0, rd, w1, w2 = h.ops
    assert w0.acked and rd.acked and rd.ok_data["keys"] == ["a", "b"]
    assert w1.outcome == "fail" and w2.outcome is None
    assert h.sessions() == ["s1", "s2"]
    assert e3 > e2 > e1 > e0


def test_recorder_continues_index_after_restart(tmp_path):
    p = str(tmp_path / "h.jsonl")
    r = HistoryRecorder(p)
    r.invoke("s1", "write", keys=["a"])
    r.close()
    r2 = HistoryRecorder(p)           # a restarted client process
    e = r2.invoke("s1", "write", keys=["b"])
    r2.close()
    assert e == 1
    assert len(History.load(p).events) == 2


def test_history_tolerates_torn_tail_only(tmp_path):
    p = tmp_path / "h.jsonl"
    good = json.dumps({"e": 0, "s": "s1", "t": "invoke", "op": "write",
                       "keys": ["a"]})
    p.write_bytes((good + "\n").encode() + b'{"e": 1, "s": "s1", "t"')
    h = History.load(str(p))          # torn final line: dropped
    assert len(h.events) == 1
    p.write_bytes(b'{"torn\n' + (good + "\n").encode())
    with pytest.raises(ValueError):   # garbage MID-file: corrupt, loud
        History.load(str(p))


# -------------------------------------------------------------- checker
def _mk(events):
    """Build a History from (session, type, op_or_of, data) tuples."""
    out = []
    for i, (s, t, x, data) in enumerate(events):
        ev = {"e": i, "s": s, "t": t, **data}
        if t == "invoke":
            ev["op"] = x
        else:
            ev["of"] = x
        out.append(ev)
    return History(out)


def test_lost_acked_write_detected():
    h = _mk([("s1", "invoke", "write", {"keys": ["a", "b"]}),
             ("s1", "ok", 0, {}),
             ("s1", "invoke", "write", {"keys": ["c"]})])  # ambiguous
    assert check_no_lost_acked_writes(h, {"a", "b"})
    assert check_no_lost_acked_writes(h, {"a", "b", "c"})  # c allowed
    r = check_no_lost_acked_writes(h, {"a"})
    assert not r.ok and "b" in r.detail


def test_lost_write_excused_by_delete():
    h = _mk([("s1", "invoke", "write", {"keys": ["a"]}),
             ("s1", "ok", 0, {}),
             ("s2", "invoke", "delete", {"keys": ["a"]})])  # even unacked
    assert check_no_lost_acked_writes(h, set())


def test_resurrection_detected():
    h = _mk([("s1", "invoke", "write", {"keys": ["a", "b"]}),
             ("s1", "ok", 0, {}),
             ("s1", "invoke", "delete", {"keys": ["a"]}),
             ("s1", "ok", 2, {})])
    assert check_no_resurrection(h, {"b"})
    undead = check_no_resurrection(h, {"a", "b"})
    assert not undead.ok and "a" in undead.detail
    nowhere = check_no_resurrection(h, {"b", "ghost"})
    assert not nowhere.ok and "ghost" in nowhere.detail


def test_read_your_writes_detected():
    h = _mk([("s1", "invoke", "write", {"keys": ["a"]}),
             ("s1", "ok", 0, {}),
             ("s1", "invoke", "read", {}),
             ("s1", "ok", 2, {"keys": []}),          # missed own write
             ("s2", "invoke", "read", {}),
             ("s2", "ok", 4, {"keys": []})])         # s2 never wrote: fine
    r = check_read_your_writes(h)
    assert not r.ok and "s1" in r.detail
    h2 = _mk([("s1", "invoke", "write", {"keys": ["a"]}),
              ("s1", "ok", 0, {}),
              ("s1", "invoke", "read", {}),
              ("s1", "ok", 2, {"keys": ["a"]})])
    assert check_read_your_writes(h2)


def test_monotonic_reads_detected():
    h = _mk([("s1", "invoke", "read", {"mono": True}),
             ("s1", "ok", 0, {"keys": ["a", "b"]}),
             ("s1", "invoke", "read", {"mono": True}),
             ("s1", "ok", 2, {"keys": ["a"]})])      # b vanished
    r = check_monotonic_reads(h)
    assert not r.ok and "b" in r.detail
    # a delete between the reads excuses the shrink
    h2 = _mk([("s1", "invoke", "read", {"mono": True}),
              ("s1", "ok", 0, {"keys": ["a", "b"]}),
              ("s2", "invoke", "delete", {"keys": ["b"]}),
              ("s2", "ok", 2, {}),
              ("s1", "invoke", "read", {"mono": True}),
              ("s1", "ok", 4, {"keys": ["a"]})])
    assert check_monotonic_reads(h2)


def test_matview_parity_and_checksum_convergence():
    assert check_matview_parity([(1, "a")], [(1, "a")])
    assert not check_matview_parity([(1, "a")], [(1, "b")]).ok
    assert check_checksum_convergence(
        {1: {"g1": "x"}, 2: {"g1": "x"}, 3: {}})
    r = check_checksum_convergence({1: {"g1": "x"}, 2: {"g1": "y"}})
    assert not r.ok and "g1" in r.detail


def test_book_feeds_metrics_export():
    from cnosdb_tpu.server.metrics import MetricsRegistry

    h = _mk([("s1", "invoke", "write", {"keys": ["a"]}),
             ("s1", "ok", 0, {})])
    book(run_client_checks(h, set()))          # no_lost fails, rest pass
    chaos.note_recovery("crash_restart", 1.25)
    snap = chaos.chaos_snapshot()
    assert snap[("no_lost_acked_writes", "fail")] == 1
    assert snap[("no_resurrection", "pass")] == 1
    m = MetricsRegistry()
    for (check, verdict), n in snap.items():
        m.set_counter("cnosdb_chaos_total", n, check=check, verdict=verdict)
    for kind, secs in chaos.recovery_snapshot().items():
        m.set_gauge("cnosdb_chaos_recovery_seconds", secs, kind=kind)
    text = m.prometheus_text()
    assert "# TYPE cnosdb_chaos_total counter" in text
    assert ('cnosdb_chaos_total{check="no_lost_acked_writes",'
            'verdict="fail"} 1') in text
    assert ('cnosdb_chaos_recovery_seconds{kind="crash_restart"} 1.25'
            in text)


# -------------------------------------------------------------- nemesis
def test_nemesis_plan_is_deterministic():
    a = nemesis.generate_plan(42, n_nodes=3, steps=8)
    b = nemesis.generate_plan(42, n_nodes=3, steps=8)
    assert a == b                      # same seed ⇒ same plan, exactly
    assert nemesis.generate_plan(43, n_nodes=3, steps=8) != a
    assert all(0 <= e.node < 3 and e.kind in nemesis.KINDS for e in a)
    assert "seed=42" in nemesis.describe(a, 42)


def test_nemesis_specs_render_and_parse():
    for ev in nemesis.generate_plan(7, n_nodes=3, steps=12):
        victim, others = nemesis.event_specs(ev, "127.0.0.1:9402", seed=7)
        for spec in (victim, others, nemesis.heal_spec(7, ev)):
            faults.configure(spec)     # must parse under the real grammar
    faults.reset()


def test_fired_sequence_reproduces_for_same_seed_and_spec():
    spec = "seed=11;rpc.send:noop:prob=0.4;wal.append:noop:nth=3"
    logs = []
    for _ in range(2):
        faults.configure(spec)
        for i in range(20):
            faults.fire("rpc.send", addr="127.0.0.1:1", method="m")
            faults.fire("wal.append", dir="d", seq=i)
        logs.append(faults.fired_log())
    assert logs[0] == logs[1] and logs[0]   # same seed+spec ⇒ same firing
    faults.configure(spec.replace("seed=11", "seed=12"))
    for i in range(20):
        faults.fire("rpc.send", addr="127.0.0.1:1", method="m")
    assert [t for t in faults.fired_log() if t[0] == "rpc.send"] != \
        [t for t in logs[0] if t[0] == "rpc.send"]


# ------------------------------------------------------------- registry
def test_fault_point_registry_covers_every_site():
    from cnosdb_tpu.chaos import sweep

    node = set(sweep.node_points())
    assert node == {"record.append", "record.sync", "wal.append",
                    "wal.sync", "wal.roll", "flush.run", "compaction.run",
                    "tsm.write", "scrub.read", "objstore.get",
                    "objstore.put", "matview.persist", "tiering.registry",
                    "serving.invalidate", "backup.archive",
                    "backup.manifest", "restore.install", "memory.spill"}
    cluster = set(faults.registered_points(scope="cluster"))
    assert cluster == {"rpc.send", "rpc.response", "rpc.server",
                       "rpc.reply", "meta.propose", "meta.apply"}
    for p in faults.registered_points().values():
        assert p.module and p.desc, f"{p.name} must carry module + desc"


def test_faults_control_lists_points():
    out = faults.control({"points": True})
    names = [row[0] for row in out["points"]]
    assert names == sorted(names) and "tiering.registry" in names


def test_noop_action_fires_but_injects_nothing(tmp_path):
    faults.configure("seed=1;wal.append:noop")
    assert faults.fire("wal.append", dir="d") is None
    assert faults.fired_log() == [("wal.append", "noop", 1)]
