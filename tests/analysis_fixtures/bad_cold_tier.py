"""Fixture: cold-tier lane exits that skip (lane, reason) accounting
(lines 10 and 20). Mirrors the guarded function names so the rule finds
its targets when scope is ignored; the counted return at 12-13, the
accounting-on-previous-line raise at 23-24, and both terminal returns
are legal shapes and must stay silent."""


def _tier_file(vnode, store, fm, _count_cold):
    if fm is None:
        return False
    if fm.size == 0:
        _count_cold("tier", "file_malformed")
        return False
    return True


def fetch_pages(pms, _count_cold, cache):
    want = [pm for pm in pms if pm.offset not in cache]
    if not want:
        return 0
    for pm in want:
        if pm.size < 0:
            _count_cold("fetch", "bad_page_meta")
            raise ValueError("negative page size")
    _count_cold("fetch", "pages_fetched", len(want))
    return len(want)
