"""Fixture: every stages.stage()/count() name must be in the catalog."""


def f(stages, method, n):
    stages.count("scan_hit")                  # ok: in the catalog
    stages.count("scan_hits")                 # unknown name (typo)
    with stages.stage("decode_ms"):           # ok
        pass
    with stages.stage("decode_time_ms"):      # unknown name
        pass
    stages.count(f"rpc_{method}_ms")          # ok: registered prefix
    stages.count(f"vnode_{n}_ms")             # unregistered dynamic prefix
    "abc".count("scan_hits")                  # ok: not the stages module
