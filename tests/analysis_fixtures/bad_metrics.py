"""Fixture: metric naming violations — unprefixed (line 6), counter
without _total (line 7), histogram without unit suffix (line 8)."""


def f(m):
    m.incr("http_writes_total")
    m.incr("cnosdb_http_writes")
    m.observe("cnosdb_query_latency", 1.0)
    m.incr("cnosdb_http_writes_total")          # ok
    m.observe("cnosdb_query_latency_ms", 1.0)   # ok
    m.set_gauge("cnosdb_queue_depth", 3)        # ok
