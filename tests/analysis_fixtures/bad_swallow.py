"""Fixture: silent except-pass (line 7); a counted swallow passes."""


def f(risky, count_error):
    try:
        risky()
    except Exception:
        pass
    try:
        risky()
    except Exception:
        count_error("swallow.fixture")
