"""Fixture: blocking calls under `with <lock>:` (lines 8, 9, 15);
cv.wait() on the with-target itself releases the lock and must pass."""
import subprocess


def f(self, rpc_call):
    with self.lock:
        rpc_call("127.0.0.1:1", "scan", {}, timeout=1.0)
        data = open("/tmp/x").read()
    return data


def g(self):
    with self._write_mutex:
        subprocess.run(["sync"])


def ok_condition_wait(self):
    with self._cv:
        self._cv.wait(1.0)


def ok_nested_def(self):
    with self.lock:
        def later():
            return open("/tmp/x").read()
        return later
