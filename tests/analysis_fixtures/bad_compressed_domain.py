"""Fixture: compressed-domain lane exits that skip reason accounting
(lines 9 and 20). The _declined return, the booked bail, the success
return of a computed name, and both terminal returns are legal shapes
and must stay silent."""


def build_spec(plan, phys_aggs, _declined):
    if plan is None:
        return None
    if not getattr(plan, "aggs", None):
        return _declined("agg_func")
    return object()


def _page_row_mask(r, pm, evt, ops, count_outcome):
    if r is None:
        count_outcome("mask", "read_error")
        return None
    if pm is None:
        return None
    dense = [evt in ops]
    if evt:
        return dense
    return None
