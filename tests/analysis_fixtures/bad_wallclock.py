"""Fixture: duration arithmetic on time.time() (lines 8, 14); monotonic
arithmetic and stored timestamps pass."""
import time


def f():
    t0 = time.time()
    return time.time() - t0


def g(deadline_s):
    start = time.time()
    while True:
        if start + deadline_s < 5:
            break


def ok_monotonic():
    t0 = time.monotonic()
    return time.monotonic() - t0


def ok_timestamp_store(kwargs):
    kwargs["at"] = time.time()
    return kwargs
