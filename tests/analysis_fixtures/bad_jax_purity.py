"""Fixture: tracer leaks in jitted code — Python branch on a traced arg
(line 9), bool() on a traced value (line 16), .item() host sync
(line 20). Static args may branch (line 27)."""
import jax


@jax.jit
def f(x):
    if x > 0:
        return x
    return -x


def g_kernel(y):
    flag = y + 1
    return bool(flag)


def host_pull(arr):
    return arr.item()


from functools import partial                             # noqa: E402


@partial(jax.jit, static_argnames=("mode",))
def h(x, mode):
    if mode == "fast":
        return x
    return x * 2
