"""Inner layer of the cross-file taint chain (device_chain_outer.py)."""
import jax.numpy as jnp


def make_rows(n):
    return jnp.arange(n)
