"""Fixture: string-filter lane exits that skip path/reason booking
(lines 10 and 21). Mirrors the guarded function names so the rule finds
its targets when scope is ignored; the booked return, the terminal
returns, and the caller-booked bare `return None` decline are legal
shapes and must stay silent."""


def unique_mask(values, pattern, note_path):
    if not len(values):
        return [], "empty"
    if pattern is None:
        note_path("host_fallback", "dynamic_pattern")
        return [False] * len(values), "dynamic"
    return [True] * len(values), "contains"


def topk_order_indices(vals, nulls, asc, k, count):
    if k <= 0:
        return None
    if nulls is not None:
        return list(range(k))
    count("topk.host", 1)
    return sorted(range(len(vals)), key=vals.__getitem__)[:k]
