"""Fixture: rpc_call without a timeout (lines 6, 7); explicit/positional
timeouts and **kwargs pass-through are fine."""


def f(rpc_call, addr, extra):
    rpc_call(addr, "scan", {})
    rpc_call(addr, "scan")
    rpc_call(addr, "scan", {}, timeout=2.0)     # explicit keyword: ok
    rpc_call(addr, "scan", {}, 2.0)             # positional 4th: ok
    rpc_call(addr, "scan", {}, **extra)         # **kwargs may carry it: ok
