"""host-sync fixture: device values pulled to host mid-pipeline."""
import jax.numpy as jnp
import numpy as np


def scan_chunk(vals):
    dev = jnp.cumsum(jnp.asarray(vals))
    total = float(dev[-1])
    host = np.asarray(dev)
    peak = dev.max().item()
    for v in dev:
        host = host + v
    return total, host, peak
