"""fault-site-coverage fixture: fire() sites outside the sweep registry."""
from cnosdb_tpu import faults

faults.register_point("demo.registered", __name__, desc="covered point")


def crossing(path, point):
    if faults.ENABLED:
        faults.fire("demo.registered", path=path)      # registered: fine
        faults.fire("demo.unregistered", path=path)    # never registered
        faults.fire(point, path=path)                  # dynamic name
