"""Fixture: per-row Python loops in the vectorized sections (line 7) and
the pinned fallback (line 21). Mirrors sql/executor.py's function names
so the row-loop rules find their targets when scope is ignored."""


def _merge_distinct_vec(idxs, out):
    for i in idxs:
        out.append(i)
    return out


def _apply_gapfill(cols):
    return cols


def _merge_results_vec(parts):
    return parts


def _merge_distinct(rows, acc):
    for row in rows:
        acc.add(row)
    return acc
