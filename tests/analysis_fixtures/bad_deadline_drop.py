"""deadline-propagation fixture: the budget stops at the middle hop."""


def fetch_remote(addr, payload, deadline=None):
    return rpc_call(addr, "scan", payload)


def run_query(addr, deadline):
    return fetch_remote(addr, {})


def run_query_ok(addr, deadline):
    return fetch_remote(addr, {}, deadline=deadline)
