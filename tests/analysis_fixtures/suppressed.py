"""Fixture: every violation here carries a `# lint: disable=` and must
produce zero findings. The last one uses the `all` token."""
import time


def f(rpc_call, addr):
    rpc_call(addr, "scan", {})  # lint: disable=rpc-call-timeout (fixture: suppression must silence the rule)
    t0 = time.time()
    return time.time() - t0  # lint: disable=wallclock-duration (fixture: cross-process timestamp)


def g(risky):
    try:
        risky()
    except Exception:  # lint: disable=swallowed-exception (fixture: reason goes here)
        pass
    try:
        risky()
    except:  # lint: disable=all (fixture: the all token silences every rule)
        pass
