"""Fixture: hedge-lane exits that skip cnosdb_hedge_total accounting
(lines 12 and 15). Mirrors the guarded function name so the rule finds
its target when scope is ignored; the booked return at 18-19, the Name
return at 21-22, the None return at 24, the booked terminal raise at
25-26 are legal shapes and must stay silent."""


def _scan_remote_hedged(split, targets, count_hedge, count_error):
    inflight = {}
    for idx, (vnode_id, node_id) in enumerate(targets):
        if node_id is None:
            raise RuntimeError("unplaced replica")
        inflight[idx] = vnode_id
    if not targets:
        return []
    result = inflight.get(0)
    if split is None:
        count_hedge("suppressed", "no_alternate")
        return []
    if result is not None:
        count_hedge("won")
        return result
    if not inflight:
        return None
    count_error("hedge.exhausted")
    raise RuntimeError("all replicas unreachable")
