"""Fixture: DR-plane exits that skip (op, outcome) accounting (lines
10 and 20). Mirrors the guarded function names so the rule finds its
targets when scope is ignored; the counted return at 12-13, the
accounting-on-previous-line raise at 23-24, and both terminal returns
are legal shapes and must stay silent."""


def archive_segment(seg_id, archived, _count_backup):
    if seg_id in archived:
        return False
    if seg_id < 0:
        _count_backup("archive", "bad_segment")
        return False
    return True


def restore_backup(catalog, backup_id, _count_backup):
    entry = [e for e in catalog if e["id"] == backup_id]
    if not entry:
        raise ValueError("no such backup")
    for vn in entry[0]["vnodes"]:
        if vn.get("torn"):
            _count_backup("restore", "torn_vnode")
            raise ValueError("torn manifest vnode")
    _count_backup("restore", "ok")
    return entry[0]
