"""recompile-hazard fixture: dynamic scalars reaching a jit boundary."""
import jax
import jax.numpy as jnp


@jax.jit
def pad_kernel(x, n):
    if x.shape[0] > 4:
        return jnp.zeros(n)
    return x


def run_batch(batch):
    return pad_kernel(batch, len(batch))
