"""Fixture: one bare except (line 7)."""


def f():
    try:
        return 1
    except:
        return 0
