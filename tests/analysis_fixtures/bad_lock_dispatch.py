"""lock-held-dispatch fixture: device work reached under a mutex."""
import threading

import jax.numpy as jnp

_LOCK = threading.Lock()


def submit_rows(rows):
    return jnp.asarray(rows).sum()


def flush(rows):
    with _LOCK:
        out = jnp.cumsum(rows)
        total = submit_rows(rows)
    return out, total
