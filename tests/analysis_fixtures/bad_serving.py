"""Fixture: serving-plane exits that skip (layer, outcome) accounting
(lines 10 and 20). Mirrors the guarded function names so the rule finds
its targets when scope is ignored; the counted return at 12-13, the
accounting-on-previous-line raise at 23-24, and both terminal returns
are legal shapes and must stay silent."""


def try_execute(sql, session, _count_serving):
    if sql is None:
        return None
    if not sql.startswith("select"):
        _count_serving("result_cache", "bypass")
        return None
    return [sql]


def submit(executor, plan, _count_serving, groups):
    key = (plan.table, tuple(plan.fields))
    if key not in groups:
        return None
    for member in groups[key]:
        if member.closed:
            _count_serving("batch", "declined_closed")
            raise RuntimeError("group already closed")
    _count_serving("batch", "fused", len(groups[key]))
    return groups[key]
