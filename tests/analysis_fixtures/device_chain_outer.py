"""Outer layer: taint crosses two call edges before the host pull."""
import numpy as np

from device_chain_inner import make_rows


def passthrough(n):
    return make_rows(n) * 2


def consume(n):
    rows = passthrough(n)
    return np.asarray(rows)
