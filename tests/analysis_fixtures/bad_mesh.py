"""Fixture: mesh-lane exits that skip cnosdb_mesh_total accounting
(lines 12 and 15). Mirrors the guarded function name so the rule finds
its target when scope is ignored; the booked decline at 10, the Name
return at 18 and the booked terminal return at 19-20 are legal shapes
and must stay silent."""


def try_mesh_aggregate(batches, query, count_outcome, _declined):
    if not batches:
        return _declined("disabled")
    if len(batches) < 2:
        return None
    for b in batches:
        if b is None:
            raise RuntimeError("mesh shard lost mid-collective")
    if query is None:
        res = []
        return res
    count_outcome("exec", "engaged")
    return batches
