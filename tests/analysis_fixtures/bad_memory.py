"""Fixture: memory-ladder exits that skip cnosdb_memory_total
accounting (lines 13 and 15). Mirrors the guarded function names so the
rule finds its targets when scope is ignored; the bare return at 11,
the booked backpressure raise at 17-18, the Name return at 19-20, the
Name return at 25-26 and the booked terminal raise at 27-28 are legal
shapes and must stay silent."""


def write_admit(used, soft, hard, count, est_bytes=0):
    if used + est_bytes <= soft:
        return
    if used >= hard:
        raise MemoryError("failed closed over hard watermark")
    if est_bytes < 0:
        return []
    if used > soft:
        count("write", "backpressure_shed")
        raise MemoryError("write shed by backpressure")
    headroom = hard - used
    return headroom


def rebalance(usage, soft, count):
    used = sum(usage.values())
    if used <= soft:
        return used
    count("admission", "shed_queued")
    raise MemoryError("still over soft after reclaim")
