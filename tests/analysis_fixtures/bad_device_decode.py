"""Fixture: device-decode lane exits that skip reason accounting
(lines 9 and 18). Mirrors the guarded function names so the rule finds
its targets when scope is ignored; the line-12 reject and both terminal
returns are legal shapes and must stay silent."""


def split_for_device(data, vt, count_outcome):
    if not data:
        return None, "empty"
    if vt == 0:
        count_outcome("host", "encoding")
        return None, "encoding"
    return {"kind": "delta"}, None


def run(jobs, count_outcome):
    if not jobs:
        return []
    count_outcome("device", "ok", len(jobs))
    return jobs
