"""Series-index checkpoint: scale + incremental-recovery correctness
(reference tskv/src/index/ts_index.rs LMDB + roaring postings; VERDICT
round-2 target: large-cardinality open without full binlog replay —
measured 1M-series open ≈ 1ms; CI runs 100k to stay fast)."""
import os
import time

import numpy as np
import pytest

from cnosdb_tpu.models.predicate import (
    AllDomain, ColumnDomains, RangeDomain, SetDomain,
)
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.storage.index import CKPT_NAME, TSIndex


def k(host, metric="m0", table="cpu"):
    return SeriesKey(table, {"host": host, "metric": metric})


def test_checkpoint_roundtrip_and_tail_replay(tmp_path):
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    sids = {}
    for i in range(500):
        sids[i] = idx.add_series_if_not_exists(k(f"h{i:04d}", f"m{i % 5}"))
    idx.checkpoint()
    # post-checkpoint mutations stay in the binlog tail
    for i in range(500, 600):
        sids[i] = idx.add_series_if_not_exists(k(f"h{i:04d}", f"m{i % 5}"))
    idx.del_series(sids[10])
    idx.rename_series(sids[20], k("renamed", "m9"))
    idx.close()

    idx2 = TSIndex(d)
    assert idx2.series_count() == 599  # 600 - 1 deleted
    # deleted sid gone everywhere
    assert idx2.get_series_key(sids[10]) is None
    assert idx2.get_series_id(k("h0010", "m0")) is None
    out = idx2.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": SetDomain(["h0010"])}))
    assert len(out) == 0
    # renamed sid answers under the new key only
    assert idx2.get_series_key(sids[20]).tag_dict()["host"] == "renamed"
    assert idx2.get_series_id(k("renamed", "m9")) == sids[20]
    out = idx2.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": SetDomain(["h0020"])}))
    assert sids[20] not in set(int(s) for s in out)
    # checkpoint + tail rows both visible
    out = idx2.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": SetDomain(["h0550"])}))
    assert [int(s) for s in out] == [sids[550]]
    idx2.close()


def test_domain_queries_vs_oracle(tmp_path):
    """Checkpoint-backed postings must answer exactly like a brute-force
    oracle across domain kinds."""
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    keys = {}
    for i in range(300):
        key = k(f"h{i % 30:03d}", f"m{i % 7}")
        keys.setdefault(idx.add_series_if_not_exists(key), key)
    idx.checkpoint()
    for i in range(300, 400):   # tail overlay on top
        key = k(f"h{i % 40:03d}", f"m{i % 7}")
        keys.setdefault(idx.add_series_if_not_exists(key), key)

    def oracle(pred):
        return sorted(s for s, key in keys.items() if pred(key.tag_dict()))

    cases = [
        (ColumnDomains({"host": SetDomain(["h005", "h033"])}),
         lambda t: t["host"] in ("h005", "h033")),
        (ColumnDomains({"host": RangeDomain.of(low="h010", high="h015")}),
         lambda t: "h010" <= t["host"] <= "h015"),
        (ColumnDomains({"metric": SetDomain(["m3"]),
                        "host": RangeDomain.ge("h020")}),
         lambda t: t["metric"] == "m3" and t["host"] >= "h020"),
        (ColumnDomains({"host": AllDomain()}), lambda t: True),
        (ColumnDomains.all(), lambda t: True),
    ]
    for doms, pred in cases:
        got = [int(s) for s in idx.get_series_ids_by_domains("cpu", doms)]
        assert got == oracle(pred), doms
    idx.close()


def test_open_scales(tmp_path):
    """100k series open well under the 1s budget (1M measured ≈ 1ms: the
    header is the only eager read)."""
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    for i in range(100_000):
        idx.add_series_if_not_exists(k(f"h{i % 10000:05d}", f"m{i // 10000}"))
    idx.checkpoint()
    idx.close()
    t0 = time.monotonic()
    idx2 = TSIndex(d)
    open_s = time.monotonic() - t0
    assert open_s < 0.5, f"open took {open_s:.3f}s"
    out = idx2.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": SetDomain(["h00042"])}))
    assert len(out) == 10
    assert idx2.series_count() == 100_000
    assert os.path.exists(os.path.join(d, CKPT_NAME))
    idx2.close()


def test_tag_values_and_keys_merge(tmp_path):
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    a = idx.add_series_if_not_exists(k("h1", "m1"))
    idx.add_series_if_not_exists(k("h2", "m1"))
    idx.checkpoint()
    idx.add_series_if_not_exists(k("h3", "m2"))
    assert idx.tag_values("cpu", "host") == ["h1", "h2", "h3"]
    assert idx.tag_keys("cpu") == ["host", "metric"]
    idx.del_series(a)
    assert idx.tag_values("cpu", "host") == ["h2", "h3"]
    idx.close()


def test_rename_then_delete_after_checkpoint(tmp_path):
    """Regression: a sid living in both overlay (re-keyed) and checkpoint
    must not resurrect under its stale checkpoint key when deleted."""
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    s1 = idx.add_series_if_not_exists(k("h1"))
    idx.checkpoint()
    idx.rename_series(s1, k("h2"))
    idx.del_series(s1)
    assert idx.get_series_key(s1) is None
    assert idx.get_series_id(k("h1")) is None
    assert idx.get_series_id(k("h2")) is None
    assert idx.series_count() == 0
    out = idx.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": SetDomain(["h1"])}))
    assert len(out) == 0
    idx.close()
    # and across a reopen (tail replay)
    idx2 = TSIndex(d)
    assert idx2.series_count() == 0
    idx2.close()


def test_range_domain_ckpt_overlay_value_overlap(tmp_path):
    """Regression: a tag value present in BOTH checkpoint and tail must
    contribute both sides' postings to range queries."""
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    s1 = idx.add_series_if_not_exists(k("h005", "m0"))
    idx.checkpoint()
    s2 = idx.add_series_if_not_exists(k("h005", "m1"))
    out = idx.get_series_ids_by_domains(
        "cpu", ColumnDomains({"host": RangeDomain.of(low="h000", high="h009")}))
    assert sorted(int(s) for s in out) == sorted([s1, s2])
    idx.close()


def test_empty_binlog_after_rotation_crash(tmp_path):
    """Regression: a 0-byte binlog (crash inside rotation) must not brick
    the index open."""
    d = str(tmp_path / "idx")
    idx = TSIndex(d)
    idx.add_series_if_not_exists(k("h1"))
    idx.checkpoint()
    idx.close()
    open(os.path.join(d, "index.binlog"), "wb").close()  # simulate crash
    idx2 = TSIndex(d)
    assert idx2.series_count() == 1
    idx2.close()
