import json

import numpy as np
import pytest

from cnosdb_tpu.config import Config
from cnosdb_tpu.errors import ParserError
from cnosdb_tpu.models.schema import Precision, ValueType
from cnosdb_tpu.protocol.line_protocol import parse_lines
from cnosdb_tpu.protocol.opentsdb import parse_opentsdb


# ---------------------------------------------------------------- line proto
def test_line_protocol_basic():
    wb = parse_lines(
        "cpu,host=h1,region=us usage_user=1.5,usage_system=2.0 1000\n"
        "cpu,host=h1,region=us usage_user=2.5 2000\n"
        "mem,host=h1 used=100i,total=200u,ok=t,name=\"srv 1\" 1000\n")
    assert set(wb.tables) == {"cpu", "mem"}
    cpu = wb.tables["cpu"][0]
    assert cpu.key.tag_value("host") == "h1"
    assert cpu.timestamps == [1000, 2000]
    assert cpu.fields["usage_user"] == (int(ValueType.FLOAT), [1.5, 2.5])
    assert cpu.fields["usage_system"] == (int(ValueType.FLOAT), [2.0, None])
    mem = wb.tables["mem"][0]
    assert mem.fields["used"][0] == int(ValueType.INTEGER)
    assert mem.fields["total"][0] == int(ValueType.UNSIGNED)
    assert mem.fields["ok"] == (int(ValueType.BOOLEAN), [True])
    assert mem.fields["name"] == (int(ValueType.STRING), ["srv 1"])


def test_line_protocol_escapes_and_precision():
    wb = parse_lines("my\\ table,tag\\,1=a\\ b value=1 5", precision=Precision.MS)
    sr = wb.tables["my table"][0]
    assert sr.key.tag_value("tag,1") == "a b"
    assert sr.timestamps == [5_000_000]


def test_line_protocol_distinct_tag_values_make_distinct_series():
    """Same tag KEYS but different values must NOT merge into one series."""
    wb = parse_lines("cpu,host=a v=1 1\ncpu,host=b v=2 1\ncpu,host=a v=3 2\n")
    series = wb.tables["cpu"]
    assert len(series) == 2
    by_host = {sr.key.tag_value("host"): sr for sr in series}
    assert by_host["a"].timestamps == [1, 2]
    assert by_host["b"].timestamps == [1]


def test_line_protocol_default_time_and_errors():
    wb = parse_lines("cpu v=1", default_time_ns=42)
    assert wb.tables["cpu"][0].timestamps == [42]
    with pytest.raises(ParserError):
        parse_lines("cpu")  # no fields
    with pytest.raises(ParserError):
        parse_lines("cpu v=")  # bad value


def test_opentsdb():
    wb = parse_opentsdb("put sys.cpu 1672531200 42.5 host=a dc=x\n"
                        "sys.cpu 1672531201000 43.5 host=a dc=x\n")
    sr = wb.tables["sys.cpu"][0]
    assert sr.timestamps == [1672531200 * 10**9, 1672531201 * 10**9]
    assert sr.fields["value"][1] == [42.5, 43.5]


# ---------------------------------------------------------------- config
def test_config_defaults_and_toml(tmp_path):
    c = Config()
    text = c.to_toml()
    assert "[storage]" in text
    p = tmp_path / "c.toml"
    p.write_text("[service]\nhttp_listen_port = 9999\n[wal]\nsync = true\n")
    c2 = Config.load(str(p))
    assert c2.service.http_listen_port == 9999
    assert c2.wal.sync is True
    c3 = Config.load(str(p), env={"CNOSDB_SERVICE_HTTP_LISTEN_PORT": "7777"})
    assert c3.service.http_listen_port == 7777
    assert c2.check() == []


# ---------------------------------------------------------------- HTTP
class _HttpHarness:
    """Runs the real aiohttp server in a background thread; plain urllib
    client — no pytest plugins needed."""

    def __init__(self, data_dir: str):
        import asyncio
        import socket
        import threading

        from cnosdb_tpu.server.http import build_server

        self.server = build_server(data_dir)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()

        def run():
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._runner = await self.server.start("127.0.0.1", self.port)
                self._started.set()

            self._loop.create_task(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        assert self._started.wait(10)

    def request(self, method: str, path: str, data: str | None = None,
                headers: dict | None = None):
        import urllib.error
        import urllib.request

        url = f"http://127.0.0.1:{self.port}{path}"
        req = urllib.request.Request(
            url, data=data.encode() if data is not None else None,
            headers=headers or {}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def close(self):
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self.server.coord.close()


@pytest.fixture
def http(tmp_path):
    h = _HttpHarness(str(tmp_path / "srv"))
    yield h
    h.close()


def test_http_ping(http):
    status, body = http.request("GET", "/api/v1/ping")
    assert status == 200
    assert json.loads(body)["status"] == "healthy"


def test_http_write_and_sql(http):
    lines = "\n".join(
        f"cpu,host=h{i % 2} usage={i}.5 {1672531200000000000 + i * 10**9}"
        for i in range(10))
    status, body = http.request("POST", "/api/v1/write?db=public", lines)
    assert status == 200, body
    status, text = http.request(
        "POST", "/api/v1/sql?db=public",
        "SELECT count(*) AS c, max(usage) AS m FROM cpu")
    assert status == 200
    assert text.splitlines()[0] == "c,m"
    assert text.splitlines()[1] == "10,9.5"


def test_http_sql_json_format(http):
    http.request("POST", "/api/v1/write?db=public", "m,h=a v=1 100")
    status, text = http.request("POST", "/api/v1/sql?db=public",
                                "SELECT * FROM m",
                                headers={"Accept": "application/json"})
    assert json.loads(text) == [{"time": 100, "h": "a", "v": 1.0}]


def test_http_sql_error(http):
    status, body = http.request("POST", "/api/v1/sql?db=public",
                                "SELECT * FROM missing")
    assert status == 422
    assert json.loads(body)["error_code"].startswith("02")


def test_http_bad_write(http):
    status, _ = http.request("POST", "/api/v1/write?db=public", "not-a-line")
    assert status == 422


def test_http_opentsdb_write(http):
    status, _ = http.request("POST", "/api/v1/opentsdb/write?db=public",
                             "put sys.load 1672531200 1.5 host=x")
    assert status == 200
    status, text = http.request("POST", "/api/v1/sql?db=public",
                                'SELECT count(*) AS c FROM "sys.load"')
    assert text.splitlines()[1] == "1"


def test_http_metrics(http):
    http.request("POST", "/api/v1/write?db=public", "m v=1 1")
    status, text = http.request("GET", "/metrics")
    assert "cnosdb_http_points_written_total" in text
