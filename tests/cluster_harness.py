"""Multi-process cluster harness for e2e tests.

Counterpart of the reference's declarative cluster bring-up
(e2e_test/src/cluster_def.rs:12-76 CnosdbClusterDefinition +
e2e_test/src/utils/ process management): spawns one meta process and N
data-node processes on localhost with distinct ports/dirs, exposes
HTTP write/sql helpers, and supports kill/restart of individual nodes.
"""
from __future__ import annotations

import base64
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # keep subprocesses off the TPU tunnel
    # the axon PJRT plugin dials the relay AT IMPORT when this is set —
    # even under JAX_PLATFORMS=cpu — and a degraded relay then stalls
    # every node process for up to minutes; tests must never depend on it
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("PYTHONUNBUFFERED", "1")
    return env


class Node:
    def __init__(self, cluster: "Cluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.http_port = free_port()
        self.rpc_port = free_port()
        self.data_dir = os.path.join(cluster.root, f"node{node_id}")
        self.proc: subprocess.Popen | None = None

    def start(self):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "cnosdb_tpu.server.main", "run",
             "--mode", "query_tskv",
             "--meta", f"127.0.0.1:{self.cluster.meta_port}",
             "--node-id", str(self.node_id),
             "--data-dir", self.data_dir,
             "--http-port", str(self.http_port),
             "--rpc-port", str(self.rpc_port)],
            env=self.cluster.env, stdout=self.cluster.log,
            stderr=self.cluster.log)
        return self

    def kill(self):
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=10)
            self.proc = None

    def wait_ready(self, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self.http("GET", "/api/v1/ping")
                return self
            except Exception:
                if self.proc is not None and self.proc.poll() is not None:
                    raise RuntimeError(
                        f"node {self.node_id} exited rc={self.proc.returncode}")
                time.sleep(0.1)
        raise TimeoutError(f"node {self.node_id} not ready")

    def http(self, method: str, path: str, body: bytes | None = None,
             timeout: float = 30.0) -> str:
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}{path}", data=body,
            method=method)
        req.add_header("Authorization",
                       "Basic " + base64.b64encode(b"root:").decode())
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode()

    def write_lp(self, lines: str, db: str = "public"):
        return self.http("POST", f"/api/v1/write?db={db}", lines.encode())

    def sql(self, q: str, db: str = "public") -> str:
        return self.http("POST", f"/api/v1/sql?db={db}", q.encode())


class Cluster:
    def __init__(self, root: str, n_nodes: int = 3):
        self.root = root
        # snapshot the spawn env ONCE: fixtures set knobs (CNOSDB_FAULTS,
        # CNOSDB_LOCKWATCH, ...) around construction and drop them right
        # after, and a node RESTARTED mid-test (crash injection) must come
        # back with the same knobs as its first boot
        self.env = _env()
        self.meta_port = free_port()
        os.makedirs(root, exist_ok=True)
        self.log = open(os.path.join(root, "cluster.log"), "ab")
        self.meta_proc: subprocess.Popen | None = None
        self.nodes = [Node(self, i + 1) for i in range(n_nodes)]

    def start(self):
        self.meta_proc = subprocess.Popen(
            [sys.executable, "-m", "cnosdb_tpu.server.main", "run",
             "--mode", "meta",
             "--data-dir", os.path.join(self.root, "meta"),
             "--meta-port", str(self.meta_port)],
            env=self.env, stdout=self.log, stderr=self.log)
        for n in self.nodes:
            n.start()
        for n in self.nodes:
            n.wait_ready()
        return self

    def stop(self):
        for n in self.nodes:
            try:
                n.kill()
            except Exception:
                pass
        if self.meta_proc is not None:
            self.meta_proc.kill()
            self.meta_proc.wait(timeout=10)
            self.meta_proc = None
        self.log.close()

    def alive_node(self) -> Node:
        for n in self.nodes:
            if n.proc is not None:
                return n
        raise RuntimeError("no node alive")


def assert_lock_graph_acyclic(cluster: Cluster) -> int:
    """Teardown invariant for suites run with CNOSDB_LOCKWATCH=1: pull
    /debug/lockgraph from every node still alive and fail on any observed
    lock-order cycle (two code paths nesting the same locks in opposite
    order — a deadlock waiting for the right interleaving). Returns the
    number of nodes checked so callers can assert coverage."""
    import json as _json

    checked = 0
    for n in cluster.nodes:
        if n.proc is None or n.proc.poll() is not None:
            continue
        rep = _json.loads(n.http("GET", "/debug/lockgraph"))
        assert rep["enabled"], f"node {n.node_id}: lockwatch not enabled"
        assert rep["cycles"] == [], (
            f"node {n.node_id}: lock-order cycles {rep['cycles']} "
            f"(edges: {rep['edges']})")
        checked += 1
    return checked
