"""Pallas segment-aggregate kernel vs the numpy oracle.

Drives ops/pallas_kernels.segment_partials_pallas in interpreter mode on
the CPU backend (pallas_call(interpret=True)) against
kernels.numpy_segment_partials — NULL columns, empty segments,
window-boundary layouts, the applicable() fallback, and the
aggregate_column_host integration behind CNOSDB_TPU_PALLAS=1.
"""
import numpy as np
import pytest

from cnosdb_tpu.ops import kernels, pallas_kernels as pk

pytestmark = pytest.mark.skipif(
    not pk.PALLAS_AVAILABLE, reason="pallas not importable")

ALL4 = {"want_count": True, "want_sum": True,
        "want_min": True, "want_max": True}


def _series_layout(rng, n_series, rows_per_series, n_buckets,
                   dtype=np.float64, null_frac=0.0):
    """Storage-shaped batch: series-contiguous rows, time-ordered buckets
    per series, seg = group(series) × n_buckets + bucket."""
    groups = rng.permutation(n_series).astype(np.int64)
    segs, vals, valid = [], [], []
    for s in range(n_series):
        m = rows_per_series
        buckets = np.sort(rng.integers(0, n_buckets, m))
        segs.append(groups[s] * n_buckets + buckets)
        if np.issubdtype(dtype, np.floating):
            vals.append(rng.normal(size=m).astype(dtype))
        else:
            vals.append(rng.integers(-1000, 1000, m).astype(dtype))
        valid.append(rng.random(m) >= null_frac)
    seg_ids = np.concatenate(segs).astype(np.int32)
    return (np.concatenate(vals), np.concatenate(valid), seg_ids,
            n_series * n_buckets)


def _check(values, valid, seg_ids, ns, wants=None):
    w = dict(ALL4 if wants is None else wants)
    got = pk.segment_partials_pallas(values, valid, seg_ids, ns,
                                     wants=w, interpret=True)
    assert got is not None, "layout unexpectedly disqualified"
    rank = np.arange(len(values), dtype=np.int32)
    exp = kernels.numpy_segment_partials(
        values, valid, seg_ids.astype(np.int64), rank, ns, w)
    counts = np.bincount(seg_ids[valid], minlength=ns)
    for k in exp:
        if k in ("min", "max"):
            # empty segments carry sentinels in both kernels by contract;
            # compare occupied segments only (callers mask by count)
            occ = counts > 0
            np.testing.assert_allclose(got[k][occ], exp[k][occ], rtol=1e-12,
                                       err_msg=k)
        else:
            np.testing.assert_allclose(got[k], exp[k], rtol=1e-12,
                                       err_msg=k)
    assert set(got) == set(exp)
    return got


def test_basic_float_matches_oracle():
    rng = np.random.default_rng(0)
    values, valid, seg_ids, ns = _series_layout(rng, 6, 700, 24)
    _check(values, valid, seg_ids, ns)


def test_nulls_and_empty_segments():
    rng = np.random.default_rng(1)
    # 40% NULLs; bucket space much larger than occupied → empty segments
    values, valid, seg_ids, ns = _series_layout(
        rng, 4, 300, 100, null_frac=0.4)
    got = _check(values, valid, seg_ids, ns)
    counts = np.bincount(seg_ids[valid], minlength=ns)
    # empty segments: count 0, sum 0, min/max sentinels (XLA convention)
    empty = counts == 0
    assert empty.any()
    assert (got["count"][empty] == 0).all()
    assert (got["sum"][empty] == 0).all()
    assert np.isposinf(got["min"][empty]).all()
    assert np.isneginf(got["max"][empty]).all()


def test_all_rows_invalid():
    n = 512
    values = np.ones(n)
    valid = np.zeros(n, dtype=bool)
    seg_ids = np.zeros(n, dtype=np.int32)
    got = pk.segment_partials_pallas(values, valid, seg_ids, 8,
                                     wants=dict(ALL4), interpret=True)
    assert got is not None
    assert (got["count"] == 0).all() and (got["sum"] == 0).all()


def test_integer_dtype_extrema():
    """Integer min/max identities must be iinfo extrema, not float inf."""
    rng = np.random.default_rng(2)
    values, valid, seg_ids, ns = _series_layout(
        rng, 3, 400, 16, dtype=np.int64, null_frac=0.2)
    got = _check(values, valid, seg_ids, ns)
    counts = np.bincount(seg_ids[valid], minlength=ns)
    empty = counts == 0
    if empty.any():
        assert (got["min"][empty] == np.iinfo(np.int64).max).all()
        assert (got["max"][empty] == np.iinfo(np.int64).min).all()


def test_window_boundary_series():
    """Series boundaries inside a tile: the window absorbs the group jump
    as long as the span stays under W_WIN."""
    # two series meeting mid-tile, group ids adjacent → span = n_buckets
    n_buckets = pk.W_WIN // 2
    a = np.arange(n_buckets, dtype=np.int32)                 # group 0
    b = n_buckets + np.arange(n_buckets, dtype=np.int32)     # group 1
    seg_ids = np.concatenate([a, b])
    values = np.linspace(-1, 1, len(seg_ids))
    valid = np.ones(len(seg_ids), dtype=bool)
    _check(values, valid, seg_ids, 2 * n_buckets)


def test_applicable_declines_wide_span():
    """A tile spanning ≥ W_WIN segments disqualifies the layout."""
    seg_ids = np.array([0, pk.W_WIN + 7] * (pk.R_TILE // 2), dtype=np.int32)
    assert pk.applicable(seg_ids) is None
    got = pk.segment_partials_pallas(
        np.ones(len(seg_ids)), np.ones(len(seg_ids), bool), seg_ids,
        pk.W_WIN + 8, wants=dict(ALL4), interpret=True)
    assert got is None


def test_declines_first_last():
    seg_ids = np.zeros(16, dtype=np.int32)
    got = pk.segment_partials_pallas(
        np.ones(16), np.ones(16, bool), seg_ids, 1,
        wants={**ALL4, "want_first": True}, interpret=True)
    assert got is None


def test_wants_subsetting():
    rng = np.random.default_rng(3)
    values, valid, seg_ids, ns = _series_layout(rng, 2, 300, 8)
    got = pk.segment_partials_pallas(
        values, valid, seg_ids, ns,
        wants={"want_count": True, "want_sum": False,
               "want_min": False, "want_max": True}, interpret=True)
    assert set(got) == {"count", "max"}


def test_aggregate_column_host_integration(monkeypatch):
    """CNOSDB_TPU_PALLAS=1 routes aggregate_column_host through the
    pallas kernel (interpret on the CPU backend) with identical results;
    =0 keeps the XLA kernel. A deliberately broken pallas result would
    fail the comparison."""
    rng = np.random.default_rng(4)
    values, valid, seg_ids, ns = _series_layout(
        rng, 5, 500, 20, null_frac=0.15)
    rank = np.arange(len(values), dtype=np.int32)
    wants = {"want_count": True, "want_sum": True, "want_min": True,
             "want_max": True, "want_first": False, "want_last": False}
    monkeypatch.setenv("CNOSDB_TPU_PALLAS", "0")
    base = kernels.aggregate_column_host(
        values, valid, seg_ids.astype(np.int32), rank, ns, wants)
    monkeypatch.setenv("CNOSDB_TPU_PALLAS", "1")
    before = pk.engagements()
    got = kernels.aggregate_column_host(
        values, valid, seg_ids.astype(np.int32), rank, ns, wants)
    assert pk.engagements() == before + 1, "pallas path did not engage"
    counts = np.bincount(seg_ids[valid], minlength=ns)
    occ = counts > 0
    for k in base:
        if k in ("min", "max"):
            np.testing.assert_allclose(got[k][occ], base[k][occ],
                                       err_msg=k)
        else:
            np.testing.assert_allclose(got[k], base[k], err_msg=k)
    assert got["count"].dtype == np.int64


def test_first_last_falls_back_to_xla(monkeypatch):
    """first/last keep the XLA rank-selection kernel even when pallas is
    forced on."""
    monkeypatch.setenv("CNOSDB_TPU_PALLAS", "1")
    n = 300
    values = np.arange(n, dtype=np.float64)
    valid = np.ones(n, dtype=bool)
    seg_ids = (np.arange(n, dtype=np.int32) // 100)
    rank = np.arange(n, dtype=np.int32)
    before = pk.engagements()
    out = kernels.aggregate_column_host(
        values, valid, seg_ids, rank, 3,
        {"want_count": True, "want_sum": False, "want_min": False,
         "want_max": False, "want_first": True, "want_last": True})
    assert pk.engagements() == before
    np.testing.assert_allclose(out["first"], [0.0, 100.0, 200.0])
    np.testing.assert_allclose(out["last"], [99.0, 199.0, 299.0])
