"""Device scan-aggregate operator tests (run on CPU backend via conftest)."""
import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.ops.tpu_exec import AggSpec, TpuQuery, execute_scan_aggregate
from cnosdb_tpu.sql.expr import BinOp, Column, InList, Literal
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage


@pytest.fixture(params=["0", "1"], ids=["rowwise", "regular"])
def _regular_mode(request, monkeypatch):
    """Exercise BOTH device layouts: explicit per-row sid/ts and the
    run-length-reconstruction variant."""
    monkeypatch.setenv("CNOSDB_TPU_REGULAR", request.param)
    return request.param


@pytest.fixture
def vnode(tmp_path, _regular_mode):
    schemas = {"cpu": TskvTableSchema.new_measurement(
        "t", "db", "cpu", tags=["host", "region"],
        fields=[("usage", ValueType.FLOAT), ("n", ValueType.INTEGER)])}
    v = VnodeStorage(1, str(tmp_path / "v"), schemas=schemas)
    wb = WriteBatch()
    # h0/h1 in us, h2 in eu; 100 rows each at 1s cadence
    for i, (host, region) in enumerate([("h0", "us"), ("h1", "us"), ("h2", "eu")]):
        ts = list(range(0, 100_000_000_000, 1_000_000_000))
        vals = [float(i * 100 + k) for k in range(100)]
        ns = [i * 100 + k for k in range(100)]
        wb.add_series("cpu", SeriesRows(
            SeriesKey("cpu", {"host": host, "region": region}), ts,
            {"usage": (int(ValueType.FLOAT), vals),
             "n": (int(ValueType.INTEGER), ns)}))
    v.write(wb)
    v.flush()
    yield v
    v.close()


def _batch(v):
    return scan_vnode(v, "cpu")


def test_global_aggregates(vnode):
    b = _batch(vnode)
    q = TpuQuery(aggs=[
        AggSpec("count", None, "cnt"),
        AggSpec("sum", "usage", "s"),
        AggSpec("mean", "usage", "m"),
        AggSpec("min", "usage", "lo"),
        AggSpec("max", "usage", "hi"),
    ])
    r = execute_scan_aggregate(b, q)
    assert r.n_rows == 1
    assert r.columns["cnt"][0] == 300
    expect = np.concatenate([np.arange(100.0) + i * 100 for i in range(3)])
    assert r.columns["s"][0] == pytest.approx(expect.sum())
    assert r.columns["m"][0] == pytest.approx(expect.mean())
    assert r.columns["lo"][0] == 0.0 and r.columns["hi"][0] == 299.0


def test_group_by_tag(vnode):
    b = _batch(vnode)
    q = TpuQuery(group_tags=["region"],
                 aggs=[AggSpec("count", None, "cnt"), AggSpec("max", "usage", "hi")])
    r = execute_scan_aggregate(b, q)
    rows = {r.columns["region"][i]: (r.columns["cnt"][i], r.columns["hi"][i])
            for i in range(r.n_rows)}
    assert rows["us"] == (200, 199.0)
    assert rows["eu"] == (100, 299.0)


def test_group_by_time_bucket(vnode):
    b = _batch(vnode)
    # 10s buckets over 100s of data → 10 buckets
    q = TpuQuery(time_bucket=(0, 10_000_000_000),
                 aggs=[AggSpec("count", None, "cnt"), AggSpec("mean", "usage", "m")])
    r = execute_scan_aggregate(b, q)
    assert r.n_rows == 10
    order = np.argsort(r.columns["time"])
    assert (r.columns["cnt"][order] == 30).all()
    # bucket k holds rows k*10..k*10+9 for each of 3 series
    m0 = np.mean([k + i * 100 for i in range(3) for k in range(10)])
    assert r.columns["m"][order][0] == pytest.approx(m0)


def test_double_groupby(vnode):
    """TSBS double-groupby shape: GROUP BY time bucket AND host."""
    b = _batch(vnode)
    q = TpuQuery(group_tags=["host"], time_bucket=(0, 50_000_000_000),
                 aggs=[AggSpec("mean", "usage", "m")])
    r = execute_scan_aggregate(b, q)
    assert r.n_rows == 6  # 3 hosts × 2 buckets
    for i in range(r.n_rows):
        host = r.columns["host"][i]
        t = r.columns["time"][i]
        base = int(host[1]) * 100
        lo = 0 if t == 0 else 50
        assert r.columns["m"][i] == pytest.approx(base + lo + 24.5)


def test_filter_pushdown(vnode):
    b = _batch(vnode)
    q = TpuQuery(filter=BinOp(">", Column("usage"), Literal(250.0)),
                 aggs=[AggSpec("count", None, "cnt"), AggSpec("min", "usage", "lo")])
    r = execute_scan_aggregate(b, q)
    assert r.columns["cnt"][0] == 49  # 251..299
    assert r.columns["lo"][0] == 251.0


def test_filter_on_tag(vnode):
    b = _batch(vnode)
    q = TpuQuery(filter=InList(Column("host"), ["h0", "h2"]),
                 aggs=[AggSpec("count", None, "cnt")])
    r = execute_scan_aggregate(b, q)
    assert r.columns["cnt"][0] == 200


def test_first_last(vnode):
    b = _batch(vnode)
    q = TpuQuery(group_tags=["host"],
                 aggs=[AggSpec("first", "usage", "f"), AggSpec("last", "usage", "l")])
    r = execute_scan_aggregate(b, q)
    rows = {r.columns["host"][i]: (r.columns["f"][i], r.columns["l"][i])
            for i in range(r.n_rows)}
    assert rows["h0"] == (0.0, 99.0)
    assert rows["h2"] == (200.0, 299.0)


def test_integer_aggregation_is_exact(vnode):
    b = _batch(vnode)
    q = TpuQuery(aggs=[AggSpec("sum", "n", "s"), AggSpec("max", "n", "mx")])
    r = execute_scan_aggregate(b, q)
    assert r.columns["s"][0] == sum(range(300))
    assert r.columns["mx"][0] == 299
    assert r.columns["s"].dtype == np.int64


def test_null_handling(tmp_path):
    schemas = {"m": TskvTableSchema.new_measurement(
        "t", "db", "m", tags=["h"], fields=[("v", ValueType.FLOAT)])}
    v = VnodeStorage(1, str(tmp_path / "v2"), schemas=schemas)
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(SeriesKey("m", {"h": "a"}), [1, 2, 3, 4],
                                  {"v": (int(ValueType.FLOAT), [1.0, None, 3.0, None])}))
    v.write(wb)
    b = scan_vnode(v, "m")
    r = execute_scan_aggregate(b, TpuQuery(aggs=[
        AggSpec("count", "v", "c"), AggSpec("count", None, "star"),
        AggSpec("sum", "v", "s")]))
    assert r.columns["c"][0] == 2       # nulls not counted
    assert r.columns["star"][0] == 4    # count(*) counts rows
    assert r.columns["s"][0] == 4.0
    v.close()


def test_filter_on_non_aggregated_column(vnode):
    """Device path must ship filter-only columns to the kernel."""
    b = _batch(vnode)
    q = TpuQuery(filter=BinOp(">", Column("n"), Literal(250)),
                 aggs=[AggSpec("sum", "usage", "s"), AggSpec("count", None, "c")])
    r = execute_scan_aggregate(b, q)
    assert r.columns["c"][0] == 49
    assert r.columns["s"][0] == pytest.approx(sum(251.0 + k for k in range(49)))


def test_empty_group_not_emitted(vnode):
    b = _batch(vnode)
    q = TpuQuery(filter=BinOp("=", Column("host"), Literal("h0")),
                 group_tags=["host"], aggs=[AggSpec("count", None, "c")])
    r = execute_scan_aggregate(b, q)
    assert r.n_rows == 1
    assert r.columns["host"][0] == "h0"


def test_first_last_recurring_series_falls_back_to_rank():
    """A series that recurs non-contiguously (synthetic batches only; the
    storage scan always emits one contiguous run per series) must NOT use
    run-endpoint first/last: filter compression would join the two chunks
    into one run whose timestamps jump backwards at the seam."""
    from cnosdb_tpu.storage.scan import ScanBatch

    sid = np.array([0, 0, 1, 1, 0, 0], dtype=np.int32)
    ts = np.array([100, 110, 5, 6, 50, 60], dtype=np.int64)
    vals = np.array([1.0, 2.0, 9.0, 9.5, 3.0, 4.0])
    batch = ScanBatch(
        "m", np.array([10, 11], dtype=np.uint64),
        [SeriesKey("m", {"host": "a"}), SeriesKey("m", {"host": "b"})],
        ts, sid,
        {"v": (ValueType.FLOAT, vals, np.ones(6, dtype=bool))})
    # filter drops the series-1 rows → series-0 chunks become adjacent
    q = TpuQuery(filter=BinOp("<", Column("v"), Literal(5.0)),
                 aggs=[AggSpec("first", "v", "f"),
                       AggSpec("last", "v", "l")])
    res = execute_scan_aggregate(batch, q)
    # first = value at min ts (ts=50 → 3.0), last = at max ts (110 → 2.0)
    assert res.columns["f"][0] == 3.0, res.columns
    assert res.columns["l"][0] == 2.0, res.columns
