"""System tables, heavy aggregates, gapfill/locf/interpolate."""
import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()


@pytest.fixture
def m(db):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    rows = []
    # h=a: values 1..9 at minutes 0..8; h=b: 10,30 at minutes 0 and 4
    for i in range(9):
        rows.append(f"({i * 60_000_000_000}, 'a', {i + 1})")
    rows.append("(0, 'b', 10)")
    rows.append(f"({4 * 60_000_000_000}, 'b', 30)")
    db.execute_one("INSERT INTO m (time, h, v) VALUES " + ", ".join(rows))
    return db


def test_information_schema(m):
    rs = m.execute_one("SELECT database_name FROM information_schema.databases "
                       "ORDER BY database_name")
    assert "public" in rs.columns[0].tolist()
    rs = m.execute_one("SELECT table_name FROM information_schema.tables "
                       "WHERE table_database = 'public'")
    assert rs.columns[0].tolist() == ["m"]
    rs = m.execute_one(
        "SELECT column_name, column_type FROM information_schema.columns "
        "WHERE table_name = 'm' ORDER BY column_name")
    d = dict(zip(rs.columns[0], rs.columns[1]))
    assert d == {"time": "TIME", "h": "TAG", "v": "FIELD"}
    rs = m.execute_one("SELECT user_name FROM information_schema.users")
    assert "root" in rs.columns[0].tolist()


def test_cluster_and_usage_schema(m):
    rs = m.execute_one("SELECT vnode_id, status FROM cluster_schema.vnodes")
    assert rs.n_rows >= 1
    rs = m.execute_one("SELECT owner, series_count FROM usage_schema.disk_usage")
    assert rs.n_rows >= 1


def test_median_stddev_mode(m):
    rs = m.execute_one(
        "SELECT median(v) AS md, stddev(v) AS sd FROM m WHERE h = 'a'")
    vals = np.arange(1.0, 10.0)
    assert rs.columns[0][0] == pytest.approx(np.median(vals))
    assert rs.columns[1][0] == pytest.approx(np.std(vals, ddof=1))
    m.execute_one("INSERT INTO m (time, h, v) VALUES (999, 'c', 5), (1000, 'c', 5), (1001, 'c', 7)")
    rs = m.execute_one("SELECT mode(v) AS mo FROM m WHERE h = 'c'")
    assert rs.columns[0][0] == 5.0


def test_increase(m):
    rs = m.execute_one("SELECT h, increase(v) AS inc FROM m GROUP BY h ORDER BY h")
    assert rs.rows() == [("a", 8.0), ("b", 20.0)]


def test_gapfill_locf(m):
    rs = m.execute_one(
        "SELECT h, time_window_gapfill(time, INTERVAL '1 minute') AS t, "
        "locf(max(v)) AS v FROM m WHERE h = 'b' GROUP BY h, t ORDER BY t")
    # b has data at minute 0 and 4 → grid fills minutes 1-3 with locf
    assert rs.n_rows == 5
    assert rs.columns[2].tolist() == [10.0, 10.0, 10.0, 10.0, 30.0]


def test_gapfill_interpolate(m):
    rs = m.execute_one(
        "SELECT h, time_window_gapfill(time, INTERVAL '1 minute') AS t, "
        "interpolate(max(v)) AS v FROM m WHERE h = 'b' GROUP BY h, t ORDER BY t")
    assert rs.columns[2].tolist() == pytest.approx([10.0, 15.0, 20.0, 25.0, 30.0])


def test_gapfill_grid_bounded_by_where(m):
    rs = m.execute_one(
        "SELECT time_window_gapfill(time, INTERVAL '1 minute') AS t, "
        "locf(max(v)) AS v FROM m WHERE h = 'b' AND time >= 0 "
        "AND time <= 360000000000 GROUP BY t ORDER BY t")
    assert rs.n_rows == 7  # minutes 0..6 despite data ending at minute 4
    assert rs.columns[1].tolist()[-1] == 30.0
