"""Serving plane (plan cache / ScanToken-keyed result cache / fused
micro-batching): fingerprint normalization, cache hit + template-rebind
correctness, and the stale-read oracle — every invalidation source
(DELETE, DDL, matview refresh, tiering, compaction) run with the push
eviction FAULTED AWAY (``serving.invalidate:fail``), so freshness must
come entirely from probe-time ScanToken revalidation. Plus fused-vs-solo
bit-identity (NULL/NaN columns, deadline shedding one member only) and
the CNOSDB_SERVING=0 byte-identical legacy A/B.
"""
import threading
import time

import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.errors import (DeadlineExceeded, MetaError, QueryError,
                               TableNotFound)
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.server import serving
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage import tiering
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import deadline as deadline_mod


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("CNOSDB_SERVING", raising=False)
    monkeypatch.delenv("CNOSDB_SERVING_BATCH_FORCE", raising=False)
    serving.reset_counters()
    yield
    faults.reset()
    serving.reset_counters()


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE DATABASE sdb")
    s = Session(database="sdb")
    ex.execute_one("CREATE TABLE t (f1 BIGINT, f2 DOUBLE, TAGS(tag))", s)
    ex.execute_one(
        "INSERT INTO t (time, tag, f1, f2) VALUES "
        "(1,'a',10,1.5),(2,'a',40,2.5),(3,'b',20,3.5),(4,'c',30,4.5)", s)
    yield ex, s
    coord.close()


def _ctr(layer, outcome):
    return serving.counters_snapshot().get((layer, outcome), 0)


def _rows(ex, s, q):
    return sorted(map(repr, ex.execute_one(q, s).rows()))


# ----------------------------------------------------------- fingerprint
def test_fingerprint_hoists_literals_case_insensitively():
    a = serving.fingerprint(
        "SELECT F1 FROM T WHERE Tag = 'a' AND f2 > 3 LIMIT 10")
    b = serving.fingerprint(
        "select f1 from t where tag='b' and f2>7 limit 20")
    assert a is not None and b is not None
    assert a[0] == b[0]                      # one family, one fingerprint
    assert a[1] == ("a", 3, 10) and b[1] == ("b", 7, 20)
    # int vs float params must not unify (type-tagged keys downstream)
    c = serving.fingerprint("select f1 from t where f2 > 3.0")
    assert c is not None and isinstance(c[1][0], float)


def test_fingerprint_declines_uncacheable_shapes():
    assert serving.fingerprint("select now()") is None
    assert serving.fingerprint("select f1 from t; select f2 from t") is None
    assert serving.fingerprint("insert into t (time) values (1)") is None
    # a single trailing ';' is not a multi-statement request
    assert serving.fingerprint("select 1;") == ("select ?", (1,))
    # quoted idents keep quotes: "A b" can never collide with a b
    q = serving.fingerprint('select "A b" from t')
    assert q is not None and '"A b"' in q[0]


# ---------------------------------------------------------- cache layers
def test_result_cache_hit_and_template_rebind(db):
    ex, s = db
    q = "select f1 from t where tag='a'"
    assert _rows(ex, s, q) == ["(10,)", "(40,)"]      # miss → stored
    h0 = _ctr("result_cache", "hit")
    assert _rows(ex, s, q) == ["(10,)", "(40,)"]      # exact hit
    assert _ctr("result_cache", "hit") == h0 + 1
    e, b = ex.serving.result_cache.stats()
    assert e >= 1 and b > 0
    # same fingerprint, new param: plan-template rebind, correct rows
    r0 = _ctr("plan_cache", "hit_rebind")
    assert _rows(ex, s, "select f1 from t where tag='b'") == ["(20,)"]
    assert _ctr("plan_cache", "hit_rebind") == r0 + 1


def test_plain_write_invalidates_via_tokens_plan_survives(db):
    ex, s = db
    q = "select f1 from t where tag='c'"
    assert _rows(ex, s, q) == ["(30,)"]
    assert _rows(ex, s, q) == ["(30,)"]               # cached
    ex.execute_one(
        "INSERT INTO t (time, tag, f1, f2) VALUES (9,'c',70,9.5)", s)
    # no push hook on INSERT: the probe must catch the token bump alone,
    # while the analyzed plan stays cached (exact plan hit, fresh scan)
    p0 = _ctr("plan_cache", "hit")
    assert _rows(ex, s, q) == ["(30,)", "(70,)"]
    assert _ctr("plan_cache", "hit") == p0 + 1


def test_errors_are_never_cached(db):
    ex, s = db
    e0 = ex.serving.result_cache.stats()[0]
    for _ in range(2):
        with pytest.raises(QueryError):
            ex.execute_one("select no_such_col from t", s)
    assert ex.serving.result_cache.stats()[0] == e0


# ------------------------------------------------- stale-read oracle
# each source of invalidation runs with push eviction faulted away:
# correctness must come from probe-time ScanToken revalidation alone
def test_stale_read_oracle_delete(db):
    ex, s = db
    q = "select f1 from t where f2 > 0"
    assert len(_rows(ex, s, q)) == 4
    assert _ctr("result_cache", "hit") >= 0 and _rows(ex, s, q)  # cached
    faults.configure("serving.invalidate:fail")
    ex.execute_one("delete from t where tag = 'a'", s)
    assert _rows(ex, s, q) == ["(20,)", "(30,)"]      # no stale 'a' rows


def test_stale_read_oracle_alter_table(db):
    ex, s = db
    q = "select f1 from t where tag='a'"
    _rows(ex, s, q)
    _rows(ex, s, q)                                    # cached
    faults.configure("serving.invalidate:fail")
    inv0 = _ctr("result_cache", "invalidate")
    ex.execute_one("ALTER TABLE t ADD FIELD f3 BIGINT", s)
    # schema version rides the token map: probe evicts, plan re-parses
    assert _rows(ex, s, q) == ["(10,)", "(40,)"]
    assert _ctr("result_cache", "invalidate") > inv0


def test_stale_read_oracle_drop_table(db):
    ex, s = db
    q = "select f1 from t where tag='a'"
    _rows(ex, s, q)
    faults.configure("serving.invalidate:fail")
    ex.execute_one("DROP TABLE t", s)
    with pytest.raises(TableNotFound):
        ex.execute_one(q, s)          # cached result must not resurrect t


def test_stale_read_oracle_drop_database_is_selective(db):
    ex, s = db
    ex.execute_one("CREATE DATABASE other")
    s2 = Session(database="other")
    ex.execute_one("CREATE TABLE t (f1 BIGINT, TAGS(tag))", s2)
    ex.execute_one("INSERT INTO t (time, tag, f1) VALUES (1,'x',5)", s2)
    q = "select f1 from t where tag='x'"
    qa = "select f1 from t where tag='a'"
    assert _rows(ex, s2, q) == ["(5,)"]
    _rows(ex, s, qa)
    faults.configure("serving.invalidate:fail")
    ex.execute_one("DROP DATABASE sdb")
    with pytest.raises((QueryError, MetaError)):
        ex.execute_one(qa, s)
    # the OTHER database's entry survives and still hits
    h0 = _ctr("result_cache", "hit")
    assert _rows(ex, s2, q) == ["(5,)"]
    assert _ctr("result_cache", "hit") == h0 + 1


def test_stale_read_oracle_matview_refresh(db, monkeypatch):
    monkeypatch.setenv("CNOSDB_MATVIEW_AUTO", "0")
    ex, s = db
    SEC = 10 ** 9
    rows = ", ".join(f"({i * SEC}, 'h{i % 2}', {i}, {i}.5)"
                     for i in range(20))
    ex.execute_one("CREATE TABLE m (f1 BIGINT, v DOUBLE, TAGS(h))", s)
    ex.execute_one(f"INSERT INTO m (time, h, f1, v) VALUES {rows}", s)
    ex.execute_one(
        "CREATE MATERIALIZED VIEW mv WATERMARK DELAY '1s' AS "
        "SELECT date_bin(INTERVAL '1 minute', time) AS tb, h, sum(v) "
        "FROM m GROUP BY tb, h", s)
    ex.matview_engine().refresh("mv", now_ns=100 * SEC)
    q = "SELECT h, sum(v) FROM m GROUP BY h"
    first = _rows(ex, s, q)
    assert _rows(ex, s, q) == first                    # cached
    faults.configure("serving.invalidate:fail")
    rows2 = ", ".join(f"({(20 + i) * SEC}, 'h{i % 2}', {20 + i}, "
                      f"{20 + i}.5)" for i in range(10))
    ex.execute_one(f"INSERT INTO m (time, h, f1, v) VALUES {rows2}", s)
    ex.matview_engine().refresh("mv", now_ns=200 * SEC)
    faults.reset()
    ex.matview_rewrite_enabled = False
    want = _rows(ex, s, "SELECT h, sum(v) FROM m GROUP BY h ")  # no-cache spelling
    ex.matview_rewrite_enabled = True
    faults.configure("serving.invalidate:fail")
    got = _rows(ex, s, q)
    assert got == want and got != first                # fresh, not stale


def test_stale_read_oracle_tiering(db, tmp_path):
    # tiering is the one source that does NOT flip the ScanToken — on
    # purpose, a tiered scan is bit-identical and coordinator scan
    # caches stay valid — so the oracle here is two-sided: with the
    # push eviction faulted away a cache hit must still be the right
    # bytes, and without the fault the push must actually evict
    ex, s = db
    store = tmp_path / "bucket"
    store.mkdir()
    tiering.configure(str(store))
    try:
        ex.coord.engine.flush_all()
        ex.execute_one(
            "INSERT INTO t (time, tag, f1, f2) VALUES (5,'a',50,5.5)", s)
        ex.coord.engine.flush_all()
        for v in list(ex.coord.engine.vnodes.values()):
            v.compact_major()                # tiering wants sealed L1+
        q = "select f1 from t where tag='a'"
        want = _rows(ex, s, q)
        assert _rows(ex, s, q) == want                 # cached
        faults.configure("serving.invalidate:fail")
        h0 = _ctr("result_cache", "hit")
        moved = sum(tiering.tier_vnode(v, boundary_ns=10 ** 18)
                    for v in list(ex.coord.engine.vnodes.values()))
        assert moved >= 1
        assert _rows(ex, s, q) == want                 # sound hit
        assert _ctr("result_cache", "hit") == h0 + 1
        # unfaulted: a fresh tier event's push eviction retires the
        # entry and the re-read goes through the cold tier, identical
        faults.reset()
        ex.execute_one(
            "INSERT INTO t (time, tag, f1, f2) VALUES (6,'a',60,6.5)", s)
        ex.coord.engine.flush_all()
        ex.execute_one(
            "INSERT INTO t (time, tag, f1, f2) VALUES (7,'b',70,7.5)", s)
        ex.coord.engine.flush_all()
        for v in list(ex.coord.engine.vnodes.values()):
            v.compact_major()
        want2 = _rows(ex, s, q)
        assert "(60,)" in want2 and _rows(ex, s, q) == want2   # cached
        inv0 = _ctr("result_cache", "invalidate")
        moved = sum(tiering.tier_vnode(v, boundary_ns=10 ** 18)
                    for v in list(ex.coord.engine.vnodes.values()))
        assert moved >= 1
        assert _ctr("result_cache", "invalidate") > inv0
        assert _rows(ex, s, q) == want2
    finally:
        tiering.configure(None)
        tiering.block_cache_clear()
        tiering.counters_reset()


def test_stale_read_oracle_compaction(db):
    ex, s = db
    ex.coord.engine.flush_all()
    ex.execute_one(
        "INSERT INTO t (time, tag, f1, f2) VALUES (8,'a',80,8.5)", s)
    ex.coord.engine.flush_all()                        # 2 L0 files
    q = "select f1 from t where tag='a'"
    want = _rows(ex, s, q)
    assert _rows(ex, s, q) == want                     # cached
    faults.configure("serving.invalidate:fail")
    inv0 = _ctr("result_cache", "invalidate")
    for owner, vid in list(ex.coord.engine.vnodes):
        if owner == "cnosdb.sdb":
            ex.coord.compact_vnode(vid)
    assert _rows(ex, s, q) == want
    assert _ctr("result_cache", "invalidate") > inv0


# -------------------------------------------------------- fused batching
def _mk_point_table(ex, s):
    ex.execute_one("CREATE TABLE p (f1 BIGINT, f2 DOUBLE, TAGS(tag))", s)
    # NULL column: rows for tag 'c' never write f2; NaN rides on 'd'
    ex.execute_one(
        "INSERT INTO p (time, tag, f1, f2) VALUES "
        "(1,'a',1,0.5),(2,'a',2,1.5),(3,'b',3,2.5),(4,'b',4,3.5)", s)
    ex.execute_one("INSERT INTO p (time, tag, f1) VALUES (5,'c',5),(6,'c',6)", s)
    try:
        ex.execute_one(
            "INSERT INTO p (time, tag, f1, f2) VALUES (7,'d',7,NaN)", s)
    except Exception:
        import numpy as np

        from cnosdb_tpu.models.points import SeriesRows, WriteBatch
        from cnosdb_tpu.models.schema import ValueType
        from cnosdb_tpu.models.series import SeriesKey
        wb = WriteBatch()
        wb.add_series("p", SeriesRows(
            SeriesKey("p", {"tag": "d"}), np.array([7], dtype=np.int64),
            {"f1": (int(ValueType.INTEGER), np.array([7])),
             "f2": (int(ValueType.FLOAT), np.array([float("nan")]))}))
        ex.coord.write_points("cnosdb", "sdb", wb)


def test_fused_point_queries_bit_identical_to_solo(db, monkeypatch):
    ex, s = db
    _mk_point_table(ex, s)
    tags = ["a", "b", "c", "d"]
    qs = {t: f"select time, f1, f2 from p where tag='{t}'" for t in tags}
    # solo baseline through a serving-disabled executor on the same data
    monkeypatch.setenv("CNOSDB_SERVING", "0")
    solo_ex = QueryExecutor(ex.meta, ex.coord)
    assert solo_ex.serving is None
    want = {t: _rows(solo_ex, s, qs[t]) for t in tags}
    monkeypatch.delenv("CNOSDB_SERVING")

    ex.serving.batcher.force = True
    ex.serving.batcher.window_s = 0.25
    got, errors = {}, {}

    def run(tag):
        try:
            got[tag] = _rows(ex, s, qs[tag])
        except Exception as e:          # surfaced via the errors dict
            errors[tag] = e

    threads = [threading.Thread(target=run, args=(t,)) for t in tags]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors
    assert got == want
    widths = serving.width_histogram()
    assert widths and max(widths) >= 2, widths         # something fused
    # and a fused answer re-served from cache is still bit-identical
    assert {t: _rows(ex, s, qs[t]) for t in tags} == want


def test_fused_member_deadline_sheds_only_that_member(db):
    ex, s = db
    _mk_point_table(ex, s)
    want_a = _rows(ex, s, "select f1 from p where tag='a' and f2 < 99")
    serving.reset_counters()
    ex.serving.result_cache.invalidate("cnosdb", "sdb")  # force re-exec
    ex.serving.batcher.force = True
    ex.serving.batcher.window_s = 0.5
    got, errors = {}, {}

    def leader():
        try:
            got["a"] = _rows(ex, s, "select f1 from p where tag='a' and f2 < 99")
        except Exception as e:
            errors["a"] = e

    def follower():
        try:
            with deadline_mod.scope(deadline_mod.Deadline(0.12)):
                got["b"] = _rows(ex, s, "select f1 from p where tag='b' and f2 < 99")
        except Exception as e:
            errors["b"] = e

    ta = threading.Thread(target=leader)
    tb = threading.Thread(target=follower)
    ta.start()
    time.sleep(0.1)                     # leader's window is open by now
    tb.start()
    ta.join()
    tb.join()
    assert isinstance(errors.get("b"), DeadlineExceeded), (got, errors)
    assert "a" not in errors and got["a"] == want_a


# --------------------------------------------------------- kill switch
def test_serving_disabled_is_byte_identical(db, monkeypatch):
    ex, s = db
    queries = [
        "select time, f1, f2 from t where tag='a'",
        "select f1 from t where f2 > 2.0 limit 2",
        "select tag, sum(f1) from t group by tag",
        "select count(f1) from t",
    ]
    monkeypatch.setenv("CNOSDB_SERVING", "0")
    legacy = QueryExecutor(ex.meta, ex.coord)
    assert legacy.serving is None
    for q in queries:
        want = _rows(legacy, s, q)
        assert _rows(ex, s, q) == want      # miss path
        assert _rows(ex, s, q) == want      # cached path


# -------------------------------------------------------------- caches
def test_result_cache_byte_cap_and_oversize_reject():
    rc = serving.ResultCache(max_bytes=1 << 20, max_entries=16)
    def ent(n):
        return serving._ResultEntry(None, {}, None, "t", "d", "m", n)
    assert not rc.store("huge", ent((1 << 20) // 8 + 1))   # > cap/8
    for i in range(20):
        assert rc.store(("k", i), ent(100_000))
    e, b = rc.stats()
    assert e <= 16 and b <= 1 << 20              # LRU bounded both ways
    assert rc.get(("k", 0)) is None and rc.get(("k", 19)) is not None


def test_plan_cache_lru_bound():
    pc = serving.PlanCache(max_entries=8)
    for i in range(20):
        pe = serving._PlanEntry(None, None, "t", "d", "m", 0, (i,), None)
        pc.store(("t", "d", "fp", (i,)), pe)
    assert pc.stats()[0] == 8
