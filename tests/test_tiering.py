"""Cold tier (tentpole of the tiered-storage PR): age sealed TSM files
into an object store keeping a local skip-index sidecar, scan the COLD
tier transparently through byte-range GETs + block cache, prune pages
locally before any byte downloads, and recover/rehydrate/scrub/purge
against the store. The parity oracle throughout: a tiered scan is
bit-identical to the hot scan of the same writes."""
import glob
import os

import numpy as np
import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.errors import ChecksumMismatch, StorageError, TsmError
from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.storage import scrub, tiering
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("CNOSDB_COLD_TIER", raising=False)
    tiering.counters_reset()
    tiering.block_cache_clear()
    yield
    faults.reset()
    tiering.configure(None)
    tiering.counters_reset()
    tiering.block_cache_clear()


@pytest.fixture
def store_dir(tmp_path):
    d = tmp_path / "bucket"
    d.mkdir()
    tiering.configure(str(d))
    return str(d)


def _schema():
    return {"cpu": TskvTableSchema.new_measurement(
        "t", "db", "cpu", tags=["host"],
        fields=[("usage", ValueType.FLOAT), ("s", ValueType.STRING)])}


def _wb(host, ts_list, usage_list, s_list=None):
    fields = {"usage": (int(ValueType.FLOAT), list(usage_list))}
    if s_list is not None:
        fields["s"] = (int(ValueType.STRING), list(s_list))
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": host}), list(ts_list), fields))
    return wb


def _build_vnode(dir_path, base_ts=0, words=("alpha", "beta"), n=200):
    """5 flushes + full compaction → one sealed L1 file. NaN floats and a
    NULL-string series ride along so parity covers the awkward values."""
    v = VnodeStorage(1, dir_path, schemas=_schema())
    for i in range(5):
        lo = base_ts + i * n
        usage = [float(j) * 0.5 for j in range(n)]
        usage[3] = float("nan")
        v.write(_wb("h1", range(lo, lo + n), usage,
                    [words[j % len(words)] for j in range(n)]))
        # second series writes no strings at all → NULL "s" column
        v.write(_wb("h2", range(lo, lo + n), [1.0] * n))
        v.flush()
    v.compact_full()
    fms = v.summary.version.all_files()
    assert len(fms) == 1 and fms[0].level >= 1, [f.level for f in fms]
    return v


def _batch_dict(b):
    def mat(x):
        return x.materialize() if hasattr(x, "materialize") else x
    out = {"ts": np.asarray(b.ts), "sids": np.asarray(b.series_ids)}
    for name, (vt, vals, valid) in b.fields.items():
        out[name] = (int(vt), np.asarray(mat(vals)),
                     None if valid is None else np.asarray(valid))
    return out


def _assert_same(a, b):
    a, b = _batch_dict(a), _batch_dict(b)
    assert a.keys() == b.keys()
    np.testing.assert_array_equal(a["ts"], b["ts"])
    np.testing.assert_array_equal(a["sids"], b["sids"])
    for k in a:
        if k in ("ts", "sids"):
            continue
        (vt1, v1, m1), (vt2, v2, m2) = a[k], b[k]
        assert vt1 == vt2
        np.testing.assert_array_equal(v1, v2)       # NaN == NaN here
        if m1 is None or m2 is None:
            assert m1 is m2
        else:
            np.testing.assert_array_equal(m1, m2)


def _tier_all(v):
    n = tiering.tier_vnode(v, boundary_ns=10 ** 18)
    assert n >= 1
    return n


# --------------------------------------------------------------- parity
def test_tier_then_cold_scan_is_bit_identical(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    hot = scan_vnode(v, "cpu")
    assert _tier_all(v) == 1
    # the data file left the hot tier; the skip-index sidecar stayed
    assert glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsm")) == []
    assert len(glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsmc"))) == 1
    assert len(tiering.cold_ids(v.dir)) == 1
    cold = scan_vnode(v, "cpu")
    _assert_same(hot, cold)
    snap = tiering.cold_tier_snapshot()
    assert snap[("fetch", "bytes_downloaded")] > 0
    v.close()


def test_cold_tier_0_knob_disables_tiering(tmp_engine_dir, store_dir,
                                           monkeypatch):
    monkeypatch.setenv("CNOSDB_COLD_TIER", "0")
    v = _build_vnode(tmp_engine_dir)
    assert not tiering.enabled()
    assert tiering.tier_vnode(v, boundary_ns=10 ** 18) == 0
    assert tiering.cold_ids(v.dir) == frozenset()
    assert len(glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsm"))) == 1
    v.close()


def test_boundary_respects_file_age(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir, base_ts=10 ** 6)
    # newest row is ~10**6 + 1000 ns; a boundary below it tiers nothing
    assert tiering.tier_vnode(v, boundary_ns=10 ** 6) == 0
    assert tiering.tier_vnode(v, boundary_ns=10 ** 9) == 1
    v.close()


# ----------------------------------------------------- near-data pruning
def _device_hook():
    from cnosdb_tpu.ops import device_decode
    return lambda: device_decode.DeviceDecodeLane(interpret=True)


def test_constraint_prune_downloads_nothing(tmp_engine_dir, store_dir):
    from cnosdb_tpu.sql.expr import BinOp, Column, Literal
    from cnosdb_tpu.storage.scan import _page_constraints

    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    # zone maps exclude every page
    flt = BinOp(">", Column("usage"), Literal(1e9))
    b = scan_vnode(v, "cpu", page_constraints=_page_constraints(
        flt, ["usage"]), decode_hook=_device_hook())
    assert len(b.ts) == 0
    snap = tiering.cold_tier_snapshot()
    assert snap.get(("prune", "pages_pruned"), 0) > 0
    assert snap.get(("fetch", "bytes_downloaded"), 0) == 0
    v.close()


def test_like_trigram_prune_parity_on_cold(tmp_path, store_dir,
                                           monkeypatch):
    """Two cold files; the LIKE needle lives in one. With n-gram skipping
    on, the other file's pages never download — and the result matches
    the skip-disabled scan of the same cold vnode bit for bit."""
    from cnosdb_tpu.sql.expr import Column, Like
    from cnosdb_tpu.storage.scan import _page_constraints

    d = str(tmp_path / "engine")
    v = _build_vnode(d, base_ts=0, words=("alpha", "beta"))
    _tier_all(v)                       # file A cold → next compaction
    for i in range(5):                 # can't merge it with batch B
        lo = 10 ** 6 + i * 200
        v.write(_wb("h1", range(lo, lo + 200), [1.0] * 200,
                    ["rare_needle" if j % 7 == 0 else "gamma"
                     for j in range(200)]))
        v.flush()
    v.compact_full()
    assert _tier_all(v) >= 1
    assert len(tiering.cold_ids(v.dir)) >= 2

    flt = Like(Column("s"), "%rare_needle%")

    def run(skip_on):
        tiering.block_cache_clear()
        tiering.counters_reset()
        # the env knob is honored at constraint-extraction time
        monkeypatch.setenv("CNOSDB_NGRAM_SKIP", "1" if skip_on else "0")
        cons = _page_constraints(flt, ["s"])
        if skip_on:
            assert any(c[0] == "ngram" for c in cons.get("s", ())), cons
        b = scan_vnode(v, "cpu", page_constraints=cons,
                       decode_hook=_device_hook())
        return b, tiering.cold_tier_snapshot()

    def matching(b):
        _, vals, valid = b.fields["s"]
        if hasattr(vals, "materialize"):
            vals = vals.materialize()
        return sorted(
            (int(t), str(s)) for t, s, ok in zip(b.ts, vals, valid)
            if ok and "rare_needle" in str(s))

    pruned, snap_on = run(True)
    oracle, snap_off = run(False)
    rows = matching(pruned)
    assert len(rows) > 0 and rows == matching(oracle)
    assert snap_on.get(("prune", "pages_pruned"), 0) \
        > snap_off.get(("prune", "pages_pruned"), 0)
    assert snap_on[("fetch", "bytes_downloaded")] \
        < snap_off[("fetch", "bytes_downloaded")]
    v.close()


# ------------------------------------------------------------ block cache
def test_block_cache_serves_repeat_scans(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    scan_vnode(v, "cpu")
    first = tiering.cold_tier_snapshot()[("fetch", "bytes_downloaded")]
    assert first > 0
    scan_vnode(v, "cpu")
    snap = tiering.cold_tier_snapshot()
    assert snap[("fetch", "bytes_downloaded")] == first   # all cache hits
    stats = tiering.block_cache_stats()
    assert stats["entries"] > 0 and stats["bytes"] > 0
    v.close()


# ------------------------------------------------ chaos: recover / rehydrate
def test_sidecar_wipe_recovers_from_object_store(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    hot = scan_vnode(v, "cpu")
    _tier_all(v)
    for side in glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsmc")):
        os.unlink(side)
    for fid in tiering.cold_ids(v.dir):
        v.summary.version.drop_reader(fid)
    tiering.block_cache_clear()
    with pytest.raises(TsmError):
        scan_vnode(v, "cpu")
    assert tiering.recover_vnode(v) == 1          # sidecars rebuilt remotely
    _assert_same(hot, scan_vnode(v, "cpu"))
    v.close()


def test_rehydrate_restores_the_hot_tier(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    hot = scan_vnode(v, "cpu")
    _tier_all(v)
    assert tiering.rehydrate_vnode(v) == 1
    assert tiering.cold_ids(v.dir) == frozenset()
    assert len(glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsm"))) == 1
    (fm,) = v.summary.version.all_files()
    assert not getattr(v.summary.version.reader(fm), "is_cold", False)
    _assert_same(hot, scan_vnode(v, "cpu"))
    v.close()


def test_cold_reader_refuses_native_buffer(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    (fm,) = v.summary.version.all_files()
    r = v.summary.version.reader(fm)
    assert r.is_cold
    with pytest.raises(StorageError):
        r.buffer_array()
    v.close()


# ----------------------------------------------------------------- scrub
def test_scrub_verifies_cold_files_without_quarantine(tmp_engine_dir,
                                                      store_dir):
    scrub.counters_reset()
    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    res = scrub.scrub_vnode(v)
    assert res["corrupt"] == [] and res["bytes"] > 0
    # flip a footer byte of the remote object → scrub must see divergence
    (obj,) = glob.glob(os.path.join(store_dir, "vnode_1", "*.tsm"))
    with open(obj, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    res = scrub.scrub_vnode(v)
    assert len(res["corrupt"]) == 1
    # the manifest entry is the ONLY pointer to the remote bytes: a cold
    # file must never be quarantined out of the Version
    assert v.quarantined_files() == []
    assert len(v.summary.version.all_files()) == 1
    v.close()


def test_verify_cold_file_raises_on_damaged_sidecar(tmp_engine_dir,
                                                    store_dir):
    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    (fid,) = tiering.cold_ids(v.dir)
    assert tiering.verify_cold_file(v, fid) > 0
    (side,) = glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsmc"))
    with open(side, "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(ChecksumMismatch):
        tiering.verify_cold_file(v, fid)
    v.close()


# ------------------------------------------------------------- compaction
def test_compaction_never_consumes_cold_files(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    _tier_all(v)
    (cold_fid,) = tiering.cold_ids(v.dir)
    assert cold_fid in v._compaction_exclude()
    # hot backfill INTO the cold window joins the freeze (LWW ordering)
    v.write(_wb("h1", [10, 11], [9.0, 9.5], ["x", "y"]))
    v.flush()
    hot_fid = max(f.file_id for f in v.summary.version.all_files())
    assert hot_fid in v._compaction_exclude()
    while v.compact():
        pass
    ids = {f.file_id for f in v.summary.version.all_files()}
    assert cold_fid in ids and hot_fid in ids
    v.close()


# -------------------------------------------------- tier-then-expire, job
def test_drop_vnode_purges_cold_objects(tmp_path, store_dir):
    from cnosdb_tpu.storage.engine import TsKv

    engine = TsKv(str(tmp_path / "data"))
    engine.schemas.setdefault("db", {}).update(_schema())
    v = engine.open_vnode("db", 1)
    for i in range(5):
        v.write(_wb("h1", range(i * 10, i * 10 + 10), [1.0] * 10))
        v.flush()
    v.compact_full()
    assert tiering.tier_vnode(v, boundary_ns=10 ** 18) == 1
    assert glob.glob(os.path.join(store_dir, "vnode_1", "*.tsm"))
    engine.drop_vnode("db", 1, purge_cold=True)
    assert glob.glob(os.path.join(store_dir, "vnode_1", "*.tsm")) == []
    engine.close()


def test_tiering_job_sweeps_engine_vnodes(tmp_path, store_dir):
    from cnosdb_tpu.storage.engine import TsKv

    engine = TsKv(str(tmp_path / "data"))
    engine.schemas.setdefault("db", {}).update(_schema())
    v = engine.open_vnode("db", 1)
    for i in range(5):       # data timestamps ≪ wall clock → instantly cold
        v.write(_wb("h1", range(i * 10, i * 10 + 10), [1.0] * 10))
        v.flush()
    v.compact_full()
    job = tiering.TieringJob(engine, interval_s=3600, cold_after_s=3600)
    assert job.sweep_once() == 1
    assert len(tiering.cold_ids(v.dir)) == 1
    assert job.sweep_once() == 0            # idempotent: already cold
    engine.close()


def test_tiering_upload_fault_leaves_file_hot(tmp_engine_dir, store_dir):
    v = _build_vnode(tmp_engine_dir)
    faults.configure("seed=1;objstore.put:fail")
    try:
        with pytest.raises(Exception):
            tiering.tier_vnode(v, boundary_ns=10 ** 18)
    finally:
        faults.reset()
    # failed upload must not flip the registry or drop the local file
    assert tiering.cold_ids(v.dir) == frozenset()
    assert len(glob.glob(os.path.join(tmp_engine_dir, "tsm", "*.tsm"))) == 1
    scan_vnode(v, "cpu")
    v.close()


# ------------------------------------------------- coordinator failover
def test_query_path_recovers_wiped_sidecars(tmp_path, store_dir):
    """End-to-end chaos: tiered vnode loses its local skip-index state;
    the coordinator's TsmError handler rebuilds it from the object store
    and retries — the query answers with no lost rows."""
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    db = QueryExecutor(meta, coord)
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    for i in range(5):
        db.execute_one(
            "INSERT INTO m (time, h, v) VALUES "
            + ",".join(f"({i * 10 + j},'a',{float(i * 10 + j)})"
                       for j in range(10)))
        for v in list(engine.vnodes.values()):
            v.flush()           # 5 sealed files per vnode → L1 compaction
    tiered = []
    for v in list(engine.vnodes.values()):
        v.compact_full()
        if tiering.tier_vnode(v, boundary_ns=10 ** 18):
            tiered.append(v)
    assert tiered

    rs = db.execute_one("SELECT count(v) FROM m")
    assert int(rs.columns[0][0]) == 50
    from cnosdb_tpu.models.predicate import ColumnDomains, TimeRanges

    splits = coord.table_vnodes("cnosdb", "public", "m",
                                TimeRanges.all(), ColumnDomains())
    assert "cold" in {s.tier for s in splits}

    for v in tiered:
        for side in glob.glob(os.path.join(v.dir, "tsm", "*.tsmc")):
            os.unlink(side)
        for fid in tiering.cold_ids(v.dir):
            v.summary.version.drop_reader(fid)
    with coord._scan_cache_lock:
        coord._scan_cache.clear()
    tiering.block_cache_clear()
    from cnosdb_tpu.server import serving as serving_mod

    serving_mod.invalidate("cnosdb", "public")   # the wipe bumps no token
    rs = db.execute_one("SELECT count(v) FROM m")
    assert int(rs.columns[0][0]) == 50      # recovered, not lost
    for v in tiered:
        assert glob.glob(os.path.join(v.dir, "tsm", "*.tsmc"))
    engine.close()
