import os

import pytest

from cnosdb_tpu.storage.record_file import RecordReader, RecordWriter
from cnosdb_tpu.storage.wal import Wal, WalEntryType


def test_record_file_roundtrip(tmp_path):
    p = str(tmp_path / "r.log")
    w = RecordWriter(p)
    for i in range(100):
        w.append(f"payload-{i}".encode())
    w.close()
    rr = RecordReader(p)
    recs = rr.records()
    assert len(recs) == 100
    assert recs[0] == b"payload-0"
    assert recs[99] == b"payload-99"


def test_record_file_append_reopen(tmp_path):
    p = str(tmp_path / "r.log")
    w = RecordWriter(p)
    w.append(b"a")
    w.close()
    w2 = RecordWriter(p)
    w2.append(b"b")
    w2.close()
    assert RecordReader(p).records() == [b"a", b"b"]


def test_record_file_torn_tail(tmp_path):
    p = str(tmp_path / "r.log")
    w = RecordWriter(p)
    w.append(b"good-record")
    w.append(b"second-record")
    w.close()
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-5])  # truncate mid-record (crash simulation)
    assert RecordReader(p).records() == [b"good-record"]


def test_record_file_corrupt_record_stops_replay(tmp_path):
    p = str(tmp_path / "r.log")
    w = RecordWriter(p)
    w.append(b"one")
    w.append(b"two")
    w.append(b"three")
    w.close()
    raw = bytearray(open(p, "rb").read())
    raw[8 + 8 + 3 + 8] ^= 0xFF  # corrupt inside record 2
    open(p, "wb").write(bytes(raw))
    assert RecordReader(p).records() == [b"one"]


# ---------------------------------------------------------------- WAL
def test_wal_append_replay(tmp_path):
    w = Wal(str(tmp_path / "wal"))
    seqs = [w.append(WalEntryType.WRITE, f"w{i}".encode()) for i in range(10)]
    assert seqs == list(range(1, 11))
    entries = list(w.replay())
    assert [e.seq for e in entries] == seqs
    assert entries[3].data == b"w3"
    w.close()


def test_wal_recover_after_reopen(tmp_path):
    d = str(tmp_path / "wal")
    w = Wal(d)
    for i in range(5):
        w.append(WalEntryType.WRITE, f"w{i}".encode())
    w.sync()
    w.close()
    w2 = Wal(d)
    assert w2.next_seq == 6
    assert [e.data for e in w2.replay(from_seq=4)] == [b"w3", b"w4"]
    w2.append(WalEntryType.WRITE, b"after")
    assert [e.data for e in w2.replay()][-1] == b"after"
    w2.close()


def test_wal_segment_roll_and_purge(tmp_path):
    d = str(tmp_path / "wal")
    w = Wal(d, max_segment_size=256)
    for i in range(100):
        w.append(WalEntryType.WRITE, b"x" * 32)
    files = [f for f in os.listdir(d) if f.startswith("wal_")]
    assert len(files) > 1
    w.purge_to(90)
    files_after = [f for f in os.listdir(d) if f.startswith("wal_")]
    assert len(files_after) < len(files)
    # entries >= 90 still replayable
    assert [e.seq for e in w.replay(from_seq=90)] == list(range(90, 101))
    w.close()


def test_wal_raft_truncate_conflict(tmp_path):
    """Raft log conflict: re-append at an existing seq invalidates tail."""
    d = str(tmp_path / "wal")
    w = Wal(d)
    for i in range(10):
        w.append(WalEntryType.WRITE, f"old{i}".encode())
    w.append(WalEntryType.WRITE, b"new5", seq=5)
    w.append(WalEntryType.WRITE, b"new6")
    entries = list(w.replay())
    assert [e.seq for e in entries] == [1, 2, 3, 4, 5, 6]
    assert entries[4].data == b"new5"
    assert entries[5].data == b"new6"
    w.close()
    # survives reopen
    w2 = Wal(d)
    entries = list(w2.replay())
    assert [e.seq for e in entries] == [1, 2, 3, 4, 5, 6]
    assert entries[4].data == b"new5"
    w2.close()


def test_wal_seq_survives_purge_all_and_restart(tmp_path):
    """Regression: roll + purge leaving only an empty active segment must
    not reset seqs below a previously handed-out watermark after restart
    (would silently drop post-restart writes in crash recovery)."""
    d = str(tmp_path / "wal")
    w = Wal(d, max_segment_size=256)
    for i in range(100):
        w.append(WalEntryType.WRITE, b"x" * 32)
    # force roll so active segment is empty, then purge everything flushed
    w._roll()
    w.purge_to(101)
    w.close()
    w2 = Wal(d, max_segment_size=256)
    assert w2.next_seq >= 101
    s = w2.append(WalEntryType.WRITE, b"after-restart")
    assert s >= 101
    # replay-from-flushed must see the new write
    assert [e.data for e in w2.replay(from_seq=101)] == [b"after-restart"]
    w2.close()
