"""Crash-point sweep: a fast deterministic subset runs in tier-1 (crash
at three early storage points, recover, check), the exhaustive sweep over
every registered node-scope point is slow-marked. Any failure message
embeds the CNOSDB_FAULTS seed + spec for one-command reproduction.

Also the regression tests for the hardening the sweep forced: a torn
cold.json registry must be refused loudly (not read as "no cold files")
and rebuilt from the local sidecars on the recover path.
"""
import json
import os

import pytest

from cnosdb_tpu import chaos, faults
from cnosdb_tpu.chaos import sweep, workload
from cnosdb_tpu.errors import TsmError


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    chaos.counters_reset()
    yield
    faults.reset()
    chaos.counters_reset()


def _fail_msg(r):
    return (f"crash run went wrong: point={r['point']} nth={r['nth']} "
            f"rc={r['rc']}\nreproduce with:\n  {r['repro']}\n"
            f"results={json.dumps(r.get('results', r.get('error')))}"[:3000])


# ------------------------------------------------------- fast (tier-1)
def test_fast_sweep_subset_recovers_at_every_site(tmp_path):
    base = str(tmp_path)
    points = list(sweep.FAST_POINTS)
    hits = sweep.probe(base, seed=7, points=points)
    for p in points:
        assert hits.get(p, 0) > 0, \
            f"canonical workload no longer crosses {p} — probe hits {hits}"
    for p in points:
        r = sweep.run_one(base, p, 1, seed=7)
        assert r["crashed"] and r.get("ok"), _fail_msg(r)
        # observed may legitimately be 0: a crash at e.g. wal.append
        # nth=1 lands before any write was ever acked
        assert r["mttr_s"] >= 0


def test_probe_trace_is_deterministic(tmp_path):
    """Same seed + spec ⇒ byte-identical fired sequence across runs —
    the property every printed repro depends on."""
    points = ["wal.append", "flush.run"]
    a = sweep.probe(str(tmp_path / "a"), seed=7, points=points)
    b = sweep.probe(str(tmp_path / "b"), seed=7, points=points)
    assert a == b
    ta = json.load(open(os.path.join(str(tmp_path / "a"), "probe",
                                     workload.TRACE)))
    tb = json.load(open(os.path.join(str(tmp_path / "b"), "probe",
                                     workload.TRACE)))
    assert ta["fired"] == tb["fired"]


# ------------------------------------------- torn-registry regression
def test_torn_cold_registry_is_loud_not_empty(tmp_path):
    """The bug the sweep surfaced: cold_map() used to read a torn
    cold.json as {} — scans silently lost every cold file and the next
    registry write would erase their records for good."""
    from cnosdb_tpu.storage import tiering

    d = str(tmp_path)
    assert tiering.cold_map(d) == {}          # missing: legitimately empty
    with open(os.path.join(d, "cold.json"), "w") as f:
        f.write('{"files": {"7": {"key"')     # torn mid-write
    with pytest.raises(TsmError):
        tiering.cold_map(d)


def test_torn_registry_recovers_through_query_path(tmp_path):
    """End-to-end: tear cold.json during the tiering step (torn action at
    the new tiering.registry fault site); the workload's own later reads
    must recover via sidecar rebuild and every checker invariant holds."""
    root = str(tmp_path / "w")
    spec = "seed=7;tiering.registry:torn(8):nth=1"
    p = sweep._run_workload(root, spec)
    assert p.returncode == 0, \
        (f"workload died under torn registry\nreproduce with:\n  "
         f"{sweep.repro_command(spec, root)}\n{p.stdout}\n{p.stderr}"[:3000])
    v = workload.verify(root)
    assert all(r.ok for r in v["results"]), \
        f"spec: {spec}\n" + "\n".join(f"{r.name}: {r.detail}"
                                      for r in v["results"] if not r.ok)


# ------------------------------------------------------------ full (slow)
@pytest.mark.slow
def test_full_sweep_covers_all_registered_points(tmp_path):
    rep = sweep.run_sweep(str(tmp_path))
    assert rep["coverage"]["uncovered"] == [], \
        (f"node-scope fault points the canonical workload never crossed: "
         f"{rep['coverage']['uncovered']} — extend chaos/workload.py")
    assert rep["runs"], "sweep executed no crash runs"
    assert not rep["failed"], "\n\n".join(_fail_msg(r)
                                          for r in rep["failed"])
