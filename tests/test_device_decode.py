"""Device-decode plane (ops/device_decode + codecs.split_for_device):
bit-identical parity against the host decoder across every codec, dtype
and null pattern (interpret mode on CPU), reason accounting for rejected
pages, and the end-to-end scan lane — engagements > 0 and batch
equivalence vs the legacy Python scan, plus device-resident column
attachment through the EagerUploader."""
import os

import numpy as np
import pytest

from cnosdb_tpu.models.codec import Encoding
from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.models.strcol import DictArray
from cnosdb_tpu.ops import device_decode
from cnosdb_tpu.storage import codecs
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage


# ---------------------------------------------------------------------------
# kernel parity: device lane output must be BIT-identical to codecs.decode
# ---------------------------------------------------------------------------
def _device_decode_block(block: bytes, vt: ValueType) -> np.ndarray:
    """Round one encoded block through the device lane (interpret=True)
    and return the decoded values, shaped like codecs.decode's output."""
    plan, reason = codecs.split_for_device(block, vt)
    assert plan is not None, f"split rejected: {reason}"
    n = plan["n"]
    lane = device_decode.DeviceDecodeLane(interpret=True)
    if vt in (ValueType.STRING, ValueType.GEOMETRY):
        got = {}

        def sink(dense, _plan=plan):
            got["vals"] = np.asarray(_plan["values"])[dense]

        lane.submit(plan, "tok", "c", vt, 0, n, None, None, None,
                    sink=sink)
        assert lane.run() == []
        return got["vals"]
    out_vals = np.zeros(n, dtype=vt.numpy_dtype())
    out_valid = np.zeros(n, dtype=bool)
    lane.submit(plan, "tok", "c", vt, 0, n, None, out_vals, out_valid)
    assert lane.run() == []
    assert out_valid.all()
    return out_vals


def _assert_bit_identical(dev: np.ndarray, host: np.ndarray):
    assert dev.dtype == host.dtype
    if dev.dtype == np.float64:
        # NaN payloads included: compare the raw bit patterns
        np.testing.assert_array_equal(dev.view(np.uint64),
                                      host.view(np.uint64))
    else:
        np.testing.assert_array_equal(dev, host)


_LENGTHS = [1, 2, 3, 127, 128, 129, 1000, 4096]


@pytest.mark.parametrize("n", _LENGTHS)
def test_delta_i64_parity(rng, n):
    vals = rng.integers(-(1 << 40), 1 << 40, n).cumsum()
    block = codecs.encode(vals, ValueType.INTEGER, Encoding.DELTA)
    host = codecs.decode(block, ValueType.INTEGER)
    _assert_bit_identical(_device_decode_block(block, ValueType.INTEGER),
                          host)


def test_delta_i64_extreme_values(rng):
    vals = np.array([np.iinfo(np.int64).min, -1, 0, 1,
                     np.iinfo(np.int64).max, 7, -(1 << 62)], np.int64)
    block = codecs.encode(vals, ValueType.INTEGER, Encoding.DELTA)
    host = codecs.decode(block, ValueType.INTEGER)
    _assert_bit_identical(_device_decode_block(block, ValueType.INTEGER),
                          host)


@pytest.mark.parametrize("n", _LENGTHS)
def test_delta_ts_const_stride_parity(rng, n):
    ts = int(rng.integers(0, 1 << 50)) \
        + np.arange(n, dtype=np.int64) * 30_000_000
    block = codecs.encode_timestamps(ts)
    host = codecs.decode_timestamps(block)
    _assert_bit_identical(_device_decode_block(block, ValueType.INTEGER),
                          host)


@pytest.mark.parametrize("n", _LENGTHS)
def test_unsigned_parity(rng, n):
    vals = rng.integers(0, np.iinfo(np.uint64).max, n, dtype=np.uint64)
    block = codecs.encode(vals, ValueType.UNSIGNED, Encoding.DELTA)
    host = codecs.decode(block, ValueType.UNSIGNED)
    _assert_bit_identical(_device_decode_block(block, ValueType.UNSIGNED),
                          host)


@pytest.mark.parametrize("n", _LENGTHS)
def test_gorilla_f64_parity(rng, n):
    vals = rng.normal(20.0, 5.0, n).round(3)
    block = codecs.encode(vals, ValueType.FLOAT, Encoding.GORILLA)
    host = codecs.decode(block, ValueType.FLOAT)
    _assert_bit_identical(_device_decode_block(block, ValueType.FLOAT),
                          host)


def test_gorilla_f64_special_values(rng):
    vals = np.array([0.0, -0.0, np.nan, np.inf, -np.inf, 5e-324,
                     np.finfo(np.float64).max, 1.0, 1.0, 1.0], np.float64)
    block = codecs.encode(vals, ValueType.FLOAT, Encoding.GORILLA)
    host = codecs.decode(block, ValueType.FLOAT)
    _assert_bit_identical(_device_decode_block(block, ValueType.FLOAT),
                          host)


@pytest.mark.parametrize("n", _LENGTHS)
def test_bitpack_bool_parity(rng, n):
    vals = rng.random(n) < 0.5
    block = codecs.encode(vals, ValueType.BOOLEAN, Encoding.BITPACK)
    host = codecs.decode(block, ValueType.BOOLEAN)
    _assert_bit_identical(_device_decode_block(block, ValueType.BOOLEAN),
                          host)


@pytest.mark.parametrize("n", [1, 127, 1000])
def test_dict_string_parity(rng, n):
    words = np.array(["", "ok", "wärn", "err", "crité"], dtype=object)
    vals = words[rng.integers(0, len(words), n)]
    block = codecs.encode(vals, ValueType.STRING)
    host = codecs.decode(block, ValueType.STRING).materialize()
    dev = _device_decode_block(block, ValueType.STRING)
    np.testing.assert_array_equal(dev, np.asarray(host, dtype=object))


def test_pallas_gorilla_path_parity(rng, monkeypatch):
    """CNOSDB_TPU_PALLAS=1 routes the gorilla XOR scan through the
    Pallas kernel (interpret on CPU) — still bit-identical, and it books
    a pallas engagement."""
    from cnosdb_tpu.ops import pallas_kernels

    monkeypatch.setenv("CNOSDB_TPU_PALLAS", "1")
    if not device_decode.PALLAS_AVAILABLE:
        pytest.skip("pallas import unavailable")
    vals = rng.normal(0.0, 100.0, 777)
    block = codecs.encode(vals, ValueType.FLOAT, Encoding.GORILLA)
    host = codecs.decode(block, ValueType.FLOAT)
    before = pallas_kernels.engagements()
    _assert_bit_identical(_device_decode_block(block, ValueType.FLOAT),
                          host)
    assert pallas_kernels.engagements() > before


# ---------------------------------------------------------------------------
# rejection accounting: split_for_device + the lane's outcome counters
# ---------------------------------------------------------------------------
def test_split_rejects_with_reasons(rng):
    ints = rng.integers(0, 100, 50)
    plan, reason = codecs.split_for_device(
        codecs.encode(ints, ValueType.INTEGER, Encoding.QUANTILE),
        ValueType.INTEGER)
    assert plan is None and reason == "encoding"
    plan, reason = codecs.split_for_device(
        codecs.encode(np.empty(0, np.int64), ValueType.INTEGER,
                      Encoding.DELTA), ValueType.INTEGER)
    assert plan is None and reason == "empty"
    plan, reason = codecs.split_for_device(b"", ValueType.INTEGER)
    assert plan is None and reason == "empty"
    plan, reason = codecs.split_for_device(
        codecs.encode(rng.normal(size=10), ValueType.FLOAT,
                      Encoding.QUANTILE), ValueType.FLOAT)
    assert plan is None and reason == "encoding"


def test_declined_pages_book_host_outcomes():
    before = device_decode.outcomes_snapshot().get(("host", "encoding"), 0)
    lane = device_decode.DeviceDecodeLane(interpret=True)
    assert not lane.accepts(int(ValueType.INTEGER), int(Encoding.QUANTILE))
    lane.declined("encoding", 3)
    snap = device_decode.outcomes_snapshot()
    assert snap[("host", "encoding")] == before + 3


def test_decoded_pages_book_device_outcomes(rng):
    before = device_decode.outcomes_snapshot().get(("device", "ok"), 0)
    eng_before = device_decode.engagements()
    block = codecs.encode(rng.integers(0, 9, 64), ValueType.INTEGER,
                          Encoding.DELTA)
    _device_decode_block(block, ValueType.INTEGER)
    assert device_decode.outcomes_snapshot()[("device", "ok")] > before
    assert device_decode.engagements() > eng_before


def test_set_counter_exports_counter_type_without_accumulating():
    """The /metrics export of externally-accumulated totals: counter
    TYPE (rate() works), assignment semantics (a re-scrape must not
    double-count the running sum the way incr would)."""
    from cnosdb_tpu.server.metrics import MetricsRegistry

    m = MetricsRegistry()
    m.set_counter("cnosdb_device_decode_total", 5,
                  lane="host", reason="encoding")
    m.set_counter("cnosdb_device_decode_total", 7,
                  lane="host", reason="encoding")
    text = m.prometheus_text()
    assert "# TYPE cnosdb_device_decode_total counter" in text
    assert 'cnosdb_device_decode_total{lane="host",reason="encoding"} 7' \
        in text


# ---------------------------------------------------------------------------
# end-to-end: the scan's third lane under CNOSDB_DEVICE_DECODE=1
# ---------------------------------------------------------------------------
def _schema():
    return {"m": TskvTableSchema.new_measurement(
        "t", "db", "m", tags=["host"],
        fields=[("f", ValueType.FLOAT), ("i", ValueType.INTEGER),
                ("b", ValueType.BOOLEAN), ("s", ValueType.STRING)])}


def _write(v, host, ts, **cols):
    types = {"f": ValueType.FLOAT, "i": ValueType.INTEGER,
             "b": ValueType.BOOLEAN, "s": ValueType.STRING,
             "u": ValueType.UNSIGNED}
    fields = {name: (int(types[name]),
                     [None if x is None
                      else (x.item() if isinstance(x, np.generic) else x)
                      for x in xs])
              for name, xs in cols.items() if xs is not None}
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(SeriesKey("m", {"host": host}),
                                  list(ts), fields))
    v.write(wb)


def _assert_batches_equal(a, b):
    assert a.n_rows == b.n_rows
    np.testing.assert_array_equal(a.series_ids, b.series_ids)
    np.testing.assert_array_equal(a.ts, b.ts)
    np.testing.assert_array_equal(a.sid_ordinal, b.sid_ordinal)
    assert set(a.fields) == set(b.fields)
    for name in a.fields:
        vt_a, vals_a, valid_a = a.fields[name]
        vt_b, vals_b, valid_b = b.fields[name]
        assert vt_a == vt_b
        np.testing.assert_array_equal(valid_a, valid_b)
        if isinstance(vals_a, DictArray) or isinstance(vals_b, DictArray):
            obj_a = np.asarray(vals_a.materialize()
                               if isinstance(vals_a, DictArray) else vals_a)
            obj_b = np.asarray(vals_b.materialize()
                               if isinstance(vals_b, DictArray) else vals_b)
            np.testing.assert_array_equal(obj_a[valid_a], obj_b[valid_b])
        else:
            np.testing.assert_array_equal(vals_a[valid_a], vals_b[valid_b])


def _device_scan(v, **kw):
    got = scan_vnode(v, "m",
                     decode_hook=lambda: device_decode.DeviceDecodeLane(
                         interpret=True), **kw)
    os.environ["CNOSDB_NO_NATIVE_SCAN"] = "1"
    try:
        want = scan_vnode(v, "m", **kw)
    finally:
        del os.environ["CNOSDB_NO_NATIVE_SCAN"]
    return got, want


def test_scan_device_lane_equivalence(tmp_engine_dir, rng):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    n = 1200
    _write(v, "h1", range(n), f=rng.normal(size=n),
           i=rng.integers(-50, 50, n), b=rng.integers(0, 2, n) > 0,
           s=[f"v{x}" for x in rng.integers(0, 5, n)])
    _write(v, "h2", range(500, 900), f=rng.normal(size=400))
    v.flush()
    before = device_decode.engagements()
    got, want = _device_scan(v)
    assert device_decode.engagements() > before, \
        "scan did not engage the device-decode lane"
    _assert_batches_equal(got, want)
    v.close()


def test_scan_device_lane_with_nulls(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    n = 500
    _write(v, "h1", range(n),
           f=[float(x) if x % 2 == 0 else None for x in range(n)],
           i=[int(x) if x % 3 == 0 else None for x in range(n)],
           s=[f"s{x}" if x % 5 == 0 else None for x in range(n)])
    v.flush()
    got, want = _device_scan(v)
    _assert_batches_equal(got, want)
    vt, vals, valid = got.fields["f"]
    assert valid.sum() == (n + 1) // 2
    v.close()


def test_scan_device_lane_multi_flush_and_trim(tmp_engine_dir, rng):
    from cnosdb_tpu.models.predicate import TimeRange, TimeRanges

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    for base in (0, 1000, 2000):
        _write(v, "h1", range(base, base + 500),
               f=np.arange(base, base + 500) * 0.5,
               i=rng.integers(0, 99, 500))
        v.flush()
    got, want = _device_scan(
        v, time_ranges=TimeRanges([TimeRange(250, 2200)]))
    _assert_batches_equal(got, want)
    v.close()


def test_scan_device_lane_attaches_device_columns(tmp_engine_dir, rng):
    """Null-free columns fully decoded on device attach to the batch as
    `_preuploaded` device arrays through EagerUploader.put_device — and
    the staged values match the host arrays exactly."""
    from cnosdb_tpu.ops.device_cache import EagerUploader

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    n = 800
    f = rng.normal(size=n)
    i = rng.integers(-1000, 1000, n)
    _write(v, "h1", range(n), f=f, i=i)
    v.flush()
    got = scan_vnode(
        v, "m", upload_hook=EagerUploader,
        decode_hook=lambda: device_decode.DeviceDecodeLane(interpret=True))
    pre = getattr(got, "_preuploaded", None)
    assert pre is not None, "no columns were staged on device"
    n_pad, cols = pre
    for name, host_vals in (("f", f), ("i", i)):
        assert name in cols, f"column {name} not device-resident"
        vt, dev_vals, dev_valid, all_valid = cols[name]
        assert all_valid and dev_valid is None
        np.testing.assert_array_equal(
            np.asarray(dev_vals)[:n].astype(host_vals.dtype), host_vals)
    v.close()
