"""Replicated-write integration: coordinator → raft group → all replicas."""
import time

import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import DatabaseOptions, DatabaseSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore, DEFAULT_TENANT
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.storage.scan import scan_vnode


@pytest.fixture
def cluster(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "rdb", DatabaseOptions(replica=3)))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    yield meta, engine, coord
    coord.close()


def _write(coord, host, ts_list, vals):
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": host}), list(ts_list),
        {"usage": (int(ValueType.FLOAT), list(vals))}))
    coord.write_points(DEFAULT_TENANT, "rdb", wb)


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_replicated_write_reaches_all_vnodes(cluster):
    meta, engine, coord = cluster
    _write(coord, "h1", [1, 2, 3], [1.0, 2.0, 3.0])
    buckets = meta.buckets_for(DEFAULT_TENANT, "rdb")
    rs = buckets[0].shard_group[0]
    assert len(rs.vnodes) == 3
    owner = f"{DEFAULT_TENANT}.rdb"

    def all_have():
        for v in rs.vnodes:
            vn = engine.vnode(owner, v.id)
            if vn is None or scan_vnode(vn, "cpu").n_rows != 3:
                return False
        return True

    assert _wait(all_have), "write did not replicate to all 3 vnodes"
    # scans read from the leader replica
    batches = coord.scan_table(DEFAULT_TENANT, "rdb", "cpu")
    assert sum(b.n_rows for b in batches) == 3


def test_write_survives_leader_crash(cluster):
    meta, engine, coord = cluster
    _write(coord, "h1", [1], [1.0])
    rs = meta.buckets_for(DEFAULT_TENANT, "rdb")[0].shard_group[0]
    owner = f"{DEFAULT_TENANT}.rdb"
    nodes = coord.replica_manager().get_or_build(owner, rs)
    leader = next(n for n in nodes.values() if n.is_leader())
    leader.crash()
    # writes keep working through the new leader
    _write(coord, "h1", [2], [2.0])
    survivors = [v.id for v in rs.vnodes if v.id != leader.node_id]

    def replicated():
        return all(
            scan_vnode(engine.vnode(owner, vid), "cpu").n_rows == 2
            for vid in survivors)

    assert _wait(replicated)
    # crashed node catches up after restart
    leader.restart()
    assert _wait(lambda: scan_vnode(
        engine.vnode(owner, leader.node_id), "cpu").n_rows == 2)


def test_replicated_vnode_recovers_from_wal(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "rdb", DatabaseOptions(replica=3)))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    _write(coord, "h1", [1, 2], [1.0, 2.0])
    rs = meta.buckets_for(DEFAULT_TENANT, "rdb")[0].shard_group[0]
    owner = f"{DEFAULT_TENANT}.rdb"
    nodes = coord.replica_manager().get_or_build(owner, rs)
    assert _wait(lambda: all(
        scan_vnode(engine.vnode(owner, v.id), "cpu").n_rows == 2
        for v in rs.vnodes))
    coord.close()
    # reopen: data recovered from WAL (idempotent re-apply)
    engine2 = TsKv(str(tmp_path / "data"))
    coord2 = Coordinator(meta, engine2)
    batches = coord2.scan_table(DEFAULT_TENANT, "rdb", "cpu")
    assert sum(b.n_rows for b in batches) == 2
    engine2.close()


def test_replica_checksums_agree(tmp_path):
    """All replicas of a raft group converge to one content checksum even
    with different flush states (reference ChecksumGroup check.rs:99)."""
    import time

    import numpy as np

    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor, Session
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    s = Session()
    ex.execute_one("CREATE DATABASE rdb WITH SHARD 1 REPLICA 3", s)
    s2 = Session(database="rdb")
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))", s2)
    vals = ", ".join(f"({i}, 'h{i % 4}', {i}.5)" for i in range(200))
    ex.execute_one(f"INSERT INTO m (time, h, v) VALUES {vals}", s2)
    # flush ONE replica only: physical divergence, logical equality
    rs_id = meta.buckets["cnosdb.rdb"][0].shard_group[0].id
    first_vnode = meta.buckets["cnosdb.rdb"][0].shard_group[0].vnodes[0]
    engine.vnode("cnosdb.rdb", first_vnode.id).flush()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rows = coord.checksum_group(rs_id)
        sums = {r[2] for r in rows}
        if len(sums) == 1 and "" not in sums:
            break
        time.sleep(0.1)
    assert len(sums) == 1 and "" not in sums, rows
    rs = ex.execute_one(f"CHECKSUM GROUP {rs_id}", s)
    assert len(set(rs.columns[2].tolist())) == 1
    coord.close()


def test_file_level_snapshot_catchup(tmp_path):
    """A lagging replica whose log was purged catches up via the FILE-level
    snapshot (reference VnodeSnapshot + DownloadFile): installed state is
    byte-identical — same content checksum as the leader."""
    import time

    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore, DEFAULT_TENANT
    from cnosdb_tpu.sql.executor import QueryExecutor, Session
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE DATABASE fs WITH SHARD 1 REPLICA 3", Session())
    s = Session(database="fs")
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))", s)
    vals = ", ".join(f"({i}, 'h{i % 3}', {i}.5)" for i in range(100))
    ex.execute_one(f"INSERT INTO m (time, h, v) VALUES {vals}", s)

    owner = f"{DEFAULT_TENANT}.fs"
    rs = meta.buckets[owner][0].shard_group[0]
    mgr = coord.replica_manager()
    nodes = mgr.get_or_build(owner, rs)
    leader = next(n for n in nodes.values() if n.is_leader())
    lagger = next(n for n in nodes.values() if not n.is_leader())
    lagger.crash()
    # more writes + flush the leader vnode → data lives in TSM files,
    # then purge the log so catch-up MUST go through a snapshot
    vals = ", ".join(f"({100 + i}, 'h{i % 3}', {i}.25)" for i in range(100))
    ex.execute_one(f"INSERT INTO m (time, h, v) VALUES {vals}", s)
    leader_vnode = engine.vnode(owner, leader.node_id)
    leader_vnode.flush()
    leader.log.purge_to(leader.commit_index + 1)
    lagger.restart()
    lag_vnode = engine.vnode(owner, lagger.node_id)
    want = leader_vnode.checksum()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if lag_vnode.checksum() == want:
            break
        time.sleep(0.2)
    assert lag_vnode.checksum() == want
    # and the files really are there: scan answers without the leader
    from cnosdb_tpu.storage.scan import scan_vnode

    assert scan_vnode(lag_vnode, "m").n_rows == 200
    coord.close()


def test_replica_add_on_live_group_then_member_loss(cluster):
    """Raft membership change (VERDICT r2 #4): REPLICA ADD on a live
    replicated group seeds a 4th member through the raft config change +
    log/snapshot catch-up; killing an original member afterwards leaves
    writes and reads correct on the grown quorum."""
    from cnosdb_tpu.models.meta_data import VnodeStatus

    meta, engine, coord = cluster
    _write(coord, "h1", [1, 2, 3], [1.0, 2.0, 3.0])
    rs = meta.buckets_for(DEFAULT_TENANT, "rdb")[0].shard_group[0]
    owner = f"{DEFAULT_TENANT}.rdb"
    orig_ids = [v.id for v in rs.vnodes]

    new_id = coord.copy_vnode_to_set(rs.id, meta.node_id)
    rs2 = meta.find_replica_set(rs.id)[1]
    assert sorted(v.id for v in rs2.vnodes) == sorted(orig_ids + [new_id])
    assert meta.find_vnode(new_id)[3].status == VnodeStatus.RUNNING

    def new_member_has_data():
        vn = engine.vnode(owner, new_id)
        return vn is not None and scan_vnode(vn, "cpu").n_rows == 3

    assert _wait(new_member_has_data), "new member did not catch up"

    # kill an ORIGINAL member: 3 of 4 remain, quorum still holds
    mgr = coord.replica_manager()
    nodes = mgr.get_or_build(owner, rs2)
    nodes[orig_ids[0]].crash()
    _write(coord, "h1", [4], [4.0])

    def read_all():
        batches = coord.scan_table(DEFAULT_TENANT, "rdb", "cpu")
        return sum(b.n_rows for b in batches) == 4

    assert _wait(read_all, timeout=10.0), "reads wrong after member loss"


def test_replica_remove_shrinks_live_group(cluster):
    """REPLICA REMOVE on a replicated set commits a config shrink through
    the leader (stepdown first when removing the leader member itself);
    the smaller group keeps accepting writes."""
    meta, engine, coord = cluster
    _write(coord, "h2", [1, 2], [1.0, 2.0])
    rs = meta.buckets_for(DEFAULT_TENANT, "rdb")[0].shard_group[0]
    owner = f"{DEFAULT_TENANT}.rdb"
    mgr = coord.replica_manager()
    nodes = mgr.get_or_build(owner, rs)
    # remove the CURRENT raft leader: exercises stepdown + retry-on-new-leader
    leader_vid = next(vid for vid, n in nodes.items() if n.is_leader())
    coord.drop_replica(leader_vid)
    rs2 = meta.find_replica_set(rs.id)[1]
    assert len(rs2.vnodes) == 2 and leader_vid not in {v.id for v in rs2.vnodes}
    _write(coord, "h2", [3], [3.0])

    def two_members_have_all():
        for v in rs2.vnodes:
            vn = engine.vnode(owner, v.id)
            if vn is None or scan_vnode(vn, "cpu").n_rows != 3:
                return False
        return True

    assert _wait(two_members_have_all, timeout=10.0)
