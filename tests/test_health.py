"""Gray-failure tolerance plane (parallel/health.py + the hedged scan
lane in parallel/coordinator.py).

Unit half: scorer classification/ranking/decay, the adaptive hedge
trigger, censored observations, slow-start and limiter mechanics,
counter shapes. Integration half (chaos/straggler.py bed — real wire,
real engine, NULL/NaN/delta-merge data): hedges fire against a
straggling primary and the winner is bit-identical to the healthy
answer, losers are cancelled by their OWN hedge qid, deadline budget
suppresses hedging instead of overrunning, CNOSDB_HEDGE=0 restores the
legacy path byte-for-byte, and a healthy bed fires zero hedges.
"""
import time

import pytest

from cnosdb_tpu.chaos import nemesis
from cnosdb_tpu.chaos.straggler import StragglerBed, batch_bytes
from cnosdb_tpu.parallel import health


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    monkeypatch.delenv("CNOSDB_HEDGE", raising=False)
    health.SCORER.reset()
    health.reset_counters()
    yield
    health.SCORER.reset()
    health.reset_counters()


def _feed(s, addr, n, elapsed=0.002, outcome=health.OK, burn=0.01):
    for _ in range(n):
        s.observe(addr, "scan_vnode", elapsed, outcome, burn=burn)


# ------------------------------------------------------------ scorer units
def test_classification_healthy_degraded_broken():
    s = health.HealthScorer(seed=1)
    _feed(s, "ok:1", 20)
    assert s.state("ok:1") == health.HEALTHY
    _feed(s, "burn:1", 20, burn=0.95)
    assert s.state("burn:1") == health.DEGRADED
    _feed(s, "down:1", 20, outcome=health.UNREACHABLE)
    assert s.state("down:1") == health.BROKEN
    # a deadline-burned completion counts as full budget burn
    for _ in range(20):
        s.observe("dl:1", "scan_vnode", 1.0, health.DEADLINE, burn=0.2)
    assert s.state("dl:1") == health.DEGRADED


def test_rank_orders_local_then_healthy_then_degraded_then_broken():
    s = health.HealthScorer(seed=1)
    _feed(s, "h:1", 20)
    _feed(s, "d:1", 20, burn=0.95)
    _feed(s, "b:1", 20, outcome=health.UNREACHABLE)
    addr = {"L": None, "H": "h:1", "D": "d:1", "B": "b:1"}
    ranked = s.rank(["B", "D", "H", "L"], addr.__getitem__)
    assert ranked == ["L", "H", "D", "B"]


def test_rank_prefers_better_scored_healthy_replica():
    s = health.HealthScorer(seed=1)
    _feed(s, "fast:1", 30, elapsed=0.001)
    _feed(s, "slow:1", 30, elapsed=0.2)   # slow but healthy (no errors)
    firsts = {s.rank(["A", "B"],
                     {"A": "slow:1", "B": "fast:1"}.get)[0]
              for _ in range(40)}
    # far from a near-tie: exploration never re-probes the slow one
    assert firsts == {"B"}


def test_p2c_near_tie_exploration_samples_both_orders():
    s = health.HealthScorer(seed=1)
    _feed(s, "a:1", 30, elapsed=0.0020)
    _feed(s, "b:1", 30, elapsed=0.0021)   # near-tie
    firsts = {s.rank(["A", "B"],
                     {"A": "a:1", "B": "b:1"}.get)[0]
              for _ in range(400)}
    assert firsts == {"A", "B"}


def test_hedge_delay_floor_and_adaptive_p95():
    s = health.HealthScorer(seed=1)
    assert s.hedge_delay("never:1", "scan", floor_s=0.025) == 0.025
    _feed(s, "warm:1", 50, elapsed=0.004)
    hd = s.hedge_delay("warm:1", "scan", floor_s=0.0001)
    assert 0.003 < hd < 0.02          # tracks the p95, not the floor
    assert s.hedge_delay("warm:1", "scan", floor_s=0.5) == 0.5


def test_censored_observation_only_raises_the_ewma():
    s = health.HealthScorer(seed=1)
    _feed(s, "n:1", 10)
    base = s.score("n:1")
    s.observe_censored("n:1", "scan", 0.5)     # lost a hedge race
    marked = s.score("n:1")
    assert marked > base + 0.2
    s.observe_censored("n:1", "scan", 0.0001)  # lower bound below ewma
    assert s.score("n:1") == marked            # never lowers


def test_idle_decay_forgives_errors_and_latency():
    s = health.HealthScorer(seed=1)
    _feed(s, "n:1", 20, elapsed=0.3, outcome=health.UNREACHABLE)
    assert s.state("n:1") == health.BROKEN
    # rewind last_seen: several half-lives of idleness
    with s._lock:
        s._nodes["n:1"].last_seen -= 10 * health._DECAY_HALF_LIFE
    assert s.state("n:1") == health.HEALTHY
    assert s.score("n:1") < 0.01


def test_slow_start_sequence_is_deterministic_and_completes():
    ss = health.SlowStart()
    ss.RAMP_S = 1e9                   # hold the ramp at RAMP_MIN
    ss.begin("n1")
    assert [ss.admit("n1") for _ in range(6)] == \
        [True, False, False, False, True, False]
    ss.RAMP_S = 1e-9                  # ramp instantly complete
    assert ss.admit("n1") is True
    assert "n1" not in ss.ramping()   # cleared once fully admitted
    assert ss.admit("n2") is True     # never-ramping nodes always admit


def test_hedge_limiter_caps_and_releases():
    lim = health.HedgeLimiter(max_inflight=2)
    assert lim.try_acquire() and lim.try_acquire()
    assert not lim.try_acquire()
    lim.release()
    assert lim.inflight() == 1
    assert not lim.try_acquire(limit=1)   # per-call override
    assert lim.try_acquire(limit=8)


def test_counters_snapshot_shapes():
    health.count_hedge("fired")
    health.count_hedge("suppressed", "limiter", n=3)
    health.count_breaker(7, "open")
    hedge, breaker = health.counters_snapshot()
    assert hedge[("fired", "")] == 1
    assert hedge[("suppressed", "limiter")] == 3
    assert breaker[("7", "open")] == 1
    health.reset_counters()
    assert health.counters_snapshot() == ({}, {})


def test_nemesis_slow_replica_spec():
    assert "slow_replica" in nemesis.KINDS
    ev = nemesis.NemesisEvent(step=0, kind="slow_replica", node=1, param=50)
    victim, peers = nemesis.event_specs(ev, "10.0.0.1:9", seed=3)
    assert "rpc.server:delay(50)" in victim
    assert peers == ""                # gray failure: peers stay clean


# --------------------------------------------------------- straggler bed
@pytest.fixture(scope="module")
def bed(tmp_path_factory):
    b = StragglerBed(str(tmp_path_factory.mktemp("sgbed")), rows=800)
    yield b
    b.close()


@pytest.fixture(autouse=True)
def _clean_bed_state(request):
    yield
    if "bed" in request.fixturenames:
        b = request.getfixturevalue("bed")
        for r in b.replicas:
            r.delay_s = 0.0
            r.cancels.clear()


def test_hedge_wins_bit_identical_and_cancels_loser_by_hedge_qid(bed):
    ref = batch_bytes(bed.scan_once(qid="ref"))
    assert ref                        # the bed data really scans
    # the split pins replicas[0] as primary (leader slot — health never
    # re-routes it), so delaying it forces the hedge to rescue the scan;
    # warm the other replica so its sketch prices the trigger honestly
    health.SCORER.reset()
    _feed(health.SCORER, bed.replicas[1].addr, 5)
    bed.replicas[0].delay_s = 0.4
    health.reset_counters()
    t0 = time.perf_counter()
    got = batch_bytes(bed.scan_once(qid="q-hedge", timeout_s=10.0))
    elapsed = time.perf_counter() - t0
    assert got == ref                 # NULL/NaN/delta-merge parity
    assert elapsed < 0.35             # rescued well before the straggler
    hedge, _ = health.counters_snapshot()
    assert hedge.get(("fired", ""), 0) >= 1
    assert hedge.get(("won", ""), 0) >= 1
    assert hedge.get(("cancelled", ""), 0) >= 1
    # the loser was cancelled through the remote fan-out, addressed by
    # its own CHILD hedge qid — never the parent query's
    deadline = time.monotonic() + 2.0
    while not bed.replicas[0].cancels and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bed.replicas[0].cancels
    assert all("#h" in q for q in bed.replicas[0].cancels)
    assert "q-hedge" not in bed.replicas[0].cancels


def test_hedge_loss_marks_straggler_and_routing_steers_around(bed):
    health.SCORER.reset()
    _feed(health.SCORER, bed.replicas[1].addr, 5)
    bed.replicas[0].delay_s = 0.4
    bed.scan_once(qid="mark")         # rescue books a censored sample
    fast_first = health.SCORER.rank(
        ["A", "B"], {"A": bed.replicas[0].addr,
                     "B": bed.replicas[1].addr}.get)
    assert fast_first[0] == "B"       # straggler no longer preferred
    t0 = time.perf_counter()
    bed.scan_once(qid="steered")
    assert time.perf_counter() - t0 < 0.2


def test_no_budget_suppresses_hedge_instead_of_overrunning(bed):
    health.SCORER.reset()
    _feed(health.SCORER, bed.replicas[1].addr, 5)
    bed.replicas[0].delay_s = 0.4
    for r in bed.replicas:
        r.cancels.clear()
    health.reset_counters()
    t0 = time.perf_counter()
    with pytest.raises(Exception):
        # budget below the hedge floor: the lane must not launch a
        # second attempt it cannot pay for
        bed.scan_once(qid="tight", timeout_s=0.06)
    assert time.perf_counter() - t0 < 2.0
    hedge, _ = health.counters_snapshot()
    assert hedge.get(("fired", ""), 0) == 0
    assert hedge.get(("suppressed", "no_budget"), 0) >= 1


def test_healthy_bed_fires_zero_hedges(bed):
    time.sleep(0.6)   # drain in-flight straggler handlers of prior tests
    health.SCORER.reset()
    ref = batch_bytes(bed.scan_once(qid="warm"))
    health.reset_counters()
    for i in range(10):
        assert batch_bytes(bed.scan_once(qid=f"calm-{i}")) == ref
    hedge, _ = health.counters_snapshot()
    assert hedge.get(("fired", ""), 0) == 0


def test_hedge_disabled_restores_legacy_path_byte_for_byte(bed, monkeypatch):
    ref = batch_bytes(bed.scan_once(qid="ref2"))
    monkeypatch.setenv("CNOSDB_HEDGE", "0")
    health.SCORER.reset()
    health.reset_counters()
    assert batch_bytes(bed.scan_once(qid="legacy")) == ref
    # legacy path: no hedge accounting, no health-ranked routing
    assert health.counters_snapshot() == ({}, {})
    # and it still fails over past a straggler-turned-dead replica:
    # stop the first replica entirely, scan must answer via the second
    bed.replicas[0].server.stop()
    try:
        assert batch_bytes(bed.scan_once(qid="legacy-fo")) == ref
    finally:
        # restart a server for the same node id so later tests (module
        # fixture) keep two live replicas
        from cnosdb_tpu.chaos.straggler import ReplicaServer
        nid = bed.replicas[0].node_id
        bed.replicas[0] = ReplicaServer(bed, nid)
        bed.meta.register_node(nid, grpc_addr=bed.replicas[0].addr)
