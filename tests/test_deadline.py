"""Request-lifecycle plane: deadlines, cancellation, admission control.

Fast (no-cluster) coverage for utils/deadline.py, the shared-pool
deadline propagation in utils/executor.py, the admission gate, the RPC
deadline envelope in parallel/net.py, and the HTTP status mapping
(429 limiter vs 503 admission vs 504 deadline, each with Retry-After
where retryable). Cluster-level acceptance lives in
test_deadline_cluster.py.
"""
import threading
import time

import pytest

from cnosdb_tpu.config import Config
from cnosdb_tpu.errors import (
    AdmissionRejected, DeadlineExceeded, LimiterError, QueryError,
)
from cnosdb_tpu.server.admission import AdmissionGate
from cnosdb_tpu.utils import deadline as deadline_mod
from cnosdb_tpu.utils import executor as pool_mod
from cnosdb_tpu.utils.deadline import CANCELS, Deadline


# ------------------------------------------------------------ Deadline unit
def test_deadline_basics():
    dl = Deadline(10.0, qid="7")
    assert not dl.expired() and not dl.dead()
    assert 9.0 < dl.remaining() <= 10.0
    dl.check()  # healthy: no raise

    dl2 = Deadline(None)
    assert dl2.remaining() is None and not dl2.expired()
    dl2.check()

    expired = Deadline(-0.01)
    assert expired.expired() and expired.dead()
    with pytest.raises(DeadlineExceeded):
        expired.check()


def test_deadline_cancel_wins_over_time():
    dl = Deadline(60.0, qid="9")
    dl.cancel("killed")
    assert dl.dead() and not dl.expired()
    with pytest.raises(QueryError, match="cancelled"):
        dl.check()
    # first reason sticks
    dl.cancel("other")
    assert dl.cancel_reason == "killed"


def test_deadline_cap():
    dl = Deadline(0.5)
    assert dl.cap(10.0) <= 0.5
    assert dl.cap(0.1) == pytest.approx(0.1, abs=0.01)
    # floor: a nearly-dead request still gets a usable socket timeout
    floor = Deadline(10.0)
    floor.expires_at = time.monotonic() + 0.01
    assert floor.cap(10.0) == pytest.approx(0.05, abs=0.02)
    with pytest.raises(DeadlineExceeded):
        Deadline(-1.0).cap(10.0)
    assert Deadline(None).cap(3.0) == 3.0


def test_wire_roundtrip():
    dl = Deadline(5.0, qid="42")
    wire = dl.to_wire_ms()
    back = deadline_mod.from_wire(wire, qid="42")
    assert back.qid == "42"
    assert abs(back.remaining() - dl.remaining()) < 0.25
    unbounded = deadline_mod.from_wire(None, qid="x")
    assert unbounded.remaining() is None


def test_scope_install_and_clear():
    assert deadline_mod.current() is None
    dl = Deadline(5.0)
    with deadline_mod.scope(dl):
        assert deadline_mod.current() is dl
        with deadline_mod.scope(None):  # cancel fan-out idiom
            assert deadline_mod.current() is None
        assert deadline_mod.current() is dl
    assert deadline_mod.current() is None


def test_check_and_cap_current_without_scope():
    deadline_mod.check_current()          # no scope: no-op
    assert deadline_mod.cap_current(7.0) == 7.0
    with deadline_mod.scope(Deadline(0.5)):
        assert deadline_mod.cap_current(7.0) <= 0.5
        with pytest.raises(DeadlineExceeded):
            with deadline_mod.scope(Deadline(-1.0)):
                deadline_mod.check_current()


# ------------------------------------------------- shared pools propagation
def test_pool_propagates_deadline_scope():
    dl = Deadline(30.0, qid="p1")
    with deadline_mod.scope(dl):
        f = pool_mod.submit("scan", deadline_mod.current)
    assert f.result(timeout=5) is dl
    # and the worker restores its own state afterwards
    f2 = pool_mod.submit("scan", deadline_mod.current)
    assert f2.result(timeout=5) is None


def test_pool_sheds_task_for_dead_request():
    shed_before = deadline_mod.counters_snapshot()["tasks_shed"]
    dl = Deadline(30.0)
    dl.cancel("killed")
    ran = []
    with deadline_mod.scope(dl):
        f = pool_mod.submit("decode", lambda: ran.append(1))
    with pytest.raises(QueryError, match="cancelled"):
        f.result(timeout=5)
    assert not ran  # shed BEFORE running
    assert deadline_mod.counters_snapshot()["tasks_shed"] == shed_before + 1


def test_run_all_unblocks_promptly_on_cancel():
    dl = Deadline(30.0, qid="p2")
    release = threading.Event()

    def slow(_):
        release.wait(10.0)
        return 1

    def killer():
        time.sleep(0.2)
        dl.cancel("killed")

    threading.Thread(target=killer, daemon=True).start()
    t0 = time.monotonic()
    with deadline_mod.scope(dl):
        with pytest.raises(QueryError, match="cancelled"):
            pool_mod.run_all("scan", slow, [1, 2])
    elapsed = time.monotonic() - t0
    release.set()  # free the workers
    assert elapsed < 2.0, f"run_all held the caller {elapsed:.2f}s past kill"


def test_run_all_without_deadline_plain_results():
    assert pool_mod.run_all("scan", lambda x: x * 2, [1, 2, 3]) == [2, 4, 6]


# --------------------------------------------------------- admission gate
def test_gate_admits_within_capacity():
    g = AdmissionGate(max_concurrent=2, max_queued=2)
    assert g.acquire(None) == 0.0
    assert g.acquire(None) == 0.0
    s = g.stats()
    assert s["running"] == 2 and s["admitted_total"] == 2
    g.release(), g.release()
    assert g.stats()["running"] == 0


def test_gate_sheds_when_queue_full():
    g = AdmissionGate(max_concurrent=1, max_queued=0)
    g.acquire(None)
    with pytest.raises(AdmissionRejected) as ei:
        g.acquire(None)
    assert ei.value.retry_after >= 1.0
    assert g.stats()["shed_total"] == 1
    g.release()


def test_gate_queued_request_shed_on_deadline_expiry():
    g = AdmissionGate(max_concurrent=1, max_queued=4)
    g.acquire(None)  # occupy the only slot
    t0 = time.monotonic()
    with pytest.raises(AdmissionRejected, match="shed while queued"):
        g.acquire(Deadline(0.3))
    elapsed = time.monotonic() - t0
    assert elapsed < 1.5, "queued waiter should shed at its own deadline"
    s = g.stats()
    assert s["shed_total"] == 1 and s["queued"] == 0
    g.release()


def test_gate_queued_request_admitted_after_release():
    g = AdmissionGate(max_concurrent=1, max_queued=4)
    g.acquire(None)
    got = []

    def waiter():
        got.append(g.acquire(Deadline(10.0)))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.15)
    assert g.stats()["queued"] == 1
    g.release()
    t.join(timeout=5)
    assert len(got) == 1 and got[0] >= 0.0  # waited, then admitted
    s = g.stats()
    assert s["admitted_total"] == 2 and s["queue_wait_ms_max"] > 0.0
    g.release()


# ---------------------------------------------------- config knob satellite
def test_query_timeout_knobs_default_and_roundtrip(tmp_path):
    c = Config()
    assert c.query.read_timeout_ms == 30_000
    assert c.query.write_timeout_ms == 10_000
    assert c.query.max_concurrent_queries == 64
    assert c.query.max_queued_queries == 128
    text = c.to_toml()
    for knob in ("read_timeout_ms", "write_timeout_ms",
                 "max_concurrent_queries", "max_queued_queries"):
        assert knob in text
    p = tmp_path / "c.toml"
    p.write_text("[query]\nread_timeout_ms = 1234\n"
                 "max_concurrent_queries = 3\n")
    c2 = Config.load(str(p))
    assert c2.query.read_timeout_ms == 1234
    assert c2.query.max_concurrent_queries == 3
    c3 = Config.load(str(p), env={"CNOSDB_QUERY_WRITE_TIMEOUT_MS": "777"})
    assert c3.query.write_timeout_ms == 777


# ------------------------------------------------------------ RPC envelope
@pytest.fixture
def rpc_server():
    from cnosdb_tpu.parallel.net import RpcServer

    calls = []

    def slow(p):
        time.sleep(float(p.get("sleep", 1.5)))
        return {"ok": True}

    def spin(p):
        # cooperative loop: runs until its installed deadline is cancelled
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            deadline_mod.check_current()
            time.sleep(0.02)
        return {"ok": True, "timed_out": True}

    def echo(p):
        calls.append(p)
        return {"ok": True}

    srv = RpcServer("127.0.0.1", 0, {"slow": slow, "spin": spin,
                                     "echo": echo}).start()
    srv.test_calls = calls
    yield srv
    srv.stop()


def test_rpc_timeout_capped_by_deadline(rpc_server):
    from cnosdb_tpu.parallel.net import RpcUnavailable, rpc_call

    t0 = time.monotonic()
    with deadline_mod.scope(Deadline(0.4)):
        with pytest.raises((RpcUnavailable, DeadlineExceeded)):
            rpc_call(rpc_server.addr, "slow", {"sleep": 5.0}, timeout=10.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.5, (
        f"hop took {elapsed:.2f}s — socket timeout was not capped to the "
        f"request's remaining budget")


def test_rpc_refuses_to_send_when_dead(rpc_server):
    from cnosdb_tpu.parallel.net import rpc_call

    with deadline_mod.scope(Deadline(-1.0)):
        with pytest.raises(DeadlineExceeded):
            rpc_call(rpc_server.addr, "echo", {}, timeout=5.0)
    assert not rpc_server.test_calls  # never reached the wire


def test_rpc_server_rejects_expired_work_on_dequeue(rpc_server):
    from cnosdb_tpu.parallel.net import rpc_call

    before = deadline_mod.counters_snapshot()["expired_rejected"]
    past = int((time.time() - 5.0) * 1000)
    with pytest.raises(DeadlineExceeded, match="expired before dispatch"):
        rpc_call(rpc_server.addr, "echo",
                 {"_deadline_ms": past, "_qid": "qx"}, timeout=5.0)
    assert not rpc_server.test_calls  # handler never dispatched
    assert deadline_mod.counters_snapshot()["expired_rejected"] == before + 1


def test_rpc_deadline_envelope_stripped_before_handler(rpc_server):
    from cnosdb_tpu.parallel.net import rpc_call

    with deadline_mod.scope(Deadline(5.0, qid="q-env")):
        rpc_call(rpc_server.addr, "echo", {"a": 1}, timeout=5.0)
    assert rpc_server.test_calls == [{"a": 1}]  # _deadline_ms/_qid popped


def test_cancel_registry_flips_inflight_handler(rpc_server):
    from cnosdb_tpu.parallel.net import rpc_call

    qid = "q-cancel-1"
    err, t0 = [], time.monotonic()

    def call():
        with deadline_mod.scope(Deadline(20.0, qid=qid)):
            try:
                rpc_call(rpc_server.addr, "spin", {}, timeout=20.0)
            except Exception as e:  # noqa: BLE001 - recording for assert
                err.append(e)

    th = threading.Thread(target=call, daemon=True)
    th.start()
    # wait for the handler to register under the qid, then cancel it
    for _ in range(100):
        if CANCELS._working.get(qid):
            break
        time.sleep(0.02)
    else:
        pytest.fail("handler never registered in CANCELS")
    assert CANCELS.cancel(qid) >= 1
    th.join(timeout=5)
    elapsed = time.monotonic() - t0
    assert err and elapsed < 3.0, "cancel did not end the in-flight handler"
    # tombstone: later work for the same qid is rejected on dequeue
    with deadline_mod.scope(Deadline(5.0, qid=qid)):
        with pytest.raises(DeadlineExceeded):
            rpc_call(rpc_server.addr, "echo", {}, timeout=5.0)


# --------------------------------------------------- HTTP status mapping
class _Harness:
    """Real aiohttp server in a thread; urllib client returning headers."""

    def __init__(self, data_dir: str):
        import asyncio
        import socket

        from cnosdb_tpu.server.http import build_server

        self.server = build_server(data_dir)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()

        def run():
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._runner = await self.server.start("127.0.0.1", self.port)
                self._started.set()

            self._loop.create_task(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        assert self._started.wait(10)

    def request(self, method, path, data=None, headers=None):
        """→ (status, body, response-headers dict)."""
        import urllib.error
        import urllib.request

        url = f"http://127.0.0.1:{self.port}{path}"
        req = urllib.request.Request(
            url, data=data.encode() if data is not None else None,
            headers=headers or {}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read().decode(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode(), dict(e.headers)

    def close(self):
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        self.server.coord.close()


@pytest.fixture
def http(tmp_path):
    h = _Harness(str(tmp_path / "srv"))
    yield h
    h.close()


def _seed_rows(h, n=20):
    lines = "\n".join(
        f"cpu,host=h{i % 4} usage={i}.5 {1672531200000000000 + i * 10**9}"
        for i in range(n))
    status, body, _ = h.request("POST", "/api/v1/write?db=public", lines)
    assert status == 200, body


def test_http_limiter_429_vs_admission_503(http):
    """Satellite: the two shed classes stay distinct, both retryable."""
    _seed_rows(http)

    def over_budget(tenant):
        raise LimiterError("tenant over query budget", retry_after=7.0)

    orig = http.server.limiters.check_query
    http.server.limiters.check_query = over_budget
    try:
        status, body, hdrs = http.request(
            "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
        assert status == 429, body
        assert hdrs.get("Retry-After") == "7"
    finally:
        http.server.limiters.check_query = orig

    # node saturated: single slot held, zero queue → immediate 503
    http.server.gate = AdmissionGate(max_concurrent=1, max_queued=0)
    http.server.gate.acquire(None)
    try:
        status, body, hdrs = http.request(
            "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
        assert status == 503, body
        assert hdrs.get("Retry-After") == "1"
    finally:
        http.server.gate.release()
    # capacity restored → back to 200
    status, body, _ = http.request(
        "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
    assert status == 200, body


def test_http_deadline_header_504_and_counter(http):
    _seed_rows(http)
    # delay execution past the 1 ms budget so expiry is deterministic even
    # in a warm process (the real checkpoints then observe a dead deadline)
    orig_exec = http.server.executor.execute_sql

    def slow_exec(sql, session):
        time.sleep(0.05)
        return orig_exec(sql, session)

    http.server.executor.execute_sql = slow_exec
    try:
        status, body, _ = http.request(
            "POST", "/api/v1/sql?db=public",
            "SELECT count(*) FROM cpu",
            headers={"X-CnosDB-Deadline-Ms": "1"})
    finally:
        http.server.executor.execute_sql = orig_exec
    assert status == 504, body
    assert "deadline" in body.lower() or "expired" in body.lower() \
        or "cancel" in body.lower(), body
    status, text, _ = http.request("GET", "/metrics")
    assert status == 200
    line = next(ln for ln in text.splitlines()
                if ln.startswith("cnosdb_requests_deadline_exceeded_total"))
    assert float(line.rsplit(" ", 1)[1]) >= 1
    # a sane deadline still succeeds
    status, body, _ = http.request(
        "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu",
        headers={"X-CnosDB-Deadline-Ms": "30000"})
    assert status == 200, body


def test_http_bad_deadline_header_400(http):
    status, body, _ = http.request(
        "POST", "/api/v1/sql?db=public", "SELECT 1",
        headers={"X-CnosDB-Deadline-Ms": "soon"})
    assert status == 400, body


def test_http_metrics_exports_request_lifecycle_gauges(http):
    _seed_rows(http, n=4)
    status, _, _ = http.request(
        "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
    assert status == 200
    status, text, _ = http.request("GET", "/metrics")
    assert status == 200
    for metric in ("cnosdb_requests_admitted_total",
                   "cnosdb_requests_shed_total",
                   "cnosdb_requests_queue_depth",
                   "cnosdb_requests_queue_wait_ms",
                   "cnosdb_deadline_total"):
        assert metric in text, f"missing {metric} on /metrics"
    # the sql above went through the gate
    line = next(ln for ln in text.splitlines()
                if ln.startswith("cnosdb_requests_admitted_total"))
    assert float(line.rsplit(" ", 1)[1]) >= 1
