import numpy as np
import pytest

from cnosdb_tpu.errors import CodecError
from cnosdb_tpu.models.codec import Encoding
from cnosdb_tpu.models.schema import ValueType
from cnosdb_tpu.storage import codecs


def _roundtrip(values, vt, enc=Encoding.DEFAULT, is_time=False):
    blk = codecs.encode(values, vt, enc, is_time=is_time)
    out = codecs.decode(blk, vt)
    return blk, out


# ------------------------------------------------------------- timestamps
def test_regular_timestamps_constant_stride_fast_path():
    ts = np.arange(0, 10_000_000_000, 1_000_000, dtype=np.int64)  # 10k pts @1ms
    blk = codecs.encode_timestamps(ts)
    assert len(blk) < 32  # constant stride encodes to ~22 bytes
    out = codecs.decode_timestamps(blk)
    np.testing.assert_array_equal(out, ts)


def test_irregular_timestamps(rng):
    base = np.int64(1_600_000_000_000_000_000)
    ts = base + np.cumsum(rng.integers(1, 1_000_000, size=5000)).astype(np.int64)
    blk = codecs.encode_timestamps(ts)
    np.testing.assert_array_equal(codecs.decode_timestamps(blk), ts)
    assert len(blk) < ts.nbytes  # compresses


# ------------------------------------------------------------- integers
@pytest.mark.parametrize("enc", [Encoding.DELTA, Encoding.QUANTILE])
def test_integer_roundtrip(rng, enc):
    for vals in [
        rng.integers(-(2**62), 2**62, size=1000),
        np.array([0], dtype=np.int64),
        np.array([-(2**63), 2**63 - 1, 0, -1, 1], dtype=np.int64),
        np.zeros(100, dtype=np.int64),
    ]:
        _, out = _roundtrip(vals.astype(np.int64), ValueType.INTEGER, enc)
        np.testing.assert_array_equal(out, vals)
        assert out.dtype == np.int64


def test_unsigned_roundtrip(rng):
    vals = rng.integers(0, 2**63, size=1000, dtype=np.uint64) * 2
    _, out = _roundtrip(vals, ValueType.UNSIGNED, Encoding.DELTA)
    np.testing.assert_array_equal(out, vals)
    assert out.dtype == np.uint64


def test_empty_blocks():
    for vt, enc in [(ValueType.INTEGER, Encoding.DELTA),
                    (ValueType.FLOAT, Encoding.GORILLA),
                    (ValueType.BOOLEAN, Encoding.BITPACK),
                    (ValueType.STRING, Encoding.ZSTD)]:
        _, out = _roundtrip(np.array([], dtype=np.float64) if vt == ValueType.FLOAT
                            else [] if vt == ValueType.STRING
                            else np.array([], dtype=np.int64), vt, enc)
        assert len(out) == 0


# ------------------------------------------------------------- floats
def test_float_gorilla_roundtrip(rng):
    for vals in [
        rng.normal(50.0, 10.0, size=10_000),
        np.array([np.nan, np.inf, -np.inf, 0.0, -0.0, 1e-300, 1e300]),
        np.full(1000, 3.14159),
        np.array([1.5]),
    ]:
        _, out = _roundtrip(vals, ValueType.FLOAT, Encoding.GORILLA)
        np.testing.assert_array_equal(out.view(np.uint64), np.asarray(vals, dtype=np.float64).view(np.uint64))


def test_float_compression_on_slowly_varying():
    t = np.arange(100_000)
    vals = 50.0 + np.sin(t / 1000.0)  # smooth signal
    blk, out = _roundtrip(vals, ValueType.FLOAT, Encoding.GORILLA)
    np.testing.assert_array_equal(out, vals)
    assert len(blk) < vals.nbytes * 0.8


# ------------------------------------------------------------- bool/string
def test_bool_roundtrip(rng):
    vals = rng.integers(0, 2, size=1237).astype(bool)
    _, out = _roundtrip(vals, ValueType.BOOLEAN, Encoding.BITPACK)
    np.testing.assert_array_equal(out, vals)


@pytest.mark.parametrize("enc", [Encoding.ZSTD, Encoding.GZIP, Encoding.ZLIB,
                                 Encoding.BZIP, Encoding.SNAPPY])
def test_string_roundtrip(enc):
    vals = ["hello", "", "世界", "x" * 1000, "tag_value_1"] * 20
    _, out = _roundtrip(vals, ValueType.STRING, enc)
    assert list(out) == vals


# ------------------------------------------------------------- errors
def test_illegal_encoding_rejected():
    with pytest.raises(CodecError):
        codecs.encode(np.array([1.0]), ValueType.FLOAT, Encoding.BITPACK)
    with pytest.raises(CodecError):
        codecs.decode(b"", ValueType.FLOAT)


# ------------------------------------------------------------- native parity
def test_native_decode_matches_numpy(rng):
    """When the C++ library is present, its fused decode must be
    bit-identical to the numpy pipeline."""
    from cnosdb_tpu.storage import native

    if not native.available():
        pytest.skip("native codec library not built")
    n = 50_000
    ts = np.int64(1.6e18) + np.cumsum(rng.integers(1, 10**6, n)).astype(np.int64)
    vals = np.cumsum(rng.normal(size=n))
    vals[::97] = np.nan
    tblk = codecs.encode_timestamps(ts)
    fblk = codecs.encode(vals, ValueType.FLOAT)
    width = tblk[1 + 13]
    first = int(np.frombuffer(tblk[1 + 5:1 + 13], dtype=np.int64)[0])
    nat_ts = native.decode_delta_i64(tblk[1 + 14:], width, first, n)
    np.testing.assert_array_equal(nat_ts, ts)
    nat_f = native.decode_xor_f64(fblk[1 + 5:], n)
    np.testing.assert_array_equal(nat_f.view(np.uint64), vals.view(np.uint64))


# ------------------------------------------------------------- perf sanity
def test_decode_speed_smoke():
    """Decode must be way faster than Python-loop speed (vectorized check)."""
    import time
    n = 1_000_000
    ts = np.arange(n, dtype=np.int64) * 1_000_000
    vals = 50.0 + np.sin(np.arange(n) / 1000.0)
    tblk = codecs.encode_timestamps(ts)
    fblk = codecs.encode(vals, ValueType.FLOAT)
    t0 = time.perf_counter()
    codecs.decode_timestamps(tblk)
    codecs.decode(fblk, ValueType.FLOAT)
    dt = time.perf_counter() - t0
    # 1M ts + 1M floats; vectorized path should run well under a second
    assert dt < 1.0, f"decode too slow: {dt:.3f}s"


def test_codec_thread_safety():
    """Concurrent encode/decode from many threads (parallel ingest writers
    + compaction pool + query pool share the codec layer; zstd contexts
    must be thread-local — a shared context segfaults)."""
    import threading

    import numpy as np

    from cnosdb_tpu.models.schema import ValueType
    from cnosdb_tpu.storage import codecs

    rng = np.random.default_rng(5)
    ts = np.cumsum(rng.integers(1, 50, 200_000).astype(np.int64))
    f = rng.normal(0, 1e5, 200_000)
    errors = []

    def worker(seed):
        try:
            r = np.random.default_rng(seed)
            for _ in range(10):
                n = int(r.integers(1_000, 200_000))
                b = codecs.encode_timestamps(ts[:n])
                assert np.array_equal(codecs.decode_timestamps(b), ts[:n])
                b = codecs.encode(f[:n], ValueType.FLOAT)
                assert np.array_equal(
                    codecs.decode(b, ValueType.FLOAT), f[:n])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
