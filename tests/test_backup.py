"""Disaster-recovery plane (storage/backup.py): continuous WAL
archiving with GC fencing, incremental consistent snapshots, and
point-in-time restore.

The oracles the nemesis scenarios rely on are pinned here in-process:
no acked write at-or-before the archived watermark survives total node
loss or an operator-error DROP, a restore to T is identical to a scan
taken at T, and the purge fence never lets local GC outrun the archive.
"""
import os
import shutil
import time

import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.errors import ExecutionError, StorageError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql import ast
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.sql.parser import parse_sql, parse_timestamp_string
from cnosdb_tpu.storage import backup, tiering
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.storage.wal import Wal, WalEntryType


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    backup.counters_reset()
    yield
    faults.reset()
    backup.configure_archive(None)
    tiering.configure(None)
    backup.counters_reset()


@pytest.fixture
def arch(tmp_path):
    d = str(tmp_path / "archive")
    backup.configure_archive(d)
    return d


@pytest.fixture
def stack(tmp_path, arch):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    yield ex
    engine.close()


def _fill(ex, lo, n, db="public", table="m"):
    vals = ",".join(f"({t},'h',{float(t)})" for t in range(lo, lo + n))
    ex.execute_one(f"INSERT INTO {table} (time, ta, v) VALUES {vals}",
                   Session(database=db))


def _rows(ex, db, table="m"):
    rs = ex.execute_one(f"SELECT time, v FROM {table} ORDER BY time",
                        Session(database=db))
    if not rs.columns:
        return []
    return list(zip([int(t) for t in rs.columns[0]],
                    [float(v) for v in rs.columns[1]]))


def _archive_all():
    """The BACKUP barrier by hand: seal every active segment and pump
    the archiver so the archived log covers everything written so far."""
    for a in backup.archivers():
        a.wal.seal_active()
        a.catch_up()


# ---------------------------------------------------------------------------
# WAL GC fencing (regression: purge may never outrun the archive)
# ---------------------------------------------------------------------------
def test_fence_blocks_purge_until_archived(tmp_path, arch):
    w = Wal(str(tmp_path / "wal"))
    for i in range(5):
        w.append(WalEntryType.WRITE, f"e{i}".encode())
    a = backup.attach_wal("t.db", 1, w)
    faults.configure("seed=1;backup.archive:fail")
    w.seal_active()                 # seal listener's upload fails (outage)
    faults.reset()
    assert len(w._list_segments()) == 2
    w.purge_to(10 ** 9)
    # the sealed segment holds the only copy of acked writes → kept
    assert len(w._list_segments()) == 2
    seg_path = w._seg_path(0)
    old = os.path.getmtime(seg_path) - 100
    os.utime(seg_path, (old, old))
    assert a.lag_seconds() >= 99    # RPO gauge sees the unarchived backlog
    assert a.catch_up() == 1        # outage over: heal
    assert a.lag_seconds() == 0.0
    w.purge_to(10 ** 9)
    assert w._list_segments() == [1]   # fence lifted, GC proceeds
    w.close()


def test_fence_fails_closed_on_archiver_error(tmp_path):
    w = Wal(str(tmp_path / "wal"))
    w.append(WalEntryType.WRITE, b"x")
    w.seal_active()

    def boom(seg_id):
        raise RuntimeError("archiver evaporated")

    w.archive_fence = boom
    w.purge_to(10 ** 9)
    assert len(w._list_segments()) == 2   # erroring fence keeps the bytes
    w.close()


def test_watermark_survives_restart_without_reupload(tmp_path, arch):
    d = str(tmp_path / "wal")
    w = Wal(d)
    w.append(WalEntryType.WRITE, b"payload")
    backup.attach_wal("t.db", 1, w)
    w.seal_active()                  # archived via the seal listener
    w.close()
    backup.counters_reset()
    # process restart: fresh registry, same store; the durable watermark
    # must seed the archived-set so nothing is re-uploaded or un-fenced
    backup.configure_archive(arch)
    w2 = Wal(d)
    a2 = backup.attach_wal("t.db", 1, w2)
    assert a2.catch_up() == 0
    assert a2.may_purge(0)
    snap = backup.backup_snapshot()
    assert snap.get(("archive", "segments_archived")) is None
    assert snap[("archive", "already_archived")] >= 1
    w2.close()


def test_archive_crash_window_healed_on_reattach(tmp_path, arch):
    """backup.archive fires before the put: a crash there leaves a
    sealed-but-unarchived segment. The next attach's catch_up must
    re-archive the same bytes to the same key (idempotent replay)."""
    d = str(tmp_path / "wal")
    w = Wal(d)
    for i in range(3):
        w.append(WalEntryType.WRITE, f"e{i}".encode())
    backup.attach_wal("t.db", 7, w)
    faults.configure("seed=1;backup.archive:fail:nth=1")
    w.seal_active()                  # the "crash": upload never happened
    faults.reset()
    w.close()
    backup.configure_archive(arch)   # restart
    w2 = Wal(d)
    backup.attach_wal("t.db", 7, w2)     # attach-time catch_up heals
    store, prefix = backup._store_and_prefix()
    key = f"{prefix}/wal/t.db/7/wal_0000000000.log"
    with open(w2._seg_path(0), "rb") as f:
        assert store.get(key) == f.read()
    w2.close()


# ---------------------------------------------------------------------------
# snapshots + restore through the SQL surface
# ---------------------------------------------------------------------------
def test_backup_restore_as_rolls_forward_to_archived_tail(stack):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 50)
    ex.execute_one("BACKUP DATABASE public")
    _fill(ex, 51, 10)
    _archive_all()
    ex.execute_one("RESTORE DATABASE public AS public_r")
    # plain restore = snapshot + full archived-WAL roll-forward: the 10
    # post-backup (but archived) rows are there, and the source is intact
    assert _rows(ex, "public_r") == _rows(ex, "public")
    assert len(_rows(ex, "public_r")) == 60


def test_pitr_restore_matches_scan_at_t(stack):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 50)
    # a tombstone-covered range rides the snapshot cut
    ex.execute_one("DELETE FROM m WHERE time >= 10 AND time < 20")
    ex.execute_one("BACKUP DATABASE public")
    _fill(ex, 100, 10)                       # B: before T
    time.sleep(0.02)
    t_mid = time.time_ns()
    expected = _rows(ex, "public")           # the scan at T
    time.sleep(0.02)
    _fill(ex, 200, 20)                       # C: after T
    _archive_all()                           # B and C both archived
    out = ex.coord.restore_database("cnosdb", "public", to_ts=t_mid,
                                    new_name="pitr")
    assert out["database"] == "pitr"
    got = _rows(ex, "pitr")
    assert got == expected                   # identical to the scan at T
    assert all(t < 200 for t, _ in got)      # C filtered by append-ts
    assert not any(10 <= t < 20 for t, _ in got)


def test_backup_references_cold_tier_without_reupload(stack, tmp_path):
    ex = stack
    tiering.configure(str(tmp_path / "cold"))
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    engine = ex.coord.engine
    # two flush generations, then a full compaction: tiering only ages
    # sealed L1+ files (L0 delta churn belongs to compaction)
    for lo in (1, 26):
        _fill(ex, lo, 25)
        for v in list(engine.vnodes.values()):
            v.flush(sync=True)
    tiered = 0
    for v in list(engine.vnodes.values()):
        v.compact_major()
        tiered += tiering.tier_vnode(v, boundary_ns=10 ** 18)
    assert tiered >= 1
    before = _rows(ex, "public")
    ex.execute_one("BACKUP DATABASE public")
    entry = ex.meta.list_backups("cnosdb.public")[-1]
    import json as _json
    store, prefix = backup._store_and_prefix()
    man = _json.loads(store.get(entry["manifest_key"]))
    refs = [r for vn in man["vnodes"] for r in vn["cold_refs"]]
    assert refs, "cold-tiered bytes must ride the manifest as references"
    # the cold data bytes live in the tiering store, not the backup area
    ex.execute_one("RESTORE DATABASE public AS public_r")
    assert _rows(ex, "public_r") == before


def test_incremental_backup_reuses_objects(stack):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 50)
    ex.execute_one("BACKUP DATABASE public")
    full = ex.meta.list_backups("cnosdb.public")[-1]
    _fill(ex, 51, 10)
    ex.execute_one("BACKUP DATABASE public INCREMENTAL")
    inc = ex.meta.list_backups("cnosdb.public")[-1]
    assert inc["incremental"] and inc["base"] == full["id"]
    assert inc["objects_reused"] >= 1       # unchanged blobs not re-sent
    ex.execute_one(f"RESTORE DATABASE public FROM '{inc['id']}' AS r2")
    assert len(_rows(ex, "r2")) == 60


def test_total_node_loss_recovers_to_watermark(tmp_path, arch):
    """The nemesis total-loss scenario in-process: every data file and
    local WAL gone, only meta + the archive store survive; restore must
    bring back every write acked at-or-before the cluster watermark."""
    meta = MetaStore(str(tmp_path / "meta.json"))
    data = str(tmp_path / "data")
    engine = TsKv(data)
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 50)
    ex.execute_one("BACKUP DATABASE public")
    _fill(ex, 51, 10)
    _archive_all()
    acked = _rows(ex, "public")
    wm = backup.cluster_watermark("cnosdb.public")
    assert wm["max_seq"] > 0 and wm["max_ts"] > 0
    engine.close()
    shutil.rmtree(data)                      # total node loss
    engine2 = TsKv(data)
    ex2 = QueryExecutor(meta, Coordinator(meta, engine2))
    out = ex2.coord.restore_database("cnosdb", "public")
    assert out["database"] == "public"
    # RPO oracle: nothing acked at-or-before the watermark is lost (here
    # the archive was caught up, so that is every acked write)
    assert _rows(ex2, "public") == acked
    engine2.close()


def test_operator_error_drop_then_restore(stack):
    ex = stack
    ex.execute_one("CREATE DATABASE app")
    s = Session(database="app")
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))", s)
    _fill(ex, 1, 30, db="app")
    ex.execute_one("BACKUP DATABASE app", s)
    _archive_all()
    before = _rows(ex, "app")
    ex.execute_one("DROP DATABASE app")      # the operator error
    with pytest.raises(Exception):
        _rows(ex, "app")
    ex.execute_one("RESTORE DATABASE app")
    assert _rows(ex, "app") == before


def test_restore_before_install_leaves_source_intact(stack):
    """restore.install fires before the wipe: a failure there must not
    have touched the source database (the sweep's recovery oracle)."""
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 20)
    ex.execute_one("BACKUP DATABASE public")
    _archive_all()
    faults.configure("seed=1;restore.install:fail:nth=1")
    with pytest.raises(Exception):
        ex.execute_one("RESTORE DATABASE public AS public_r")
    faults.reset()
    assert len(_rows(ex, "public")) == 20


def test_show_backups_and_counters(stack):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    _fill(ex, 1, 10)
    ex.execute_one("BACKUP DATABASE public")
    _fill(ex, 11, 10)
    ex.execute_one("BACKUP DATABASE public INCREMENTAL")
    rs = ex.execute_one("SHOW BACKUPS")
    assert "backup_id" in rs.names and "incremental" in rs.names
    assert len(rs.columns[0]) == 2
    snap = backup.backup_snapshot()
    assert snap[("backup", "ok")] == 2
    assert snap[("archive", "segments_archived")] >= 1


def test_backup_requires_archive_store(stack):
    ex = stack
    backup.configure_archive(None)
    with pytest.raises((StorageError, ExecutionError),
                       match="wal_archive_uri"):
        ex.execute_one("BACKUP DATABASE public")


def test_restore_unknown_backup_errors(stack):
    ex = stack
    with pytest.raises((StorageError, ExecutionError), match="no backup"):
        ex.execute_one("RESTORE DATABASE public")


def test_gc_backups_retention(stack):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    for i in range(3):
        _fill(ex, 1 + i * 10, 10)
        ex.execute_one("BACKUP DATABASE public")
    out = backup.gc_backups(ex.meta, "cnosdb", "public", keep=1)
    assert out["removed"] == 2
    cat = ex.meta.list_backups("cnosdb.public")
    assert len(cat) == 1
    store, prefix = backup._store_and_prefix()
    manifests = store.list_prefix(f"{prefix}/manifests/cnosdb.public/")
    assert len(manifests) == 1               # dropped manifests deleted
    ex.execute_one("RESTORE DATABASE public AS kept")
    assert len(_rows(ex, "kept")) == 30


# ---------------------------------------------------------------------------
# client-history checker: the PITR/no-lost-acked-writes bound
# ---------------------------------------------------------------------------
def test_checker_before_ts_bounds_lost_write_obligation(tmp_path):
    from cnosdb_tpu.chaos.checker import check_no_lost_acked_writes
    from cnosdb_tpu.chaos.history import History, HistoryRecorder

    p = str(tmp_path / "hist.jsonl")
    h = HistoryRecorder(p)
    e1 = h.invoke("c0", "write", keys=["k1"])
    h.ok("c0", e1)
    time.sleep(0.02)
    watermark_ts = time.time()               # the archived watermark
    time.sleep(0.02)
    e2 = h.invoke("c0", "write", keys=["k2"])
    h.ok("c0", e2)                           # acked after the watermark
    h.close()
    hist = History.load(p)
    # restore-to-watermark lost k2 — allowed: it was acked after T
    r = check_no_lost_acked_writes(hist, {"k1"}, before_ts=watermark_ts)
    assert r.ok, r.detail
    # but k1 was acked before T: losing it is a real violation
    r = check_no_lost_acked_writes(hist, set(), before_ts=watermark_ts)
    assert not r.ok
    # and with no bound, every acked write is owed
    r = check_no_lost_acked_writes(hist, {"k1"})
    assert not r.ok


# ---------------------------------------------------------------------------
# SQL surface: parser round-trips
# ---------------------------------------------------------------------------
def test_parser_backup_restore_roundtrip():
    (b,) = parse_sql("BACKUP DATABASE d")
    assert b == ast.BackupStmt(database="d", incremental=False)
    (b,) = parse_sql("BACKUP DATABASE d INCREMENTAL")
    assert b.incremental
    (r,) = parse_sql("RESTORE DATABASE d FROM 'd-000001' "
                     "TO TIMESTAMP '2026-01-02T03:04:05Z' AS r2")
    assert r.database == "d" and r.backup_id == "d-000001"
    assert r.new_name == "r2"
    assert r.to_ts == parse_timestamp_string("2026-01-02T03:04:05Z")
    (r,) = parse_sql("RESTORE DATABASE d TO TIMESTAMP 123456789")
    assert r.to_ts == 123456789 and r.backup_id is None
    (s,) = parse_sql("SHOW BACKUPS")
    assert s == ast.ShowStmt("backups")


# ---------------------------------------------------------------------------
# information_schema.tables options (was the literal 'TODO')
# ---------------------------------------------------------------------------
def test_information_schema_tables_renders_real_options(stack, tmp_path):
    ex = stack
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(ta))")
    csv = tmp_path / "ext.csv"
    csv.write_text("a,b\n1,2\n")
    ex.execute_one("CREATE EXTERNAL TABLE ext STORED AS csv "
                   f"WITH HEADER ROW LOCATION '{csv}'")
    rs = ex.execute_one("SELECT table_name, table_engine, table_options "
                        "FROM information_schema.tables")
    opts = {n: (e, o) for n, e, o in
            zip(rs.columns[0], rs.columns[1], rs.columns[2])}
    engine, o = opts["m"]
    assert engine == "TSKV"
    assert "ttl=" in o and "replica=" in o and "shard=" in o
    engine, o = opts["ext"]
    assert engine == "EXTERNAL"
    assert f"path={csv}" in o and "format=csv" in o and "header=true" in o
    assert all("TODO" not in o for _, o in opts.values())
