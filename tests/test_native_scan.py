"""Native batch-scan fast path (storage.scan._scan_vnode_native +
native/pagedec.cpp): equivalence against the legacy per-series Python
decode across the tricky shapes — nulls, multiple disjoint flushes,
overlapping chunks (fallback), tombstones (fallback), memcache overlay
(fallback), time-range trims, string/bool/int columns — plus predicate
page pruning soundness."""
import os

import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.predicate import TimeRange, TimeRanges
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.models.strcol import DictArray
from cnosdb_tpu.storage import native
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage

pytestmark = pytest.mark.skipif(
    not native.pagedec_available(), reason="native pagedec unavailable")


def _schema():
    return {"m": TskvTableSchema.new_measurement(
        "t", "db", "m", tags=["host"],
        fields=[("f", ValueType.FLOAT), ("i", ValueType.INTEGER),
                ("b", ValueType.BOOLEAN), ("s", ValueType.STRING)])}


def _write(v, host, ts, f=None, i=None, b=None, s=None):
    def py(xs):
        return [None if x is None else
                (x.item() if isinstance(x, np.generic) else x) for x in xs]

    fields = {}
    if f is not None:
        fields["f"] = (int(ValueType.FLOAT), py(f))
    if i is not None:
        fields["i"] = (int(ValueType.INTEGER), py(i))
    if b is not None:
        fields["b"] = (int(ValueType.BOOLEAN), py(b))
    if s is not None:
        fields["s"] = (int(ValueType.STRING), py(s))
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(SeriesKey("m", {"host": host}),
                                  list(ts), fields))
    v.write(wb)


def _assert_batches_equal(a, b):
    assert a.n_rows == b.n_rows
    assert a.n_series == b.n_series
    np.testing.assert_array_equal(a.series_ids, b.series_ids)
    np.testing.assert_array_equal(a.ts, b.ts)
    np.testing.assert_array_equal(a.sid_ordinal, b.sid_ordinal)
    assert set(a.fields) == set(b.fields)
    for name in a.fields:
        vt_a, vals_a, valid_a = a.fields[name]
        vt_b, vals_b, valid_b = b.fields[name]
        assert vt_a == vt_b
        np.testing.assert_array_equal(valid_a, valid_b)
        if isinstance(vals_a, DictArray) or isinstance(vals_b, DictArray):
            obj_a = np.asarray(vals_a.materialize()
                               if isinstance(vals_a, DictArray) else vals_a)
            obj_b = np.asarray(vals_b.materialize()
                               if isinstance(vals_b, DictArray) else vals_b)
            np.testing.assert_array_equal(obj_a[valid_a], obj_b[valid_b])
        else:
            np.testing.assert_array_equal(vals_a[valid_a], vals_b[valid_b])


def _both_scans(v, **kw):
    got = scan_vnode(v, "m", **kw)
    os.environ["CNOSDB_NO_NATIVE_SCAN"] = "1"
    try:
        want = scan_vnode(v, "m", **kw)
    finally:
        del os.environ["CNOSDB_NO_NATIVE_SCAN"]
    return got, want


def test_flushed_basic(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    rng = np.random.default_rng(1)
    _write(v, "h1", range(0, 1000), f=rng.normal(size=1000),
           i=rng.integers(-50, 50, 1000), b=rng.integers(0, 2, 1000) > 0,
           s=[f"v{x}" for x in rng.integers(0, 5, 1000)])
    _write(v, "h2", range(500, 900), f=rng.normal(size=400))
    v.flush()
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    v.close()


def test_multiple_disjoint_flushes(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    for base in (0, 1000, 2000):
        _write(v, "h1", range(base, base + 500),
               f=np.arange(base, base + 500) * 0.5)
        v.flush()
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    assert (np.diff(got.ts) > 0).all()
    v.close()


def test_overlapping_chunks_fall_back(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    _write(v, "h1", range(0, 100), f=np.ones(100))
    v.flush()
    _write(v, "h1", range(50, 150), f=np.full(100, 2.0))  # overlap: dedup
    v.flush()
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    assert got.n_rows == 150
    # overlap region takes the later write
    vt, vals, valid = got.fields["f"]
    assert vals[got.ts == 75][0] == 2.0
    v.close()


def test_memcache_overlay_falls_back(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    _write(v, "h1", range(0, 100), f=np.ones(100))
    v.flush()
    _write(v, "h1", range(90, 120), f=np.full(30, 9.0))  # unflushed
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    assert got.fields["f"][1][got.ts == 95][0] == 9.0
    v.close()


def test_tombstone_falls_back(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    _write(v, "h1", range(0, 100), f=np.arange(100.0))
    _write(v, "h2", range(0, 100), f=np.arange(100.0))
    v.flush()
    v.delete_time_range("m", None, 10, 20)
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    assert got.n_rows == 2 * (100 - 11)
    v.close()


def test_nulls_across_pages(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    # one field written on even rows only → other field null there
    n = 500
    ts = list(range(n))
    f = [float(x) if x % 2 == 0 else None for x in range(n)]
    i = [int(x) if x % 3 == 0 else None for x in range(n)]
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(
        SeriesKey("m", {"host": "h1"}), ts,
        {"f": (int(ValueType.FLOAT), f),
         "i": (int(ValueType.INTEGER), i)}))
    v.write(wb)
    v.flush()
    got, want = _both_scans(v)
    _assert_batches_equal(got, want)
    vt, vals, valid = got.fields["f"]
    assert valid.sum() == sum(1 for x in f if x is not None)
    v.close()


def test_time_range_trim(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    _write(v, "h1", range(0, 1000), f=np.arange(1000.0))
    _write(v, "h2", range(2000, 3000), f=np.arange(1000.0))
    v.flush()
    trs = TimeRanges([TimeRange(250, 2200)])
    got, want = _both_scans(v, time_ranges=trs)
    _assert_batches_equal(got, want)
    assert got.ts.min() >= 250 and got.ts.max() <= 2200
    # h2 trimmed to 201 rows, h1 to 750
    assert got.n_rows == 750 + 201
    v.close()


def test_time_range_drops_series_entirely(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    _write(v, "h1", range(0, 100), f=np.arange(100.0))
    _write(v, "h2", range(5000, 5100), f=np.arange(100.0))
    v.flush()
    trs = TimeRanges([TimeRange(0, 200)])
    got, want = _both_scans(v, time_ranges=trs)
    _assert_batches_equal(got, want)
    assert got.n_series == 1
    v.close()


def test_field_projection(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    rng = np.random.default_rng(3)
    _write(v, "h1", range(0, 300), f=rng.normal(size=300),
           i=rng.integers(0, 9, 300))
    v.flush()
    got, want = _both_scans(v, field_names=["i"])
    _assert_batches_equal(got, want)
    assert set(got.fields) == {"i"}
    v.close()


def test_predicate_page_pruning_sound(tmp_engine_dir):
    """Pruned scan must keep every page that can hold a matching row;
    the aggregate over (pruned batch + row filter) must equal the
    aggregate over the full batch + row filter."""
    from cnosdb_tpu.sql.expr import BinOp, Column, Literal

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    rng = np.random.default_rng(4)
    n = 600_000   # > 2 pages (256k rows each) with distinct stat ranges
    vals = np.concatenate([rng.uniform(0, 10, n // 2),
                           rng.uniform(50, 60, n // 2)])
    _write(v, "h1", range(n), f=vals)
    v.flush()
    flt = BinOp(">", Column("f"), Literal(55.0))
    pruned = scan_vnode(v, "m", page_filter=flt)
    full = scan_vnode(v, "m")
    assert pruned.n_rows < full.n_rows   # something actually pruned
    pm = pruned.fields["f"][1] > 55.0
    fm = full.fields["f"][1] > 55.0
    assert pm.sum() == fm.sum()
    assert pruned.fields["f"][1][pm].sum() == \
        pytest.approx(full.fields["f"][1][fm].sum())
    v.close()


def test_pruning_keeps_inf(tmp_engine_dir):
    """±inf participates in page stats (NaN doesn't): an inf row must
    survive pruning for a > comparison."""
    from cnosdb_tpu.sql.expr import BinOp, Column, Literal

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    vals = np.zeros(1000)
    vals[500] = np.inf
    _write(v, "h1", range(1000), f=vals)
    v.flush()
    flt = BinOp(">", Column("f"), Literal(1e300))
    pruned = scan_vnode(v, "m", page_filter=flt)
    m = pruned.fields["f"][1] > 1e300
    assert m.sum() == 1
    v.close()


def test_no_prune_on_ne_with_nan(tmp_engine_dir):
    """`!=` must not prune: page stats exclude NaN but NaN satisfies !=
    (sql 3VL evaluates it as ~(a == b)) — a constant page may hide a
    matching NaN row."""
    from cnosdb_tpu.sql.expr import BinOp, Column, Literal

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    vals = np.full(1000, 5.0)
    vals[123] = np.nan
    _write(v, "h1", range(1000), f=vals)
    v.flush()
    flt = BinOp("!=", Column("f"), Literal(5.0))
    pruned = scan_vnode(v, "m", page_filter=flt)
    assert pruned.n_rows == 1000   # page kept despite lo == hi == 5
    fv = pruned.fields["f"][1]
    with np.errstate(invalid="ignore"):
        m = ~(fv == 5.0)
    assert m.sum() == 1
    v.close()


def test_unsigned_and_bool_roundtrip(tmp_engine_dir):
    schemas = {"m": TskvTableSchema.new_measurement(
        "t", "db", "m", tags=["host"],
        fields=[("u", ValueType.UNSIGNED), ("b", ValueType.BOOLEAN)])}
    v = VnodeStorage(1, tmp_engine_dir, schemas=schemas)
    rng = np.random.default_rng(5)
    u = rng.integers(0, 2**63, 400, dtype=np.uint64) * 2  # exercises u64
    b = rng.integers(0, 2, 400) > 0
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(
        SeriesKey("m", {"host": "h1"}), list(range(400)),
        {"u": (int(ValueType.UNSIGNED), u.tolist()),
         "b": (int(ValueType.BOOLEAN), b.tolist())}))
    v.write(wb)
    v.flush()
    got = scan_vnode(v, "m")
    os.environ["CNOSDB_NO_NATIVE_SCAN"] = "1"
    try:
        want = scan_vnode(v, "m")
    finally:
        del os.environ["CNOSDB_NO_NATIVE_SCAN"]
    np.testing.assert_array_equal(got.fields["u"][1], want.fields["u"][1])
    np.testing.assert_array_equal(got.fields["b"][1], want.fields["b"][1])
    v.close()
