"""Periphery round-trips: prometheus remote write→read, COPY out→in,
external tables, meta dump→restore, cli --dump-ddl (reference
prom/remote_server.rs:478, create_external_table.rs:189,
meta/src/service/http.rs:187-276)."""
import os

import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.parallel.meta_service import MetaClient, MetaService
from cnosdb_tpu.parallel.net import rpc_call
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    coord.close()


def test_prom_read_request_roundtrip():
    from cnosdb_tpu.protocol import prometheus as p

    if not p.snappy_available():
        pytest.skip("snappy unavailable")
    # hand-build a ReadRequest: Query{start,end,matchers=[EQ __name__ cpu]}
    q = bytearray()
    p._w_tag(q, 1, 0)
    p._w_varint(q, 1000)
    p._w_tag(q, 2, 0)
    p._w_varint(q, 2000)
    m = bytearray()
    p._w_tag(m, 1, 0)
    p._w_varint(m, p.MATCH_EQ)
    p._w_bytes(m, 2, b"__name__")
    p._w_bytes(m, 3, b"cpu")
    p._w_bytes(q, 3, bytes(m))
    req = bytearray()
    p._w_bytes(req, 1, bytes(q))
    parsed = p.parse_read_request(p.snappy_compress(bytes(req)))
    assert parsed == [{"start_ms": 1000, "end_ms": 2000,
                       "matchers": [(p.MATCH_EQ, "__name__", "cpu")]}]
    # response round-trips through our own decoder helpers
    raw = p.encode_read_response(
        [[({"__name__": "cpu", "host": "a"}, [(1500, 0.5), (1600, 1.5)])]],
        compress=False)
    # decode: 1 query result → 1 timeseries → 2 labels + 2 samples
    (fno, qr), = list(p._fields(raw))
    assert fno == 1
    (f2, ts_msg), = list(p._fields(qr))
    labels, samples = {}, []
    for f3, v in p._fields(ts_msg):
        if f3 == 1:
            kv = {f4: x for f4, x in p._fields(v)}
            labels[kv[1].decode()] = kv[2].decode()
        else:
            kv = {f4: x for f4, x in p._fields(v)}
            import struct
            samples.append((kv[2], struct.unpack("<d", kv[1])[0]))
    assert labels == {"__name__": "cpu", "host": "a"}
    assert samples == [(1500, 0.5), (1600, 1.5)]


def test_copy_export_import_roundtrip(db, tmp_path):
    db.execute_one("CREATE TABLE src (v DOUBLE, n BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO src (time, h, v, n) VALUES "
                   "(1,'a',1.5,10),(2,'b',2.5,20),(3,'c',3.5,30)")
    out_csv = str(tmp_path / "out.csv")
    rs = db.execute_one(f"COPY INTO '{out_csv}' FROM src")
    assert rs.columns[0][0] == 3
    assert os.path.exists(out_csv)
    # import into a fresh table with the same shape
    db.execute_one("CREATE TABLE dst (v DOUBLE, n BIGINT, TAGS(h))")
    rs = db.execute_one(f"COPY INTO dst FROM '{out_csv}'")
    assert rs.columns[0][0] == 3
    a = db.execute_one("SELECT time, h, v, n FROM src ORDER BY time")
    b = db.execute_one("SELECT time, h, v, n FROM dst ORDER BY time")
    for ca, cb in zip(a.columns, b.columns):
        assert ca.tolist() == cb.tolist()
    # parquet round-trip too
    out_pq = str(tmp_path / "out.parquet")
    db.execute_one(f"COPY INTO '{out_pq}' FROM src")
    db.execute_one("CREATE TABLE dst2 (v DOUBLE, n BIGINT, TAGS(h))")
    rs = db.execute_one(f"COPY INTO dst2 FROM '{out_pq}'")
    assert rs.columns[0][0] == 3
    c = db.execute_one("SELECT sum(v) FROM dst2")
    assert c.columns[0][0] == 7.5


def test_external_table(db, tmp_path):
    p = tmp_path / "ext.csv"
    p.write_text("city,pop\nberlin,3million\nparis,2million\n")
    db.execute_one(
        f"CREATE EXTERNAL TABLE cities STORED AS CSV WITH HEADER ROW "
        f"LOCATION '{p}'")
    rs = db.execute_one("SELECT city, pop FROM cities ORDER BY city")
    assert rs.columns[0].tolist() == ["berlin", "paris"]
    rs = db.execute_one("SELECT count(*) FROM cities WHERE city = 'paris'")
    assert rs.columns[0][0] == 1
    # joinable against real tables
    db.execute_one("CREATE TABLE visits (n BIGINT, TAGS(city))")
    db.execute_one("INSERT INTO visits (time, city, n) VALUES "
                   "(1,'berlin',7),(2,'rome',9)")
    rs = db.execute_one(
        "SELECT v.city, c.pop FROM visits v JOIN cities c "
        "ON v.city = c.city")
    assert rs.columns[0].tolist() == ["berlin"]
    assert rs.columns[1].tolist() == ["3million"]


def test_meta_dump_restore_roundtrip(tmp_path):
    store = MetaStore(str(tmp_path / "m.json"), register_self=False)
    svc = MetaService(store, port=0).start()
    try:
        c = MetaClient(svc.addr, node_id=7, watch=False)
        c.register_node(7, grpc_addr="127.0.0.1:1")
        c.create_user("u", "p")
        c.create_tenant("t")
        dump = rpc_call(svc.addr, "meta_dump")
        # wipe into a new service, restore, verify state equality
        store2 = MetaStore(str(tmp_path / "m2.json"), register_self=False)
        svc2 = MetaService(store2, port=0).start()
        try:
            rpc_call(svc2.addr, "meta_restore",
                     {"snapshot": dump["snapshot"]})
            c2 = MetaClient(svc2.addr, node_id=8, watch=False)
            assert "t" in c2.tenants
            assert c2.check_user("u", "p") is not None
            assert c2.node_addr(7) == "127.0.0.1:1"
        finally:
            svc2.stop()
    finally:
        svc.stop()


def test_dump_ddl_output(db, capsys):
    db.execute_one("CREATE DATABASE d9 WITH TTL '30d' SHARD 2")
    db.execute_one("CREATE TABLE m9 (v DOUBLE, TAGS(h))",
                   Session(database="d9"))

    class FakeClient:
        def sql_rows(self, q):
            from cnosdb_tpu.server.http import format_csv
            import csv, io

            rs = db.execute_one(q)
            rows = list(csv.reader(io.StringIO(format_csv(rs))))
            return rows[1:]

    from cnosdb_tpu.client.cli import dump_ddl

    dump_ddl(FakeClient())
    out = capsys.readouterr().out
    assert "CREATE DATABASE IF NOT EXISTS d9" in out
    assert "CREATE TABLE IF NOT EXISTS d9.m9" in out and "TAGS(h)" in out
    # the emitted DDL must re-run cleanly
    for stmt in out.strip().splitlines():
        db.execute_one(stmt.rstrip(";"))


def test_external_table_security_and_lifecycle(db):
    from cnosdb_tpu.errors import AuthError

    root = Session()
    # non-admin users cannot touch the server filesystem
    db.execute_one("CREATE USER fsuser WITH PASSWORD = 'f'", root)
    db.execute_one("ALTER TENANT cnosdb ADD USER fsuser AS owner", root)
    with pytest.raises(AuthError):
        db.execute_one(
            "CREATE EXTERNAL TABLE pw STORED AS CSV LOCATION '/etc/passwd'",
            Session(user="fsuser"))
    with pytest.raises(AuthError):
        db.execute_one("COPY INTO '/tmp/x.csv' FROM m", Session(user="fsuser"))


def test_external_table_drop_and_shadowing(db, tmp_path):
    p = tmp_path / "e.csv"
    p.write_text("a\n1\n")
    db.execute_one(f"CREATE EXTERNAL TABLE e1 STORED AS CSV WITH HEADER ROW "
                   f"LOCATION '{p}'")
    # a tskv table cannot shadow the external name
    with pytest.raises(Exception):
        db.execute_one("CREATE TABLE e1 (v DOUBLE, TAGS(h))")
    # DROP TABLE removes the external and frees the name
    db.execute_one("DROP TABLE e1")
    db.execute_one("CREATE TABLE e1 (v DOUBLE, TAGS(h))")
