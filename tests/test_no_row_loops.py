"""Lint: no per-row Python loops in the vectorized aggregation sections.

The aggregation plane's contract (ops/group_agg.py) is that per-group
work happens through factorized codes + numpy/segment kernels; a
``for i in idxs:`` loop over row indices reintroduces the O(rows)
Python accumulation the plane replaced, and it regresses silently (the
results stay right, only 10-100× slower at ClickBench cardinalities).
This lint walks the named vectorized functions and flags any for-loop
over a row-index iterable. The deliberate scalar fallbacks (mixed-type
payloads that defeat factorization) stay allowed — but ratcheted, so
they can't quietly multiply.
"""
import ast
import os

import pytest

import cnosdb_tpu

_PKG_ROOT = os.path.dirname(cnosdb_tpu.__file__)

# function → file: the sections that must stay loop-free over rows
_VECTORIZED_FUNCS = {
    "_merge_distinct_vec": os.path.join("sql", "executor.py"),
    "_apply_gapfill": os.path.join("sql", "executor.py"),
    "_merge_results_vec": os.path.join("sql", "executor.py"),
}

# iterable names that mean "one element per data row"
_ROW_ITER_NAMES = {"idxs", "idx", "rows", "row_idxs"}


def _find_func(tree: ast.Module, name: str):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _row_loops(fn: ast.AST):
    """For-loops whose iterable is a row-index array: a bare name from
    the denylist, or a direct np.nonzero(...) subscript."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        if isinstance(it, ast.Name) and it.id in _ROW_ITER_NAMES:
            yield node.lineno
        elif isinstance(it, ast.Subscript) \
                and isinstance(it.value, ast.Call) \
                and isinstance(it.value.func, ast.Attribute) \
                and it.value.func.attr == "nonzero":
            yield node.lineno


def _parse(relpath: str) -> ast.Module:
    path = os.path.join(_PKG_ROOT, relpath)
    with open(path, "r", encoding="utf-8") as f:
        return ast.parse(f.read(), filename=path)


@pytest.mark.parametrize("func,relpath", sorted(_VECTORIZED_FUNCS.items()))
def test_vectorized_agg_sections_have_no_row_loops(func, relpath):
    tree = _parse(relpath)
    fn = _find_func(tree, func)
    assert fn is not None, (
        f"{func} not found in {relpath} — update _VECTORIZED_FUNCS if it "
        f"was renamed (the lint must keep covering it)")
    offenders = list(_row_loops(fn))
    assert not offenders, (
        f"per-row loop in vectorized section {relpath}:{func} at lines "
        f"{offenders} — use factorized codes + bincount/reduceat/"
        f"grouped_order (ops/group_agg.py) instead")


def test_scalar_fallback_row_loops_ratcheted():
    """_merge_distinct keeps per-row folds ONLY as the fallback for
    payloads that defeat factorization. Three exist (count_multi,
    collect grouping, distinct). Adding a fourth means a new code path
    skipped the vectorized plane — stop and route it through
    _merge_distinct_vec instead."""
    tree = _parse(os.path.join("sql", "executor.py"))
    fn = _find_func(tree, "_merge_distinct")
    assert fn is not None
    offenders = list(_row_loops(fn))
    assert len(offenders) <= 3, (
        f"scalar row-loop count in _merge_distinct grew to "
        f"{len(offenders)} (lines {offenders}) — new aggregation work "
        f"belongs in _merge_distinct_vec")
