"""Differential tests: native line-protocol parser vs the Python reference
implementation. The native parser (native/lineproto.cpp) must either produce
an identical WriteBatch or reject the input (returning None) so the Python
path decides — it must never silently diverge."""
import random
import string

import numpy as np
import pytest

from cnosdb_tpu.models.schema import Precision
from cnosdb_tpu.protocol import native_lp
from cnosdb_tpu.protocol.line_protocol import _parse_lines_py, parse_lines
from cnosdb_tpu.errors import ParserError

pytestmark = pytest.mark.skipif(not native_lp.available(),
                                reason="native lib unavailable")


def _norm(wb):
    """Order-insensitive, type-normalized view of a WriteBatch."""
    out = {}
    for table, srs in wb.tables.items():
        for sr in srs:
            cols = {}
            for name, (vt, vals) in sr.fields.items():
                if isinstance(vals, np.ndarray):
                    vals = vals.tolist()
                cols[name] = (int(vt), [None if v is None else
                                        (float(v) if isinstance(v, float) else
                                         bool(v) if isinstance(v, (bool, np.bool_)) else
                                         int(v) if not isinstance(v, str) else v)
                                        for v in vals])
            out[(table, sr.key.encode())] = (
                [int(t) for t in sr.timestamps], cols)
    return out


def _assert_same(text, default=1234, factor=1):
    nat = native_lp.try_parse(text, default, factor)
    try:
        py = _parse_lines_py(text, factor, default)
    except ParserError:
        # Python rejects → native must have rejected too (None); a native
        # success on input Python errors on would be a divergence.
        assert nat is None, f"native accepted input Python rejects: {text!r}"
        return
    if nat is None:
        return  # conservative rejection is always allowed
    assert _norm(nat) == _norm(py), f"divergence on: {text!r}"


def test_basic_shapes():
    _assert_same("cpu,host=a usage=1.5 1000\ncpu,host=a usage=2.5 2000\n")
    _assert_same("cpu,host=a,region=e u=1i,f=2.0,s=\"x\",b=t 5\n")
    _assert_same("m v=1u\n")                      # default ts
    _assert_same("# comment\n\nm v=1 7\n")
    _assert_same("m,t=1 a=1 1\nm,t=1 b=2 2\n")    # disjoint fields → None pads
    _assert_same("m,b=2,a=1 v=1 1\nm,a=1,b=2 v=2 2\n")  # tag order canonical
    _assert_same("m v=1,v=2 9\n")                 # duplicate field: last wins
    _assert_same("m,t=x,t=y v=1 9\n")             # duplicate tag: last wins


def test_escapes_and_quotes():
    _assert_same("m\\,1,ta\\ g=v\\=1 fi\\ eld=3i 5\n")
    _assert_same('m s="a\\"b",t=1 7\n')
    _assert_same('m s="with space, and comma" 7\n')
    _assert_same('m s="" 7\n')


def test_precision_factor():
    wb = parse_lines("m v=1 5\n" * 20, Precision.MS)
    sr = wb.tables["m"][0]
    assert int(sr.timestamps[0]) == 5_000_000


def test_errors_fall_back_to_python():
    for bad in ("m\n", "m,t v=1\n", "m v=\n", "m v=abc\n", "m v=1 zz\n",
                ",t=1 v=1\n", "m v=1x 5\n"):
        big = bad * 40  # over the native threshold
        assert native_lp.try_parse(big, 0, 1) is None
        with pytest.raises(ParserError):
            parse_lines(big)


def test_large_batch_uses_arrays():
    text = "".join(f"cpu,host=h{i%3} usage={i}.5,cnt={i}i {i}\n"
                   for i in range(1000))
    wb = native_lp.try_parse(text, 0, 1)
    assert wb is not None
    sr = wb.tables["cpu"][0]
    assert isinstance(sr.timestamps, np.ndarray)
    assert isinstance(sr.fields["usage"][1], np.ndarray)
    assert _norm(wb) == _norm(_parse_lines_py(text, 1, 0))


def test_fuzz_differential():
    rng = random.Random(20260729)
    measurements = ["m", "cpu", "we ird", "esc\\,aped", "m\\ e"]
    tagkeys = ["h", "t1", "k\\=ey"]
    vals = ["a", "b2", "v\\ al", "x\\,y"]

    def tok(options):
        return rng.choice(options)

    for trial in range(300):
        n_lines = rng.randint(1, 6)
        lines = []
        for _ in range(n_lines):
            m = tok(measurements)
            parts = [m]
            for _ in range(rng.randint(0, 2)):
                parts.append(f"{tok(tagkeys)}={tok(vals)}")
            head = ",".join(parts)
            fields = []
            for _ in range(rng.randint(1, 3)):
                name = tok(["f", "g", "h2"])
                kind = rng.randint(0, 4)
                if kind == 0:
                    fields.append(f"{name}={rng.randint(-99, 99)}i")
                elif kind == 1:
                    fields.append(f"{name}={rng.uniform(-5, 5):.3f}")
                elif kind == 2:
                    fields.append(f"{name}={rng.randint(0, 99)}u")
                elif kind == 3:
                    fields.append(f'{name}="{tok(["s", "a b", "q,r"])}"')
                else:
                    fields.append(f"{name}={tok(['t', 'f', 'true', 'FALSE'])}")
            line = f"{head} {','.join(fields)}"
            if rng.random() < 0.7:
                line += f" {rng.randint(0, 10**9)}"
            lines.append(line)
        text = "\n".join(lines) + ("\n" if rng.random() < 0.5 else "")
        _assert_same(text, default=rng.randint(0, 10**6),
                     factor=rng.choice([1, 1000, 10**6]))


def test_ascii_control_separators():
    # \x1c/\x1d/\x1e are splitlines() terminators AND strip() whitespace
    _assert_same("m\x1cx,t=a v=1 5\n" * 30)
    _assert_same("m v=1 5\x1dm v=2 6\n" * 30)
    _assert_same("\x1em v=3 7\n" * 30)
    # \x1f (unit separator) is strip() whitespace but NOT a splitlines()
    # terminator — a \x1f-prefixed line must strip to the same measurement
    # on both paths (round-3 advisor finding)
    _assert_same("\x1fm2,t=a f=1i 100\n" * 30)
    _assert_same("m2,t=a f=1i 100\x1f\n" * 30)


def test_nul_in_tags_keeps_series_distinct():
    a = "m,a=b\\ c v=1 5\n" * 20
    _assert_same(a)
    # distinct tag layouts that a naive NUL-joined key would alias
    t1 = "m,ab=cd v=1 5\n" * 20
    t2 = "m,a=bcd v=2 6\n" * 20
    _assert_same(t1 + t2)
    nat = native_lp.try_parse(t1 + t2, 0, 1)
    if nat is not None:
        assert len(nat.tables["m"]) == 2


def test_oversized_counts_rejected():
    line = "m," + ",".join(f"t{i}=v" for i in range(70000)) + " v=1 5\n"
    assert native_lp.try_parse(line, 0, 1) is None
    # entry point must not 500: Python path handles it
    wb = parse_lines(line)
    assert wb.n_rows() == 1


def test_exotic_whitespace_rejected():
    # unicode line/space separators the byte parser can't honor → fallback
    for ws in (" ", " ", " "):
        text = f"m v=1 5{ws}m v=2 6\n"
        assert native_lp.try_parse(text, 0, 1) is None
        # and the full entry point still behaves (python path handles it)
        try:
            parse_lines(text)
        except ParserError:
            pass


def test_http_write_path_uses_native(monkeypatch):
    """parse_lines prefers native above the size threshold and matches."""
    text = "cpu,host=a v=1.5 1000\n" * 60
    wb = parse_lines(text)
    assert _norm(wb) == _norm(_parse_lines_py(text, 1, 0))
