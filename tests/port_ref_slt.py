#!/usr/bin/env python
"""Port reference sqllogictest cases into the repo's slt dialect.

Reads the upstream corpus (standard sqllogictest format: multi-line SQL,
`----` result separators, arrow-rendered values) and emits
`tests/sqllogic_ref/*.slt` in this repo's single-line format, translating
the VALUE rendering, not the semantics:

  - quoted strings `"abc"`     → abc (CSV-escaped)
  - `NULL`                     → \\N   (empty cell marker)
  - ISO timestamps             → int64 ns (this engine's time rendering)
  - `(empty)`                  → empty string
  - error-message regexes      → dropped (we assert "an error", not the
                                 reference's gRPC error text — documented
                                 divergence D1)

Directives: `include` is inlined (converted recursively), `sleep` dropped,
`--#DATABASE=x` becomes create+use statements. `query ... rowsort` becomes
`querysort`, compared order-insensitively by the runner.

Usage: python tests/port_ref_slt.py <ref-case-file-or-dir>...
Output file name: ref_<family>_<stem>.slt
"""
from __future__ import annotations

import os
import re
import sys

REF_ROOT = "/root/reference/query_server/sqllogicaltests/cases"
OUT_DIR = os.path.join(os.path.dirname(__file__), "sqllogic_ref")

_TS_RE = re.compile(
    r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}(\.\d+)?$")
# non-integer numerics (decimal point or exponent): normalize arrow's
# rendering (1.0e-6) to this engine's repr() rendering (1e-06)
_FLOAT_RE = re.compile(
    r"^-?(?:\d+\.\d*|\.\d+)(?:[eE][+-]?\d+)?$|^-?\d+[eE][+-]?\d+$")
_TOKEN_RE = re.compile(r'"((?:[^"\\]|\\.)*)"|(\S+)')


def _ts_to_ns(tok: str) -> str:
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from cnosdb_tpu.sql.parser import parse_timestamp_string

    return str(parse_timestamp_string(tok))


def _convert_value(tok: str, quoted: bool) -> str:
    if not quoted:
        if tok == "NULL":
            return "\\N"
        if tok == "(empty)":
            return ""
        if _TS_RE.match(tok):
            return _ts_to_ns(tok)
        if _FLOAT_RE.match(tok):
            return repr(float(tok))
        return tok
    s = tok.replace('\\"', '"')
    if s == "NULL":
        return "\\N"          # string NULL renders quoted upstream
    if "," in s or '"' in s or "\n" in s:
        return '"' + s.replace('"', '""') + '"'
    return s


_INT_RE = re.compile(r"^-?\d+$")
_NUM_ANY_RE = re.compile(
    r"^-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?$")


def _regroup_tokens(toks: list[tuple[str, bool]]) -> list[tuple[str, bool]]:
    """Rejoin upstream values the whitespace tokenizer shredded:
    brace-balanced structs ('{first: {ts: ..., val: 4.0}, ...}') and
    arrow interval renderings ('0 years 0 mons ... 0.035000000 secs')
    become ONE cell each; interval seconds normalize through repr(float)
    like every other numeric."""
    out: list[tuple[str, bool]] = []
    i = 0
    n = len(toks)
    while i < n:
        tok, quoted = toks[i]
        if not quoted and tok.startswith("{"):
            depth = 0
            parts = []
            while i < n:
                t = toks[i][0]
                parts.append(t)
                depth += t.count("{") - t.count("}")
                i += 1
                if depth <= 0:
                    break
            out.append((" ".join(parts), True))
            continue
        if (not quoted and _INT_RE.match(tok) and i + 11 < n
                and [t[0] for t in toks[i + 1:i + 12:2]]
                == ["years", "mons", "days", "hours", "mins", "secs"]
                and all(_NUM_ANY_RE.match(toks[i + k][0])
                        for k in (2, 4, 6, 8))
                and _NUM_ANY_RE.match(toks[i + 10][0])):
            vals = [toks[i + k][0] for k in range(0, 12, 2)]
            secs = repr(float(vals[5]))
            cell = (f"{vals[0]} years {vals[1]} mons {vals[2]} days "
                    f"{vals[3]} hours {vals[4]} mins {secs} secs")
            out.append((cell, True))
            i += 12
            continue
        out.append((tok, quoted))
        i += 1
    return out


def _convert_row(line: str) -> str:
    toks = []
    for m in _TOKEN_RE.finditer(line):
        if m.group(1) is not None:
            toks.append((m.group(1), True))
        else:
            toks.append((m.group(2), False))
    cells = [_convert_value(tok, quoted)
             for tok, quoted in _regroup_tokens(toks)]
    return ",".join(cells)


def _join_sql(lines: list[str]) -> str:
    """Multi-line SQL → one line; strip trailing `;` and `--` comments."""
    parts = []
    for ln in lines:
        ln = ln.strip()
        if ln.startswith("--"):
            continue
        # sqlancer-style trailing timing comments (`...; -- 0ms`); a
        # quoted literal containing " -- " would be clipped, none exist
        # in the ported families
        ln = re.sub(r"\s--\s.*$", "", ln)
        if ln:
            parts.append(ln)
    sql = " ".join(parts)
    # external-table resources resolve relative to the upstream repo root
    sql = sql.replace("'query_server/sqllogicaltests/resource",
                      "'/root/reference/query_server/sqllogicaltests"
                      "/resource")
    return sql.rstrip(";").strip()


def parse_ref_slt(path: str) -> list:
    """→ [(kind, payload)]: kind ∈ ok|error|query|querysort|use|include."""
    with open(path) as f:
        lines = f.read().splitlines()
    out = []
    i, n = 0, len(lines)
    while i < n:
        raw = lines[i]
        line = raw.strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line.startswith("include "):
            out.append(("include", line[len("include "):].strip()))
            continue
        if line.startswith("sleep") or line == "halt":
            continue
        if line.startswith("--#DATABASE"):
            out.append(("use", line.split("=", 1)[1].strip()))
            continue
        if line.startswith("--#TENANT"):
            out.append(("usetenant", line.split("=", 1)[1].strip()))
            continue
        if line.startswith("--#USER_NAME"):
            out.append(("useuser", line.split("=", 1)[1].strip()))
            continue
        if line.startswith("--#"):
            continue
        if line.startswith("statement"):
            is_err = "error" in line.split()[1:2]
            sql_lines = []
            while i < n and lines[i].strip() != "":
                s = lines[i].strip()
                if s.startswith("--#DATABASE"):
                    out.append(("use", s.split("=", 1)[1].strip()))
                elif s.startswith("--#TENANT"):
                    out.append(("usetenant", s.split("=", 1)[1].strip()))
                elif s.startswith("--#USER_NAME"):
                    out.append(("useuser", s.split("=", 1)[1].strip()))
                elif s == "--#LP_BEGIN":
                    i += 1
                    while i < n and lines[i].strip() != "--#LP_END":
                        if lines[i].strip():
                            out.append(("lineproto", lines[i].strip()))
                        i += 1
                elif not s.startswith("--#"):
                    sql_lines.append(lines[i])
                i += 1
            sql = _join_sql(sql_lines)
            if sql:
                out.append(("error" if is_err else "ok", sql))
            continue
        if line.startswith("query"):
            head = line.split()
            is_err = len(head) > 1 and head[1] == "error"
            rowsort = head[-1] == "rowsort"
            sql_lines, expected = [], []
            while i < n and lines[i].strip() not in ("----",) \
                    and lines[i].strip() != "":
                sql_lines.append(lines[i])
                i += 1
            if i < n and lines[i].strip() == "----":
                i += 1
                while i < n and lines[i].strip() != "":
                    expected.append(lines[i])
                    i += 1
            sql = _join_sql(sql_lines)
            if not sql:
                continue
            if is_err:
                out.append(("error", sql))
            elif sql.lower().startswith("explain"):
                # plan text is engine-specific (divergence D3): pin that
                # EXPLAIN executes, not the rendering
                out.append(("ok", sql))
            else:
                kind = "querysort" if rowsort else "query"
                out.append((kind, (sql, [_convert_row(e)
                                         for e in expected])))
            continue
        # stray SQL outside a record (malformed upstream block): skip
    return out


def convert_file(path: str, seen=None) -> list[str]:
    """→ emitted lines (includes inlined)."""
    seen = seen or set()
    rp = os.path.realpath(path)
    if rp in seen:
        return []
    seen.add(rp)
    out_lines = []
    for kind, payload in parse_ref_slt(path):
        if kind == "include":
            inc = os.path.normpath(
                os.path.join(os.path.dirname(path), payload))
            out_lines.extend(convert_file(inc, seen))
        elif kind == "use":
            out_lines.append(f"usedb {payload}")
        elif kind == "usetenant":
            out_lines.append(f"usetenant {payload}")
        elif kind == "useuser":
            out_lines.append(f"useuser {payload}")
        elif kind == "lineproto":
            out_lines.append(f"lineproto {payload}")
        elif kind == "ok":
            out_lines.append(f"statement ok {payload}")
        elif kind == "error":
            out_lines.append(f"statement error {payload}")
        elif kind in ("query", "querysort"):
            sql, expected = payload
            out_lines.append(f"{kind} {sql}")
            out_lines.extend(expected)
            out_lines.append("")
    return out_lines


def main(argv):
    targets = []
    for a in argv or [os.path.join(REF_ROOT, "dql")]:
        if os.path.isdir(a):
            for root, _, files in os.walk(a):
                targets.extend(os.path.join(root, f)
                               for f in sorted(files) if f.endswith(".slt"))
        else:
            targets.append(a)
    os.makedirs(OUT_DIR, exist_ok=True)
    for t in targets:
        if "WINDOWS" in t:
            continue   # Windows-path duplicate of the UNIX case
        rel = os.path.relpath(t, REF_ROOT)
        stem = rel[:-4].replace(os.sep, "_").replace(".", "")
        name = f"ref_{stem}.slt"
        body = convert_file(t)
        lines = [
            f"# Ported from reference sqllogicaltests: cases/{rel}",
            "# (values translated to this engine's rendering — see",
            "#  tests/sqllogic_ref/DIVERGENCES.md)",
            "",
        ]
        if any("file:///tmp/data" in ln for ln in body):
            # exports accumulate part files; the case assumes a fresh dir
            lines.append("cleandir /tmp/data")
        lines += body
        with open(os.path.join(OUT_DIR, name), "w") as f:
            f.write("\n".join(lines).rstrip() + "\n")
        print(f"wrote {name} ({len(lines)} lines)")


if __name__ == "__main__":
    main(sys.argv[1:])
