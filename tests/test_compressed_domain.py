"""Compressed-domain execution lane (storage/compressed_domain.py).

Parity contract: everything the lane answers from the encoded
representation must be BIT-identical to the decode-lane oracle — the
property tests below drive randomized pages (NaN/±inf/denormals, int64
extremes, NULL runs, legacy v1 string pages) through both paths, and the
SQL-level suite A/Bs whole queries against `CNOSDB_COMPRESSED_DOMAIN=0`.
The cold-tier case additionally asserts the lane's point: strictly fewer
bytes fetched from the object store.
"""
import os
from types import SimpleNamespace

import numpy as np
import pytest

from cnosdb_tpu.models.codec import Encoding
from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.storage import codecs, compressed_domain as cd, tiering
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage

rng = np.random.default_rng(1234)


def _bits(x):
    """Exact-comparison key: floats by their bit pattern (NaN == NaN,
    -0.0 != 0.0 stays visible), everything else by value."""
    if isinstance(x, (float, np.floating)):
        return np.array([x], dtype=np.float64).view(np.uint64)[0]
    return x


# ---------------------------------------------------------------------------
# closed-form first/last vs the decode oracle
# ---------------------------------------------------------------------------
def _int_payloads():
    yield np.array([0], dtype=np.int64)
    yield np.array([2**63 - 1, -2**63, 0, -1, 1], dtype=np.int64)
    yield rng.integers(-2**62, 2**62, 257, dtype=np.int64)
    yield np.arange(1000, 5000, 7, dtype=np.int64)          # const stride
    yield np.full(100, -(2**63), dtype=np.int64)            # zero stride
    yield rng.integers(-5, 5, 64, dtype=np.int64).cumsum()


def test_closed_delta_first_last_int64():
    for vals in _int_payloads():
        blk = codecs.encode(vals, ValueType.INTEGER, Encoding.DELTA)
        plan, why = codecs.split_for_device(blk, ValueType.INTEGER)
        assert plan is not None, why
        first, last = cd._CLOSED[plan["kind"]]
        dec = codecs.decode(blk, ValueType.INTEGER)
        assert first(plan) == dec[0]
        assert last(plan) == dec[-1]
        assert isinstance(first(plan), np.int64)


def test_closed_delta_unsigned_wrap():
    vals = np.array([2**64 - 1, 0, 2**63, 17], dtype=np.uint64)
    blk = codecs.encode(vals, ValueType.UNSIGNED, Encoding.DELTA)
    plan, _ = codecs.split_for_device(blk, ValueType.UNSIGNED)
    dec = codecs.decode(blk, ValueType.UNSIGNED)
    first, last = cd._CLOSED[plan["kind"]]
    # lane reinterprets the wrapping-int64 closed form as uint64, exactly
    # like the decode lane's .view(uint64)
    assert np.uint64(int(first(plan)) & (2**64 - 1)) == dec[0]
    assert np.uint64(int(last(plan)) & (2**64 - 1)) == dec[-1]


def _float_payloads():
    yield np.array([0.0], dtype=np.float64)
    awkward = np.array([np.nan, np.inf, -np.inf, -0.0, 0.0,
                        5e-324, -5e-324, 2.2250738585072014e-308,
                        1.7976931348623157e308], dtype=np.float64)
    yield awkward
    v = rng.normal(size=311)
    v[::13] = np.nan
    v[7] = np.inf
    yield v
    yield np.full(64, np.nan)


def test_closed_gorilla_first_last_bitpattern():
    for vals in _float_payloads():
        blk = codecs.encode(vals, ValueType.FLOAT, Encoding.GORILLA)
        plan, why = codecs.split_for_device(blk, ValueType.FLOAT)
        assert plan is not None, why
        first, last = cd._CLOSED[plan["kind"]]
        dec = codecs.decode(blk, ValueType.FLOAT)
        assert _bits(first(plan)) == _bits(dec[0])
        assert _bits(last(plan)) == _bits(dec[-1])


def test_closed_bitpack_first_last():
    for n in (1, 7, 8, 9, 64, 65, 333):
        vals = rng.integers(0, 2, n).astype(bool)
        blk = codecs.encode(vals, ValueType.BOOLEAN, Encoding.BITPACK)
        plan, _ = codecs.split_for_device(blk, ValueType.BOOLEAN)
        first, last = cd._CLOSED[plan["kind"]]
        dec = codecs.decode(blk, ValueType.BOOLEAN)
        assert first(plan) == dec[0]
        assert last(plan) == dec[-1]


def test_time_value_at_prefix_sum():
    for ts in (np.arange(10**9, 10**9 + 500 * 7, 7, dtype=np.int64),
               np.sort(rng.integers(0, 10**12, 400)).astype(np.int64),
               np.array([42], dtype=np.int64)):
        blk = codecs.encode_timestamps(ts)
        plan, why = codecs.split_for_device(blk, ValueType.INTEGER)
        assert plan is not None, why
        for k in {0, len(ts) - 1, len(ts) // 2, len(ts) // 3}:
            assert cd._time_value_at(plan, k) == ts[k]


# ---------------------------------------------------------------------------
# straddling time-bucket counts, arithmetic vs bincount oracle
# ---------------------------------------------------------------------------
def _bucket_oracle(ts, origin, interval):
    b = (ts - origin) // interval
    lo = b.min()
    return np.bincount((b - lo).astype(np.int64)), int(lo)


@pytest.mark.parametrize("origin,interval", [(0, 1000), (17, 333),
                                             (-5000, 7777)])
def test_bucket_counts_const_stride(origin, interval):
    ts = np.arange(10_000, 10_000 + 350 * 97, 97, dtype=np.int64)
    blk = codecs.encode_timestamps(ts)
    plan, _ = codecs.split_for_device(blk, ValueType.INTEGER)
    assert plan["kind"] == "delta_const"
    lane = cd.ScanLane(
        cd.CompressedSpec((), (origin, interval), {}, {}), None, None)
    tp = SimpleNamespace(min_ts=int(ts[0]), max_ts=int(ts[-1]))
    counts, blo = lane._bucket_counts(plan, tp)
    want, wlo = _bucket_oracle(ts, origin, interval)
    assert blo == wlo
    np.testing.assert_array_equal(counts, want)
    assert counts.sum() == len(ts)


def test_bucket_counts_jittered_delta():
    ts = np.sort(rng.integers(0, 10**7, 500)).astype(np.int64)
    blk = codecs.encode_timestamps(ts)
    plan, _ = codecs.split_for_device(blk, ValueType.INTEGER)
    if plan["kind"] != "delta":
        pytest.skip("rng produced constant stride")
    lane = cd.ScanLane(
        cd.CompressedSpec((), (3, 12345), {}, {}), None, None)
    tp = SimpleNamespace(min_ts=int(ts[0]), max_ts=int(ts[-1]))
    counts, blo = lane._bucket_counts(plan, tp)
    want, wlo = _bucket_oracle(ts, 3, 12345)
    assert blo == wlo
    np.testing.assert_array_equal(counts, want)


# ---------------------------------------------------------------------------
# interval tri-state soundness (the predicate classifier)
# ---------------------------------------------------------------------------
def _eval_pred(op, val, x):
    if np.isnan(x):
        return op == "!=" if not isinstance(val, tuple) else False
    if op == "between":
        return val[0] <= x <= val[1]
    if op == "in":
        return any(x == v for v in val)
    return {"=": x == val, "!=": x != val, "<": x < val,
            "<=": x <= val, ">": x > val, ">=": x >= val}[op]


def test_interval_verdict_sound_int():
    for _ in range(200):
        vals = rng.integers(-50, 50, rng.integers(1, 30))
        lo, hi = int(vals.min()), int(vals.max())
        op = rng.choice(["=", "!=", "<", "<=", ">", ">=", "between", "in"])
        if op == "between":
            a, b = sorted(rng.integers(-60, 60, 2).tolist())
            pred = (a, b)
        elif op == "in":
            pred = rng.integers(-60, 60, 3).tolist()
        else:
            pred = int(rng.integers(-60, 60))
        v = cd._interval_verdict(op, pred, lo, hi, is_float=False)
        results = [_eval_pred(op, pred, float(x)) for x in vals]
        if v == cd._TRUE:
            assert all(results), (op, pred, vals)
        elif v == cd._FALSE:
            assert not any(results), (op, pred, vals)


def test_interval_verdict_sound_float_with_nan():
    for _ in range(200):
        vals = np.round(rng.normal(size=rng.integers(1, 30)) * 10, 1)
        has_nan = rng.random() < 0.5
        dense = np.concatenate([vals, [np.nan]]) if has_nan else vals
        lo, hi = float(np.nanmin(dense)), float(np.nanmax(dense))
        op = rng.choice(["=", "!=", "<", "<=", ">", ">="])
        pred = float(np.round(rng.normal() * 10, 1))
        v = cd._interval_verdict(op, pred, lo, hi, is_float=True)
        results = [_eval_pred(op, pred, x) for x in dense]
        # float pages may hide NaN rows the stats exclude: TRUE/FALSE
        # must hold over the dense stream INCLUDING them
        if v == cd._TRUE:
            assert all(results), (op, pred, dense)
        elif v == cd._FALSE:
            assert not any(results), (op, pred, dense)


# ---------------------------------------------------------------------------
# code-space row masks (dictionary strings, bitpacked bools, NULL runs)
# ---------------------------------------------------------------------------
class _FakeReader:
    def __init__(self, block, nm):
        self._block, self._nm = block, nm

    def read_field_page_split(self, pm):
        return self._block, self._nm


def _mask_lane():
    return cd.ScanLane(cd.CompressedSpec((), None, {}, {}), None, None)


def _string_page(values):
    """Encode object strings (None = NULL) → (reader, pm, oracle rows)."""
    vals = np.array(values, dtype=object)
    nulls = np.array([v is None for v in vals])
    dense = vals[~nulls]
    blk = codecs.encode(dense, ValueType.STRING, Encoding.ZSTD)
    nm = nulls if nulls.any() else None
    pm = SimpleNamespace(n_rows=len(vals), n_values=len(dense),
                         value_type=int(ValueType.STRING))
    return _FakeReader(blk, nm), pm, vals, nulls


def test_string_mask_eq_ne_in_with_nulls():
    words = ["alpha", "beta", "gamma", None, "alpha", None, "delta",
             "beta", "beta", "Ωμέγα"]
    r, pm, vals, nulls = _string_page(words)
    for ops, oracle in [
        ((("str_eq", "beta"),), lambda v: v == "beta"),
        ((("str_ne", "alpha"),), lambda v: v != "alpha"),
        ((("str_in", ("alpha", "Ωμέγα", "nope")),),
         lambda v: v in ("alpha", "Ωμέγα")),
        ((("str_ne", "alpha"), ("str_ne", "beta")),
         lambda v: v not in ("alpha", "beta")),
    ]:
        m = _mask_lane()._page_row_mask(r, pm, ValueType.STRING, ops)
        want = np.array([(not nulls[i]) and oracle(vals[i])
                         for i in range(len(vals))])
        np.testing.assert_array_equal(m, want)


def test_string_mask_v1_page_rejects_with_reason():
    # legacy v1 payload (no dict marker) wrapped in the container codec
    from cnosdb_tpu.utils.zstd_compat import zstandard

    lens = np.array([1, 2], dtype=np.uint32)
    v1 = np.uint32(2).tobytes() + lens.tobytes() + b"abb"
    blk = bytes([int(Encoding.ZSTD)]) \
        + zstandard.ZstdCompressor().compress(v1)
    dec = codecs.decode(blk, ValueType.STRING)
    dec = dec.materialize() if hasattr(dec, "materialize") else dec
    assert list(dec) == ["a", "bb"]
    pm = SimpleNamespace(n_rows=2, n_values=2,
                         value_type=int(ValueType.STRING))
    before = cd.outcomes_snapshot().get(("mat", "string_v1"), 0)
    m = _mask_lane()._page_row_mask(_FakeReader(blk, None), pm,
                                    ValueType.STRING, (("str_eq", "a"),))
    assert m is None   # sound: no mask keeps every row
    assert cd.outcomes_snapshot().get(("mat", "string_v1"), 0) == before + 1


def test_bool_mask_bitpack_with_nulls():
    flags = [True, False, None, True, None, False, True, True]
    nulls = np.array([f is None for f in flags])
    dense = np.array([f for f in flags if f is not None], dtype=bool)
    blk = codecs.encode(dense, ValueType.BOOLEAN, Encoding.BITPACK)
    pm = SimpleNamespace(n_rows=len(flags), n_values=len(dense),
                         value_type=int(ValueType.BOOLEAN))
    r = _FakeReader(blk, nulls)
    m = _mask_lane()._page_row_mask(r, pm, ValueType.BOOLEAN,
                                    (("bool_eq", True),))
    want = np.array([f is True for f in flags])
    np.testing.assert_array_equal(m, want)
    m = _mask_lane()._page_row_mask(r, pm, ValueType.BOOLEAN,
                                    (("bool_ne", True),))
    want = np.array([f is False for f in flags])
    np.testing.assert_array_equal(m, want)


# ---------------------------------------------------------------------------
# SQL-level A/B: lane on vs CNOSDB_COMPRESSED_DOMAIN=0, bit-identical
# ---------------------------------------------------------------------------
@pytest.fixture
def db(tmp_path):
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex, engine
    engine.close()


BASE = 1_672_531_200_000_000_000
DAY = 86_400_000_000_000


@pytest.fixture
def sealed(db):
    """Two hosts, four field types, NULL runs, sealed into TSM."""
    ex, engine = db
    ex.execute_one("CREATE TABLE m (ival BIGINT, fval DOUBLE, "
                   "status STRING, ok BOOLEAN, TAGS(host))")
    r = np.random.default_rng(77)
    rows = []
    for i in range(600):
        t = BASE + i * (DAY // 48)
        host = f"h{i % 2}"
        ival = int(r.integers(-1000, 1000)) if i % 7 else "NULL"
        fval = round(float(r.normal()), 3) if i % 5 else "NULL"
        status = ("'rare'" if i % 149 == 0 else
                  "'common'" if i % 3 else "NULL")
        ok = ("true" if i % 2 else "false") if i % 11 else "NULL"
        rows.append(f"({t}, '{host}', {ival}, {fval}, {status}, {ok})")
    ex.execute_one("INSERT INTO m (time, host, ival, fval, status, ok) "
                   "VALUES " + ", ".join(rows))
    engine.flush_all(sync=True)
    return ex


QUERIES = [
    "SELECT count(*) FROM m",
    "SELECT count(ival), count(fval), count(status) FROM m",
    "SELECT sum(ival), min(ival), max(ival) FROM m",
    "SELECT first(ival), last(ival) FROM m",
    "SELECT first(fval), last(fval), first(ok) FROM m",
    "SELECT host, count(*), sum(ival) FROM m GROUP BY host",
    "SELECT time_bucket(time, '1d') AS b, count(*), count(ival) "
    "FROM m GROUP BY b ORDER BY b",
    "SELECT count(*), sum(ival) FROM m WHERE status = 'rare'",
    "SELECT count(*) FROM m WHERE status != 'common'",
    "SELECT count(*), max(ival) FROM m WHERE ok = true",
    "SELECT count(*) FROM m WHERE ival BETWEEN -100 AND 100",
    "SELECT count(*) FROM m WHERE ival > 2000",         # page-FALSE
    "SELECT sum(ival) FROM m WHERE fval < 100.0",
    "SELECT host, time_bucket(time, '1d') AS b, count(*) FROM m "
    "WHERE status IN ('rare', 'missing') GROUP BY host, b ORDER BY b",
]


def _norm(rows):
    return sorted(tuple(_bits(c) for c in row) for row in rows)


def test_sql_parity_vs_decode_lane(sealed, monkeypatch):
    # lane pass FIRST: an oracle pass would seed the coordinator's scan
    # cache with full batches under the unfiltered key, which a spec'd
    # probe legitimately falls back to — and then nothing engages.
    # Engaged batches cache under a spec-extended key, so the oracle
    # pass below re-scans fresh (cache isolation is part of the test).
    ex = sealed
    before = cd.outcomes_snapshot()
    got = [_norm(ex.execute_one(q).rows()) for q in QUERIES]
    after = cd.outcomes_snapshot()
    answered = sum(n - before.get(k, 0) for k, n in after.items()
                   if k[0] in ("meta", "closed", "skip"))
    assert answered > 0, "lane never engaged on the sealed table"
    monkeypatch.setenv("CNOSDB_COMPRESSED_DOMAIN", "0")
    oracle = [_norm(ex.execute_one(q).rows()) for q in QUERIES]
    for q, a, b in zip(QUERIES, oracle, got):
        assert a == b, q


def test_sql_parity_unflushed_memcache_unaffected(db, monkeypatch):
    """Rows still in the memcache never classify; results stay exact."""
    ex, _engine = db
    ex.execute_one("CREATE TABLE w (v BIGINT, TAGS(k))")
    ex.execute_one("INSERT INTO w (time, k, v) VALUES "
                   + ", ".join(f"({BASE + i}, 'a', {i})" for i in range(50)))
    q = "SELECT count(v), sum(v), first(v), last(v) FROM w"
    got = _norm(ex.execute_one(q).rows())
    monkeypatch.setenv("CNOSDB_COMPRESSED_DOMAIN", "0")
    assert _norm(ex.execute_one(q).rows()) == got


# ---------------------------------------------------------------------------
# cold tier: answered pages are never downloaded
# ---------------------------------------------------------------------------
def _cold_schema():
    return {"cpu": TskvTableSchema.new_measurement(
        "t", "db", "cpu", tags=["host"],
        fields=[("val", ValueType.INTEGER)])}


def _cold_vnode(tmp_path, monkeypatch):
    """1500 rows, val == row index, split into 100-row pages (small
    max_page_rows so page-level verdicts are visible), tiered cold."""
    from cnosdb_tpu.storage import tsm

    orig = tsm.TsmWriter.__init__

    def small_pages(self, path, max_page_rows=100):
        orig(self, path, max_page_rows=100)

    monkeypatch.setattr(tsm.TsmWriter, "__init__", small_pages)
    v = VnodeStorage(1, str(tmp_path / "vn"), schemas=_cold_schema())
    for i in range(5):
        lo = i * 300
        wb = WriteBatch()
        wb.add_series("cpu", SeriesRows(
            SeriesKey("cpu", {"host": "h1"}),
            list(range(lo, lo + 300)),
            {"val": (int(ValueType.INTEGER),
                     [int(x) for x in range(lo, lo + 300)])}))
        v.write(wb)
        v.flush()
    v.compact_full()
    n = tiering.tier_vnode(v, boundary_ns=10**18)
    assert n >= 1
    return v


def _downloaded():
    return tiering.cold_tier_snapshot().get(("fetch", "bytes_downloaded"),
                                            0)


def test_cold_scan_parity_fewer_bytes(tmp_path, monkeypatch):
    """Selective predicate over cold pages: provably-false pages are
    never downloaded, provably-true pages answer from metadata, only the
    straddling page materializes — strictly fewer fetched bytes with the
    result bit-identical to the full-scan oracle."""
    store = tmp_path / "bucket"
    store.mkdir()
    tiering.configure(str(store))
    try:
        v = _cold_vnode(tmp_path, monkeypatch)
        spec = cd.CompressedSpec(
            (("count", None, "c"),), None,
            {"val": [(">", 1200)]}, {"val": ValueType.INTEGER})

        tiering.block_cache_clear()
        tiering.counters_reset()
        b0 = scan_vnode(v, "cpu", field_names=["val"])
        oracle_bytes = _downloaded()
        assert oracle_bytes > 0
        vals, valid = b0.fields["val"][1], b0.fields["val"][2]
        dense = np.asarray(vals)[np.asarray(valid)]
        oracle_count = int((dense > 1200).sum())
        assert oracle_count == 299

        tiering.block_cache_clear()
        tiering.counters_reset()
        b1 = scan_vnode(v, "cpu", field_names=["val"],
                        compressed_spec=spec)
        lane_bytes = _downloaded()
        cp = getattr(b1, "compressed_partials", None)
        assert cp, "lane did not answer any cold page"
        got = sum(int(p.get("c", 0)) for p in cp["rows"].values())
        # the straddling [1200, 1299] page materialized: count its
        # surviving rows the way the executor's re-applied filter would
        v1, m1 = b1.fields["val"][1], b1.fields["val"][2]
        got += int((np.asarray(v1)[np.asarray(m1)] > 1200).sum())
        assert got == oracle_count
        # skipped pages were never fetched; answered pages counted from
        # metadata alone — strictly fewer object-store bytes
        assert 0 < lane_bytes < oracle_bytes, (lane_bytes, oracle_bytes)
    finally:
        tiering.configure(None)
        tiering.counters_reset()
        tiering.block_cache_clear()


def test_cold_scan_stats_only_downloads_nothing(tmp_path, monkeypatch):
    """count/sum/min/max over every page need no page bytes: zero GETs."""
    store = tmp_path / "bucket2"
    store.mkdir()
    tiering.configure(str(store))
    try:
        v = _cold_vnode(tmp_path, monkeypatch)
        spec = cd.CompressedSpec(
            (("count", None, "c"), ("sum", "val", "s"),
             ("min", "val", "lo"), ("max", "val", "hi")),
            None, {}, {"val": ValueType.INTEGER})
        tiering.block_cache_clear()
        tiering.counters_reset()
        b = scan_vnode(v, "cpu", field_names=["val"],
                       compressed_spec=spec)
        cp = getattr(b, "compressed_partials", None)
        assert cp
        assert b.n_rows == 0
        parts: dict = {}
        for p in cp["rows"].values():
            for func, col, alias in spec.aggs:
                if alias in p:
                    cd._fold_partial(parts, func, alias, p[alias])
        assert int(parts["c"]) == 1500
        assert int(parts["s"]) == sum(range(1500))
        assert int(parts["lo"]) == 0 and int(parts["hi"]) == 1499
        assert _downloaded() == 0
    finally:
        tiering.configure(None)
        tiering.counters_reset()
        tiering.block_cache_clear()
