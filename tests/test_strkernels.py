"""String/search plane (ops/strkernels + the planes it feeds).

Parity is the contract everywhere: the vectorized per-unique lanes, the
n-gram page skipper and the device top-K must be bit-identical to the
host paths they replace — the property tests below drive randomized
patterns (wildcards, regex metachars, unicode, empty strings, trailing
newlines) through both and diff the outputs, and the skipper is checked
against a never-drops-a-matching-page oracle with the index disabled.
"""
import os
import re

import numpy as np
import pytest

from cnosdb_tpu.models.strcol import (DictArray, dict_encode_strict,
                                      unify_dictionaries)
from cnosdb_tpu.ops import strkernels
from cnosdb_tpu.utils import stages


@pytest.fixture
def rng():
    return np.random.default_rng(20260805)


# alphabet stresses every lane: wildcards, regex metachars the translator
# must escape, multi-byte unicode, and the `$`-quirk newline
_ALPHA = list("ab%_.*+()[^\\") + ["é", "日", "\n", ""]


def _rand_strings(rng, n, maxlen=6):
    out = []
    for _ in range(n):
        k = int(rng.integers(0, maxlen))
        out.append("".join(rng.choice(_ALPHA) for _ in range(k)))
    return np.array(out, dtype=object)


def _host_like(pattern):
    """From-scratch reference for the host LIKE automaton (mirrors
    sql.expr.Like._compile deliberately, quirk and all)."""
    out = []
    for ch in pattern:
        out.append(".*" if ch == "%" else "." if ch == "_"
                   else re.escape(ch))
    rx = re.compile("^" + "".join(out) + "$", re.DOTALL)
    return lambda s: bool(rx.match(s))


# ---------------------------------------------------------------- classify
def test_classify_kinds():
    assert strkernels.classify("abc") == ("exact", "abc")
    assert strkernels.classify("abc%") == ("prefix", "abc")
    assert strkernels.classify("%abc") == ("suffix", "abc")
    assert strkernels.classify("%abc%") == ("contains", "abc")
    assert strkernels.classify("%%abc%%") == ("contains", "abc")
    assert strkernels.classify("") == ("exact", "")
    assert strkernels.classify("%") == ("suffix", "")
    assert strkernels.classify("%%") == ("suffix", "")
    # `_` anywhere, or an interior `%`, forces the regex lane
    assert strkernels.classify("a_c")[0] == "generic"
    assert strkernels.classify("a%c")[0] == "generic"
    assert strkernels.classify("%a%c%")[0] == "generic"


# -------------------------------------------------- per-unique mask parity
def test_unique_mask_matches_host_like_property(rng):
    for _ in range(60):
        values = np.array(sorted(set(_rand_strings(rng, 40).tolist())),
                          dtype=object)
        k = int(rng.integers(0, 5))
        pattern = "".join(rng.choice(_ALPHA) for _ in range(k))
        want = np.array([_host_like(pattern)(v) for v in values])
        got, reason = strkernels.unique_mask(values, pattern)
        np.testing.assert_array_equal(
            got, want, err_msg=f"pattern={pattern!r} ({reason})")


def test_unique_mask_trailing_newline_quirk():
    values = np.array(["abc", "abc\n", "abc\n\n", "xabc", "abcx"],
                      dtype=object)
    for pattern, want in [
        ("abc", [True, True, False, False, False]),     # $ eats one \n
        ("%abc", [True, True, False, True, False]),
        ("abc%", [True, True, True, False, True]),      # prefix: no quirk
        ("%abc%", [True, True, True, True, True]),
    ]:
        got, _ = strkernels.unique_mask(values, pattern)
        assert got.tolist() == want, pattern


def test_like_rows_negation_and_lane_ab(rng, monkeypatch):
    values = np.array(sorted({"", "ab", "abc", "abc\n", "xaby", "日本"}),
                      dtype=object)
    codes = rng.integers(0, len(values), 200).astype(np.int32)
    da = DictArray(codes, values)
    for pattern in ["ab%", "%b%", "_b_", "", "%", "日%"]:
        for negated in (False, True):
            fast = strkernels.like_rows(da, pattern, negated=negated)
            ref = np.array([_host_like(pattern)(v)
                            for v in da.materialize()])
            np.testing.assert_array_equal(
                fast, ~ref if negated else ref,
                err_msg=f"pattern={pattern!r} negated={negated}")


def test_like_eval_e2e_lane_ab_with_nulls(db, monkeypatch):
    """Full pipeline A/B: the dictionary lane (default) vs the per-row
    host fallback (CNOSDB_STR_LANE=0) must return identical rows, NULLs
    and NOT LIKE included."""
    db.execute_one("CREATE TABLE logs (body STRING, n BIGINT, TAGS(svc))")
    rows = []
    bodies = ["error: timeout", "ok", "error: disk", None, "warn", ""]
    for i, b in enumerate(bodies * 5):
        t = 1672531200000000000 + i * 1_000_000_000
        sv = "'" + b + "'" if b is not None else "NULL"
        rows.append(f"({t}, 's{i % 2}', {sv}, {i})")
    db.execute_one("INSERT INTO logs (time, svc, body, n) VALUES "
                   + ", ".join(rows))
    for sql in [
        "SELECT count(*) FROM logs WHERE body LIKE '%error%'",
        "SELECT count(*) FROM logs WHERE body NOT LIKE '%error%'",
        "SELECT time, body FROM logs WHERE body LIKE 'e%r: __me%' "
        "ORDER BY time",
        "SELECT svc, count(*) FROM logs WHERE body LIKE '%o%' "
        "GROUP BY svc ORDER BY svc",
    ]:
        monkeypatch.setenv("CNOSDB_STR_LANE", "1")
        fast = db.execute_one(sql, _session()).rows()
        monkeypatch.setenv("CNOSDB_STR_LANE", "0")
        slow = db.execute_one(sql, _session()).rows()
        assert fast == slow, sql


# --------------------------------------------------------- per-unique cmp
def test_per_unique_cmp_e2e(db, monkeypatch):
    db.execute_one("CREATE TABLE urls (url STRING, TAGS(site))")
    vals = [f"http://h{i % 7}/p{i % 11}" for i in range(40)] \
        + [f"ftp://h{i}" for i in range(5)]
    rows = [f"({1672531200000000000 + i * 1_000_000_000}, 's', '{u}')"
            for i, u in enumerate(vals)]
    db.execute_one("INSERT INTO urls (time, site, url) VALUES "
                   + ", ".join(rows))
    for sql in [
        "SELECT count(*) FROM urls WHERE substr(url, 1, 4) = 'http'",
        "SELECT count(*) FROM urls WHERE lower(url) != upper(url)",
        "SELECT count(*) FROM urls WHERE length(url) > 12",
    ]:
        prof = stages.QueryProfile()
        monkeypatch.setenv("CNOSDB_STR_LANE", "1")
        with stages.profile_scope(prof):
            fast = db.execute_one(sql, _session()).rows()
        monkeypatch.setenv("CNOSDB_STR_LANE", "0")
        slow = db.execute_one(sql, _session()).rows()
        assert fast == slow, sql
        assert prof.snapshot().get("string_path.per_unique", 0) > 0, sql


# ------------------------------------------------------------ n-gram index
def test_trigram_soundness_property(rng):
    """host-LIKE match ⇒ required_trigrams(pattern) ⊆ value trigrams.
    This is the invariant page skipping rests on."""
    for _ in range(200):
        k = int(rng.integers(0, 8))
        pattern = "".join(rng.choice(_ALPHA) for _ in range(k))
        req = strkernels.required_trigrams(pattern)
        if req is None:
            continue
        for v in _rand_strings(rng, 20, maxlen=10):
            if _host_like(pattern)(v):
                have = set(strkernels._trigrams(
                    v.encode("utf-8", "surrogatepass")))
                assert set(req) <= have, (pattern, v)


def test_signature_never_rejects_a_matching_page(rng):
    for _ in range(80):
        uniques = _rand_strings(rng, 12, maxlen=8)
        sig = strkernels.build_page_signature(uniques)
        k = int(rng.integers(1, 6))
        pattern = "%" + "".join(rng.choice(_ALPHA) for _ in range(k)) + "%"
        req = strkernels.required_trigrams(pattern)
        if any(_host_like(pattern)(v) for v in uniques):
            assert strkernels.signature_admits(sig, req), \
                (pattern, uniques.tolist())


def test_signature_edges():
    # no value reaches 3 bytes → b"" → any trigram probe prunes
    sig = strkernels.build_page_signature(np.array(["ab", "", "xy"],
                                                   dtype=object))
    assert sig == b""
    assert not strkernels.signature_admits(sig, (b"abc",))
    # legacy page (pre-signature file) always admits
    assert strkernels.signature_admits(None, (b"abc",))
    # empty probe set admits anything
    assert strkernels.signature_admits(sig, ())
    assert strkernels.signature_admits(b"", None)
    # multi-byte unicode spans several byte-trigrams and must round-trip
    sig = strkernels.build_page_signature(np.array(["日本語"], dtype=object))
    assert strkernels.signature_admits(
        sig, strkernels.required_trigrams("%日本%"))
    # patterns with no 3-byte literal run can't probe at all
    assert strkernels.required_trigrams("%ab%") is None
    assert strkernels.required_trigrams("a_c") is None
    assert strkernels.required_trigrams("%") is None


def test_pagemeta_signature_roundtrip(tmp_path):
    from cnosdb_tpu.models.codec import Encoding
    from cnosdb_tpu.models.schema import ValueType
    from cnosdb_tpu.storage.tsm import PageMeta, TsmReader, TsmWriter

    p = str(tmp_path / "sig.tsm")
    w = TsmWriter(p)
    ts = np.arange(10, dtype=np.int64)
    strs = np.array([f"needle_{i}" for i in range(10)], dtype=object)
    w.write_series("t", 5, ts, {
        "s": (1, ValueType.STRING, Encoding.ZSTD, strs, None),
        "f": (2, ValueType.FLOAT, Encoding.GORILLA,
              np.arange(10.0), None),
    })
    w.finish()
    r = TsmReader(p)
    pm = r.chunk("t", 5).column("s").pages[0]
    assert isinstance(pm.ngram, bytes) and len(pm.ngram) > 0
    assert strkernels.signature_admits(
        pm.ngram, strkernels.required_trigrams("%needle%"))
    assert not strkernels.signature_admits(
        pm.ngram, strkernels.required_trigrams("%haystack%"))
    # numeric pages carry no signature
    assert r.chunk("t", 5).column("f").pages[0].ngram is None
    r.close()
    # a 12-field list (pre-signature file) hydrates with ngram=None
    legacy = PageMeta.from_list(pm.to_list()[:12])
    assert legacy.ngram is None


def test_ngram_scan_never_drops_matching_pages(tmp_path, rng):
    """E2E oracle: the pruned scan (device-decode lane engaged, signatures
    live) returns exactly the batch the index-disabled scan returns,
    while provably skipping pages."""
    from cnosdb_tpu.models.points import SeriesRows, WriteBatch
    from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
    from cnosdb_tpu.models.series import SeriesKey
    from cnosdb_tpu.ops import device_decode
    from cnosdb_tpu.sql.expr import Column, Like
    from cnosdb_tpu.storage.scan import _page_constraints, scan_vnode
    from cnosdb_tpu.storage.vnode import VnodeStorage

    schemas = {"m": TskvTableSchema.new_measurement(
        "t", "db", "m", tags=["host"],
        fields=[("s", ValueType.STRING)])}
    v = VnodeStorage(1, str(tmp_path), schemas=schemas)
    # several flushes → several pages; the needle lives in ONE of them
    for base, words in [(0, ["alpha", "beta"]), (5000, ["rare_needle"]),
                        (10000, ["gamma", "delta"])]:
        n = 1500
        wb = WriteBatch()
        wb.add_series("m", SeriesRows(
            SeriesKey("m", {"host": "h"}), list(range(base, base + n)),
            {"s": (int(ValueType.STRING),
                   [words[i % len(words)] for i in range(n)])}))
        v.write(wb)
        v.flush()
    flt = Like(Column("s"), "%rare_needle%")
    cons = _page_constraints(flt, ["s"])
    assert any(c[0] == "ngram" for c in cons.get("s", ())), cons

    def run(skip_on):
        os.environ["CNOSDB_NGRAM_SKIP"] = "1" if skip_on else "0"
        prof = stages.QueryProfile()
        try:
            with stages.profile_scope(prof):
                b = scan_vnode(
                    v, "m",
                    page_constraints=_page_constraints(flt, ["s"]),
                    decode_hook=lambda: device_decode.DeviceDecodeLane(
                        interpret=True))
        finally:
            del os.environ["CNOSDB_NGRAM_SKIP"]
        return b, prof.snapshot().get("ngram_pages_skipped", 0)

    pruned, skipped = run(True)
    oracle, skipped_off = run(False)
    assert skipped > 0 and skipped_off == 0

    def matching_rows(b):
        """(ts, value) pairs the LIKE actually selects — the only rows a
        pruned batch is contracted to preserve."""
        vals = b.fields["s"][1]
        vals = np.asarray(vals.materialize()
                          if isinstance(vals, DictArray) else vals)
        like = _host_like("%rare_needle%")
        keep = np.array([like(x) for x in vals])
        return list(zip(b.ts[keep].tolist(), vals[keep].tolist()))

    assert matching_rows(pruned) == matching_rows(oracle)
    assert len(matching_rows(pruned)) == 1500
    # pruning actually shrank the decode set: only the needle page decoded
    assert pruned.n_rows < oracle.n_rows
    v.close()


# ------------------------------------------------------------- LIKE domain
def test_like_domain_algebra_and_wire():
    from cnosdb_tpu.models.predicate import (AllDomain, LikeDomain,
                                             NoneDomain, RangeDomain,
                                             SetDomain, domain_from_wire,
                                             domain_to_wire)

    d = LikeDomain("%err%")
    assert d.contains_value("an error") and not d.contains_value("ok")
    assert not d.contains_value(7)   # non-strings never match
    got = d.intersect(SetDomain(["xerrx", "nope"]))
    assert isinstance(got, SetDomain) and got.values == SetDomain(
        ["xerrx"]).values
    assert isinstance(d.intersect(SetDomain(["nope"])), NoneDomain)
    r = RangeDomain.of("a", True, "z", True)
    assert r.intersect(d) is r           # sound over-approximation
    assert isinstance(r.union(d), AllDomain)
    assert isinstance(d.union(NoneDomain()), LikeDomain)
    rt = domain_from_wire(domain_to_wire(d))
    assert rt == d


def test_like_domain_regex_matches_host_compile(rng):
    from cnosdb_tpu.models.predicate import LikeDomain

    for _ in range(40):
        k = int(rng.integers(0, 6))
        pattern = "".join(rng.choice(_ALPHA) for _ in range(k))
        dom = LikeDomain(pattern)
        for v in _rand_strings(rng, 15):
            assert dom.contains_value(v) == _host_like(pattern)(v), \
                (pattern, v)


def test_extract_like_pushdown_domains():
    from cnosdb_tpu.models.predicate import LikeDomain, SetDomain
    from cnosdb_tpu.sql.expr import Column, Like, extract_domains

    # wildcard-free → exact set incl. the trailing-newline twin
    doms = extract_domains(Like(Column("t"), "abc"), {"t"})
    d = doms.domains["t"]
    assert isinstance(d, SetDomain) and set(d.values) == {"abc", "abc\n"}
    doms = extract_domains(Like(Column("t"), "ab%"), {"t"})
    assert isinstance(doms.domains["t"], LikeDomain)
    # negated patterns must NOT constrain the column
    doms = extract_domains(
        Like(Column("t"), "ab%", negated=True), {"t"})
    assert "t" not in doms.domains


def test_tag_like_pushdown_e2e(db):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(host))")
    rows = []
    for i, h in enumerate(["web-1", "web-2", "db-1", "cache-1"]):
        rows.append(f"({1672531200000000000 + i}, '{h}', {i}.0)")
    db.execute_one("INSERT INTO m (time, host, v) VALUES "
                   + ", ".join(rows))
    rs = db.execute_one(
        "SELECT host, v FROM m WHERE host LIKE 'web%' ORDER BY host",
        _session())
    assert rs.rows() == [("web-1", 0.0), ("web-2", 1.0)]
    rs = db.execute_one(
        "SELECT count(*) FROM m WHERE host LIKE '%-1'", _session())
    assert rs.rows() == [(3,)]


# ------------------------------------------------------------- device top-K
def test_topk_order_indices_matches_lexsort_property(rng):
    for _ in range(200):
        n = int(rng.integers(2, 60))
        k = int(rng.integers(1, n))
        asc = bool(rng.integers(0, 2))
        if rng.integers(0, 2):
            vals = rng.integers(-5, 5, n)       # dense ties
        else:
            vals = rng.normal(size=n).round(1)
        got = strkernels.topk_order_indices(vals, None, asc, k)
        assert got is not None
        ref = np.lexsort((vals,))
        if not asc:
            ref = ref[::-1]
        np.testing.assert_array_equal(got, ref[:k],
                                      err_msg=f"asc={asc} k={k}")


def test_topk_declines():
    vals = np.arange(10.0)

    def declined(*a):
        prof = stages.QueryProfile()
        with stages.profile_scope(prof):
            out = strkernels.topk_order_indices(*a)
        return out is None and prof.snapshot().get("topk.declined", 0) > 0

    assert declined(vals, np.zeros(10, bool) | (np.arange(10) == 3),
                    True, 2)                       # NULLs present
    assert declined(np.array([1.0, np.nan, 2.0]), None, True, 1)
    assert declined(np.array(["a", "b"], dtype=object), None, True, 1)
    nat = np.array(["2020-01-01", "NaT"], dtype="datetime64[ns]")
    assert declined(nat, None, True, 1)
    assert strkernels.topk_order_indices(vals, None, True, 0) is None
    assert strkernels.topk_order_indices(vals, None, True, 10) is None
    # clean datetimes are eligible
    ts = np.array(["2020-01-02", "2020-01-01", "2020-01-03"],
                  dtype="datetime64[ns]")
    got = strkernels.topk_order_indices(ts, None, True, 2)
    np.testing.assert_array_equal(got, [1, 0])


def test_topk_e2e_order_limit(db):
    db.execute_one("CREATE TABLE hits (d BIGINT, TAGS(page))")
    rows = []
    for i in range(50):
        rows.append(f"({1672531200000000000 + i * 1000000}, "
                    f"'p{i % 7}', {(i * 37) % 50})")
    db.execute_one("INSERT INTO hits (time, page, d) VALUES "
                   + ", ".join(rows))
    sql = ("SELECT page, max(d) AS m FROM hits GROUP BY page "
           "ORDER BY m DESC LIMIT 3")
    prof = stages.QueryProfile()
    with stages.profile_scope(prof):
        rs = db.execute_one(sql, _session())
    snap = prof.snapshot()
    assert snap.get("topk.host", 0) + snap.get("topk.device", 0) > 0
    ms = [r[1] for r in rs.rows()]
    assert ms == sorted(ms, reverse=True) and len(ms) == 3


# ------------------------------------------- dictionary machinery parity
def test_unify_dictionaries_matches_np_unique(rng):
    das = []
    for _ in range(4):
        vals = np.array(sorted(set(_rand_strings(rng, 20).tolist())),
                        dtype=object)
        das.append(DictArray(
            rng.integers(0, len(vals), 30).astype(np.int32), vals))
    das.append(DictArray(das[0].codes.copy(), das[0].values))  # shared dict
    union = unify_dictionaries(das)
    want = np.unique(np.concatenate([d.values for d in das]))
    np.testing.assert_array_equal(union, want)
    assert union.dtype == object


def test_dict_encode_strict_parity(rng):
    vals = _rand_strings(rng, 300, maxlen=4)
    enc = dict_encode_strict(vals)
    if enc is None:   # pyarrow absent in this env: fallback path covers
        pytest.skip("pyarrow unavailable")
    np.testing.assert_array_equal(enc.materialize(), vals)
    # values sorted + codes are ranks (the DictArray invariant)
    assert list(enc.values) == sorted(set(vals.tolist()))
    # nulls and non-strings refuse (caller falls back to np.unique)
    assert dict_encode_strict(np.array(["a", None], dtype=object)) is None
    assert dict_encode_strict(np.arange(3)) is None


def test_group_indices_dict_vs_legacy(rng):
    from cnosdb_tpu.sql.relational import group_indices

    vals = np.array(["x", "y", "z\x00", "z"], dtype=object)
    obj = vals[rng.integers(0, 4, 500)]
    da = dict_encode_strict(obj)
    nums = rng.integers(0, 3, 500)
    gid_obj, rep_obj = group_indices([obj, nums], 500)
    np.testing.assert_array_equal(obj[rep_obj][gid_obj], obj)
    np.testing.assert_array_equal(nums[rep_obj][gid_obj], nums)
    if da is not None:
        gid_da, rep_da = group_indices([da, nums], 500)
        np.testing.assert_array_equal(gid_obj, gid_da)
        np.testing.assert_array_equal(rep_obj, rep_da)


# ------------------------------------------------------- fallback booking
def test_every_fallback_books_a_reason(monkeypatch):
    base = dict(strkernels.outcomes_snapshot())
    monkeypatch.setenv("CNOSDB_STR_LANE", "0")
    assert not strkernels.enabled()
    monkeypatch.setenv("CNOSDB_STR_LANE", "1")
    values = np.array([1, 2, None], dtype=object)   # non-string uniques
    strkernels.unique_mask(values, "a%")
    snap = strkernels.outcomes_snapshot()
    key = ("per_unique", "non_string_uniques")
    assert snap.get(key, 0) > base.get(key, 0)
    assert all(isinstance(p, str) and isinstance(r, str)
               for p, r in snap)


def _session():
    from cnosdb_tpu.sql.executor import Session

    return Session(database="public")


@pytest.fixture
def db(tmp_path):
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()
