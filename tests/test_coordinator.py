import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.predicate import ColumnDomains, SetDomain, TimeRange, TimeRanges
from cnosdb_tpu.models.schema import (
    DatabaseOptions, DatabaseSchema, Duration, ValueType,
)
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore, DEFAULT_TENANT
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.errors import DatabaseNotFound

DAY = 86_400_000_000_000


@pytest.fixture
def cluster(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    yield meta, engine, coord
    engine.close()


def _write(coord, host, ts_list, vals, table="cpu", db="public"):
    wb = WriteBatch()
    wb.add_series(table, SeriesRows(
        SeriesKey(table, {"host": host}), list(ts_list),
        {"usage": (int(ValueType.FLOAT), list(vals))}))
    coord.write_points(DEFAULT_TENANT, db, wb)


def test_write_creates_schema_and_bucket(cluster):
    meta, engine, coord = cluster
    _write(coord, "h1", [10, 20], [1.0, 2.0])
    schema = meta.table(DEFAULT_TENANT, "public", "cpu")
    assert schema.tag_names() == ["host"]
    assert schema.field_names() == ["usage"]
    assert len(meta.buckets_for(DEFAULT_TENANT, "public")) == 1
    batches = coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    assert sum(b.n_rows for b in batches) == 2


def test_schema_evolution_on_write(cluster):
    meta, engine, coord = cluster
    _write(coord, "h1", [10], [1.0])
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": "h1", "rack": "r1"}), [20],
        {"usage": (int(ValueType.FLOAT), [2.0]),
         "temp": (int(ValueType.FLOAT), [55.0])}))
    coord.write_points(DEFAULT_TENANT, "public", wb)
    schema = meta.table(DEFAULT_TENANT, "public", "cpu")
    assert "rack" in schema.tag_names()
    assert "temp" in schema.field_names()


def test_multi_bucket_split(cluster):
    meta, engine, coord = cluster
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "db2",
        DatabaseOptions(vnode_duration=Duration.parse("1d"))))
    # rows across 3 days → 3 buckets
    _write(coord, "h1", [0, DAY + 5, 2 * DAY + 5], [1.0, 2.0, 3.0], db="db2")
    assert len(meta.buckets_for(DEFAULT_TENANT, "db2")) == 3
    batches = coord.scan_table(DEFAULT_TENANT, "db2", "cpu")
    assert sum(b.n_rows for b in batches) == 3
    # time-pruned scan only touches one bucket's vnode
    batches = coord.scan_table(
        DEFAULT_TENANT, "db2", "cpu",
        time_ranges=TimeRanges([TimeRange(DAY, 2 * DAY - 1)]))
    assert sum(b.n_rows for b in batches) == 1


def test_shard_split(cluster):
    meta, engine, coord = cluster
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "sharded", DatabaseOptions(shard_num=4)))
    wb = WriteBatch()
    for i in range(40):
        wb.add_series("cpu", SeriesRows(
            SeriesKey("cpu", {"host": f"h{i}"}), [1],
            {"usage": (int(ValueType.FLOAT), [float(i)])}))
    coord.write_points(DEFAULT_TENANT, "sharded", wb)
    buckets = meta.buckets_for(DEFAULT_TENANT, "sharded")
    assert len(buckets) == 1 and len(buckets[0].shard_group) == 4
    owner = f"{DEFAULT_TENANT}.sharded"
    used = engine.local_vnodes(owner)
    assert len(used) > 1  # series spread over shards
    assert sum(v.series_count() for v in used) == 40
    batches = coord.scan_table(DEFAULT_TENANT, "sharded", "cpu")
    assert sum(b.n_rows for b in batches) == 40


def test_tag_domain_pushdown(cluster):
    meta, engine, coord = cluster
    for h in ("h1", "h2", "h3"):
        _write(coord, h, [1, 2], [1.0, 2.0])
    batches = coord.scan_table(
        DEFAULT_TENANT, "public", "cpu",
        tag_domains=ColumnDomains.of("host", SetDomain(["h2"])))
    assert sum(b.n_rows for b in batches) == 2
    assert all(b.n_series == 1 for b in batches)


def test_unknown_database_rejected(cluster):
    meta, engine, coord = cluster
    with pytest.raises(DatabaseNotFound):
        _write(coord, "h1", [1], [1.0], db="nope")


def test_meta_persistence(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    meta.create_database(DatabaseSchema(DEFAULT_TENANT, "mydb",
                                        DatabaseOptions(shard_num=2)))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    _write(coord, "h1", [5], [1.0], db="mydb")
    engine.close()
    meta2 = MetaStore(str(tmp_path / "meta.json"))
    assert meta2.database(DEFAULT_TENANT, "mydb").options.shard_num == 2
    assert meta2.table(DEFAULT_TENANT, "mydb", "cpu").field_names() == ["usage"]
    assert len(meta2.buckets_for(DEFAULT_TENANT, "mydb")) == 1
    engine2 = TsKv(str(tmp_path / "data"))
    engine2.open_existing()
    coord2 = Coordinator(meta2, engine2)
    batches = coord2.scan_table(DEFAULT_TENANT, "mydb", "cpu")
    assert sum(b.n_rows for b in batches) == 1
    engine2.close()


def test_drop_table_and_database(cluster):
    meta, engine, coord = cluster
    _write(coord, "h1", [1], [1.0])
    coord.drop_table(DEFAULT_TENANT, "public", "cpu")
    assert coord.scan_table(DEFAULT_TENANT, "public", "cpu") == []
    assert "cpu" not in meta.list_tables(DEFAULT_TENANT, "public")


def test_tag_values_and_series_keys(cluster):
    meta, engine, coord = cluster
    for h in ("b", "a", "c"):
        _write(coord, h, [1], [1.0])
    assert coord.tag_values(DEFAULT_TENANT, "public", "cpu", "host") == ["a", "b", "c"]
    keys = coord.series_keys(DEFAULT_TENANT, "public", "cpu")
    assert [k.tag_value("host") for k in keys] == ["a", "b", "c"]


def test_multi_bucket_split_array_native(cluster):
    """Array-form SeriesRows straddling a bucket boundary: the fancy-index
    take() path must route rows identically to the list path."""
    meta, engine, coord = cluster
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "db3",
        DatabaseOptions(vnode_duration=Duration.parse("1d"))))
    ts = np.array([5, DAY + 7, 2 * DAY + 9, 2 * DAY + 11], dtype=np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": "ha"}), ts,
        {"usage": (int(ValueType.FLOAT), vals)}))
    coord.write_points(DEFAULT_TENANT, "db3", wb)
    assert len(meta.buckets_for(DEFAULT_TENANT, "db3")) == 3
    batches = coord.scan_table(DEFAULT_TENANT, "db3", "cpu")
    assert sum(b.n_rows for b in batches) == 4
    got = sorted((int(t), float(v))
                 for b in batches
                 for t, v in zip(b.ts, b.fields["usage"][1]))
    assert got == [(5, 1.0), (DAY + 7, 2.0),
                   (2 * DAY + 9, 3.0), (2 * DAY + 11, 4.0)]
    # day-2 bucket alone holds the two straddled-off rows
    batches = coord.scan_table(
        DEFAULT_TENANT, "db3", "cpu",
        time_ranges=TimeRanges([TimeRange(2 * DAY, 3 * DAY - 1)]))
    assert sum(b.n_rows for b in batches) == 2
