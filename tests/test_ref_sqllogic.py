"""Runner for the PORTED reference slt corpus (tests/sqllogic_ref/).

Differences from the self-generated corpus runner (test_sqllogic.py):
  - expected blocks carry DATA rows only (no header line) — the
    reference corpus pins values, not our column naming;
  - `querysort` compares rows order-insensitively (upstream `rowsort`);
  - `usedb <name>` switches the session database (upstream
    `--#DATABASE=` directive);
  - `statement error` asserts "an error", not the reference's error
    text (divergence D1 in sqllogic_ref/DIVERGENCES.md).

Source corpus: /root/reference/query_server/sqllogicaltests/cases/
ported by tests/port_ref_slt.py.
"""
import os

import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.server.http import format_csv
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv

CASES_DIR = os.path.join(os.path.dirname(__file__), "sqllogic_ref")


def _parse(path):
    blocks = []
    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        for kind, prefix in (("ok", "statement ok "),
                             ("error", "statement error "),
                             ("lineproto", "lineproto "),
                             ("opentsdbjson", "opentsdbjson "),
                             ("opentsdb", "opentsdb "),
                             ("writeprecision", "writeprecision "),
                             ("cleandir", "cleandir "),
                             ("usetenant", "usetenant "),
                             ("useuser", "useuser "),
                             ("use", "usedb ")):
            if line.startswith(prefix):
                body = line[len(prefix):]
                if body == "<<":
                    # heredoc: multi-line statement (real newlines are
                    # significant, e.g. multi-line tenant comments)
                    part = []
                    while i < len(lines) and lines[i].rstrip() != ">>":
                        part.append(lines[i])
                        i += 1
                    i += 1   # skip '>>'
                    body = "\n".join(part)
                blocks.append((kind, body, None, i))
                break
        else:
            for kind in ("querysort", "query"):
                if line.startswith(kind + " "):
                    sql = line[len(kind) + 1:]
                    expected = []
                    while i < len(lines) and lines[i].strip() != "":
                        expected.append(lines[i].rstrip())
                        i += 1
                    blocks.append((kind, sql, expected, i))
                    break
    return blocks


def _known_gaps() -> set:
    """Files still being brought to parity (tracked work list; each line
    is a ported file with residual value/feature mismatches). A gap file
    that STARTS passing must be removed from the list — xfail is strict."""
    p = os.path.join(CASES_DIR, "KNOWN_GAPS.txt")
    if not os.path.exists(p):
        return set()
    with open(p) as f:
        return {ln.strip() for ln in f if ln.strip()
                and not ln.startswith("#")}


def _case_files():
    if not os.path.isdir(CASES_DIR):
        return []
    gaps = _known_gaps()
    return [
        pytest.param(f, marks=pytest.mark.xfail(
            reason="known parity gap (tests/sqllogic_ref/KNOWN_GAPS.txt)",
            strict=True)) if f in gaps else f
        for f in sorted(os.listdir(CASES_DIR)) if f.endswith(".slt")
    ]


@pytest.fixture(autouse=True)
def _external_data_root(monkeypatch):
    """The corpus references fixture files by reference-repo-relative
    LOCATION; resolve them against the read-only reference checkout."""
    if os.path.isdir("/root/reference"):
        monkeypatch.setenv("CNOSDB_EXTERNAL_DATA_ROOT", "/root/reference")


@pytest.mark.parametrize("case", _case_files())
def test_ref_sqllogic(case, tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    session = Session()
    write_precision = "ns"   # set by the `writeprecision` directive
    try:
        for kind, sql, expected, lineno in _parse(
                os.path.join(CASES_DIR, case)):
            if kind == "cleandir":
                import shutil

                assert sql.startswith("/tmp/"), sql   # safety rail
                shutil.rmtree(sql, ignore_errors=True)
            elif kind == "writeprecision":
                write_precision = sql.strip()
            elif kind == "lineproto":
                from cnosdb_tpu.models.schema import Precision
                from cnosdb_tpu.protocol.line_protocol import parse_lines

                batch = parse_lines(sql, Precision.parse(write_precision))
                coord.write_points(session.tenant, session.database, batch)
            elif kind in ("opentsdb", "opentsdbjson"):
                from cnosdb_tpu.models.schema import Precision
                from cnosdb_tpu.protocol.opentsdb import (
                    parse_opentsdb, parse_opentsdb_json)

                fn = parse_opentsdb_json if kind == "opentsdbjson" \
                    else parse_opentsdb
                batch = fn(sql, Precision.parse(write_precision))
                coord.write_points(session.tenant, session.database, batch)
            elif kind == "usetenant":
                session.tenant = sql
            elif kind == "useuser":
                session.user = sql
            elif kind == "use":
                dbname = sql.rstrip(";").strip()
                try:
                    ex.execute_one(
                        f"CREATE DATABASE IF NOT EXISTS {dbname}", session)
                except Exception:
                    pass
                session.database = dbname
            elif kind == "ok":
                try:
                    ex.execute_one(sql, session)
                except Exception as e:
                    raise AssertionError(
                        f"{case}:{lineno} statement failed: {sql!r}\n"
                        f"  -> {type(e).__name__}: {e}") from e
            elif kind == "error":
                try:
                    ex.execute_one(sql, session)
                except Exception:
                    pass
                else:
                    raise AssertionError(
                        f"{case}:{lineno} expected an error: {sql!r}")
            else:
                rs = ex.execute_one(sql, session)
                got = format_csv(rs)[:-1].split("\n")[1:]   # drop header
                if got == [""] and rs.n_rows == 0:
                    got = []   # zero rows ≠ one all-NULL row
                # trailing whitespace is not representable in the
                # upstream slt format; compare rstripped (their runner
                # does the same)
                got = [ln.rstrip() for ln in got]
                want = [ln.replace("\\N", "").rstrip()
                        for ln in expected]
                if kind == "querysort":
                    got, want = sorted(got), sorted(want)
                assert got == want, (
                    f"{case}:{lineno} for {sql!r}\n"
                    f"expected: {want}\n     got: {got}")
    finally:
        coord.close()
