"""Flight SQL service + stream micro-batch engine."""
import socket
import time

import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.sql.stream import StreamEngine, StreamQuery
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex, str(tmp_path)
    coord.close()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_flight_sql_roundtrip(db):
    ex, _ = db
    pytest.importorskip("pyarrow.flight")
    import pyarrow.flight as fl

    from cnosdb_tpu.server.flight import start_flight_server

    ex.execute_one("CREATE TABLE air (visibility DOUBLE, TAGS(station))")
    ex.execute_one("INSERT INTO air (time, station, visibility) VALUES "
                   "(1, 'a', 10.5), (2, 'b', 20.5)")
    port = _free_port()
    server = start_flight_server(ex, port)
    try:
        client = fl.connect(f"grpc://127.0.0.1:{port}")
        reader = client.do_get(fl.Ticket(b"public\x00SELECT station, visibility "
                                         b"FROM air ORDER BY time"))
        table = reader.read_all()
        assert table.column("station").to_pylist() == ["a", "b"]
        assert table.column("visibility").to_pylist() == [10.5, 20.5]
        # aggregates through flight
        reader = client.do_get(fl.Ticket(b"public\x00SELECT count(*) AS c FROM air"))
        assert reader.read_all().column("c").to_pylist() == [2]
    finally:
        server.shutdown()


def test_stream_micro_batch_to_table(db):
    ex, state = db
    ex.execute_one("CREATE TABLE src (v DOUBLE, TAGS(h))")
    ex.execute_one("CREATE TABLE agg_1m (mean_v DOUBLE, TAGS(h))")
    se = StreamEngine(ex, state)
    sq = StreamQuery(
        name="s1",
        sql=("SELECT h, date_bin(INTERVAL '1 minute', time) AS time, "
             "avg(v) AS mean_v FROM src "
             "WHERE time >= $START AND time < $END GROUP BY h, time"),
        interval_s=3600,  # manual triggering in the test
        sink=("table", "agg_1m"))
    se.streams[sq.name] = sq
    se.tracker.set("s1", 0)
    # minute 0: v = 1..4 for h=a
    ex.execute_one("INSERT INTO src (time, h, v) VALUES " + ", ".join(
        f"({i * 10_000_000_000}, 'a', {i + 1})" for i in range(4)))
    rs = se.trigger_once("s1", now_ns=60_000_000_000)
    assert rs is not None and rs.n_rows == 1
    out = ex.execute_one("SELECT h, mean_v FROM agg_1m")
    assert out.rows() == [("a", 2.5)]
    # watermark advanced: empty second trigger at same time
    assert se.trigger_once("s1", now_ns=60_000_000_000) is None
    # minute 1 data arrives → only the new slice aggregates
    ex.execute_one("INSERT INTO src (time, h, v) VALUES (70000000000, 'a', 10)")
    rs = se.trigger_once("s1", now_ns=120_000_000_000)
    assert rs.n_rows == 1
    out = ex.execute_one("SELECT mean_v FROM agg_1m ORDER BY time")
    assert out.columns[0].tolist() == [2.5, 10.0]
    # watermark survives restart
    se2 = StreamEngine(ex, state)
    assert se2.tracker.get("s1", 0) == 120_000_000_000


def test_create_stream_sql_ddl(db):
    """CREATE STREAM / SHOW STREAMS / DROP STREAM through plain SQL."""
    ex, _ = db
    ex.execute_one("CREATE TABLE src3 (v DOUBLE, TAGS(h))")
    ex.execute_one("CREATE TABLE out3 (mean_v DOUBLE, TAGS(h))")
    ex.execute_one(
        "CREATE STREAM s3 TRIGGER INTERVAL '1 hour' INTO out3 AS "
        "SELECT h, date_bin(INTERVAL '1 minute', time) AS time, "
        "avg(v) AS mean_v FROM src3 GROUP BY h, time")
    rs = ex.execute_one("SHOW STREAMS")
    assert rs.columns[0].tolist() == ["s3"]
    assert rs.columns[1][0] == "out3"
    se = ex.stream_engine()
    ex.execute_one("INSERT INTO src3 (time, h, v) VALUES "
                   "(1000000000, 'a', 2), (2000000000, 'a', 4)")
    # the trigger thread fired once at register time with wall-clock now;
    # rewind the watermark to drive the window manually (1h cadence means
    # the thread stays parked for the rest of the test)
    se.tracker.set("s3", 0)
    se.trigger_once("s3", now_ns=60_000_000_000)
    out = ex.execute_one("SELECT h, mean_v FROM out3")
    assert out.rows() == [("a", 3.0)]
    # invalid stream definitions fail at CREATE time, not silently later
    with pytest.raises(Exception):
        ex.execute_one("CREATE STREAM bad INTO out3 AS "
                       "SELECT avg(nope) AS mean_v FROM src3")
    # definitions persist in meta for restart restore
    assert "s3" in ex.meta.streams
    ex.execute_one("DROP STREAM s3")
    assert ex.execute_one("SHOW STREAMS").n_rows == 0
    assert "s3" not in ex.meta.streams
    # watermark cleared: re-created stream starts fresh
    assert se.tracker.get("s3", -1) == -1


def test_stream_watermark_delay(db):
    ex, state = db
    ex.execute_one("CREATE TABLE src2 (v DOUBLE, TAGS(h))")
    collected = []
    se = StreamEngine(ex, state)
    sq = StreamQuery(
        name="s2",
        sql="SELECT count(v) AS c FROM src2 WHERE time >= $START AND time < $END",
        interval_s=3600, delay_ns=30_000_000_000,
        sink=lambda rs: collected.append(rs.columns[0][0]))
    se.streams[sq.name] = sq
    se.tracker.set("s2", 0)
    ex.execute_one("INSERT INTO src2 (time, h, v) VALUES (50000000000, 'x', 1)")
    # now=60s, delay 30s → slice [0, 30s): row at 50s not yet visible
    rs = se.trigger_once("s2", now_ns=60_000_000_000)
    assert collected == [0] or rs.columns[0][0] == 0
    rs = se.trigger_once("s2", now_ns=100_000_000_000)  # slice [30s, 70s)
    assert rs.columns[0][0] == 1


def test_flight_sql_standard_descriptor_flow(db):
    """The REAL FlightSQL protocol (reference flight_sql_server.rs):
    FlightDescriptor.cmd = Any(CommandStatementQuery) → GetFlightInfo
    advertises the TRUE result schema + a TicketStatementQuery endpoint;
    DoGet on that ticket streams the rows. Catalog commands too."""
    ex, _ = db
    pytest.importorskip("pyarrow.flight")
    import pyarrow as pa
    import pyarrow.flight as fl

    from cnosdb_tpu.server.flight import (
        command_get_catalogs, command_get_tables, command_statement_query,
        start_flight_server,
    )

    ex.execute_one("CREATE TABLE fsq (v DOUBLE, n BIGINT, TAGS(host))")
    ex.execute_one("INSERT INTO fsq (time, host, v, n) VALUES "
                   "(1, 'a', 1.5, 10), (2, 'b', 2.5, 20)")
    port = _free_port()
    server = start_flight_server(ex, port)
    try:
        client = fl.connect(f"grpc://127.0.0.1:{port}")
        desc = fl.FlightDescriptor.for_command(
            command_statement_query(
                "SELECT host, v, n FROM fsq ORDER BY time"))
        info = client.get_flight_info(desc)
        # the schema is REAL, known before fetching any data
        assert info.schema.names == ["host", "v", "n"]
        assert info.schema.field("v").type == pa.float64()
        assert info.schema.field("n").type == pa.int64()
        assert info.total_records == 2
        table = client.do_get(info.endpoints[0].ticket).read_all()
        assert table.schema.names == ["host", "v", "n"]
        assert table.column("host").to_pylist() == ["a", "b"]
        assert table.column("n").to_pylist() == [10, 20]
        # a second DoGet on the same ticket re-executes from the handle
        table2 = client.do_get(info.endpoints[0].ticket).read_all()
        assert table2.column("v").to_pylist() == [1.5, 2.5]

        # catalog browsing commands
        info = client.get_flight_info(fl.FlightDescriptor.for_command(
            command_get_catalogs()))
        cats = client.do_get(info.endpoints[0].ticket).read_all()
        assert cats.column("catalog_name").to_pylist() == ["cnosdb"]
        info = client.get_flight_info(fl.FlightDescriptor.for_command(
            command_get_tables()))
        tbl = client.do_get(info.endpoints[0].ticket).read_all()
        assert "fsq" in tbl.column("table_name").to_pylist()
        assert set(tbl.schema.names) >= {"catalog_name", "db_schema_name",
                                         "table_name", "table_type"}
    finally:
        server.shutdown()


def test_flight_sql_prepared_statements(db):
    """Prepared-statement flow (reference flight_sql_server.rs:933
    do_action_create_prepared_statement + get_flight_info_prepared_statement
    + do_put_prepared_statement_update): create → schema + handle, query
    via CommandPreparedStatementQuery, update via DoPut, close."""
    ex, _ = db
    pytest.importorskip("pyarrow.flight")
    import pyarrow as pa
    import pyarrow.flight as fl

    from cnosdb_tpu.server.flight import (
        _any_unpack, _pb_parse, action_create_prepared_statement,
        action_close_prepared_statement, command_prepared_statement_query,
        command_statement_update, start_flight_server,
    )

    ex.execute_one("CREATE TABLE prep (v DOUBLE, TAGS(host))")
    ex.execute_one("INSERT INTO prep (time, host, v) VALUES "
                   "(1, 'a', 1.5), (2, 'b', 2.5)")
    port = _free_port()
    server = start_flight_server(ex, port)
    try:
        client = fl.connect(f"grpc://127.0.0.1:{port}")
        results = list(client.do_action(fl.Action(
            "CreatePreparedStatement",
            action_create_prepared_statement(
                "SELECT host, v FROM prep ORDER BY time"))))
        kind, val = _any_unpack(results[0].body.to_pybytes())
        assert kind == "ActionCreatePreparedStatementResult"
        fields = _pb_parse(val)
        handle = fields[1][0]
        schema = pa.ipc.read_schema(pa.py_buffer(fields[2][0]))
        assert schema.names == ["host", "v"]

        # execute twice through the handle — prepared statements replay
        for _ in range(2):
            info = client.get_flight_info(fl.FlightDescriptor.for_command(
                command_prepared_statement_query(handle)))
            assert info.schema.names == ["host", "v"]
            t = client.do_get(info.endpoints[0].ticket).read_all()
            assert t.column("v").to_pylist() == [1.5, 2.5]

        # DoPut statement update (how JDBC runs DML/DDL)
        desc = fl.FlightDescriptor.for_command(command_statement_update(
            "INSERT INTO prep (time, host, v) VALUES (3, 'c', 3.5)"))
        writer, reader = client.do_put(desc, pa.schema([]))
        writer.done_writing()
        buf = reader.read()
        writer.close()
        assert buf is not None
        info = client.get_flight_info(fl.FlightDescriptor.for_command(
            command_prepared_statement_query(handle)))
        t = client.do_get(info.endpoints[0].ticket).read_all()
        assert t.num_rows == 3

        client.do_action(fl.Action(
            "ClosePreparedStatement",
            action_close_prepared_statement(handle)))
    finally:
        server.shutdown()


def test_flight_prepared_dml_no_side_effects_and_affected_count(db):
    """Preparing an INSERT must not apply it; executing it via DoPut
    reports the REAL affected-row count (JDBC executeUpdate)."""
    ex, _ = db
    pytest.importorskip("pyarrow.flight")
    import pyarrow as pa
    import pyarrow.flight as fl

    from cnosdb_tpu.server.flight import (
        _any_unpack, _pb_parse, action_create_prepared_statement,
        command_statement_update, start_flight_server,
    )

    ex.execute_one("CREATE TABLE pdml (v DOUBLE, TAGS(host))")
    port = _free_port()
    server = start_flight_server(ex, port)
    try:
        client = fl.connect(f"grpc://127.0.0.1:{port}")
        ins = ("INSERT INTO pdml (time, host, v) VALUES "
               "(1,'a',1.0), (2,'b',2.0), (3,'c',3.0)")
        results = list(client.do_action(fl.Action(
            "CreatePreparedStatement",
            action_create_prepared_statement(ins))))
        assert results  # handle returned
        rs = ex.execute_one("SELECT count(v) AS c FROM pdml")
        assert int(rs.columns[0][0]) == 0  # prepare applied NOTHING

        desc = fl.FlightDescriptor.for_command(command_statement_update(ins))
        writer, reader = client.do_put(desc, pa.schema([]))
        writer.done_writing()
        buf = reader.read()
        writer.close()
        fields = _pb_parse(buf.to_pybytes() if hasattr(buf, "to_pybytes")
                           else bytes(buf))
        assert fields[1][0] == 3  # DoPutUpdateResult.record_count
        rs = ex.execute_one("SELECT count(v) AS c FROM pdml")
        assert int(rs.columns[0][0]) == 3
    finally:
        server.shutdown()


def test_bind_sql_unit():
    """Quote-aware `?` substitution (flight.bind_sql)."""
    from cnosdb_tpu.server.flight import bind_sql
    assert bind_sql("SELECT * FROM t WHERE v = ? AND h = ?", [1.5, "a'b"]) \
        == "SELECT * FROM t WHERE v = 1.5 AND h = 'a''b'"
    # ? inside string literals / quoted identifiers is not a placeholder
    assert bind_sql("SELECT '?' , \"a?b\" FROM t WHERE x = ?", [7]) \
        == "SELECT '?' , \"a?b\" FROM t WHERE x = 7"
    assert bind_sql("SELECT 'it''s ?' FROM t WHERE b = ?", [True]) \
        == "SELECT 'it''s ?' FROM t WHERE b = true"
    assert bind_sql("x = ?", [None]) == "x = NULL"
    import pytest as _pt
    with _pt.raises(ValueError):
        bind_sql("x = ?", [])
    with _pt.raises(ValueError):
        bind_sql("x = ?", [1, 2])


def test_flight_prepared_statement_bound_parameters(db):
    """DoPut(CommandPreparedStatementQuery) binds `?` parameters for the
    next get_flight_info; DoPut(CommandPreparedStatementUpdate) with a
    parameter batch executes once per row (JDBC executeBatch). The
    reference returns unimplemented for query binding
    (flight_sql_server.rs do_put_prepared_statement_query)."""
    ex, _ = db
    pytest.importorskip("pyarrow.flight")
    import pyarrow as pa
    import pyarrow.flight as fl

    from cnosdb_tpu.server.flight import (
        _any_unpack, _pb_parse, action_create_prepared_statement,
        command_prepared_statement_query, command_prepared_statement_update,
        start_flight_server,
    )

    ex.execute_one("CREATE TABLE bindp (v DOUBLE, TAGS(host))")
    ex.execute_one("INSERT INTO bindp (time, host, v) VALUES "
                   "(1, 'a', 1.5), (2, 'b', 2.5), (3, 'c', 3.5)")
    port = _free_port()
    server = start_flight_server(ex, port)
    try:
        client = fl.connect(f"grpc://127.0.0.1:{port}")
        results = list(client.do_action(fl.Action(
            "CreatePreparedStatement",
            action_create_prepared_statement(
                "SELECT host, v FROM bindp WHERE v > ? ORDER BY time"))))
        handle = _pb_parse(_any_unpack(results[0].body.to_pybytes())[1])[1][0]

        # bind v > 2.0 then execute through the handle
        desc = fl.FlightDescriptor.for_command(
            command_prepared_statement_query(handle))
        params = pa.table({"p1": [2.0]})
        writer, reader = client.do_put(desc, params.schema)
        writer.write_table(params)
        writer.done_writing()
        assert reader.read() is not None    # DoPutPreparedStatementResult
        writer.close()
        info = client.get_flight_info(desc)
        t = client.do_get(info.endpoints[0].ticket).read_all()
        assert t.column("v").to_pylist() == [2.5, 3.5]

        # rebind with a different value — the handle replays with new params
        writer, reader = client.do_put(desc, params.schema)
        writer.write_table(pa.table({"p1": [3.0]}))
        writer.done_writing()
        reader.read()
        writer.close()
        info = client.get_flight_info(desc)
        t = client.do_get(info.endpoints[0].ticket).read_all()
        assert t.column("v").to_pylist() == [3.5]

        # batched prepared INSERT: one execution per parameter row
        results = list(client.do_action(fl.Action(
            "CreatePreparedStatement",
            action_create_prepared_statement(
                "INSERT INTO bindp (time, host, v) VALUES (?, ?, ?)"))))
        ihandle = _pb_parse(_any_unpack(results[0].body.to_pybytes())[1])[1][0]
        idesc = fl.FlightDescriptor.for_command(
            command_prepared_statement_update(ihandle))
        batch = pa.table({"t": [10, 11], "h": ["x", "y"], "v": [10.5, 11.5]})
        writer, reader = client.do_put(idesc, batch.schema)
        writer.write_table(batch)
        writer.done_writing()
        buf = reader.read()
        writer.close()
        assert buf is not None
        rs = ex.execute_one("SELECT count(v) FROM bindp")
        assert rs.columns[0].tolist() == [5]
        rs = ex.execute_one("SELECT host FROM bindp WHERE time = 11")
        assert rs.columns[0].tolist() == ["y"]
    finally:
        server.shutdown()


def test_stream_offset_tracker_caps_at_available(db):
    """The watermark must not advance past the source's max ingested
    timestamp (reference offset_tracker): a trigger 'now' far in the
    future processes only available data; later-arriving in-order rows
    are still picked up by the next trigger."""
    ex, state = db
    se = StreamEngine(ex, state)
    ex.execute_one("CREATE TABLE src_ot (v DOUBLE, TAGS(h))")
    ex.execute_one("CREATE TABLE sink_ot (c BIGINT, TAGS(h))")
    ex.execute_one("INSERT INTO src_ot (time, h, v) VALUES "
                   "(1000000000, 'a', 1.0), (2000000000, 'a', 2.0)")
    from cnosdb_tpu.sql import stream as stream_mod
    from cnosdb_tpu.sql.parser import Parser

    stmt = Parser(
        "SELECT date_bin(INTERVAL '1 second', time) AS time, h, "
        "count(v) AS c FROM src_ot GROUP BY 1, h").parse_statement()
    sq = stream_mod.StreamQuery(name="ot", stmt=stmt, interval_s=3600,
                                sink=("table", "sink_ot"),
                                session=Session())
    se.streams[sq.name] = sq
    # trigger with a far-future now: offset tracker caps end at max(ts)+1
    se.trigger_once("ot", now_ns=10**15)
    assert se.tracker.get("ot", 0) == 2000000001
    # in-order late data beyond the old max is processed next trigger
    ex.execute_one(
        "INSERT INTO src_ot (time, h, v) VALUES (3000000000, 'a', 9.0)")
    se.trigger_once("ot", now_ns=10**15)
    assert se.tracker.get("ot", 0) == 3000000001
    rs = ex.execute_one("SELECT sum(c) AS s FROM sink_ot")
    assert int(rs.columns[0][0]) == 3


def test_stream_state_store_roundtrip(db):
    """MemoryStateStore commit/expire/state semantics (reference
    stream/state_store/memory.rs)."""
    import numpy as np

    from cnosdb_tpu.sql.executor import ResultSet
    from cnosdb_tpu.sql.expr import BinOp, Column, Literal
    from cnosdb_tpu.sql.stream import StateStoreFactory

    f = StateStoreFactory()
    store = f.get_or_default("q1", 0, 0)
    assert f.get_or_default("q1", 0, 0) is store
    assert f.get_or_default("q1", 1, 0) is not store
    rs = ResultSet(["k", "v"], [np.array([1, 2, 3]),
                                np.array([10.0, 20.0, 30.0])])
    store.put(rs)
    assert store.state() == []          # uncommitted is not visible
    v1 = store.commit()
    assert v1 == 1 and len(store.state()) == 1
    # expire rows k < 3: removed returned, state keeps the rest
    removed = store.expire(BinOp("<", Column("k"), Literal(3)))
    assert [c.tolist() for c in removed[0].columns] == [[1, 2], [10.0, 20.0]]
    assert store.state()[0].columns[0].tolist() == [3]
    f.drop_query("q1")
    assert f.get_or_default("q1", 0, 0) is not store
