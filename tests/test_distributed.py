"""Distributed aggregation over the virtual 8-device CPU mesh."""
import numpy as np
import pytest

import jax

from cnosdb_tpu.parallel.mesh import make_mesh, mesh_size
from cnosdb_tpu.parallel.distributed_agg import distributed_aggregate_host


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_mesh_has_8_devices(mesh):
    assert mesh_size(mesh) == 8


def test_distributed_matches_local(mesh, rng):
    n, nseg = 100_000, 37
    vals = rng.normal(size=n)
    valid = rng.random(n) > 0.1
    segs = rng.integers(0, nseg, n).astype(np.int32)
    rank = np.arange(n, dtype=np.int32)
    rng.shuffle(rank)
    out = distributed_aggregate_host(vals, valid, segs, rank, nseg, mesh,
                                     want_first=True, want_last=True)
    # numpy oracle
    for s in range(0, nseg, 5):
        m = valid & (segs == s)
        assert out["count"][s] == m.sum()
        np.testing.assert_allclose(out["sum"][s], vals[m].sum(), rtol=1e-12)
        assert out["min"][s] == vals[m].min()
        assert out["max"][s] == vals[m].max()
        first_idx = np.nonzero(m)[0][np.argmin(rank[m])]
        last_idx = np.nonzero(m)[0][np.argmax(rank[m])]
        assert out["first"][s] == vals[first_idx]
        assert out["last"][s] == vals[last_idx]


def test_distributed_int64_exact(mesh, rng):
    n, nseg = 10_000, 4
    vals = rng.integers(-(2**40), 2**40, n)
    valid = np.ones(n, dtype=bool)
    segs = (np.arange(n) % nseg).astype(np.int32)
    rank = np.arange(n, dtype=np.int32)
    out = distributed_aggregate_host(vals, valid, segs, rank, nseg, mesh)
    for s in range(nseg):
        m = segs == s
        assert out["sum"][s] == vals[m].sum()
        assert out["min"][s] == vals[m].min()


def test_empty_segment_handling(mesh):
    n, nseg = 64, 8
    vals = np.ones(n)
    valid = np.zeros(n, dtype=bool)  # everything filtered out
    segs = np.zeros(n, dtype=np.int32)
    rank = np.arange(n, dtype=np.int32)
    out = distributed_aggregate_host(vals, valid, segs, rank, nseg, mesh)
    assert (out["count"] == 0).all()
    assert (out["sum"] == 0).all()
