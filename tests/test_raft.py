"""Raft consensus tests: election, replication, failover, recovery."""
import time

import msgpack
import pytest

from cnosdb_tpu.errors import ReplicationError
from cnosdb_tpu.parallel.raft import (
    InProcessTransport, LogEntry, MemoryLogStore, NotLeader, RaftNode,
    StateMachine, WalLogStore,
)
from cnosdb_tpu.storage.wal import Wal


class KvSM(StateMachine):
    """Tiny kv state machine for tests."""

    def __init__(self):
        self.data = {}
        self.applied = []

    def apply(self, entry: LogEntry):
        k, v = msgpack.unpackb(entry.data, raw=False)
        self.data[k] = v
        self.applied.append(entry.index)

    def snapshot(self):
        return msgpack.packb(self.data)

    def install_snapshot(self, data, last_index, last_term):
        self.data = msgpack.unpackb(data, raw=False, strict_map_key=False)


def make_cluster(n=3, tick=True):
    tx = InProcessTransport()
    nodes = {}
    sms = {}
    for i in range(1, n + 1):
        sm = KvSM()
        node = RaftNode("g1", i, list(range(1, n + 1)), MemoryLogStore(), sm,
                        tx, election_timeout=(0.05, 0.15),
                        heartbeat_interval=0.02, tick=tick)
        nodes[i] = node
        sms[i] = sm
    return tx, nodes, sms


def wait_leader(nodes, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [n for n in nodes.values() if n.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader elected")


def put(leader, k, v):
    return leader.propose(1, msgpack.packb([k, v]))


def test_election_single_leader():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        assert leader.metrics()["role"] == "leader"
        followers = [n for n in nodes.values() if n is not leader]
        assert all(n.metrics()["role"] == "follower" for n in followers)
    finally:
        for n in nodes.values():
            n.stop()


def test_replication_applies_on_all():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        for i in range(5):
            put(leader, f"k{i}", i)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if all(len(sm.data) == 5 for sm in sms.values()):
                break
            time.sleep(0.02)
        for sm in sms.values():
            assert sm.data == {f"k{i}": i for i in range(5)}
    finally:
        for n in nodes.values():
            n.stop()


def test_follower_rejects_propose():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        follower = next(n for n in nodes.values() if n is not leader)
        with pytest.raises(NotLeader) as ei:
            follower.propose(1, b"x")
        assert ei.value.leader_id == leader.node_id
    finally:
        for n in nodes.values():
            n.stop()


def test_leader_failover_and_rejoin():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        put(leader, "a", 1)
        leader.crash()
        others = {i: n for i, n in nodes.items() if n is not leader}
        new_leader = wait_leader(others)
        assert new_leader is not leader
        put(new_leader, "b", 2)
        # old leader rejoins and catches up. NOTE: once caught up it may
        # legitimately WIN a later election (raft does not forbid it), so
        # the contract is catch-up + a single live leader — not that the
        # restarted node stays follower forever.
        leader.restart()
        deadline = time.monotonic() + 10
        sm = sms[leader.node_id]
        while time.monotonic() < deadline and sm.data.get("b") != 2:
            time.sleep(0.02)
        assert sm.data == {"a": 1, "b": 2}
        # raft safety: at most one leader PER TERM (a deposed leader may
        # transiently still claim leadership in an older term)
        by_term: dict = {}
        for n in nodes.values():
            if n.is_leader():
                by_term.setdefault(n.term, []).append(n)
        assert all(len(v) <= 1 for v in by_term.values()), by_term
    finally:
        for n in nodes.values():
            n.stop()


def test_partition_minority_cannot_commit():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        others = [n for n in nodes.values() if n is not leader]
        # isolate the leader from both followers
        for o in others:
            tx.partition(leader.node_id, o.node_id)
        new_leader = wait_leader({n.node_id: n for n in others})
        put(new_leader, "x", 42)
        # isolated old leader cannot commit
        with pytest.raises(ReplicationError):
            leader.propose(1, msgpack.packb(["y", 1]), timeout=0.5)
        tx.heal()
        # generous deadline: under full-suite load the healed leader's
        # term disruption + re-election + catch-up can take seconds
        deadline = time.monotonic() + 10
        sm = sms[leader.node_id]
        while time.monotonic() < deadline and sm.data.get("x") != 42:
            time.sleep(0.02)
        assert sm.data.get("x") == 42
        assert "y" not in sms[new_leader.node_id].data
    finally:
        for n in nodes.values():
            n.stop()


def test_wal_log_store_roundtrip(tmp_path):
    wal = Wal(str(tmp_path / "wal"))
    store = WalLogStore(wal, str(tmp_path / "hardstate"))
    for i in range(1, 6):
        store.append(LogEntry(1, i, 1, f"data{i}".encode()))
    store.save_hard_state(3, 2)
    wal.sync()
    wal.close()
    wal2 = Wal(str(tmp_path / "wal"))
    store2 = WalLogStore(wal2, str(tmp_path / "hardstate"))
    assert store2.last_index() == 5
    assert store2.entry_at(3).data == b"data3"
    assert store2.entry_at(3).term == 1
    assert store2.load_hard_state() == (3, 2)
    # conflict truncation
    store2.truncate_from(4)
    assert store2.last_index() == 3
    store2.append(LogEntry(2, 4, 1, b"new4"))
    wal2.sync()
    wal2.close()
    wal3 = Wal(str(tmp_path / "wal"))
    store3 = WalLogStore(wal3, str(tmp_path / "hardstate"))
    assert store3.entry_at(4).data == b"new4"
    assert store3.entry_at(4).term == 2
    wal3.close()


def test_purged_log_does_not_force_snapshot_install(tmp_path):
    """After every member GCs its applied log prefix (what a vnode flush
    does to the WAL-backed store), heartbeats and new appends must ride
    the remembered purged terms: falling back to install_snapshot here is
    both wasteful (full state clone per heartbeat) and dangerous (it is
    the path that cloned a quarantined leader's stripped state machine
    onto healthy followers)."""

    class InstallCountingSM(KvSM):
        def __init__(self):
            super().__init__()
            self.installs = 0

        def install_snapshot(self, data, last_index, last_term):
            self.installs += 1
            super().install_snapshot(data, last_index, last_term)

    tx = InProcessTransport()
    nodes, sms, wals = {}, {}, []
    for i in range(1, 4):
        wal = Wal(str(tmp_path / f"wal{i}"))
        store = WalLogStore(wal, str(tmp_path / f"hs{i}"))
        sm = InstallCountingSM()
        nodes[i] = RaftNode("g1", i, [1, 2, 3], store, sm, tx,
                            election_timeout=(0.05, 0.15),
                            heartbeat_interval=0.02)
        sms[i] = sm
        wals.append(wal)
    try:
        leader = wait_leader(nodes)
        for i in range(6):
            put(leader, f"k{i}", i)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline \
                and not all(len(sm.data) == 6 for sm in sms.values()):
            time.sleep(0.02)
        assert all(len(sm.data) == 6 for sm in sms.values())
        # GC the applied prefix everywhere (vnode flush → wal purge)
        for n in nodes.values():
            n.log.purge_to(n.commit_index + 1)
            assert n.log.entry_at(1) is None
        # continued traffic replicates in place — no snapshot installs
        put(leader, "post", 99)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline \
                and not all(sm.data.get("post") == 99 for sm in sms.values()):
            time.sleep(0.02)
        assert all(sm.data.get("post") == 99 for sm in sms.values())
        assert all(sm.installs == 0 for sm in sms.values())
    finally:
        for n in nodes.values():
            n.stop()
        for w in wals:
            w.close()


def test_snapshot_install_for_lagging_follower():
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        lagger = next(n for n in nodes.values() if n is not leader)
        lagger.crash()
        for i in range(10):
            put(leader, f"k{i}", i)
        # purge leader log so catch-up must go through a snapshot
        leader.log.truncate_from(1)  # memory store: simulate purge
        leader.log.append(LogEntry(leader.term, leader.commit_index,
                                   5, b""))
        lagger.restart()
        deadline = time.monotonic() + 3
        sm = sms[lagger.node_id]
        while time.monotonic() < deadline and len(sm.data) < 10:
            time.sleep(0.02)
        assert len(sm.data) == 10
    finally:
        for n in nodes.values():
            n.stop()


def test_chaos_loss_delay_reorder():
    """Raft safety under injected message loss, latency, and reordering
    (the gRPC-link faults the reference only simulates by killing
    processes, chaos_tests.rs): every acknowledged write must survive and
    all members converge once the faults clear."""
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        put(leader, "pre", 0)
        tx.chaos(loss=0.25, delay_s=0.02, reorder=0.2)
        acked = {"pre": 0}
        deadline = time.monotonic() + 8
        i = 0
        while time.monotonic() < deadline and i < 25:
            target = next((n for n in nodes.values() if n.is_leader()), None)
            if target is None:
                time.sleep(0.05)
                continue
            try:
                put(target, f"k{i}", i)
                acked[f"k{i}"] = i
                i += 1
            except Exception:
                pass  # unacked writes may or may not survive — both legal
        assert len(acked) > 5, "chaos prevented all progress"
        tx.chaos()  # heal
        leader = wait_leader(nodes)
        put(leader, "post", 99)
        acked["post"] = 99
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(all(sm.data.get(k) == v for k, v in acked.items())
                   for sm in sms.values()):
                break
            time.sleep(0.05)
        for nid, sm in sms.items():
            for k, v in acked.items():
                assert sm.data.get(k) == v, (nid, k, sm.data.get(k))
    finally:
        for n in nodes.values():
            n.stop()


def test_membership_add_voter():
    """Single-step add: a 4th member joins a live 3-node group via a
    MEMBERSHIP entry and receives all data (log or snapshot catch-up)."""
    tx, nodes, sms = make_cluster(3)
    try:
        leader = wait_leader(nodes)
        put(leader, "a", 1)
        put(leader, "b", 2)
        # build the new member (empty log, knows the full config)
        sm4 = KvSM()
        nodes[4] = RaftNode("g1", 4, [1, 2, 3, 4], MemoryLogStore(), sm4,
                            tx, election_timeout=(0.05, 0.15),
                            heartbeat_interval=0.02)
        sms[4] = sm4
        leader.change_membership([1, 2, 3, 4])
        assert sorted(leader.peers + [leader.node_id]) == [1, 2, 3, 4]
        put(leader, "c", 3)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if sm4.data == {"a": 1, "b": 2, "c": 3}:
                break
            time.sleep(0.02)
        assert sm4.data == {"a": 1, "b": 2, "c": 3}, sm4.data
    finally:
        for n in nodes.values():
            n.stop()


def test_membership_remove_follower_then_commit_with_new_majority():
    """Removing a follower shrinks the quorum: a 3→2 group must commit
    with both remaining members and never count the removed one."""
    tx, nodes, sms = make_cluster(3)
    try:
        leader = wait_leader(nodes)
        put(leader, "a", 1)
        victim = next(i for i in nodes if i != leader.node_id)
        leader.change_membership(
            [i for i in (1, 2, 3) if i != victim])
        nodes[victim].stop()
        put(leader, "b", 2)   # must commit on the 2-member config
        rest = [i for i in (1, 2, 3) if i != victim]
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(sms[i].data.get("b") == 2 for i in rest):
                break
            time.sleep(0.02)
        for i in rest:
            assert sms[i].data.get("b") == 2
    finally:
        for n in nodes.values():
            n.stop()


def test_membership_rejects_multi_step_and_leader_self_removal():
    tx, nodes, sms = make_cluster(3)
    try:
        leader = wait_leader(nodes)
        with pytest.raises(ReplicationError):
            leader.change_membership([leader.node_id])  # removes two
        others = [i for i in (1, 2, 3) if i != leader.node_id]
        with pytest.raises(ReplicationError):
            leader.change_membership(others)  # removes the leader itself
    finally:
        for n in nodes.values():
            n.stop()


def test_stepdown_yields_leadership():
    tx, nodes, sms = make_cluster(3)
    try:
        leader = wait_leader(nodes)
        old = leader.node_id
        leader.stepdown()
        deadline = time.monotonic() + 5
        new = None
        while time.monotonic() < deadline:
            leaders = [n for n in nodes.values()
                       if n.is_leader() and n.node_id != old]
            if leaders:
                new = leaders[0]
                break
            time.sleep(0.02)
        assert new is not None, "no new leader after stepdown"
        put(new, "x", 9)
    finally:
        for n in nodes.values():
            n.stop()


def test_leader_refuses_prevote():
    """A live leader must refuse prevotes: a healed node that can reach
    the leader must not assemble a prevote majority to depose it."""
    tx, nodes, sms = make_cluster()
    try:
        leader = wait_leader(nodes)
        last = leader.log.last_index()
        reply = leader._on_request_prevote({
            "term": leader.term + 1,
            "candidate": 99,
            "last_log_index": last,
            "last_log_term": leader.log.term_at(last),
        })
        assert reply["granted"] is False
    finally:
        for n in nodes.values():
            n.stop()
