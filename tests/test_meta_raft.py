"""Replicated meta: a 3-member meta raft group (reference: the meta crate
runs its own single-group openraft cluster — meta/src/service/server.rs,
store/storage.rs ApplyStorage)."""
import time

import pytest

from cnosdb_tpu.models.schema import DatabaseOptions, DatabaseSchema
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.parallel.meta_service import MetaClient, MetaService
from cnosdb_tpu.parallel.net import rpc_call


@pytest.fixture
def meta_group(tmp_path):
    import socket

    def free():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    ports = {i: free() for i in (1, 2, 3)}
    peers = {i: f"127.0.0.1:{p}" for i, p in ports.items()}
    services = []
    for i in (1, 2, 3):
        store = MetaStore(str(tmp_path / f"m{i}.json"), register_self=False)
        svc = MetaService(store, port=ports[i], node_id=i, peers=peers,
                          raft_dir=str(tmp_path / f"raft{i}"))
        services.append(svc.start())
    # wait for a leader
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.raft.is_leader() for s in services):
            break
        time.sleep(0.05)
    assert any(s.raft.is_leader() for s in services), "no meta leader"
    yield services
    for s in services:
        s.stop()


def test_meta_raft_write_replicates(meta_group):
    services = meta_group
    follower = next(s for s in services if not s.raft.is_leader())
    # write THROUGH A FOLLOWER: proxied to the leader, applied everywhere
    c = MetaClient(follower.addr, node_id=50, watch=False)
    c.register_node(50, grpc_addr="127.0.0.1:5")
    c.create_user("ru", "pw")
    c.create_database(DatabaseSchema("cnosdb", "rdb",
                                     DatabaseOptions(shard_num=2)))
    b = c.locate_bucket_for_write("cnosdb", "rdb", 10**18)
    assert len(b.shard_group) == 2
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all("cnosdb.rdb" in s.store.databases
               and s.store.buckets.get("cnosdb.rdb") for s in services):
            break
        time.sleep(0.05)
    for s in services:
        assert "cnosdb.rdb" in s.store.databases
        bl = s.store.buckets["cnosdb.rdb"]
        assert [x.id for rs in bl[0].shard_group for x in rs.vnodes] == \
            [x.id for rs in b.shard_group for x in rs.vnodes]
        assert s.store.check_user("ru", "pw") is not None


def test_meta_raft_leader_failover(meta_group):
    services = meta_group
    leader = next(s for s in services if s.raft.is_leader())
    survivors = [s for s in services if s is not leader]
    c = MetaClient(survivors[0].addr, node_id=51, watch=False)
    c.register_node(51, grpc_addr="127.0.0.1:6")
    c.create_tenant("t1")
    # kill the leader's raft member AND rpc server
    leader.stop()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if any(s.raft.is_leader() for s in survivors):
            break
        time.sleep(0.05)
    assert any(s.raft.is_leader() for s in survivors), "no re-election"
    # writes keep working through the remaining members
    c.create_tenant("t2")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all("t2" in s.store.tenants for s in survivors):
            break
        time.sleep(0.05)
    for s in survivors:
        assert "t1" in s.store.tenants and "t2" in s.store.tenants


def test_meta_member_restart_no_double_apply(tmp_path):
    """Regression: a restarted member replays the raft log onto a store
    that already persisted those mutations — the applied-index watermark
    (inside meta.json's atomic write) must prevent double-application of
    non-idempotent commands like add_replica_vnode."""
    import socket

    def free():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    ports = {i: free() for i in (1, 2)}
    peers = {i: f"127.0.0.1:{p}" for i, p in ports.items()}

    def boot(i):
        store = MetaStore(str(tmp_path / f"m{i}.json"), register_self=False)
        return MetaService(store, port=ports[i], node_id=i, peers=peers,
                           raft_dir=str(tmp_path / f"raft{i}")).start()

    services = {i: boot(i) for i in (1, 2)}
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(
                s.raft.is_leader() for s in services.values()):
            time.sleep(0.05)
        c = MetaClient(services[1].addr, node_id=60, watch=False)
        c.register_node(60, grpc_addr="127.0.0.1:9")
        c.create_database(DatabaseSchema("cnosdb", "rr",
                                         DatabaseOptions(shard_num=1)))
        b = c.locate_bucket_for_write("cnosdb", "rr", 1)
        rs_id = b.shard_group[0].id
        new_vid = c.add_replica_vnode(rs_id, 60)
        def replica_counts():
            out = {}
            for i, s in services.items():
                bl = s.store.buckets.get("cnosdb.rr")
                out[i] = len(bl[0].shard_group[0].vnodes) if bl else 0
            return out

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                set(replica_counts().values()) != {2}:
            time.sleep(0.05)
        assert set(replica_counts().values()) == {2}, replica_counts()
        # restart member 2: its store must NOT grow extra replicas
        services[2].stop()
        time.sleep(0.2)
        services[2] = boot(2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            vs = services[2].store.buckets.get("cnosdb.rr")
            if vs:
                time.sleep(0.5)   # allow any (wrong) replay to land
                break
            time.sleep(0.05)
        vnodes = services[2].store.buckets["cnosdb.rr"][0].shard_group[0].vnodes
        assert len(vnodes) == 2, [v.id for v in vnodes]
    finally:
        for s in services.values():
            try:
                s.stop()
            except Exception:
                pass


def test_meta_dedup_survives_restart(tmp_path):
    """A retried duplicate proposal can land in the log AFTER the original
    was applied and the member crashed: the dedup set must be rebuilt from
    the persisted store (recent_req_ids rides the same atomic meta.json
    write as the mutation), or replay re-executes a committed
    non-idempotent mutation."""
    import msgpack

    from cnosdb_tpu.models.schema import DatabaseOptions, DatabaseSchema
    from cnosdb_tpu.parallel.meta_service import MetaStateMachine
    from cnosdb_tpu.parallel.raft import LogEntry

    path = str(tmp_path / "meta.json")
    store = MetaStore(path, register_self=False)
    store.register_node(1, grpc_addr="a")
    store.create_database(DatabaseSchema("cnosdb", "d",
                                         DatabaseOptions(shard_num=1)))
    b = store.locate_bucket_for_write("cnosdb", "d", 1, nodes=[1])
    rs_id = b.shard_group[0].id

    sm = MetaStateMachine(store)
    cmd = msgpack.packb(["add_replica_vnode",
                         {"rs_id": rs_id, "node_id": 1}, "req-dup-1"],
                        use_bin_type=True)
    sm.apply(LogEntry(1, 1, 1, cmd))
    n_after_first = len(store.buckets["cnosdb.d"][0].shard_group[0].vnodes)
    assert n_after_first == 2

    # crash + restart: fresh store from disk, fresh state machine
    store2 = MetaStore(path, register_self=False)
    sm2 = MetaStateMachine(store2)
    # replay of the original arms dedup even though it is skipped
    sm2.apply(LogEntry(1, 1, 1, cmd))
    # the retried DUPLICATE (same req id, later index) must be a no-op
    sm2.apply(LogEntry(1, 2, 1, cmd))
    vnodes = store2.buckets["cnosdb.d"][0].shard_group[0].vnodes
    assert len(vnodes) == 2, [v.id for v in vnodes]


def test_rpc_cluster_secret(tmp_path, monkeypatch):
    """With CNOSDB_CLUSTER_SECRET set, the msgpack-HTTP plane rejects
    callers that do not present it (ADVICE r2: the RPC plane exposes
    destructive admin methods and must not be open on non-loopback)."""
    import http.client

    from cnosdb_tpu.parallel.net import RpcError, RpcServer, pack

    monkeypatch.setenv("CNOSDB_CLUSTER_SECRET", "s3cret")
    srv = RpcServer("127.0.0.1", 0, {"echo": lambda p: {"ok": p["x"]}})
    srv.start()
    try:
        # authorized: rpc_call reads the secret from the env
        assert rpc_call(srv.addr, "echo", {"x": 5})["ok"] == 5
        # unauthorized: raw request without the header → 403
        host, _, port = srv.addr.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=5)
        conn.request("POST", "/rpc/echo", pack({"x": 5}),
                     {"Content-Type": "application/msgpack"})
        assert conn.getresponse().status == 403
        conn.close()
        # wrong secret (server and client share this process's env, so
        # exercise the mismatch with a raw header) → 403
        conn = http.client.HTTPConnection(host, int(port), timeout=5)
        conn.request("POST", "/rpc/echo", pack({"x": 5}),
                     {"Content-Type": "application/msgpack",
                      "x-cnosdb-cluster-secret": "wrong"})
        assert conn.getresponse().status == 403
        conn.close()
    finally:
        srv.stop()
