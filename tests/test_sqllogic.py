"""Golden-file SQL logic test runner (reference sqllogicaltests analog)."""
import os

import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.server.http import format_csv

CASES_DIR = os.path.join(os.path.dirname(__file__), "sqllogic")


def _parse_slt(path):
    blocks = []
    with open(path) as f:
        lines = f.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line.startswith("statement ok "):
            blocks.append(("ok", line[len("statement ok "):], None, i))
        elif line.startswith("statement error "):
            blocks.append(("error", line[len("statement error "):], None, i))
        elif line.startswith("query "):
            sql = line[len("query "):]
            expected = []
            while i < len(lines) and lines[i].strip() != "":
                expected.append(lines[i].rstrip())
                i += 1
            blocks.append(("query", sql, expected, i))
    return blocks


@pytest.mark.parametrize(
    "case", sorted(f for f in os.listdir(CASES_DIR) if f.endswith(".slt")))
def test_sqllogic(case, tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    session = Session()
    try:
        for kind, sql, expected, lineno in _parse_slt(
                os.path.join(CASES_DIR, case)):
            if kind == "ok":
                ex.execute_one(sql, session)
            elif kind == "error":
                with pytest.raises(Exception):
                    ex.execute_one(sql, session)
            else:
                rs = ex.execute_one(sql, session)
                # no .strip(): a trailing all-NULL row renders as an empty
                # line that must still count as a row
                got = format_csv(rs)[:-1].split("\n")
                # \N in expected = empty cell (NULL/NaN); the explicit
                # marker keeps all-NULL rows from reading as blank
                # block-terminator lines
                expected = [ln.replace("\\N", "") for ln in expected]
                assert got == expected, (
                    f"{case}:{lineno} for {sql!r}\n"
                    f"expected: {expected}\n     got: {got}")
    finally:
        coord.close()
