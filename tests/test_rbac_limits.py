"""RBAC roles/privileges, tenant rate limiters, memory pool (reference
common/models/src/auth/, meta/src/limiter/local_request_limiter.rs:44,
common/memory_pool/src/lib.rs:18-60)."""
import time

import pytest

from cnosdb_tpu.errors import AuthError, LimiterError
from cnosdb_tpu.models.schema import TenantOptions
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.limiter import TenantLimiters, TokenBucket
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils.memory_pool import MemoryExhausted, MemoryPool


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    coord.close()


def test_rbac_grant_revoke_flow(db):
    root = Session()
    db.execute_one("CREATE USER reader WITH PASSWORD = 'r'", root)
    db.execute_one("CREATE USER writer WITH PASSWORD = 'w'", root)
    db.execute_one("CREATE ROLE rw INHERIT member", root)
    db.execute_one("GRANT WRITE ON DATABASE public TO ROLE rw", root)
    db.execute_one("ALTER TENANT cnosdb ADD USER reader AS member", root)
    db.execute_one("ALTER TENANT cnosdb ADD USER writer AS rw", root)
    db.execute_one("CREATE TABLE t (v DOUBLE, TAGS(h))", root)
    db.execute_one("INSERT INTO t (time, h, v) VALUES (1, 'a', 1)", root)

    reader = Session(user="reader")
    writer = Session(user="writer")
    # member: read ok, write denied
    assert db.execute_one("SELECT count(*) FROM t", reader).columns[0][0] == 1
    with pytest.raises(AuthError):
        db.execute_one("INSERT INTO t (time, h, v) VALUES (2, 'a', 2)", reader)
    # custom role with WRITE: read + write ok, DDL denied
    db.execute_one("INSERT INTO t (time, h, v) VALUES (2, 'a', 2)", writer)
    assert db.execute_one("SELECT count(*) FROM t", writer).columns[0][0] == 2
    with pytest.raises(AuthError):
        db.execute_one("DROP TABLE t", writer)
    # revoke takes write away again
    db.execute_one("REVOKE WRITE ON DATABASE public FROM ROLE rw", root)
    with pytest.raises(AuthError):
        db.execute_one("INSERT INTO t (time, h, v) VALUES (3, 'a', 3)", writer)
    # SHOW ROLES lists system + custom roles
    rs = db.execute_one("SHOW ROLES", root)
    assert set(rs.columns[0].tolist()) >= {"owner", "member", "rw"}


def test_rbac_owner_and_nonmember(db):
    root = Session()
    db.execute_one("CREATE USER boss WITH PASSWORD = 'b'", root)
    db.execute_one("CREATE USER stranger WITH PASSWORD = 's'", root)
    db.execute_one("CREATE TENANT acme", root)
    db.execute_one("ALTER TENANT acme ADD USER boss AS owner", root)
    owner = Session(tenant="acme", user="boss")
    # owners may run DDL in their tenant
    db.execute_one("CREATE DATABASE d WITH SHARD 1", owner)
    # non-member denied on anything touching the tenant's databases
    # (constant SELECTs are privilege-free — function/session.slt)
    with pytest.raises(AuthError):
        db.execute_one("SHOW TABLES", Session(tenant="acme", database="d",
                                              user="stranger"))
    # the constant-SELECT exemption must not extend to aliased tables,
    # joins, or derived tables (stmt.table is None but from_item is set)
    db.execute_one("CREATE TABLE d.secret (v BIGINT, TAGS(tg))", owner)
    for q in ("SELECT * FROM secret s",
              "SELECT * FROM (SELECT * FROM secret) q"):
        with pytest.raises(AuthError):
            db.execute_one(q, Session(tenant="acme", database="d",
                                      user="stranger"))


def test_token_bucket():
    b = TokenBucket(10)  # 10/s, burst 10
    assert all(b.try_acquire() for _ in range(10))
    assert not b.try_acquire()
    time.sleep(0.25)
    assert b.try_acquire()  # ~2.5 tokens refilled
    assert not b.try_acquire(5)


def test_tenant_limiters(db):
    meta = db.meta
    meta.create_tenant("lim", TenantOptions(
        limiter={"max_writes_per_sec": 2, "max_queries_per_sec": 1000}))
    lims = TenantLimiters(meta)
    lims.check_write("lim")
    lims.check_write("lim")
    with pytest.raises(LimiterError):
        lims.check_write("lim")
    # unlimited tenant never throttles
    for _ in range(100):
        lims.check_write("cnosdb")
    lims.check_query("lim")


def test_memory_pool_gates_queries(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord, memory_pool=MemoryPool(512))
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    vals = ", ".join(f"({i}, 'a', {i})" for i in range(200))
    ex.execute_one(f"INSERT INTO m (time, h, v) VALUES {vals}")
    with pytest.raises(MemoryExhausted):
        ex.execute_one("SELECT sum(v) FROM m")   # scan exceeds 512 bytes
    ex.memory_pool = MemoryPool(1 << 20)
    assert ex.execute_one("SELECT count(*) FROM m").columns[0][0] == 200
    assert ex.memory_pool.used == 0   # released after the query
    coord.close()


def test_rbac_no_privilege_escalation(db):
    """Tenant owners must NOT reach instance administration or foreign
    tenants (review findings: ALTER USER root, cross-tenant ALTER TENANT)."""
    root = Session()
    db.execute_one("CREATE USER boss WITH PASSWORD = 'b'", root)
    db.execute_one("CREATE TENANT corp", root)
    db.execute_one("ALTER TENANT corp ADD USER boss AS owner", root)
    owner = Session(tenant="corp", user="boss")
    with pytest.raises(AuthError):
        db.execute_one("ALTER USER root SET PASSWORD = 'hacked'", owner)
    with pytest.raises(AuthError):
        db.execute_one("CREATE USER mallory WITH PASSWORD = 'm'", owner)
    with pytest.raises(AuthError):
        db.execute_one("DROP TENANT cnosdb", owner)
    with pytest.raises(AuthError):
        # owner of corp must not grant himself membership elsewhere
        db.execute_one("ALTER TENANT cnosdb ADD USER boss AS owner", owner)
    # but CAN manage membership of his own tenant
    db.execute_one("CREATE USER worker WITH PASSWORD = 'w'", root)
    db.execute_one("ALTER TENANT corp ADD USER worker AS member", owner)


def test_role_validation(db):
    root = Session()
    with pytest.raises(Exception):
        db.execute_one("CREATE ROLE IF NOT EXISTS bad INHERIT bogus", root)
    with pytest.raises(Exception):
        db.execute_one("DROP ROLE nosuch", root)
    db.execute_one("DROP ROLE IF EXISTS nosuch", root)
    db.execute_one("CREATE USER u9 WITH PASSWORD = 'x'", root)
    with pytest.raises(Exception):
        db.execute_one("ALTER TENANT cnosdb ADD USER u9 AS missing_role", root)
