"""Fast (single-process) fault-injection suite.

Exercises the deterministic fault plane (cnosdb_tpu/faults.py) and the
invariants it exists to prove: schedule determinism, at-most-once RPC
apply under lost replies, WAL torn-tail truncation on recovery, server
error counters, and the coordinator circuit breaker / backoff hardening.
The multi-process partition/crash soak lives in test_chaos_cluster.py
(slow-marked).
"""
import threading
import time

import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.parallel.net import (RpcError, RpcServer, RpcUnavailable,
                                     rpc_call, wait_rpc_ready)
from cnosdb_tpu.storage.record_file import RecordReader, RecordWriter
from cnosdb_tpu.storage.wal import Wal, WalEntryType
from cnosdb_tpu.utils import stages
from cnosdb_tpu.utils.backoff import Backoff


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()
    stages.reset()


# ------------------------------------------------------------- schedule plane
def test_disabled_by_default_zero_overhead():
    assert faults.ENABLED is False
    assert faults.fire("rpc.send", addr="x") is None


def test_schedule_nth_times_if():
    faults.configure("wal.append:fail:nth=2")
    assert faults.fire("wal.append", dir="d") is None
    with pytest.raises(faults.FaultInjected):
        faults.fire("wal.append", dir="d")
    assert faults.fire("wal.append", dir="d") is None

    faults.configure("rpc.reply:drop:times=2,if=write")
    assert faults.fire("rpc.reply", method="scan_vnode") is None
    assert faults.fire("rpc.reply", method="write_replica") == ("drop", None)
    assert faults.fire("rpc.reply", method="write_replica") == ("drop", None)
    assert faults.fire("rpc.reply", method="write_replica") is None


def test_schedule_after_and_args():
    faults.configure("record.append:torn(4):after=2")
    assert faults.fire("record.append", path="p") is None
    assert faults.fire("record.append", path="p") is None
    assert faults.fire("record.append", path="p") == ("torn", "4")
    assert faults.fire("record.append", path="p") == ("torn", "4")


def test_prob_schedule_is_deterministic():
    """Same seed + same call sequence → identical firing sequence and
    fired log, across reconfigurations (stands in for across processes:
    the RNG is seeded from the spec text via crc32, not hash())."""
    spec = "seed=42;flush.run:delay(1):prob=0.3;wal.sync:delay(1):prob=0.7"

    def run():
        faults.configure(spec)
        for i in range(30):
            faults.fire("flush.run", path=f"p{i}")
            faults.fire("wal.sync", dir="d")
        return faults.fired_log()

    log1, log2 = run(), run()
    assert log1 == log2
    assert any(p == "flush.run" for p, _, _ in log1)
    assert any(p == "wal.sync" for p, _, _ in log1)


def test_different_seed_different_schedule():
    logs = []
    for seed in (1, 2):
        faults.configure(f"seed={seed};flush.run:delay(1):prob=0.5")
        for i in range(40):
            faults.fire("flush.run", path=f"p{i}")
        logs.append([h for _, _, h in faults.fired_log()])
    assert logs[0] != logs[1]


def test_malformed_spec_rejected():
    with pytest.raises(ValueError):
        faults.configure("wal.append:explode")
    with pytest.raises(ValueError):
        faults.configure("justapoint")
    with pytest.raises(ValueError):
        faults.configure("wal.append:fail:bogus=1")


def test_control_surface():
    out = faults.control({"spec": "wal.append:fail:once", "log": True})
    assert out["ok"] and out["enabled"] and out["log"] == []
    with pytest.raises(faults.FaultInjected):
        faults.fire("wal.append", dir="d")
    out = faults.control({"log": True})
    assert out["log"] == [["wal.append", "fail", 1]]
    out = faults.control({"spec": ""})
    assert out["enabled"] is False


# ------------------------------------------------------------------ RPC plane
@pytest.fixture()
def rpc_server():
    calls = {"n": 0, "lock": threading.Lock()}

    def apply_(payload):
        with calls["lock"]:
            calls["n"] += 1
        return {"ok": True, "n": calls["n"]}

    def boom(payload):
        raise ValueError("handler exploded")

    srv = RpcServer("127.0.0.1", 0, {"apply": apply_, "boom": boom,
                                     "ping": lambda p: {"pong": True}})
    srv.start()
    yield srv, calls
    srv.stop()


def test_rpc_send_partition(rpc_server):
    """rpc.send models a network partition toward (addr, method): the
    client sees RpcUnavailable and the server never applies anything."""
    srv, calls = rpc_server
    faults.configure(f"rpc.send:fail:if={srv.addr}")
    with pytest.raises(RpcUnavailable):
        rpc_call(srv.addr, "apply", {})
    assert calls["n"] == 0
    # a different peer is unaffected by the if= filter
    faults.configure("rpc.send:fail:if=9.9.9.9:1")
    assert rpc_call(srv.addr, "apply", {})["ok"]
    assert calls["n"] == 1


def test_lost_reply_is_at_most_once(rpc_server):
    """The net.py:204 lost-ack case: the server applies the mutation but
    the reply is dropped. The client MUST see a response-phase failure and
    MUST NOT auto-retry — exactly one apply happened."""
    srv, calls = rpc_server
    faults.configure("rpc.reply:drop:nth=1,if=apply")
    with pytest.raises(RpcUnavailable):
        rpc_call(srv.addr, "apply", {})
    assert calls["n"] == 1  # applied exactly once despite the lost ack
    # the plane recovered: a fresh call applies a second time
    assert rpc_call(srv.addr, "apply", {})["n"] == 2


def test_lost_response_client_side_at_most_once(rpc_server):
    """rpc.response: reply lost in the network after the server processed
    the request — same at-most-once contract, client-side injection."""
    srv, calls = rpc_server
    faults.configure(f"rpc.response:fail:once,if={srv.addr}")
    with pytest.raises(RpcUnavailable):
        rpc_call(srv.addr, "apply", {})
    # the request was on the wire before the injected loss: the server
    # finishes applying it asynchronously — wait, then assert exactly once
    deadline = time.monotonic() + 5.0
    while calls["n"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert calls["n"] == 1
    assert rpc_call(srv.addr, "apply", {})["n"] == 2


def test_server_fault_point_and_error_counter(rpc_server):
    srv, _ = rpc_server
    faults.configure("rpc.server:fail:nth=1,if=apply")
    with pytest.raises(RpcError):
        rpc_call(srv.addr, "apply", {})
    # injected server-side failure and real handler errors both count
    with pytest.raises(RpcError, match="handler exploded"):
        rpc_call(srv.addr, "boom", {})
    errs = stages.errors_snapshot()
    assert errs.get("rpc.apply") == 1
    assert errs.get("rpc.boom") == 1


def test_wait_rpc_ready_reports_elapsed_and_cause():
    t0 = time.monotonic()
    with pytest.raises(RpcUnavailable) as ei:
        wait_rpc_ready("127.0.0.1:1", timeout=0.4)
    assert time.monotonic() - t0 < 5.0
    msg = str(ei.value)
    assert "not ready after" in msg and "last error" in msg
    assert ei.value.__cause__ is not None


# ------------------------------------------------------------------ WAL layer
def test_wal_torn_final_record_recovery(tmp_path):
    """Crash mid-append (torn tail): recovery keeps every entry before the
    tear, truncates the tear, and post-recovery appends are replayable."""
    d = str(tmp_path / "wal")
    w = Wal(d)
    for i in range(10):
        w.append(WalEntryType.WRITE, f"w{i}".encode())
    w.sync()
    faults.configure("record.append:torn:nth=1")
    with pytest.raises(faults.FaultInjected):
        w.append(WalEntryType.WRITE, b"torn-victim")
    faults.reset()
    # the process "died" here: drop the handle without a clean close
    w._writer._f.close()

    w2 = Wal(d)
    entries = list(w2.replay())
    assert [e.seq for e in entries] == list(range(1, 11))
    assert [e.data for e in entries] == [f"w{i}".encode() for i in range(10)]
    assert w2.next_seq == 11
    # the tear was truncated on reopen, so new appends stay replayable
    s = w2.append(WalEntryType.WRITE, b"after-recovery")
    assert s == 11
    assert list(w2.replay())[-1].data == b"after-recovery"
    w2.close()


def test_wal_truncated_segment_header_recovery(tmp_path):
    """Crash during segment creation leaves a file shorter than the magic;
    reopening must restart that segment instead of appending after it."""
    import os

    d = str(tmp_path / "wal")
    w = Wal(d, max_segment_size=128)
    for i in range(10):
        w.append(WalEntryType.WRITE, b"x" * 24)
    w.close()
    segs = sorted(f for f in os.listdir(d) if f.startswith("wal_"))
    assert len(segs) > 1
    # simulate the crash: newest segment died mid-header
    newest = os.path.join(d, segs[-1])
    with open(newest, "wb") as f:
        f.write(b"CNO")
    w2 = Wal(d)
    before = [e.seq for e in w2.replay()]
    s = w2.append(WalEntryType.WRITE, b"fresh")
    assert [e.seq for e in w2.replay()] == before + [s]
    assert list(w2.replay())[-1].data == b"fresh"
    w2.close()


def test_record_writer_truncates_torn_tail_on_reopen(tmp_path):
    p = str(tmp_path / "r.log")
    w = RecordWriter(p)
    w.append(b"one")
    w.append(b"two")
    w.close()
    import os
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-2])  # tear the last record
    w2 = RecordWriter(p)
    w2.append(b"three")
    w2.close()
    assert RecordReader(p).records() == [b"one", b"three"]
    # file holds no dead bytes: reported size equals the valid prefix
    from cnosdb_tpu.storage.record_file import _valid_prefix_len
    assert _valid_prefix_len(p) == os.path.getsize(p)


def test_wal_sync_enospc_surfaces(tmp_path):
    import errno

    w = Wal(str(tmp_path / "wal"))
    w.append(WalEntryType.WRITE, b"a")
    faults.configure("wal.sync:enospc:once")
    with pytest.raises(OSError) as ei:
        w.sync()
    assert ei.value.errno == errno.ENOSPC
    w.sync()  # once: next sync succeeds
    w.close()


# ------------------------------------------------------- hardening primitives
def test_backoff_grows_and_caps():
    bo = Backoff(initial=0.1, cap=0.5, factor=2.0)
    import random
    bo._rng = random.Random(7)
    delays = [bo.next() for _ in range(8)]
    assert all(0.0 <= d <= 0.5 for d in delays)
    # ceilings: 0.1, 0.2, 0.4, then capped at 0.5
    assert delays[0] <= 0.1
    bo.reset()
    assert bo.attempt == 0


def test_backoff_sleep_respects_deadline():
    bo = Backoff(initial=10.0, cap=10.0)
    assert bo.sleep(time.monotonic() - 1.0) is False  # already expired
    t0 = time.monotonic()
    assert bo.sleep(time.monotonic() + 0.05) is True  # clamped to 50ms
    assert time.monotonic() - t0 < 1.0


def test_circuit_breaker_fast_fails_then_probes(monkeypatch, tmp_path):
    """After CB_THRESHOLD consecutive connection failures to a node, the
    coordinator fast-fails without paying the RPC timeout, then re-probes
    after the cooldown and closes the circuit on success."""
    from cnosdb_tpu.parallel import coordinator as coord_mod
    from cnosdb_tpu.parallel.coordinator import Coordinator

    monkeypatch.setattr(coord_mod, "CB_THRESHOLD", 2)
    monkeypatch.setattr(coord_mod, "CB_COOLDOWN", 0.15)

    co = Coordinator.__new__(Coordinator)  # breaker state only, no engine
    co._cb = {}
    co._cb_lock = threading.Lock()
    co.meta = type("M", (), {"node_addr": staticmethod(
        lambda nid: "127.0.0.1:9")})()

    calls = {"n": 0}
    state = {"up": False}

    def fake_rpc_call(addr, method, payload, timeout=10.0):
        calls["n"] += 1
        if not state["up"]:
            raise RpcUnavailable(f"{method}@{addr}: down")
        return {"ok": True}

    monkeypatch.setattr(coord_mod, "rpc_call", fake_rpc_call,
                        raising=False)
    import cnosdb_tpu.parallel.net as net_mod
    monkeypatch.setattr(net_mod, "rpc_call", fake_rpc_call)

    for _ in range(2):
        with pytest.raises(RpcUnavailable):
            co._rpc(1, "ping", {})
    assert calls["n"] == 2
    # circuit now open: the wire is NOT touched
    with pytest.raises(RpcUnavailable, match="circuit open"):
        co._rpc(1, "ping", {})
    assert calls["n"] == 2
    # after the cooldown one probe goes through and closes the circuit
    state["up"] = True
    time.sleep(0.2)
    assert co._rpc(1, "ping", {})["ok"]
    assert calls["n"] == 3
    assert co._rpc(1, "ping", {})["ok"]


def test_rpc_error_does_not_trip_breaker(monkeypatch):
    """An app-level rejection proves the peer is alive: it must reset the
    consecutive-failure count, not add to it."""
    from cnosdb_tpu.parallel import coordinator as coord_mod
    from cnosdb_tpu.parallel.coordinator import Coordinator

    monkeypatch.setattr(coord_mod, "CB_THRESHOLD", 2)
    co = Coordinator.__new__(Coordinator)
    co._cb = {}
    co._cb_lock = threading.Lock()
    co.meta = type("M", (), {"node_addr": staticmethod(
        lambda nid: "127.0.0.1:9")})()

    seq = [RpcUnavailable("down"), RpcError("rejected"),
           RpcUnavailable("down"), RpcError("rejected")]

    def fake_rpc_call(addr, method, payload, timeout=10.0):
        raise seq.pop(0)

    import cnosdb_tpu.parallel.net as net_mod
    monkeypatch.setattr(net_mod, "rpc_call", fake_rpc_call)

    for exc in (RpcUnavailable, RpcError, RpcUnavailable, RpcError):
        with pytest.raises(exc):
            co._rpc(1, "ping", {})
    assert co._cb == {}  # never accumulated to the threshold
