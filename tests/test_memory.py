"""Memory-governance plane tests (cnosdb_tpu/server/memory.py).

Covers the broker's degradation ladder (pool reclaim largest-first →
queued-query shed → write backpressure → fail-closed), the dtype-aware
memcache sizing that replaced the flat 48-byte row heuristic, per-query
accounting kills, spill-to-disk group-by state (bit-identical to the
in-memory path AND to the CNOSDB_MEMORY=0 legacy path), and the HTTP
status mapping for the new typed errors. Global knobs the tests touch
(GROUP_BYTES, PER_QUERY_BYTES, WRITE_DELAY_MS, the broker override and
the admission-gate hook) are always saved and restored.
"""
import os
import threading

import numpy as np
import pytest

from cnosdb_tpu.errors import (AdmissionRejected, MemoryExceeded,
                               WriteBackpressure)
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.server import memory as memgov
from cnosdb_tpu.server.admission import AdmissionGate
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import deadline as dmod


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()


@pytest.fixture
def gate_hook():
    """Snapshot/restore the broker's admission-gate hook."""
    prev = memgov._GATE.get("gate")
    yield
    memgov.set_admission_gate(prev)


def _delta(c0, c1, pool, action):
    return c1.get((pool, action), 0) - c0.get((pool, action), 0)


# ------------------------------------------------------------- the ladder
def test_rebalance_reclaims_largest_pool_first(gate_hook):
    b = memgov.MemoryBroker()
    usage = {"a": 600, "b": 300}
    calls = []

    def reclaim(name):
        def run(need):
            calls.append((name, need))
            freed, usage[name] = usage[name], 0
            return freed
        return run

    b.register_pool("a", usage_fn=lambda: usage["a"], reclaim=reclaim("a"))
    b.register_pool("b", usage_fn=lambda: usage["b"], reclaim=reclaim("b"))
    memgov.set_admission_gate(None)
    b.resize(1000)                       # soft 700, hard 900; used 900
    used = b.rebalance(force=True)
    # largest pool reclaimed first, and ONLY it — freeing 600 puts the
    # node back under soft, so 'b' must survive untouched
    assert calls == [("a", 200)]
    assert used == 300 and usage["b"] == 300


def test_rebalance_sheds_queued_queries_when_reclaim_insufficient(gate_hook):
    b = memgov.MemoryBroker()
    b.register_pool("pinned", usage_fn=lambda: 900)   # nothing evictable

    class FakeGate:
        def __init__(self):
            self.retry_afters = []

        def shed_queued(self, retry_after=1.0):
            self.retry_afters.append(retry_after)
            return 3

    g = FakeGate()
    memgov.set_admission_gate(g)
    c0 = memgov.counters_snapshot()
    b.resize(1000)
    b.rebalance(force=True)
    c1 = memgov.counters_snapshot()
    assert len(g.retry_afters) == 1
    assert 0.5 <= g.retry_afters[0] <= 5.0
    assert _delta(c0, c1, "admission", "shed_queued") == 3


def test_write_admit_free_below_soft(gate_hook):
    b = memgov.MemoryBroker()
    memgov.set_admission_gate(None)
    b.resize(1000)
    b.write_admit(10)                    # no pools, used 0: must not block


def test_write_admit_fails_closed_above_hard(gate_hook):
    b = memgov.MemoryBroker()
    b.register_pool("pinned", usage_fn=lambda: 950)
    memgov.set_admission_gate(None)
    b.resize(1000)                       # hard 900
    c0 = memgov.counters_snapshot()
    with pytest.raises(MemoryExceeded):
        b.write_admit(10)
    assert _delta(c0, memgov.counters_snapshot(), "write", "fail_hard") == 1


def test_write_admit_bounded_delay_admits_on_drain(gate_hook):
    """Between soft and hard the write waits for flush progress: the
    first reclaim attempt fails, the in-loop forced rebalance drains
    the pool, and the write goes through counted as 'delayed'."""
    b = memgov.MemoryBroker()
    state = {"usage": 800, "attempts": 0}

    def reclaim(_need):
        state["attempts"] += 1
        if state["attempts"] < 2:
            return 0                     # flush not done yet
        freed, state["usage"] = state["usage"], 0
        return freed

    b.register_pool("mc", usage_fn=lambda: state["usage"], reclaim=reclaim)
    memgov.set_admission_gate(None)
    prev_delay = memgov.WRITE_DELAY_MS
    memgov.WRITE_DELAY_MS = 1000
    c0 = memgov.counters_snapshot()
    try:
        b.resize(1000)                   # soft 700 < used 800 < hard 900
        b.write_admit(10)                # must return, not raise
    finally:
        memgov.WRITE_DELAY_MS = prev_delay
    assert state["attempts"] >= 2
    assert _delta(c0, memgov.counters_snapshot(), "write", "delayed") == 1


def test_write_admit_sheds_backpressure_when_drain_stalls(gate_hook):
    b = memgov.MemoryBroker()
    b.register_pool("stuck", usage_fn=lambda: 800)    # never drains
    memgov.set_admission_gate(None)
    prev_delay = memgov.WRITE_DELAY_MS
    memgov.WRITE_DELAY_MS = 60           # keep the test fast
    c0 = memgov.counters_snapshot()
    try:
        b.resize(1000)
        with pytest.raises(WriteBackpressure) as ei:
            b.write_admit(10)
    finally:
        memgov.WRITE_DELAY_MS = prev_delay
    assert 0.5 <= ei.value.retry_after <= 10.0
    assert _delta(c0, memgov.counters_snapshot(),
                  "write", "backpressure_shed") == 1


def test_admission_gate_sheds_queued_waiter_with_retry_after():
    gate = AdmissionGate(max_concurrent=1, max_queued=4)
    gate.acquire()                       # occupy the only slot
    queued = threading.Event()
    err: list = []

    def waiter():
        queued.set()
        try:
            gate.acquire()
            gate.release()
        except AdmissionRejected as e:
            err.append(e)

    t = threading.Thread(target=waiter)
    t.start()
    queued.wait(5)
    # let the waiter actually enter the queue before shedding it
    deadline = dmod.Deadline(timeout_s=5)
    while gate.stats()["queued"] == 0 and not deadline.dead():
        pass
    shed = gate.shed_queued(retry_after=2.5)
    t.join(5)
    gate.release()
    assert shed == 1
    assert len(err) == 1 and err[0].retry_after == 2.5
    assert "memory" in str(err[0])


# ------------------------------------------------ dtype-aware memcache
def test_memcache_sizing_is_dtype_aware():
    """Regression for the flat _APPROX_ROW_BYTES=48 heuristic: 100 rows
    of 1 KiB strings are ~105 KiB of real payload, which the old sizing
    booked as 100×2×48 ≈ 9.4 KiB — never flushing a 64 KiB cache. The
    same row count of floats stays far under the cap."""
    from cnosdb_tpu.models.points import SeriesRows
    from cnosdb_tpu.models.schema import ValueType
    from cnosdb_tpu.models.series import SeriesKey
    from cnosdb_tpu.storage.memcache import MemCache, _series_rows_bytes

    ts = list(range(100))
    heavy = SeriesRows(SeriesKey("t", {}), ts,
                       {"s": (int(ValueType.STRING), ["x" * 1024] * 100)})
    assert _series_rows_bytes(heavy) >= 100 * 1024

    mc = MemCache(1, max_bytes=64 * 1024)
    mc.write_series("t", 1, heavy, seq=1)
    assert mc.should_flush(), \
        "string-heavy cache crossed its byte cap without noticing"

    light = SeriesRows(SeriesKey("t", {}), ts,
                       {"v": (int(ValueType.FLOAT),
                              np.zeros(100, dtype=np.float64))})
    mc2 = MemCache(1, max_bytes=64 * 1024)
    mc2.write_series("t", 1, light, seq=1)
    assert not mc2.should_flush(), \
        "float cache flushed at ~3 KiB of real payload"

    # gauge parity: the reference's 80-bytes-per-row-column usage gauge
    # (vnode_cache_size.slt) is decoupled from flush sizing — identical
    # shapes read identical regardless of dtype
    assert mc.usage_size == mc2.usage_size == 100 * 2 * 80


# -------------------------------------------------- spill-to-disk groups
def _spill_bed(db):
    db.execute_one("CREATE DATABASE sp WITH SHARD 4")
    s = Session(database="sp")
    db.execute_one("CREATE TABLE w (v DOUBLE, TAGS(h))", s)
    rng = np.random.default_rng(7)
    rows = []
    for i in range(400):
        # mixed magnitudes make float-sum association observable: any
        # reordering of the fold shows up in the low-order bits
        v = 1e15 if i % 17 == 0 else float(rng.normal(0.1, 0.05))
        rows.append(f"({1_700_000_000_000_000_000 + i * 1_000_000}, "
                    f"'h{i % 7}', {v!r})")
    db.execute_one("INSERT INTO w (time, h, v) VALUES " + ", ".join(rows),
                   s)

    def q(u: int) -> str:
        # the u-varying predicate matches every row (no h is ever 'zzN'):
        # identical answer, but a fresh query text defeats the serving
        # result cache so each run truly reaches the accumulator
        return (f"SELECT h, count(DISTINCT v), sum(v), min(v), max(v) "
                f"FROM w WHERE h <> 'zz{u}' GROUP BY h")

    return s, q


def test_group_spill_is_bit_identical(db):
    """The acceptance oracle: a 4-shard count(DISTINCT) group-by with
    the group budget squeezed to 1 byte spills every epoch to disk and
    must reproduce the in-memory answer EXACTLY — same float bits, same
    row order — and both must match the CNOSDB_MEMORY=0 legacy path."""
    s, q = _spill_bed(db)
    base = db.execute_one(q(0), s).rows()
    assert len(base) == 7

    prev = memgov.GROUP_BYTES
    memgov.GROUP_BYTES = 1
    c0 = memgov.counters_snapshot()
    try:
        spilled = db.execute_one(q(1), s).rows()
    finally:
        memgov.GROUP_BYTES = prev
    c1 = memgov.counters_snapshot()
    assert _delta(c0, c1, "query_groups", "spill") >= 1, \
        "1-byte group budget never engaged the spiller"
    assert _delta(c0, c1, "query_groups", "unspill") >= 1
    assert spilled == base

    # legacy path: plane off ignores the squeezed budget entirely
    prev_env = os.environ.get("CNOSDB_MEMORY")
    os.environ["CNOSDB_MEMORY"] = "0"
    memgov.GROUP_BYTES = 1
    c2 = memgov.counters_snapshot()
    try:
        legacy = db.execute_one(q(2), s).rows()
    finally:
        memgov.GROUP_BYTES = prev
        if prev_env is None:
            os.environ.pop("CNOSDB_MEMORY", None)
        else:
            os.environ["CNOSDB_MEMORY"] = prev_env
    assert _delta(c2, memgov.counters_snapshot(),
                  "query_groups", "spill") == 0
    assert legacy == base


def test_group_spill_crash_point_is_registered():
    from cnosdb_tpu import faults
    import cnosdb_tpu.sql.executor  # noqa: F401  (registers the point)

    assert "memory.spill" in faults.registered_points(scope="node")


# ---------------------------------------------------- per-query accounts
def test_query_memory_charge_release_peak():
    qm = memgov.QueryMemory(100)
    qm.charge(60, "scan")
    qm.release(30)
    qm.charge(50, "scan")
    assert (qm.used, qm.peak) == (80, 80)
    with pytest.raises(MemoryExceeded) as ei:
        qm.charge(30, "group_state", qid="q9")
    assert "group_state" in str(ei.value)


def test_per_query_budget_kills_only_the_oversized_query(db):
    db.execute_one("CREATE TABLE big (v DOUBLE, TAGS(h))")
    rows = ", ".join(
        f"({1_700_000_000_000_000_000 + i * 1_000_000}, 'h{i % 4}', {i}.5)"
        for i in range(5000))
    db.execute_one("INSERT INTO big (time, h, v) VALUES " + rows)

    # the filtered count scans 1250 rows (~30 KB live); the full SELECT
    # materializes all 5000 (~120 KB): a 64 KiB budget cleaves them
    prev = memgov.PER_QUERY_BYTES
    memgov.PER_QUERY_BYTES = 64 * 1024
    try:
        results: dict = {}

        def small(i):
            with dmod.scope(dmod.Deadline(timeout_s=30, qid=f"s{i}")):
                rs = db.execute_one(
                    "SELECT count(*) FROM big WHERE h = 'h0'")
                results[i] = int(rs.columns[0][0])

        ths = [threading.Thread(target=small, args=(i,)) for i in range(3)]
        for t in ths:
            t.start()
        c0 = memgov.counters_snapshot()
        with dmod.scope(dmod.Deadline(timeout_s=30, qid="big")):
            with pytest.raises(MemoryExceeded):
                db.execute_one("SELECT time, h, v FROM big")
        for t in ths:
            t.join()
        assert _delta(c0, memgov.counters_snapshot(),
                      "query", "killed") >= 1
        # the oversized query died alone: its in-budget neighbors
        # finished with correct answers
        assert results == {0: 1250, 1: 1250, 2: 1250}
    finally:
        memgov.PER_QUERY_BYTES = prev


def test_plane_off_disables_accounting_and_admission(gate_hook):
    prev_env = os.environ.get("CNOSDB_MEMORY")
    os.environ["CNOSDB_MEMORY"] = "0"
    try:
        assert memgov.query_mem() is None
        with dmod.scope(dmod.Deadline(timeout_s=5)):
            memgov.charge_query(1 << 40, "scan")     # no-op, no kill
        memgov.write_admit(1 << 40)                  # facade gates on env
    finally:
        if prev_env is None:
            os.environ.pop("CNOSDB_MEMORY", None)
        else:
            os.environ["CNOSDB_MEMORY"] = prev_env


# ------------------------------------------------------- observability
def test_debug_snapshot_and_runtime_control():
    out = memgov.control({"total_bytes": 12345})
    try:
        assert out["ok"]
        assert out["snapshot"]["total_bytes"] == 12345
    finally:
        out = memgov.control({"total_bytes": 0})     # back to auto
    snap = out["snapshot"]
    assert snap["total_bytes"] >= (1 << 30)          # auto floor
    assert {"enabled", "total_bytes", "soft_bytes", "hard_bytes",
            "used_bytes", "pools", "per_query_budget_bytes",
            "group_budget_bytes", "recent_events",
            "counters"} <= set(snap)
    assert snap["soft_bytes"] < snap["hard_bytes"] < snap["total_bytes"]
    # the counters fold as cnosdb_memory_total{pool,action} cells
    assert all("/" in k for k in snap["counters"])


def test_http_status_mapping_for_memory_errors():
    from cnosdb_tpu.server import http as http_mod

    assert http_mod._status_for(MemoryExceeded("too big")) == 413
    assert http_mod._status_for(
        WriteBackpressure("shed", retry_after=2.2)) == 503
    resp = http_mod._err_response(
        503, WriteBackpressure("shed", retry_after=2.2))
    assert resp.headers["Retry-After"] == "2"
    assert http_mod._status_for(AdmissionRejected("queue full")) == 503
