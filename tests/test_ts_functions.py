"""Function-family parity tests vs hand-computed oracles (reference
extension/expr: increase.rs, sample.rs, gauge/, state_agg/, data_quality/,
ts_gen_func/data_repair/, gis/)."""
import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.sql import tsfuncs
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    coord.close()


def test_increase_counter_reset(db):
    db.execute_one("CREATE TABLE c (v DOUBLE, TAGS(h))")
    # 1→5→8 rises 7; reset to 2 adds 2 (increase.rs:98-103); 2→4 adds 2
    db.execute_one("INSERT INTO c (time, h, v) VALUES "
                   "(1,'a',1),(2,'a',5),(3,'a',8),(4,'a',2),(5,'a',4)")
    rs = db.execute_one("SELECT increase(time, v) FROM c")
    assert rs.columns[0][0] == 11.0
    # short form without the explicit time arg
    rs = db.execute_one("SELECT increase(v) FROM c")
    assert rs.columns[0][0] == 11.0
    # per-group
    db.execute_one("INSERT INTO c (time, h, v) VALUES (1,'b',10),(2,'b',3)")
    rs = db.execute_one("SELECT h, increase(time, v) FROM c GROUP BY h "
                        "ORDER BY h")
    assert rs.columns[1].tolist() == [11.0, 3.0]


def test_gauge_agg_accessors(db):
    db.execute_one("CREATE TABLE g (v DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO g (time, h, v) VALUES "
                   "(1,'a',1),(2,'a',5),(3,'a',8),(4,'a',2),(5,'a',4)")
    rs = db.execute_one(
        "SELECT delta(gauge_agg(time, v)), rate(gauge_agg(time, v)), "
        "time_delta(gauge_agg(time, v)), first_val(gauge_agg(time, v)), "
        "last_val(gauge_agg(time, v)), idelta_left(gauge_agg(time, v)), "
        "idelta_right(gauge_agg(time, v)), num_elements(gauge_agg(time, v)) "
        "FROM g")
    row = [c[0] for c in rs.columns]
    assert row[0] == 3.0                 # last - first (gauge/mod.rs:44)
    assert abs(row[1] - 0.75) < 1e-12    # delta / time_delta
    # interval rendering (arrow IntervalMonthDayNano, 4ns span; seconds
    # carry float repr — the slt port normalizes the reference's fixed
    # 9 digits the same way)
    assert str(row[2]) == ("0 years 0 mons 0 days 0 hours 0 mins "
                           "4e-09 secs")
    assert row[3] == 1.0 and row[4] == 4.0
    assert row[5] == 4.0                 # second - first
    assert row[6] == 2.0                 # last - penultimate
    assert row[7] == 5


def test_state_agg_duration_in_state_at(db):
    db.execute_one("CREATE TABLE st (s STRING, TAGS(h))")
    db.execute_one("INSERT INTO st (time, h, s) VALUES "
                   "(0,'a','up'),(10,'a','down'),(30,'a','up'),"
                   "(40,'a','up'),(60,'a','down')")
    one = lambda q: db.execute_one(q).columns[0][0]  # noqa: E731
    assert one("SELECT duration_in(state_agg(time, s), 'up') FROM st") == 40
    assert one("SELECT duration_in(state_agg(time, s), 'down') FROM st") == 20
    assert one("SELECT state_at(state_agg(time, s), 35) FROM st") == "up"
    assert one("SELECT state_at(state_agg(time, s), 15) FROM st") == "down"
    # windowed duration_in [5, 5+30): up in [5,10) + [30,35)
    assert one("SELECT duration_in(state_agg(time, s), 'up', 5, 30) "
               "FROM st") == 10
    # compact form answers totals only
    assert one("SELECT duration_in(compact_state_agg(time, s), 'up') "
               "FROM st") == 40


def test_sample(db):
    db.execute_one("CREATE TABLE smp (v BIGINT, TAGS(h))")
    vals = ", ".join(f"({i},'a',{i})" for i in range(1, 101))
    db.execute_one(f"INSERT INTO smp (time, h, v) VALUES {vals}")
    s = db.execute_one("SELECT sample(v, 10) FROM smp").columns[0][0]
    assert isinstance(s, list) and len(s) == 10
    assert all(1 <= x <= 100 for x in s) and len(set(s)) == 10
    # n <= k returns everything
    s = db.execute_one("SELECT sample(v, 500) FROM smp").columns[0][0]
    assert len(s) == 100


def test_data_quality_clean_series(db):
    db.execute_one("CREATE TABLE dq (v DOUBLE, TAGS(h))")
    vals = ", ".join(f"({i * 10},'a',{float(i)})" for i in range(1, 21))
    db.execute_one(f"INSERT INTO dq (time, h, v) VALUES {vals}")
    for fn in ("completeness", "consistency", "timeliness"):
        rs = db.execute_one(f"SELECT {fn}(time, v) FROM dq")
        assert rs.columns[0][0] == 1.0, fn
    assert db.execute_one("SELECT validity(time, v) FROM dq").columns[0][0] >= 0.9


def test_data_quality_detects_missing_points():
    # direct oracle: evenly spaced except one 3-interval gap → 2 missing
    ts = np.array([0, 10, 20, 50, 60, 70, 80, 90, 100, 110, 120], dtype=np.int64)
    vals = np.arange(len(ts), dtype=np.float64)
    c = tsfuncs.data_quality("completeness", ts, vals)
    n, miss = len(ts), 2
    assert abs(c - (1.0 - miss / (n + miss))) < 1e-12


def test_timestamp_repair(db):
    db.execute_one("CREATE TABLE tr (v DOUBLE, TAGS(h))")
    # 10ns cadence with one missing slot (40) and one jittered point (71)
    db.execute_one("INSERT INTO tr (time, h, v) VALUES "
                   "(10,'a',1),(20,'a',2),(30,'a',3),(50,'a',5),"
                   "(60,'a',6),(71,'a',7)")
    rs = db.execute_one("SELECT timestamp_repair(time, v) FROM tr")
    # reference DP semantics (timestamp_repair.rs dp_repair): the grid
    # extends to cover the last sample (ceil((71-10)/10)+1 slots → ..80),
    # inserted slots are NaN (never interpolated), 71 aligns to 70
    assert rs.columns[0].tolist() == [10, 20, 30, 40, 50, 60, 70, 80]
    got = rs.columns[1].tolist()
    assert got[:3] == [1, 2, 3] and got[4:7] == [5, 6, 7]
    assert np.isnan(got[3]) and np.isnan(got[7])


def test_value_fill(db):
    db.execute_one("CREATE TABLE vf (v DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO vf (time, h, v) VALUES "
                   "(10,'a',1),(20,'a',2),(40,'a',4)")
    # linear interpolation over a NaN injected via direct API
    ts = np.array([10, 20, 30, 40], dtype=np.int64)
    v = np.array([1.0, 2.0, np.nan, 4.0])
    assert tsfuncs.value_fill(ts, v, "linear").tolist() == [1, 2, 3, 4]
    assert tsfuncs.value_fill(ts, v, "previous").tolist() == [1, 2, 2, 4]
    filled = tsfuncs.value_fill(ts, v, "mean")
    assert abs(filled[2] - np.mean([1, 2, 4])) < 1e-12


def test_value_repair_screen():
    ts = np.arange(0, 100, 10, dtype=np.int64)
    v = np.array([1.0, 2, 3, 4, 500, 6, 7, 8, 9, 10])  # spike at i=4
    out = tsfuncs.value_repair(ts, v)
    assert out[4] < 50  # spike clamped toward the speed envelope
    assert out[0] == 1.0 and out[-1] <= 10.0


def test_gis_scalars(db):
    one = lambda q: db.execute_one(q).columns[0][0]  # noqa: E731
    assert one("SELECT st_distance('POINT(0 0)', 'POINT(3 4)')") == 5.0
    assert one("SELECT st_area('POLYGON((0 0, 4 0, 4 3, 0 3, 0 0))')") == 12.0
    # point to segment distance
    d = one("SELECT st_distance('POINT(2 2)', 'LINESTRING(0 0, 4 0)')")
    assert d == 2.0
