"""LIKE, EXPLAIN ANALYZE, ES bulk, TSBS cpu-max-all-8 shape."""
import json

import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore, DEFAULT_TENANT
from cnosdb_tpu.protocol.es_bulk import parse_es_bulk
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    coord.close()


def test_like(db):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(host))")
    db.execute_one("INSERT INTO m (time, host, v) VALUES "
                   "(1, 'web-01', 1), (2, 'web-02', 2), (3, 'db-01', 3)")
    rs = db.execute_one("SELECT host FROM m WHERE host LIKE 'web-%' ORDER BY host")
    assert rs.columns[0].tolist() == ["web-01", "web-02"]
    rs = db.execute_one("SELECT count(*) FROM m WHERE host NOT LIKE 'web%'")
    assert rs.columns[0][0] == 1
    rs = db.execute_one("SELECT host FROM m WHERE host LIKE '__-01' ORDER BY host")
    assert rs.columns[0].tolist() == ["db-01"]  # exactly two leading chars
    rs = db.execute_one("SELECT host FROM m WHERE host LIKE '%-01' ORDER BY host")
    assert rs.columns[0].tolist() == ["db-01", "web-01"]


def test_explain_analyze(db):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO m (time, h, v) VALUES (1, 'a', 1), (2, 'a', 2)")
    rs = db.execute_one("EXPLAIN ANALYZE SELECT count(*) FROM m")
    text = "\n".join(rs.columns[0])
    assert "Execution: 1 rows" in text
    assert "TpuAggregateExec" in text


def test_es_bulk_parse_and_ingest(db):
    body = "\n".join([
        json.dumps({"index": {}}),
        json.dumps({"@timestamp": "2023-01-01T00:00:00Z", "service": "api",
                    "level": "error", "latency": 12.5, "code": 500}),
        json.dumps({"index": {}}),
        json.dumps({"@timestamp": "2023-01-01T00:00:01Z", "service": "api",
                    "level": "info", "latency": 3.25, "code": 200}),
    ])
    wb = parse_es_bulk(body, "logs", tag_keys=("service",))
    db.coord.write_points(DEFAULT_TENANT, "public", wb)
    rs = db.execute_one("SELECT count(*) AS c, max(latency) AS l FROM logs")
    assert rs.rows()[0] == (2, 12.5)
    rs = db.execute_one("SELECT level FROM logs WHERE code = 500")
    assert rs.columns[0].tolist() == ["error"]


def test_tsbs_cpu_max_all_8_shape(db):
    """The cpu-max-all-8 headline: max of 8 fields by hour for 8 hosts."""
    fields = [f"usage_{k}" for k in
              ("user", "system", "idle", "nice", "iowait", "irq",
               "softirq", "steal")]
    db.execute_one("CREATE TABLE cpu (" + ", ".join(f"{f} DOUBLE" for f in fields)
                   + ", TAGS(hostname))")
    rows = []
    rng = np.random.default_rng(7)
    for h in range(8):
        for i in range(120):  # 2 hours at 1m cadence
            t = i * 60_000_000_000
            vals = rng.integers(0, 100, 8)
            rows.append(f"({t}, 'host_{h}', " + ", ".join(map(str, vals)) + ")")
    db.execute_one(
        "INSERT INTO cpu (time, hostname, " + ", ".join(fields) + ") VALUES "
        + ", ".join(rows))
    sql = ("SELECT date_bin(INTERVAL '1 hour', time) AS t, hostname, "
           + ", ".join(f"max({f}) AS mx_{f}" for f in fields)
           + " FROM cpu WHERE hostname IN ('host_0','host_1','host_2','host_3',"
           "'host_4','host_5','host_6','host_7') GROUP BY t, hostname "
           "ORDER BY hostname, t")
    rs = db.execute_one(sql)
    assert rs.n_rows == 16  # 8 hosts × 2 hours
    assert len(rs.names) == 10
    # oracle check for one cell
    chk = db.execute_one(
        "SELECT max(usage_user) FROM cpu WHERE hostname = 'host_3' "
        "AND time < 3600000000000")
    row3 = [i for i in range(16) if rs.columns[1][i] == "host_3"
            and rs.columns[0][i] == 0][0]
    assert rs.columns[2][row3] == chk.columns[0][0]


def test_order_by_mixed_desc_asc_ties(db):
    """Regression: ORDER BY a DESC, b ASC must keep b ascending within
    equal a groups (reversing a stable argsort broke this)."""
    db.execute_one("CREATE TABLE mo (a BIGINT, b BIGINT, TAGS(t))")
    rows = [(i + 1, a, b) for i, (a, b) in enumerate(
        [(1, 3), (2, 1), (1, 1), (2, 3), (1, 2), (2, 2)])]
    vals = ", ".join(f"({t}, 'x', {a}, {b})" for t, a, b in rows)
    db.execute_one(f"INSERT INTO mo (time, t, a, b) VALUES {vals}")
    rs = db.execute_one("SELECT a, b FROM mo ORDER BY a DESC, b ASC")
    got = list(zip(rs.columns[0].tolist(), rs.columns[1].tolist()))
    assert got == [(2, 1), (2, 2), (2, 3), (1, 1), (1, 2), (1, 3)]
    rs = db.execute_one("SELECT a, b FROM mo ORDER BY a ASC, b DESC")
    got = list(zip(rs.columns[0].tolist(), rs.columns[1].tolist()))
    assert got == [(1, 3), (1, 2), (1, 1), (2, 3), (2, 2), (2, 1)]
