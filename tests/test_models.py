import numpy as np
import pytest

from cnosdb_tpu.models import (
    AllDomain,
    BucketInfo,
    ColumnDomains,
    ColumnType,
    DatabaseOptions,
    DatabaseSchema,
    Duration,
    Encoding,
    NoneDomain,
    Precision,
    ReplicationSet,
    SeriesKey,
    Tag,
    TableColumn,
    TimeRange,
    TimeRanges,
    TskvTableSchema,
    ValueType,
    VnodeInfo,
)
from cnosdb_tpu.models.predicate import RangeDomain, SetDomain, ValueRange
from cnosdb_tpu.errors import SchemaError, ColumnNotFound
from cnosdb_tpu.utils import BloomFilter, bkdr_hash


# ---------------------------------------------------------------- hash/bloom
def test_bkdr_hash_matches_definition():
    # h = h*1313 + byte, wrapping u64
    assert bkdr_hash(b"") == 0
    assert bkdr_hash(b"a") == ord("a")
    assert bkdr_hash(b"ab") == (ord("a") * 1313 + ord("b"))


def test_bloom_filter_roundtrip():
    bf = BloomFilter(1 << 12)
    ids = [1, 42, 999999, 2**63]
    for i in ids:
        bf.insert_u64(i)
    for i in ids:
        assert bf.maybe_contains_u64(i)
    # serialization round-trip
    bf2 = BloomFilter.from_bytes(bf.to_bytes())
    for i in ids:
        assert bf2.maybe_contains_u64(i)
    misses = sum(bf.maybe_contains_u64(i) for i in range(10_000, 11_000))
    assert misses < 20  # false-positive rate sanity


def test_bloom_batch_matches_scalar():
    bf = BloomFilter(1 << 12)
    ids = np.array([3, 17, 2**40, 2**63 + 5], dtype=np.uint64)
    bf.insert_u64_batch(ids)
    for i in ids:
        assert bf.maybe_contains_u64(int(i))
    batch = bf.maybe_contains_u64_batch(np.array([3, 17, 4444, 2**63 + 5], dtype=np.uint64))
    assert batch[0] and batch[1] and batch[3]
    # scalar insert visible to batch query
    bf2 = BloomFilter(1 << 12)
    bf2.insert_u64(12345)
    assert bf2.maybe_contains_u64_batch(np.array([12345], dtype=np.uint64))[0]


def test_non_ascii_series_key():
    k = SeriesKey("温度", {"主机": "h1", "区": "华东"})
    assert SeriesKey.decode(k.encode()) == k


def test_column_id_not_reused_after_drop_and_serde():
    s = _schema()
    s.add_column("f2", ColumnType.field(ValueType.FLOAT))
    dropped_id = s.column("f2").id
    s.drop_column("f2")
    s2 = TskvTableSchema.from_json(s.to_json())
    c = s2.add_column("f3", ColumnType.field(ValueType.FLOAT))
    assert c.id > dropped_id


def test_zero_duration_parses():
    # the reference accepts zero durations (dcl_tenant.slt: drop_after
    # '0' serializes as secs 0); ns=0 doubles as the INF sentinel
    assert Duration.parse("0d").ns == 0
    assert Duration.parse("0").ns == 0


# ---------------------------------------------------------------- series key
def test_series_key_sorted_tags_and_roundtrip():
    k1 = SeriesKey("cpu", [("host", "h1"), ("az", "us")])
    k2 = SeriesKey("cpu", [("az", "us"), ("host", "h1")])
    assert k1 == k2
    assert k1.hash_id() == k2.hash_id()
    k3 = SeriesKey.decode(k1.encode())
    assert k3 == k1
    assert k3.tag_value("host") == "h1"
    assert k3.tag_value("nope") is None


def test_series_key_distinct():
    a = SeriesKey("cpu", {"host": "h1"})
    b = SeriesKey("cpu", {"host": "h2"})
    c = SeriesKey("mem", {"host": "h1"})
    assert len({a, b, c}) == 3
    assert a.hash_id() != b.hash_id()


# ---------------------------------------------------------------- schema
def _schema():
    return TskvTableSchema.new_measurement(
        "cnosdb", "db1", "cpu",
        tags=["host", "region"],
        fields=[("usage_user", ValueType.FLOAT), ("n", ValueType.INTEGER)],
    )


def test_schema_structure():
    s = _schema()
    assert s.time_column.name == "time"
    assert s.tag_names() == ["host", "region"]
    assert s.field_names() == ["usage_user", "n"]
    assert s.column("usage_user").column_type.value_type == ValueType.FLOAT
    assert s.column("usage_user").encoding == Encoding.GORILLA
    assert s.column("time").encoding == Encoding.DELTA_TS
    with pytest.raises(ColumnNotFound):
        s.column("missing")


def test_schema_evolution_and_serde():
    s = _schema()
    v0 = s.schema_version
    s.add_column("usage_system", ColumnType.field(ValueType.FLOAT))
    assert s.schema_version == v0 + 1
    ids = [c.id for c in s.columns]
    assert len(ids) == len(set(ids))
    s2 = TskvTableSchema.from_json(s.to_json())
    assert s2.field_names() == s.field_names()
    assert s2.column("usage_system").encoding == s.column("usage_system").encoding
    with pytest.raises(SchemaError):
        s.drop_column("time")
    s.drop_column("n")
    assert "n" not in s.field_names()


def test_duration_parse():
    assert Duration.parse("1d").ns == 86_400_000_000_000
    assert Duration.parse("inf").is_inf
    assert Duration.parse("10m").ns == 600_000_000_000
    assert str(Duration.parse("365d")) == "365d"


def test_database_schema_serde():
    d = DatabaseSchema("cnosdb", "db1", DatabaseOptions(
        ttl=Duration.parse("30d"), shard_num=4,
        vnode_duration=Duration.parse("1d"), replica=2, precision=Precision.MS))
    d2 = DatabaseSchema.from_dict(d.to_dict())
    assert d2.options.shard_num == 4
    assert d2.options.precision == Precision.MS
    assert d2.owner == "cnosdb.db1"


# ---------------------------------------------------------------- time ranges
def test_time_ranges_normalize_and_ops():
    trs = TimeRanges([TimeRange(10, 20), TimeRange(15, 30), TimeRange(50, 60)])
    assert trs.ranges == [TimeRange(10, 30), TimeRange(50, 60)]
    assert trs.overlaps(TimeRange(25, 55))
    assert not trs.overlaps(TimeRange(31, 49))
    assert trs.contains(55)
    assert not trs.contains(40)
    inter = trs.intersect(TimeRanges([TimeRange(0, 12), TimeRange(55, 100)]))
    assert inter.ranges == [TimeRange(10, 12), TimeRange(55, 60)]
    assert TimeRanges.empty().is_empty
    assert TimeRanges.all().is_all


# ---------------------------------------------------------------- domains
def test_range_domain_algebra():
    d = RangeDomain.ge(10).intersect(RangeDomain.lt(20))
    assert d.contains_value(10)
    assert d.contains_value(19)
    assert not d.contains_value(20)
    none = RangeDomain.gt(5).intersect(RangeDomain.lt(5))
    assert isinstance(none, NoneDomain)
    s = SetDomain(["a", "b"]).intersect(SetDomain(["b", "c"]))
    assert s == SetDomain(["b"])
    s2 = RangeDomain.of(low="a", high="b").intersect(SetDomain(["b", "z"]))
    assert s2 == SetDomain(["b"])


def test_column_domains():
    cd = ColumnDomains.of("host", SetDomain(["h1", "h2"]))
    cd2 = ColumnDomains.of("host", SetDomain(["h2", "h3"]))
    inter = cd.intersect(cd2)
    assert inter.get("host") == SetDomain(["h2"])
    assert isinstance(inter.get("other"), AllDomain)
    empty = cd.intersect(ColumnDomains.of("host", SetDomain(["zzz"])))
    assert empty.is_none
    u = cd.union(ColumnDomains.all())
    assert u.is_all or isinstance(u.get("host"), AllDomain)


# ---------------------------------------------------------------- placement
def test_bucket_vnode_for():
    rs = [ReplicationSet(i, vnodes=[VnodeInfo(i * 10, 1)]) for i in range(4)]
    b = BucketInfo(1, 0, 1000, rs)
    assert b.contains(0) and b.contains(999) and not b.contains(1000)
    k = SeriesKey("cpu", {"host": "h7"})
    chosen = b.vnode_for(k.hash_id())
    assert chosen is rs[k.hash_id() % 4]


def test_password_hash_roundtrip():
    from cnosdb_tpu.parallel.meta import hash_password, verify_password
    h = hash_password("s3cret")
    assert "s3cret" not in h
    assert verify_password(h, "s3cret")
    assert not verify_password(h, "wrong")
    # legacy plaintext values still verify (constant-time)
    assert verify_password("plain", "plain")
    assert not verify_password("plain", "nope")


def test_meta_tenant_membership(tmp_path):
    from cnosdb_tpu.parallel.meta import MetaStore
    m = MetaStore(str(tmp_path / "meta.json"))
    m.create_user("alice", "pw")
    m.create_tenant("acme")
    assert m.check_user("alice", "pw") is not None
    assert m.check_user("alice", "bad") is None
    assert m.check_user("ghost", "pw") is None
    # non-member cannot reach a private tenant; default tenant is open
    assert not m.user_can_access("alice", "acme")
    assert m.user_can_access("alice", "cnosdb")
    m.add_member("acme", "alice", "member")
    assert m.user_can_access("alice", "acme")
    # persisted across reopen
    m2 = MetaStore(str(tmp_path / "meta.json"))
    assert m2.user_can_access("alice", "acme")
    assert m2.check_user("alice", "pw") is not None
    m.remove_member("acme", "alice")
    assert not m.user_can_access("alice", "acme")


def test_writebatch_array_native_roundtrip():
    """Array-native SeriesRows (the fast ingest path) must round-trip the
    WAL/RPC encoding bit-exactly and interoperate with list-form rows."""
    import numpy as np

    from cnosdb_tpu.models.points import SeriesRows, WriteBatch
    from cnosdb_tpu.models.schema import ValueType
    from cnosdb_tpu.models.series import SeriesKey

    ts = np.arange(5, dtype=np.int64) * 1_000_000_000
    vals = np.array([1.5, 2.5, -3.0, np.nan, 0.0])
    ints = np.array([1, -2, 3, 4, 5], dtype=np.int64)
    wb = WriteBatch()
    wb.add_series("m", SeriesRows(
        SeriesKey("m", {"h": "a"}), ts,
        {"f": (int(ValueType.FLOAT), vals),
         "i": (int(ValueType.INTEGER), ints)}))
    # list-form with a None rides alongside unchanged
    wb.add_series("m", SeriesRows(
        SeriesKey("m", {"h": "b"}), [10, 20],
        {"f": (int(ValueType.FLOAT), [7.0, None])}))
    out = WriteBatch.decode(wb.encode())
    srs = out.tables["m"]
    a, b = srs[0], srs[1]
    np.testing.assert_array_equal(np.asarray(a.timestamps), ts)
    got_f = np.asarray(a.fields["f"][1])
    assert got_f.dtype == np.float64
    np.testing.assert_array_equal(got_f, vals)  # NaN-exact
    np.testing.assert_array_equal(np.asarray(a.fields["i"][1]), ints)
    assert list(b.timestamps) == [10, 20]
    assert b.fields["f"][1] == [7.0, None]
