"""End-to-end SQL tests: parse → plan → execute over a live engine."""
import numpy as np
import pytest

from cnosdb_tpu.errors import CnosError, QueryError, TableNotFound
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()


@pytest.fixture
def air(db):
    """The reference's demo table (oceanic_station)."""
    db.execute_one("CREATE TABLE air (visibility DOUBLE, temperature DOUBLE, "
                   "pressure DOUBLE, TAGS(station))")
    rows = []
    for i in range(10):
        t = 1672531200000000000 + i * 60_000_000_000  # 2023-01-01 + i min
        st = "XiaoMaiDao" if i % 2 == 0 else "LianYunGang"
        rows.append(f"({t}, '{st}', {50 + i}, {20 + i * 0.5}, {1000 + i})")
    db.execute_one("INSERT INTO air (time, station, visibility, temperature, pressure) "
                   "VALUES " + ", ".join(rows))
    return db


def test_create_show_describe(db):
    db.execute_one("CREATE DATABASE mydb WITH TTL '30d' SHARD 2")
    rs = db.execute_one("SHOW DATABASES")
    assert "mydb" in rs.columns[0].tolist()
    db.execute_one("CREATE TABLE air (visibility DOUBLE, TAGS(station))")
    rs = db.execute_one("SHOW TABLES")
    assert rs.columns[0].tolist() == ["air"]
    rs = db.execute_one("DESCRIBE TABLE air")
    d = dict(zip(rs.columns[0].tolist(), rs.columns[2].tolist()))
    assert d["time"] == "TIME" and d["station"] == "TAG" and d["visibility"] == "FIELD"


def test_insert_select_star(air):
    rs = air.execute_one("SELECT * FROM air ORDER BY time")
    assert rs.n_rows == 10
    assert rs.names == ["time", "station", "visibility", "temperature", "pressure"]
    assert rs.columns[2][0] == 50.0
    assert rs.columns[1][1] == "LianYunGang"


def test_select_where_projection(air):
    rs = air.execute_one(
        "SELECT temperature, visibility FROM air "
        "WHERE station = 'XiaoMaiDao' AND visibility > 53 ORDER BY time")
    assert rs.n_rows == 3  # i in {4,6,8}
    np.testing.assert_allclose(rs.columns[0], [22.0, 23.0, 24.0])


def test_global_aggregate(air):
    rs = air.execute_one(
        "SELECT count(*), avg(visibility), min(pressure), max(pressure) FROM air")
    assert rs.rows()[0] == (10, pytest.approx(54.5), 1000.0, 1009.0)


def test_group_by_tag(air):
    rs = air.execute_one(
        "SELECT station, count(*) AS c, max(temperature) AS mx FROM air "
        "GROUP BY station ORDER BY station")
    assert rs.rows() == [("LianYunGang", 5, pytest.approx(24.5)),
                         ("XiaoMaiDao", 5, pytest.approx(24.0))]


def test_group_by_time_bucket(air):
    rs = air.execute_one(
        "SELECT date_bin(INTERVAL '5 minutes', time) AS t, count(*) AS c "
        "FROM air GROUP BY t ORDER BY t")
    assert rs.n_rows == 2
    assert rs.columns[1].tolist() == [5, 5]


def test_double_groupby(air):
    rs = air.execute_one(
        "SELECT station, date_bin(INTERVAL '5 minutes', time) AS t, "
        "avg(visibility) AS v FROM air GROUP BY station, t ORDER BY station, t")
    assert rs.n_rows == 4
    # LianYunGang odd minutes: {1,3} then {5,7,9}; XiaoMaiDao {0,2,4} then {6,8}
    assert rs.columns[2].tolist() == pytest.approx([52.0, 57.0, 52.0, 57.0])


def test_first_last(air):
    rs = air.execute_one(
        "SELECT station, first(visibility) AS f, last(visibility) AS l "
        "FROM air GROUP BY station ORDER BY station")
    assert rs.rows() == [("LianYunGang", 51.0, 59.0), ("XiaoMaiDao", 50.0, 58.0)]


def test_having_and_arith(air):
    rs = air.execute_one(
        "SELECT station, max(visibility) - min(visibility) AS spread FROM air "
        "GROUP BY station HAVING count(*) >= 5 ORDER BY station")
    assert rs.columns[1].tolist() == [8.0, 8.0]


def test_time_range_filter(air):
    rs = air.execute_one(
        "SELECT count(*) FROM air WHERE time >= '2023-01-01T00:03:00Z' "
        "AND time < '2023-01-01T00:07:00Z'")
    assert rs.columns[0][0] == 4


def test_count_distinct(air):
    rs = air.execute_one("SELECT count(DISTINCT station) FROM air")
    assert rs.columns[0][0] == 2


def test_limit_offset(air):
    rs = air.execute_one("SELECT time FROM air ORDER BY time LIMIT 3 OFFSET 2")
    assert rs.n_rows == 3
    assert rs.columns[0][0] == 1672531200000000000 + 2 * 60_000_000_000


def test_order_desc(air):
    rs = air.execute_one("SELECT visibility FROM air ORDER BY visibility DESC LIMIT 2")
    assert rs.columns[0].tolist() == [59.0, 58.0]


def test_delete(air):
    air.execute_one("DELETE FROM air WHERE time < '2023-01-01T00:05:00Z'")
    rs = air.execute_one("SELECT count(*) FROM air")
    assert rs.columns[0][0] == 5
    air.execute_one("DELETE FROM air WHERE station = 'XiaoMaiDao'")
    rs = air.execute_one("SELECT count(*) FROM air")
    assert rs.columns[0][0] == 3


def test_update_tag(air):
    air.execute_one("UPDATE air SET station = 'Renamed' WHERE station = 'XiaoMaiDao'")
    rs = air.execute_one("SHOW TAG VALUES FROM air WITH KEY = station")
    assert rs.columns[1].tolist() == ["LianYunGang", "Renamed"]


def test_show_series_tag_values(air):
    rs = air.execute_one("SHOW SERIES FROM air")
    assert rs.n_rows == 2
    rs = air.execute_one("SHOW TAG VALUES FROM air WITH KEY = station")
    assert rs.names == ["key", "value"]
    assert set(rs.columns[1]) == {"XiaoMaiDao", "LianYunGang"}
    rs = air.execute_one(
        "SHOW TAG VALUES FROM air WITH KEY != station")
    assert rs.n_rows == 0
    rs = air.execute_one(
        "SHOW TAG VALUES FROM air WITH KEY IN (station)")
    assert set(zip(rs.columns[0], rs.columns[1])) == {
        ("station", "XiaoMaiDao"), ("station", "LianYunGang")}


def test_explain(air):
    rs = air.execute_one("EXPLAIN SELECT station, count(*) FROM air "
                         "WHERE time > 100 GROUP BY station")
    text = "\n".join(rs.columns[0])
    assert "TpuAggregateExec" in text


def test_alter_table_add_field(air):
    air.execute_one("ALTER TABLE air ADD FIELD humidity DOUBLE")
    rs = air.execute_one("DESCRIBE TABLE air")
    assert "humidity" in rs.columns[0].tolist()
    rs = air.execute_one("SELECT humidity FROM air LIMIT 1")
    assert rs.columns[0][0] is None or np.isnan(rs.columns[0][0])


def test_flush_then_query(air):
    air.execute_one("FLUSH")
    rs = air.execute_one("SELECT count(*) FROM air")
    assert rs.columns[0][0] == 10


def test_constant_select(db):
    rs = db.execute_one("SELECT 1 + 2 AS x")
    assert rs.columns[0][0] == 3


def test_unknown_table_error(db):
    with pytest.raises(TableNotFound):
        db.execute_one("SELECT * FROM nope")


def test_null_field_aggregation(db):
    db.execute_one("CREATE TABLE m (a DOUBLE, b DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO m (time, h, a) VALUES (1, 'x', 1.0)")
    db.execute_one("INSERT INTO m (time, h, b) VALUES (2, 'x', 5.0)")
    rs = db.execute_one("SELECT count(a), count(b), count(*), sum(a) FROM m")
    assert rs.rows()[0] == (1, 1, 2, 1.0)


def test_tenant_user_ddl(db):
    db.execute_one("CREATE TENANT t2")
    db.execute_one("CREATE USER u1 WITH PASSWORD = 'pw'")
    db.execute_one("ALTER USER u1 SET PASSWORD = 'pw2'")
    db.execute_one("DROP USER u1")
    db.execute_one("DROP TENANT t2")
