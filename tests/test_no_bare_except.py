"""Lint: no bare ``except:`` in the distributed/storage planes.

A bare except swallows KeyboardInterrupt/SystemExit — in the RPC server
and raft/WAL recovery paths that turns an operator Ctrl-C or an injected
crash into a silently-ignored event and can mask real corruption. Use
``except Exception`` (or narrower) so control-flow exceptions propagate.
"""
import ast
import os

import pytest

import cnosdb_tpu

_PKG_ROOT = os.path.dirname(cnosdb_tpu.__file__)
_CHECKED_DIRS = ("parallel", "storage")


def _py_files():
    for sub in _CHECKED_DIRS:
        root = os.path.join(_PKG_ROOT, sub)
        for dirpath, _, names in os.walk(root):
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


@pytest.mark.parametrize("path", list(_py_files()),
                         ids=lambda p: os.path.relpath(p, _PKG_ROOT))
def test_no_bare_except(path):
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    offenders = [node.lineno for node in ast.walk(tree)
                 if isinstance(node, ast.ExceptHandler) and node.type is None]
    assert not offenders, (
        f"bare 'except:' at {os.path.relpath(path, _PKG_ROOT)}:"
        f"{offenders} — catch 'Exception' (or narrower) instead")


def test_checked_dirs_nonempty():
    files = list(_py_files())
    assert len(files) > 10, files  # the lint must actually cover the tree


# --------------------------------------------------------------------------
# Lint: every rpc_call() must pass an explicit timeout. The 10 s default
# is a trap: a hop that silently inherits it ignores the caller's request
# deadline, so one slow peer absorbs the node for 10 s per split. Passing
# `timeout=` forces the author to pick a budget (which net.rpc_call then
# caps to the calling request's remaining deadline).
# --------------------------------------------------------------------------
def _rpc_call_sites(tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        if name == "rpc_call":
            yield node


@pytest.mark.parametrize("path", list(_py_files()),
                         ids=lambda p: os.path.relpath(p, _PKG_ROOT))
def test_rpc_call_has_explicit_timeout(path):
    if path.endswith(os.path.join("parallel", "net.py")):
        return  # the definition module (wait_rpc_ready's probe is capped)
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    offenders = [
        node.lineno for node in _rpc_call_sites(tree)
        if not any(kw.arg == "timeout" or kw.arg is None  # **kwargs may carry it
                   for kw in node.keywords)
        and len(node.args) < 4  # positional timeout is the 4th arg
    ]
    assert not offenders, (
        f"rpc_call without explicit timeout= at "
        f"{os.path.relpath(path, _PKG_ROOT)}:{offenders} — every hop must "
        f"pick a budget (the request deadline then caps it)")
