"""Request-lifecycle plane against a real 2-node cluster.

Fast (tier-1) coverage:

- a remote vnode fetch slower than the request deadline returns 504
  within ~1.2x the deadline (the capped socket timeout, NOT the 10 s RPC
  default or the injected 3 s delay), and the deadline-exceeded counter
  increments;
- KILL QUERY landing while the coordinator is blocked in a remote scan
  RPC ends the query promptly AND the remote node receives the
  best-effort cancel_scan fan-out.

Slow (excluded from tier-1): an overload storm against a tiny admission
gate yields only 200/429/503, admitted queries return correct results,
and the node-side pools/gate drain back to zero afterwards.

The injected delay uses the fault plane exactly like test_chaos_cluster:
CNOSDB_FAULTS in the spawned nodes' env arms the `_faults` control RPC.
"""
import base64
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from cluster_harness import Cluster, assert_lock_graph_acyclic
from cnosdb_tpu.parallel.net import rpc_call

pytestmark = [pytest.mark.cluster]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    # lock-order watchdog on in every node: the deadline/cancel fan-out
    # paths exercise most cross-lock nesting, so teardown checks the
    # observed order graph stayed acyclic (and /metrics carries counters)
    knobs = {"CNOSDB_FAULTS": "seed=1", "CNOSDB_LOCKWATCH": "1"}
    os.environ.update(knobs)
    try:
        c = Cluster(str(tmp_path_factory.mktemp("ddl")), n_nodes=2).start()
    finally:
        for k in knobs:
            del os.environ[k]
    yield c
    assert assert_lock_graph_acyclic(c) > 0
    assert "cnosdb_lockwatch_total" in c.alive_node().http("GET", "/metrics")
    c.stop()


def _set_faults(node, spec: str) -> dict:
    return rpc_call(f"127.0.0.1:{node.rpc_port}", "_faults",
                    {"spec": spec}, timeout=5.0)


def _req(node, method, path, data=None, headers=None, timeout=30.0):
    """Like Node.http but returns (status, body) instead of raising, and
    accepts extra request headers (the deadline header, Accept, ...)."""
    hdrs = {"Authorization": "Basic " + base64.b64encode(b"root:").decode()}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{node.http_port}{path}",
        data=data.encode() if isinstance(data, str) else data,
        headers=hdrs, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _metric(node, prefix: str) -> float:
    """Sum of all /metrics samples whose rendered name starts with prefix
    (labelled gauges contribute one line per label set)."""
    status, text = _req(node, "GET", "/metrics")
    assert status == 200
    total, found = 0.0, False
    for ln in text.splitlines():
        if ln.startswith(prefix):
            total += float(ln.rsplit(" ", 1)[1])
            found = True
    return total if found else 0.0


def _csv_rows(out: str) -> list[list[str]]:
    lines = [l for l in out.strip().splitlines() if l]
    return [l.split(",") for l in lines[1:]]


N_ROWS = 40


@pytest.fixture(scope="module")
def seeded(cluster):
    """Database with SHARD 2 REPLICA 1 on 2 nodes: the round-robin bucket
    placement puts one vnode on each node, so any full-table scan issued
    at node 1 must fetch the other shard from node 2 over scan_vnode."""
    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE ddl WITH SHARD 2 REPLICA 1", db="public")
    base = 1_700_000_000_000_000_000
    lines = "\n".join(
        f"m,host=h{i % 16} v={i} {base + i * 1_000_000}"
        for i in range(N_ROWS))
    n1.write_lp(lines, db="ddl")
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        rows = _csv_rows(n1.sql("SELECT count(*) FROM m", db="ddl"))
        if rows and int(rows[0][0]) == N_ROWS:
            return "ddl"
        time.sleep(0.3)
    pytest.fail("seed rows never became readable")


def test_remote_fetch_slower_than_deadline_is_504(cluster, seeded):
    """Acceptance: injected 3 s delay on the remote scan RPC vs an 800 ms
    request deadline → deadline-exceeded in ~the deadline (capped socket
    timeout), nowhere near the delay or the 10 s RPC default."""
    n1, n2 = cluster.nodes
    before = _metric(n1, "cnosdb_requests_deadline_exceeded_total")
    _set_faults(n2, "rpc.server:delay(3000):if=scan_vnode")
    try:
        t0 = time.monotonic()
        # count(v), not count(*): the seeding poll already warmed the
        # serving result cache for count(*), and a cache hit would never
        # touch the delayed remote scan this test is about
        status, body = _req(n1, "POST", f"/api/v1/sql?db={seeded}",
                            "SELECT count(v) FROM m",
                            headers={"X-CnosDB-Deadline-Ms": "800"})
        elapsed = time.monotonic() - t0
    finally:
        _set_faults(n2, "")
    assert status == 504, (status, body)
    # ~1.2x the 800 ms budget plus scheduling slack — and provably not
    # the 3 s injected delay or the 10 s default socket timeout
    assert elapsed < 1.6, f"504 took {elapsed:.2f}s; deadline not enforced"
    after = _metric(n1, "cnosdb_requests_deadline_exceeded_total")
    assert after >= before + 1
    # the node still serves normally once the fault is lifted (same
    # uncached spelling, so this provably re-runs the remote fetch)
    status, body = _req(n1, "POST", f"/api/v1/sql?db={seeded}",
                        "SELECT count(v) FROM m")
    assert status == 200 and _csv_rows(body)[0][0] == str(N_ROWS)


def test_kill_query_during_remote_fetch(cluster, seeded):
    """Satellite: KILL QUERY lands while the coordinator is blocked in a
    remote vnode fetch → the query ends promptly and the remote node
    observes the cancel_scan fan-out."""
    n1, n2 = cluster.nodes
    cancels_before = _metric(
        n2, 'cnosdb_deadline_total{kind="cancel_scan_received"}')
    _set_faults(n2, "rpc.server:delay(4000):if=scan_vnode")
    result = {}

    def go():
        result["status"], result["body"] = _req(
            n1, "POST", f"/api/v1/sql?db={seeded}",
            "SELECT max(v) FROM m")
        result["done_at"] = time.monotonic()

    th = threading.Thread(target=go, daemon=True)
    th.start()
    try:
        qid = None
        poll_until = time.monotonic() + 10.0
        while qid is None and time.monotonic() < poll_until:
            for row in _csv_rows(n1.sql("SHOW QUERIES")):
                if "max(v)" in row[1]:
                    qid = int(row[0])
                    break
            else:
                time.sleep(0.05)
        assert qid is not None, "victim query never appeared in SHOW QUERIES"
        t_kill = time.monotonic()
        n1.sql(f"KILL QUERY {qid}")
        th.join(timeout=5.0)
        assert not th.is_alive(), "query did not end after KILL"
        assert result["done_at"] - t_kill < 2.5, (
            "KILL took %.2fs to unblock the query (remote delay is 4 s)"
            % (result["done_at"] - t_kill))
        assert result["status"] != 200
        assert "cancel" in result["body"].lower(), result["body"]
        # the remote node must have received the best-effort cancel RPC
        # (from the KILL handler and/or the unwinding worker)
        fanout_until = time.monotonic() + 5.0
        while time.monotonic() < fanout_until:
            if _metric(n2, 'cnosdb_deadline_total{kind="cancel_scan_received"}'
                       ) > cancels_before:
                break
            time.sleep(0.1)
        else:
            pytest.fail("remote node never observed cancel_scan")
    finally:
        _set_faults(n2, "")
        th.join(timeout=10.0)


# --------------------------------------------------------------- overload
@pytest.fixture(scope="module")
def storm_cluster(tmp_path_factory):
    """Own cluster with a deliberately tiny admission gate (2 running +
    2 queued per node), configured through the documented env overrides."""
    knobs = {"CNOSDB_FAULTS": "seed=1",
             "CNOSDB_LOCKWATCH": "1",
             "CNOSDB_QUERY_MAX_CONCURRENT_QUERIES": "2",
             "CNOSDB_QUERY_MAX_QUEUED_QUERIES": "2"}
    os.environ.update(knobs)
    try:
        c = Cluster(str(tmp_path_factory.mktemp("storm")), n_nodes=2).start()
    finally:
        for k in knobs:
            del os.environ[k]
    yield c
    assert assert_lock_graph_acyclic(c) > 0
    c.stop()


@pytest.mark.slow
def test_overload_storm_sheds_cleanly(storm_cluster):
    """Acceptance (slow): a storm beyond gate capacity yields ONLY
    success/429/503 — never a hang, a 500, or a wrong answer — and the
    gate + scan pools drain to zero afterwards, including for a client
    that disconnects mid-query."""
    n1, n2 = storm_cluster.nodes
    n1.sql("CREATE DATABASE dstorm WITH SHARD 2 REPLICA 1", db="public")
    base = 1_700_000_000_000_000_000
    n1.write_lp("\n".join(
        f"s,host=h{i % 16} v={i} {base + i * 1_000_000}" for i in range(32)),
        db="dstorm")
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        rows = _csv_rows(n1.sql("SELECT count(*) FROM s", db="dstorm"))
        if rows and int(rows[0][0]) == 32:
            break
        time.sleep(0.3)
    else:
        pytest.fail("seed rows never became readable")

    # make every query slow enough to pile up at the gate: the shard on
    # node 2 answers its scan RPC only after 600 ms
    _set_faults(n2, "rpc.server:delay(600):if=scan_vnode")
    outcomes = []
    lock = threading.Lock()

    def client():
        status, body = _req(n1, "POST", "/api/v1/sql?db=dstorm",
                            "SELECT count(*) FROM s")
        with lock:
            outcomes.append((status, body))

    def dropper():
        # client that walks away mid-query: its worker must be reaped
        # (disconnect → cancel flag → worker unwinds + fans out cancels)
        try:
            _req(n1, "POST", "/api/v1/sql?db=dstorm",
                 "SELECT count(*) FROM s", timeout=0.2)
        except Exception:
            pass

    try:
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(12)]
        threads.append(threading.Thread(target=dropper, daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert not any(t.is_alive() for t in threads), "storm client hung"
    finally:
        _set_faults(n2, "")

    assert len(outcomes) == 12
    statuses = {s for s, _ in outcomes}
    assert statuses <= {200, 429, 503}, statuses
    assert 200 in statuses, "nothing was admitted during the storm"
    assert 503 in statuses, "nothing was shed — gate limits not applied"
    for status, body in outcomes:
        if status == 200:
            assert _csv_rows(body)[0][0] == "32", body
    shed = _metric(n1, "cnosdb_requests_shed_total")
    assert shed >= sum(1 for s, _ in outcomes if s == 503)

    # drain: gate empty, scan/decode pools idle on BOTH nodes
    drain_until = time.monotonic() + 20.0
    while time.monotonic() < drain_until:
        if (_metric(n1, "cnosdb_requests_running") == 0
                and _metric(n1, "cnosdb_requests_queue_depth") == 0
                and _metric(n1, "cnosdb_scan_executor_active") == 0
                and _metric(n2, "cnosdb_scan_executor_active") == 0):
            return
        time.sleep(0.25)
    pytest.fail("gate/pools did not drain to zero after the storm")
