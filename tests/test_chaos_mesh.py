"""device_loss nemesis sweep over a live cluster (slow, excluded from
tier-1).

A dedicated 3-node cluster boots with the mesh execution lane armed
(CNOSDB_MESH_MIN_ROWS=0 so small soak tables engage, CNOSDB_SERVING=0 so
every query really runs the lane): a seeded device_loss schedule injects
`mesh.collective:fail` into one node at a time — the merge kernel dies
mid-collective on the victim — while recorded clients keep writing and
reading through the survivors. The invariants:

- the victim keeps answering aggregates BYTE-identically through the
  transparent host-merge fallback, and books the device_loss decline
- healing re-engages the collective lane on the ex-victim
- the full client history passes no-lost-acked-write / no-resurrection /
  monotonic-read checks on every node's final state
"""
import os
import time

import pytest

from cluster_harness import Cluster
from cnosdb_tpu.parallel.net import rpc_call

pytestmark = [pytest.mark.slow, pytest.mark.cluster]

NEM_BASE = 1_700_000_000_000_000_000
SEED = 23


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    knobs = {"CNOSDB_FAULTS": "seed=1", "CNOSDB_MESH_MIN_ROWS": "0",
             "CNOSDB_SERVING": "0"}
    os.environ.update(knobs)
    try:
        c = Cluster(str(tmp_path_factory.mktemp("meshchaos")),
                    n_nodes=3).start()
    finally:
        for k in knobs:
            del os.environ[k]
    yield c
    c.stop()


def _set_faults(node, spec: str) -> dict:
    return rpc_call(f"127.0.0.1:{node.rpc_port}", "_faults",
                    {"spec": spec}, timeout=5.0)


def _mesh_metric(node, reason: str) -> int:
    total = 0
    for line in node.http("GET", "/metrics").splitlines():
        if line.startswith("cnosdb_mesh_total") \
                and f'reason="{reason}"' in line:
            total += int(float(line.rsplit(" ", 1)[1]))
    return total


def _csv_rows(out: str) -> list[list[str]]:
    lines = [l for l in out.strip().splitlines() if l]
    return [l.split(",") for l in lines[1:]]


def _keys_on(node, table, db) -> set[str]:
    rows = _csv_rows(node.sql(f"SELECT DISTINCT k FROM {table}", db=db))
    return {r[0] for r in rows}


def _wait_keys(node, table, db, expect, timeout=60.0) -> set[str]:
    deadline = time.monotonic() + timeout
    got: set[str] = set()
    while time.monotonic() < deadline:
        try:
            got = _keys_on(node, table, db)
            if got == expect:
                return got
        except Exception:
            pass
        time.sleep(0.3)
    return got


AGG_Q = ("SELECT k, count(*) AS c, sum(v) AS s, min(v) AS mn, "
         "max(v) AS mx, first(v) AS f, last(v) AS l "
         "FROM dl GROUP BY k ORDER BY k")


def _query_until_booked(node, reason, floor, want, tries=10) -> bool:
    """Re-run the static aggregate until cnosdb_mesh_total{reason}
    rises past `floor`; every answer along the way must equal `want`
    byte-for-byte regardless of which lane served it."""
    for _ in range(tries):
        assert node.sql(AGG_Q, db="dmesh") == want, \
            f"node {node.node_id} aggregate answer diverged"
        if _mesh_metric(node, reason) > floor:
            return True
        time.sleep(0.2)
    return False


def _engaging_node(cluster, baseline, start: int):
    """The mesh lane only engages on a coordinator whose scans are all
    local (leader-follow pins each shard scan to its raft leader, so
    which node that is shifts over the cluster's life). Probe from a
    plan-determined offset and return the first node whose engaged
    counter moves — answers must stay byte-identical on every probe."""
    for i in range(len(cluster.nodes)):
        n = cluster.nodes[(start + i) % len(cluster.nodes)]
        before = _mesh_metric(n, "engaged")
        assert n.sql(AGG_Q, db="dmesh") == baseline[n.node_id]
        if _mesh_metric(n, "engaged") > before:
            return n
    return None


def test_device_loss_sweep_answers_stay_identical(cluster, tmp_path):
    from cnosdb_tpu.chaos import nemesis
    from cnosdb_tpu.chaos.checker import run_client_checks
    from cnosdb_tpu.chaos.history import History, HistoryRecorder

    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dmesh WITH SHARD 4 REPLICA 3", db="public")
    # client traffic rides its OWN database: any write into dmesh would
    # invalidate its scan cache, and the re-scan may route shards to
    # peer replicas (adaptive routing) — a legal off_mesh decline, but
    # the sweep needs the victim's lane deterministically engaged
    n1.sql("CREATE DATABASE dcw WITH SHARD 1 REPLICA 3", db="public")

    # a STATIC aggregate table: the sweep compares its answer text
    # byte-for-byte across injections, so nothing may write to it later
    lines = "\n".join(
        f"dl,k=k{i % 16} v={(i % 23) * 0.5 + i * 1e-3} "
        f"{NEM_BASE + i * 1_000}" for i in range(240))
    n1.write_lp(lines, db="dmesh")
    for n in cluster.nodes:
        assert _wait_keys(n, "dl", "dmesh", {f"k{i}" for i in range(16)})

    baseline = {n.node_id: n.sql(AGG_Q, db="dmesh")
                for n in cluster.nodes}
    engaged0 = {n.node_id: _mesh_metric(n, "engaged")
                for n in cluster.nodes}
    assert any(_mesh_metric(n, "engaged") > 0 for n in cluster.nodes), \
        "mesh lane never engaged on the sealed aggregate table"

    # recorded client traffic rides a separate table through the sweep
    rec = HistoryRecorder(str(tmp_path / "dl.jsonl"))
    acked: set[str] = set()
    nwrite = 0

    def client_write(node, k):
        nonlocal nwrite
        keys = [f"w{nwrite + i}" for i in range(k)]
        body = "\n".join(
            f"cw,k={key} v=1 {NEM_BASE + (nwrite + i) * 1_000}"
            for i, key in enumerate(keys))
        e = rec.invoke("cw", "write", keys=keys)
        try:
            node.write_lp(body, db="dcw")
        except Exception as ex:
            rec.fail("cw", e, str(ex)[:200])
            return
        rec.ok("cw", e)
        nwrite += k
        acked.update(keys)

    def client_read(node):
        e = rec.invoke(f"r{node.node_id}", "read", durable=False,
                       mono=True)
        try:
            keys = _keys_on(node, "cw", "dcw")
        except Exception as ex:
            rec.fail(f"r{node.node_id}", e, str(ex)[:200])
            return
        rec.ok(f"r{node.node_id}", e, keys=sorted(keys))

    client_write(n1, 10)

    plan = nemesis.generate_plan(SEED, n_nodes=3, steps=3,
                                 kinds=("device_loss",))
    ctx = nemesis.describe(plan, SEED)
    for ev in plan:
        # the plan's victim index seeds the probe order; the actual
        # victim must be a node whose lane currently engages, or the
        # injection would never reach a collective to kill
        victim = _engaging_node(cluster, baseline, ev.node)
        assert victim is not None, \
            f"{ctx}\nstep #{ev.step}: no coordinator engages the lane"
        healthy = [n for n in cluster.nodes if n is not victim]
        vspec, ospec = nemesis.event_specs(
            ev, f"127.0.0.1:{victim.rpc_port}", SEED)
        assert ospec == "", "device_loss only arms the victim"
        loss0 = _mesh_metric(victim, "device_loss")
        _set_faults(victim, vspec)
        try:
            # the victim's collective merge dies mid-kernel; every
            # answer must come back byte-identical through the host
            # fallback
            assert _query_until_booked(
                victim, "device_loss", loss0,
                baseline[victim.node_id]), \
                f"{ctx}\nstep #{ev.step}: device_loss never booked"
            # survivors keep acking writes and serving monotone reads
            client_write(healthy[0], 5)
            for n in cluster.nodes:
                client_read(n)
        finally:
            _set_faults(victim, nemesis.heal_spec(SEED, ev))
        # healed: the ex-victim answers clean, and the collective lane
        # re-engages somewhere (client writes may have re-routed shard
        # leadership, so the engaging coordinator can move)
        assert victim.sql(AGG_Q, db="dmesh") == baseline[victim.node_id]
        assert _engaging_node(cluster, baseline, ev.node) is not None, \
            f"{ctx}\nstep #{ev.step}: lane stayed declined after heal"
        for n in cluster.nodes:
            assert _wait_keys(n, "cw", "dcw", acked) == acked, \
                f"{ctx}\nstep #{ev.step}: node {n.node_id} lost writes"
    rec.close()

    assert all(_mesh_metric(n, "engaged") >= engaged0[n.node_id]
               for n in cluster.nodes)
    h = History.load(str(tmp_path / "dl.jsonl"))
    for n in cluster.nodes:
        final = _wait_keys(n, "cw", "dcw", acked, timeout=90.0)
        bad = [r for r in run_client_checks(h, final) if not r.ok]
        assert not bad, ctx + f"\nnode {n.node_id}: " + "; ".join(
            f"{r.name}: {r.detail}" for r in bad)
