"""Vectorized grouped-aggregation plane (ops/group_agg.py) parity tests.

Every vectorized path is checked against a naive per-row Python
reference — the accumulation loops the plane replaced — over the
payload shapes that break sort-based factorization: NULL-heavy columns,
empty groups, a single group, >64k groups, and mixed tag/bucket keys.
A property check forces the device (jax segment-kernel) DISTINCT route
on the CPU backend and asserts it agrees with the host sort path.
"""
import os

import numpy as np
import pytest

from cnosdb_tpu.ops import group_agg as ga
from cnosdb_tpu.ops import kernels
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv

rng = np.random.default_rng(7)


# ---------------------------------------------------------------------------
# naive references
# ---------------------------------------------------------------------------
def naive_distinct(gid, values, n_groups):
    sets = [set() for _ in range(n_groups)]
    for g, v in zip(gid, values):
        sets[g].add(v)
    return np.array([len(s) for s in sets], dtype=np.int64)


def naive_min_max(func, gid, values, n_groups):
    best = [None] * n_groups
    red = min if func == "min" else max
    for g, v in zip(gid, values):
        best[g] = v if best[g] is None else red(best[g], v)
    return best


# ---------------------------------------------------------------------------
# factorize
# ---------------------------------------------------------------------------
def test_factorize_roundtrip_numeric():
    arr = rng.integers(0, 50, size=1000)
    f = ga.factorize(arr)
    assert f.n_values == len(np.unique(arr))
    np.testing.assert_array_equal(f.values[f.codes], arr)
    # sorted-dictionary invariant: code order == value order
    assert np.all(np.diff(f.values) > 0)


def test_factorize_object_strings():
    arr = np.array(["b", "a", "b", "c", "a"], dtype=object)
    f = ga.factorize(arr)
    assert f.values.tolist() == ["a", "b", "c"]
    assert f.values[f.codes].tolist() == arr.tolist()


def test_factorize_object_ints_and_bools():
    # Python sets treat True == 1 — the int64 cast must too
    arr = np.array([True, 1, 2, False, 0], dtype=object)
    f = ga.factorize(arr)
    assert f.n_values == 3
    assert len(set(arr.tolist())) == 3


def test_factorize_mixed_types_falls_back():
    arr = np.array(["x", 1, 3.5], dtype=object)
    assert ga.factorize(arr) is None


def test_factorize_nan_object_falls_back():
    arr = np.array([1.5, float("nan"), 2.0], dtype=object)
    assert ga.factorize(arr) is None


def test_combine_codes_overflow_redensify():
    # dims whose product overflows int64: prefix must re-densify
    c0 = np.array([0, 1, 2], dtype=np.int64)
    c1 = np.array([0, 1, 0], dtype=np.int64)
    codes, dim = ga.combine_codes([(c0, 2 ** 40), (c1, 2 ** 40)])
    assert len(np.unique(codes)) == 3


# ---------------------------------------------------------------------------
# distinct_count parity
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("n_groups,n_rows", [(1, 500), (16, 2000),
                                             (70000, 200000)])
def test_distinct_count_parity(n_groups, n_rows):
    gid = rng.integers(0, n_groups, size=n_rows).astype(np.int64)
    vals = rng.integers(0, 97, size=n_rows)
    got = ga.distinct_count(gid, vals, n_groups)
    np.testing.assert_array_equal(got, naive_distinct(gid, vals, n_groups))


def test_distinct_count_empty_groups():
    # groups 3..9 never observed → 0, not missing
    gid = np.array([0, 0, 1, 2], dtype=np.int64)
    vals = np.array([5.0, 5.0, 6.0, 5.0])
    got = ga.distinct_count(gid, vals, 10)
    np.testing.assert_array_equal(got, [1, 1, 1, 0, 0, 0, 0, 0, 0, 0])


def test_distinct_count_null_heavy_strings():
    # NULLs are filtered by the CALLER (valid-mask) — simulate that:
    # 90% of rows invalid, the rest strings
    n = 5000
    gid_all = rng.integers(0, 8, size=n).astype(np.int64)
    vals_all = np.array([f"v{i % 13}" for i in range(n)], dtype=object)
    valid = rng.random(n) > 0.9
    gid, vals = gid_all[valid], vals_all[valid]
    got = ga.distinct_count(gid, vals, 8)
    np.testing.assert_array_equal(got, naive_distinct(gid, vals, 8))


def test_distinct_count_unfactorizable_returns_none():
    gid = np.zeros(3, dtype=np.int64)
    vals = np.array(["x", 7, object()], dtype=object)
    assert ga.distinct_count(gid, vals, 1) is None


# ---------------------------------------------------------------------------
# min/max parity (incl. object columns via the sorted dictionary)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("func", ["min", "max"])
@pytest.mark.parametrize("dtype", ["int", "float", "str"])
def test_group_min_max_parity(func, dtype):
    n, n_groups = 3000, 17
    gid = rng.integers(0, n_groups - 1, size=n).astype(np.int64)  # one empty
    if dtype == "int":
        vals = rng.integers(-100, 100, size=n)
    elif dtype == "float":
        vals = rng.normal(size=n)
    else:
        opts = np.array([f"s{i:03d}" for i in range(40)], dtype=object)
        vals = opts[rng.integers(0, 40, size=n)]
    out = ga.group_min_max(func, gid, vals, n_groups)
    assert out is not None
    best, filled = out
    ref = naive_min_max(func, gid, vals.tolist(), n_groups)
    for g in range(n_groups):
        if ref[g] is None:
            assert not filled[g]
        else:
            assert filled[g] and best[g] == ref[g]


# ---------------------------------------------------------------------------
# grouped_order (collect slicing)
# ---------------------------------------------------------------------------
def test_grouped_order_runs():
    gid = np.array([3, 1, 3, 0, 1, 3], dtype=np.int64)
    order, bounds, run_codes = ga.grouped_order(gid)
    got = {}
    for k, code in enumerate(run_codes.tolist()):
        got[code] = order[bounds[k]:bounds[k + 1]].tolist()
    assert got == {0: [3], 1: [1, 4], 3: [0, 2, 5]}
    # stability: original row order preserved within each group
    for rows in got.values():
        assert rows == sorted(rows)


def test_grouped_order_empty():
    order, bounds, run_codes = ga.grouped_order(np.empty(0, dtype=np.int64))
    assert len(order) == 0 and len(run_codes) == 0


# ---------------------------------------------------------------------------
# device kernels: CPU-backend property check vs the host sort path
# ---------------------------------------------------------------------------
def test_segment_distinct_count_kernel():
    gid = np.array([0, 0, 1, 1, 1, 2], dtype=np.int64)
    vc = np.array([0, 1, 0, 0, 1, 2], dtype=np.int64)
    out = np.asarray(kernels.segment_distinct_count(gid, vc, 3, 3))
    np.testing.assert_array_equal(out, [2, 2, 1])


def test_sorted_pair_codes_dedup():
    gid = np.array([1, 0, 1, 0, 2], dtype=np.int64)
    vc = np.array([1, 0, 1, 1, 2], dtype=np.int64)
    out = kernels.sorted_pair_codes(gid, vc, 3)
    np.testing.assert_array_equal(out, [0, 1, 4, 8])


def test_merge_distinct_pairs_roundtrip():
    da = pytest.importorskip("cnosdb_tpu.parallel.distributed_agg",
                             exc_type=ImportError)
    a = np.array([0, 4, 8], dtype=np.int64)      # groups 0,1,2 @ nv=3
    b = np.array([0, 1, 8], dtype=np.int64)
    out = da.merge_distinct_pairs([a, b], 3, 4)
    np.testing.assert_array_equal(out, [2, 1, 1, 0])


def test_device_distinct_matches_host(monkeypatch):
    monkeypatch.setenv("CNOSDB_TPU_GROUP_AGG", "1")
    assert ga.device_enabled()
    n, n_groups = 70000, 23          # ≥65536 rows: device route engages
    gid = rng.integers(0, n_groups, size=n).astype(np.int64)
    vals = rng.integers(0, 211, size=n)
    got = ga.distinct_count(gid, vals, n_groups)
    monkeypatch.setenv("CNOSDB_TPU_GROUP_AGG", "0")
    host = ga.distinct_count(gid, vals, n_groups)
    np.testing.assert_array_equal(got, host)
    np.testing.assert_array_equal(host, naive_distinct(gid, vals, n_groups))


def test_device_distinct_chunked(monkeypatch):
    # multi-chunk path: partial pair arrays merged host-side
    n, n_groups = 9000, 11
    gid = rng.integers(0, n_groups, size=n).astype(np.int64)
    vals = rng.integers(0, 19, size=n)
    f = ga.factorize(vals)
    out = ga._device_distinct_count(gid, f.codes, n_groups, f.n_values,
                                    chunk_rows=1024)
    if out is None:     # distributed_agg unimportable in this env: fine,
        pytest.skip("device merge unavailable")
    np.testing.assert_array_equal(out, naive_distinct(gid, vals, n_groups))


# ---------------------------------------------------------------------------
# end-to-end: the fused field-GROUP-BY path vs naive references
# ---------------------------------------------------------------------------
@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    engine.close()


@pytest.fixture
def events(db):
    """Mixed tag / field-key / NULL-heavy table driven through SQL."""
    db.execute_one("CREATE TABLE ev (uid BIGINT, phrase STRING, "
                   "val DOUBLE, TAGS(region))")
    rows = []
    base = 1672531200000000000
    r = np.random.default_rng(3)
    for i in range(400):
        t = base + i * 1_000_000_000
        region = f"r{i % 3}"
        uid = int(r.integers(0, 40))
        phrase = f"'p{i % 7}'" if i % 5 else "NULL"   # NULL-heavy key
        val = round(float(r.normal()), 3)
        rows.append(f"({t}, '{region}', {uid}, {phrase}, {val})")
    db.execute_one("INSERT INTO ev (time, region, uid, phrase, val) "
                   "VALUES " + ", ".join(rows))
    arr = {"i": np.arange(400),
           "region": np.array([f"r{i % 3}" for i in range(400)]),
           "uid": None, "phrase": None}
    return db


def test_field_group_by_count_distinct(events):
    """GROUP BY field + count(DISTINCT) rides the fused plan (planner no
    longer forces the relational fallback) and matches a naive oracle."""
    rs = events.execute_one(
        "SELECT phrase, count(DISTINCT uid) AS u, count(*) AS c "
        "FROM ev GROUP BY phrase ORDER BY phrase")
    got = {row[0]: (row[1], row[2]) for row in rs.rows()}
    # rebuild the oracle exactly as the fixture wrote it
    r = np.random.default_rng(3)
    ref: dict = {}
    for i in range(400):
        uid = int(r.integers(0, 40))
        r.normal()
        phrase = f"p{i % 7}" if i % 5 else None
        s, c = ref.setdefault(phrase, (set(), 0))
        s.add(uid)
        ref[phrase] = (s, c + 1)
    assert set(got) == set(ref)
    for k, (s, c) in ref.items():
        # count(DISTINCT) / count(uid) both skip NULL-uid rows (none here)
        assert got[k][0] == len(s), k
        assert got[k][1] == c, k


def test_mixed_tag_field_bucket_keys(events):
    rs = events.execute_one(
        "SELECT region, phrase, date_bin(INTERVAL '2 minutes', time) "
        "AS b, count(DISTINCT uid) AS u FROM ev "
        "GROUP BY region, phrase, b ORDER BY region, phrase, b")
    r = np.random.default_rng(3)
    base = 1672531200000000000
    ref: dict = {}
    for i in range(400):
        t = base + i * 1_000_000_000
        uid = int(r.integers(0, 40))
        r.normal()
        key = (f"r{i % 3}", f"p{i % 7}" if i % 5 else None,
               (t // 120_000_000_000) * 120_000_000_000)
        ref.setdefault(key, set()).add(uid)
    got = {(row[0], row[1], int(row[2])): row[3] for row in rs.rows()}
    assert got == {k: len(s) for k, s in ref.items()}


def test_field_group_by_median_and_collect(events):
    """Non-kernel aggregates (median → collect) with a field key now take
    the fused path too — parity against the naive per-group collect."""
    rs = events.execute_one(
        "SELECT phrase, median(val) AS m FROM ev "
        "WHERE phrase IS NOT NULL GROUP BY phrase ORDER BY phrase")
    r = np.random.default_rng(3)
    ref: dict = {}
    for i in range(400):
        r.integers(0, 40)
        val = round(float(r.normal()), 3)
        if i % 5:
            ref.setdefault(f"p{i % 7}", []).append(val)
    for row in rs.rows():
        assert row[1] == pytest.approx(float(np.median(ref[row[0]]))), row


def test_group_agg_counters_move():
    before = ga.counters_snapshot().get("distinct_sort", 0)
    gid = np.zeros(10, dtype=np.int64)
    ga.distinct_count(gid, np.arange(10), 1)
    assert ga.counters_snapshot().get("distinct_sort", 0) > before
