"""Integrity plane, fast single-process suite (tentpole of the corruption
scrubber PR): CRC sweep over at-rest artifacts, quarantine-on-read and
quarantine-by-scrub, scan-cache invalidation after quarantine, the
`corrupt` fault-grammar action, and the scrubber's rate limiter. The
multi-node bit-flip → failover → anti-entropy-repair proof lives in
test_chaos_cluster.py (slow-marked)."""
import os
import time

import numpy as np
import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.errors import ChecksumMismatch
from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.storage import scrub
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage


@pytest.fixture(autouse=True)
def _clean():
    scrub.counters_reset()
    yield
    faults.reset()
    scrub.counters_reset()


def _schema():
    return {"cpu": TskvTableSchema.new_measurement(
        "t", "db", "cpu", tags=["host"],
        fields=[("usage", ValueType.FLOAT)])}


def _wb(host, ts_list, usage_list):
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": host}), list(ts_list),
        {"usage": (int(ValueType.FLOAT), list(usage_list))}))
    return wb


def _tsm_paths(v):
    version = v.summary.version
    return [version.file_path(fm) for fm in version.all_files()]


# ------------------------------------------------------------- clean sweep
def test_clean_sweep_verifies_all_artifacts(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("h1", range(100), np.arange(100) * 0.5))
    v.flush()
    res = scrub.scrub_vnode(v)
    assert res["corrupt"] == []
    assert res["files"] >= 1
    assert res["bytes"] >= os.path.getsize(_tsm_paths(v)[0])
    snap = scrub.counters_snapshot()
    assert snap["scrub_bytes"] == res["bytes"]
    assert snap["scrub_files"] == res["files"]
    assert snap["corruptions_detected"] == 0
    v.close()


def test_verify_tsm_catches_any_flip_region(tmp_engine_dir):
    """A flip anywhere — page, meta, footer — must read as corruption."""
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("h1", range(50), np.arange(50) * 1.0))
    v.flush()
    path = _tsm_paths(v)[0]
    v.close()
    with open(path, "rb") as f:
        orig = f.read()
    size = len(orig)
    import struct

    meta_off = struct.unpack_from("<Q", orig, size - 64)[0]
    # one offset per region: a page byte, a meta byte, a footer byte
    # (the bloom region carries no crc — a known, documented gap)
    for off in (16, meta_off + 2, size - 8):
        with open(path, "r+b") as f:
            f.seek(off)
            f.write(bytes([orig[off] ^ 0xFF]))
        with pytest.raises(ChecksumMismatch):
            scrub.verify_tsm(path)
        with open(path, "wb") as f:
            f.write(orig)
    assert scrub.verify_tsm(path) == size


# ------------------------------------------------------------- quarantine
def test_scrub_quarantines_and_scan_excludes_file(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    # file 1 sealed CORRUPT via the tsm.write fault (flips inside the page
    # region, so magic/meta/footer stay valid — the read-path signature)
    faults.configure("tsm.write:corrupt(2):nth=1")
    v.write(_wb("h1", [10, 20, 30], [1.0, 2.0, 3.0]))
    v.flush()
    faults.reset()
    v.write(_wb("h2", [40, 50], [4.0, 5.0]))
    v.flush()
    assert len(_tsm_paths(v)) == 2

    with pytest.raises(ChecksumMismatch):
        scan_vnode(v, "cpu")

    res = scrub.scrub_vnode(v)
    assert len(res["corrupt"]) == 1
    snap = scrub.counters_snapshot()
    assert snap["corruptions_detected"] == 1
    assert snap["files_quarantined"] == 1
    # quarantined: dropped from the Version, renamed aside, kept on disk
    assert len(_tsm_paths(v)) == 1
    qs = v.quarantined_files()
    assert len(qs) == 1 and qs[0].endswith(".quarantine")
    # scans work again and serve exactly the surviving file
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(np.sort(b.ts), [40, 50])
    # GC never deletes the evidence
    from cnosdb_tpu.storage.summary import delete_unreferenced_files

    delete_unreferenced_files(v.summary.version)
    assert os.path.exists(qs[0])
    v.close()


def test_quarantined_vnode_refuses_file_snapshot(tmp_engine_dir):
    """A quarantined state machine diverged from its applied raft log —
    serving a file snapshot (to a follower or a repair fetch) would clone
    the data loss onto healthy replicas, so it must refuse. Repair's
    install wipes the evidence, which is what re-enables snapshots."""
    from cnosdb_tpu.errors import StorageError

    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("h1", [1, 2, 3], [1.0, 2.0, 3.0]))
    v.flush()
    snap = v.file_snapshot()
    assert snap["files"]
    assert not any(r.endswith(".quarantine") for r in snap["files"])
    assert v.quarantine_file(path=_tsm_paths(v)[0]) is not None
    with pytest.raises(StorageError):
        v.file_snapshot()
    # install (repair) clears the evidence and re-enables snapshots
    v.install_file_snapshot(snap)
    assert v.quarantined_files() == []
    snap2 = v.file_snapshot()
    assert not any(r.endswith(".quarantine") for r in snap2["files"])
    v.close()


def test_quarantine_invalidates_scan_token(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("h1", range(20), np.arange(20) * 1.0))
    v.flush()
    tok = v.scan_token()
    path = _tsm_paths(v)[0]
    assert v.quarantine_file(path=path) is not None
    tok2 = v.scan_token()
    # both versions bump: exact-match cache entries AND delta rescans off
    # the stale token are refused
    assert tok2.data_version != tok.data_version
    assert tok2.destructive_version != tok.destructive_version
    v.close()


def test_coordinator_scan_cache_invalidated_after_quarantine(tmp_path):
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"), background_compaction=False)
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex._engine = engine

    ex.execute_one("CREATE TABLE cpu (usage DOUBLE, TAGS(host))")
    ex.execute_one(
        "INSERT INTO cpu (time, host, usage) VALUES "
        + ", ".join(f"({t}, 'h1', {t}.5)" for t in range(1, 31)))
    engine.flush_all()
    assert len(list(ex.execute_one("SELECT * FROM cpu").rows())) == 30
    # cached now; corrupt + scrub-quarantine behind the cache's back
    owner = "cnosdb.public"
    (vnode,) = engine.local_vnodes(owner)
    path = _tsm_paths(vnode)[0]
    faults.configure("scrub.read:corrupt(2)")
    res = scrub.scrub_engine(engine,
                             on_corruption=coord.on_scrub_corruption)
    faults.reset()
    assert path in res["corrupt"]
    # the cache must NOT serve the pre-quarantine snapshot
    assert list(ex.execute_one("SELECT * FROM cpu").rows()) == []
    engine.close()


# ------------------------------------------------------------- fault grammar
def test_corrupt_action_parses_and_fires():
    faults.configure("scrub.read:corrupt(3):nth=2")
    assert faults.fire("scrub.read", path="p") is None
    assert faults.fire("scrub.read", path="p") == ("corrupt", "3")
    assert faults.fire("scrub.read", path="p") is None
    faults.configure("tsm.write:corrupt")
    assert faults.fire("tsm.write", path="p") == ("corrupt", None)


def test_corrupt_file_is_deterministic(tmp_path):
    p = str(tmp_path / "blob.bin")
    payload = bytes(range(256)) * 8
    with open(p, "wb") as f:
        f.write(payload)
    off1 = faults.corrupt_file(p, 2)
    with open(p, "rb") as f:
        flipped = f.read()
    assert flipped != payload
    assert flipped[off1:off1 + 2] == bytes(
        b ^ 0xFF for b in payload[off1:off1 + 2])
    with open(p, "wb") as f:
        f.write(payload)
    assert faults.corrupt_file(p, 2) == off1  # same name → same offset


def test_sealed_wal_segment_scrub(tmp_path):
    from cnosdb_tpu.storage.record_file import RecordWriter

    p = str(tmp_path / "wal_0000000001.log")
    w = RecordWriter(p)
    for i in range(10):
        w.append(b"x" * 100 + bytes([i]))
    w.close()
    assert scrub.verify_record_file(p) == os.path.getsize(p)
    faults.corrupt_file(p, 1, lo=16)
    with pytest.raises(ChecksumMismatch):
        scrub.verify_record_file(p)


# ------------------------------------------------------------- rate limiter
def test_rate_limiter_holds_long_run_rate():
    rate = 40 * (1 << 20)
    lim = scrub.RateLimiter(rate)
    lim.take(rate)  # drain the one-second burst allowance
    t0 = time.monotonic()
    for _ in range(4):
        lim.take(8 << 20)
    elapsed = time.monotonic() - t0
    # post-burst steady state: 32MB at 40MB/s, debt-bucket overshoots by
    # at most one chunk → expect ~0.6s; the acceptance bound is "within
    # 2x of scrub_mb_per_sec", i.e. must finish well under 1.6s and must
    # not run unthrottled either
    assert 0.4 <= elapsed < 1.6
