import numpy as np
import pytest

from cnosdb_tpu.errors import TsmError
from cnosdb_tpu.models.codec import Encoding
from cnosdb_tpu.models.schema import ValueType
from cnosdb_tpu.storage.tsm import TsmReader, TsmWriter


def _write_basic(path, n=1000, series=(1, 2, 3)):
    w = TsmWriter(path)
    for sid in series:
        ts = np.arange(n, dtype=np.int64) * 1_000_000 + sid
        vals = np.linspace(0, 100, n) + sid
        nulls = np.zeros(n, dtype=bool)
        nulls[::97] = True
        ints = np.arange(n, dtype=np.int64) * sid
        w.write_series("cpu", sid, ts, {
            "usage": (1, ValueType.FLOAT, Encoding.GORILLA, vals, nulls),
            "n": (2, ValueType.INTEGER, Encoding.DELTA, ints, None),
        })
    return w.finish()


def test_write_read_roundtrip(tmp_path):
    p = str(tmp_path / "f1.tsm")
    footer = _write_basic(p)
    assert footer.series_count == 3
    r = TsmReader(p)
    assert r.tables() == ["cpu"]
    assert sorted(r.series_ids("cpu")) == [1, 2, 3]
    ts = r.read_series_timestamps("cpu", 2)
    assert len(ts) == 1000 and ts[0] == 2
    vals, valid = r.read_series_column("cpu", 2, "usage")
    assert len(vals) == 1000
    assert not valid[0] and valid[1]  # row 0 null (::97 mask)
    expect = np.linspace(0, 100, 1000) + 2
    np.testing.assert_allclose(vals[valid], expect[~(np.arange(1000) % 97 == 0)])
    ints, ivalid = r.read_series_column("cpu", 2, "n")
    assert ivalid.all()
    np.testing.assert_array_equal(ints, np.arange(1000, dtype=np.int64) * 2)
    r.close()


def test_bloom_and_stats(tmp_path):
    p = str(tmp_path / "f2.tsm")
    _write_basic(p)
    r = TsmReader(p)
    assert r.maybe_contains_series(1)
    misses = sum(r.maybe_contains_series(i) for i in range(1000, 1500))
    assert misses < 10
    cm = r.chunk("cpu", 1)
    assert cm.n_rows == 1000
    pm = cm.column("n").pages[0]
    assert pm.stat_min == 0 and pm.stat_max == 999
    assert pm.stat_sum == sum(range(1000))
    assert pm.n_values == 1000
    upm = cm.column("usage").pages[0]
    assert upm.n_values == 1000 - len(range(0, 1000, 97))
    r.close()


def test_multi_page_chunks(tmp_path):
    p = str(tmp_path / "f3.tsm")
    n = 10_000
    w = TsmWriter(p, max_page_rows=1024)
    ts = np.arange(n, dtype=np.int64)
    vals = np.random.default_rng(1).normal(size=n)
    w.write_series("m", 7, ts, {"v": (1, ValueType.FLOAT, Encoding.GORILLA, vals, None)})
    w.finish()
    r = TsmReader(p)
    cm = r.chunk("m", 7)
    assert len(cm.time_pages) == (n + 1023) // 1024
    out, valid = r.read_series_column("m", 7, "v")
    np.testing.assert_array_equal(out, vals)
    np.testing.assert_array_equal(r.read_series_timestamps("m", 7), ts)
    r.close()


def test_string_and_bool_columns(tmp_path):
    p = str(tmp_path / "f4.tsm")
    w = TsmWriter(p)
    ts = np.arange(10, dtype=np.int64)
    strs = np.array([f"s{i}" for i in range(10)], dtype=object)
    bools = np.array([i % 2 == 0 for i in range(10)])
    w.write_series("t", 5, ts, {
        "s": (1, ValueType.STRING, Encoding.ZSTD, strs, None),
        "b": (2, ValueType.BOOLEAN, Encoding.BITPACK, bools, None),
    })
    w.finish()
    r = TsmReader(p)
    sv, _ = r.read_series_column("t", 5, "s")
    assert list(sv) == [f"s{i}" for i in range(10)]
    bv, _ = r.read_series_column("t", 5, "b")
    np.testing.assert_array_equal(bv, bools)
    r.close()


def test_missing_column_is_all_null(tmp_path):
    p = str(tmp_path / "f5.tsm")
    _write_basic(p, n=50)
    r = TsmReader(p)
    vals, valid = r.read_series_column("cpu", 1, "added_later")
    assert len(vals) == 50 and not valid.any()
    r.close()


def test_unsorted_timestamps_rejected(tmp_path):
    w = TsmWriter(str(tmp_path / "f6.tsm"))
    with pytest.raises(TsmError):
        w.write_series("t", 1, np.array([5, 3, 1], dtype=np.int64), {})
    w.abort()


def test_corrupt_page_detected(tmp_path):
    p = str(tmp_path / "f7.tsm")
    _write_basic(p, n=100)
    raw = bytearray(open(p, "rb").read())
    raw[10] ^= 0xFF  # flip a byte inside first page
    open(p, "wb").write(bytes(raw))
    r = TsmReader(p)
    from cnosdb_tpu.errors import ChecksumMismatch
    with pytest.raises(ChecksumMismatch):
        r.read_series_timestamps("cpu", 1)
    r.close()


def test_atomic_write_no_partial_file(tmp_path):
    p = str(tmp_path / "f8.tsm")
    w = TsmWriter(p)
    w.write_series("t", 1, np.arange(5, dtype=np.int64), {})
    w.abort()
    import os
    assert not os.path.exists(p)
    assert not os.path.exists(p + ".tmp")
