#!/usr/bin/env python
"""Repair ported slt blocks whose upstream expected output was TRUNCATED
by the sqllogictest file format (an empty-string cell renders as a blank
line, which terminates the expected block — see DIVERGENCES.md D6).

For each failing `query` block in tests/sqllogic_ref/*.slt: execute the
file up to that query; if the upstream expected rows are a strict PREFIX
of this engine's output (rstripped), extend the block with the remaining
rows. The upstream prefix stays authoritative — a block is only extended,
never rewritten; mismatching blocks are left alone and reported.

Usage: python tests/fixup_ref_slt.py [file.slt ...]   (default: all)
"""
from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

CASES_DIR = os.path.join(os.path.dirname(__file__), "sqllogic_ref")


def process(path: str) -> list[str]:
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.server.http import format_csv
    from cnosdb_tpu.sql.executor import QueryExecutor, Session
    from test_ref_sqllogic import _parse

    with open(path) as f:
        lines = f.read().splitlines()
    blocks = _parse(path)

    tmp = tempfile.mkdtemp()
    meta = MetaStore(tmp + "/meta.json")
    from cnosdb_tpu.storage.engine import TsKv

    coord = Coordinator(meta, TsKv(tmp + "/data"))
    ex = QueryExecutor(meta, coord)
    session = Session()
    notes = []
    # lineno in blocks is the line AFTER the block; rebuild file lines
    out_lines = list(lines)
    inserts: list[tuple[int, list[str]]] = []   # (after_line_idx, rows)
    try:
        for kind, sql, expected, lineno in blocks:
            try:
                if kind == "cleandir":
                    import shutil

                    shutil.rmtree(sql, ignore_errors=True)
                    continue
                if kind == "lineproto":
                    from cnosdb_tpu.models.schema import Precision
                    from cnosdb_tpu.protocol.line_protocol import \
                        parse_lines

                    coord.write_points(session.tenant, session.database,
                                       parse_lines(sql,
                                                   Precision.parse("ns")))
                    continue
                if kind == "use":
                    try:
                        ex.execute_one(
                            f"CREATE DATABASE IF NOT EXISTS {sql}", session)
                    except Exception:
                        pass
                    session.database = sql
                    continue
                if kind == "error":
                    try:
                        ex.execute_one(sql, session)
                    except Exception:
                        pass
                    continue
                rs = ex.execute_one(sql, session)
                if kind in ("query", "querysort"):
                    got = format_csv(rs)[:-1].split("\n")[1:]
                    if got == [""] and rs.n_rows == 0:
                        got = []
                    got = [ln.rstrip() for ln in got]
                    want = [ln.replace("\\N", "").rstrip()
                            for ln in expected]
                    cmp_got = sorted(got) if kind == "querysort" else got
                    cmp_want = sorted(want) if kind == "querysort" else want
                    if cmp_got != cmp_want and len(want) < len(got) \
                            and got[:len(want)] == want:
                        # upstream prefix matches: extend (format
                        # truncation, D6) — re-render empty cells as \N
                        tail = [r if r else "\\N" for r in got[len(want):]]
                        inserts.append((lineno, tail))
                        notes.append(f"{os.path.basename(path)}:{lineno} "
                                     f"+{len(tail)} rows")
            except Exception:
                continue
    finally:
        coord.close()
    for after, rows in sorted(inserts, reverse=True):
        out_lines[after:after] = rows
    if inserts:
        with open(path, "w") as f:
            f.write("\n".join(out_lines).rstrip() + "\n")
    return notes


def main(argv):
    sys.path.insert(0, os.path.dirname(__file__))
    targets = argv or sorted(
        os.path.join(CASES_DIR, f) for f in os.listdir(CASES_DIR)
        if f.endswith(".slt"))
    for t in targets:
        for note in process(t):
            print(note)


if __name__ == "__main__":
    main(sys.argv[1:])
