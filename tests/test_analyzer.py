"""Analyzer rules: topk/bottom selector rewrite + exact_count→count
(reference extension/analyse/transform_{topk,bottom}_func_to_topk_node.rs,
transform_exact_count_to_count.rs)."""
import numpy as np
import pytest

from cnosdb_tpu.errors import PlanError, QueryError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    ex.execute_one("CREATE TABLE m (v DOUBLE, w DOUBLE, TAGS(h))")
    rows = []
    vals = [5.0, 1.0, 9.0, 3.0, 7.0, 2.0, 8.0, 4.0, 6.0, None]
    for i, v in enumerate(vals):
        rows.append(f"({i + 1}, 'h{i % 2}', "
                    + ("NULL" if v is None else str(v)) + f", {i * 1.0})")
    ex.execute_one("INSERT INTO m (time, h, v, w) VALUES " + ", ".join(rows))
    yield ex
    engine.close()


def test_topk_rewrites_to_sort_limit(db):
    rs = db.execute_one("SELECT topk(v, 3) FROM m")
    assert rs.n_rows == 3
    assert [float(x) for x in rs.columns[0]] == [9.0, 8.0, 7.0]


def test_bottom_rewrites_ascending(db):
    rs = db.execute_one("SELECT bottom(v, 2) AS b FROM m")
    assert rs.names == ["b"]
    assert [float(x) for x in rs.columns[0]] == [1.0, 2.0]


def test_topk_k_bounds_and_shape(db):
    for bad in ("topk(v, 0)", "topk(v, 256)", "topk(v)", "topk(v, 1.5)"):
        with pytest.raises((PlanError, QueryError)):
            db.execute_one(f"SELECT {bad} FROM m")


def test_topk_rejects_multiple_and_nested(db):
    with pytest.raises((PlanError, QueryError)):
        db.execute_one("SELECT topk(v, 3), bottom(w, 2) FROM m")
    with pytest.raises((PlanError, QueryError)):
        db.execute_one("SELECT topk(v, 3) FROM m ORDER BY w")


def test_topk_with_companion_columns(db):
    # other projected columns ride along with the selected rows
    rs = db.execute_one("SELECT time, topk(v, 2) AS t FROM m")
    cols = dict(zip(rs.names, rs.columns))
    assert [float(x) for x in cols["t"]] == [9.0, 8.0]
    assert [int(x) for x in cols["time"]] == [3, 7]


def test_topk_limit_caps_k(db):
    rs = db.execute_one("SELECT topk(v, 5) FROM m LIMIT 2")
    assert rs.n_rows == 2


def test_topk_offset_stays_within_k(db):
    # pagination happens WITHIN the top-k set: top-3 of v is {9,8,7},
    # so OFFSET 2 leaves exactly [7] — never rows outside the top-3
    rs = db.execute_one("SELECT topk(v, 3) AS t FROM m OFFSET 2")
    assert [float(x) for x in rs.columns[0]] == [7.0]
    rs = db.execute_one("SELECT topk(v, 3) AS t FROM m LIMIT 5 OFFSET 1")
    assert [float(x) for x in rs.columns[0]] == [8.0, 7.0]


def test_exact_count_rewrites_to_count(db):
    rs = db.execute_one("SELECT exact_count(v) AS c FROM m")
    assert int(rs.columns[0][0]) == 9   # NULL row excluded
    rs = db.execute_one(
        "SELECT h, exact_count(v) AS c FROM m GROUP BY h ORDER BY h")
    assert [int(x) for x in rs.columns[1]] == [5, 4]
