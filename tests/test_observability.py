"""Tracing, debug endpoints, TCP OpenTSDB listener, TLS config, gzip
(reference common/trace/, main/src/http/http_service.rs debug routes,
tcp/tcp_service.rs)."""
import asyncio
import threading
import time

import pytest

from cnosdb_tpu.server.trace import (
    GLOBAL_COLLECTOR, TRACE_HEADER, TraceCollector, current_trace_header,
)


def test_span_nesting_and_collection():
    col = TraceCollector()
    with col.span("root") as root:
        root.set_tag("k", "v")
        with col.span("child"):
            pass
    spans = col.spans()
    assert [s["name"] for s in spans] == ["child", "root"]
    child, root_d = spans
    assert child["trace_id"] == root_d["trace_id"]
    assert child["parent_id"] == root_d["span_id"]
    assert root_d["tags"] == {"k": "v"}
    assert root_d["duration_ns"] > 0


def test_header_propagation():
    col = TraceCollector()
    with col.span("origin") as s:
        hdr = current_trace_header()
        assert hdr == f"{s.trace_id}:{s.span_id}"
    # remote side continues the trace
    with col.from_headers({TRACE_HEADER: hdr}, "remote") as r:
        assert r.trace_id == s.trace_id
        assert r.parent_id == s.span_id


def test_rpc_plane_propagates_trace():
    from cnosdb_tpu.parallel.net import RpcServer, rpc_call

    seen = []

    def handler(p):
        seen.append(current_trace_header())
        return {"ok": True}

    srv = RpcServer("127.0.0.1", 0, {"x": handler}).start()
    try:
        with GLOBAL_COLLECTOR.span("caller") as s:
            rpc_call(srv.addr, "x", {})
        assert seen and seen[0].startswith(s.trace_id + ":")
    finally:
        srv.stop()


@pytest.fixture
def http_server(tmp_path):
    from aiohttp import web

    from cnosdb_tpu.server.http import build_server

    srv = build_server(str(tmp_path / "data"))
    loop_holder = {}

    async def run():
        runner = web.AppRunner(srv.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        loop_holder["port"] = site._server.sockets[0].getsockname()[1]
        loop_holder["tcp"] = await srv.start_tcp_opentsdb("127.0.0.1", 0)
        loop_holder["tcp_port"] = \
            loop_holder["tcp"].sockets[0].getsockname()[1]
        loop_holder["ready"] = True
        await asyncio.sleep(120)

    t = threading.Thread(target=lambda: asyncio.run(run()), daemon=True)
    t.start()
    deadline = time.monotonic() + 15
    while not loop_holder.get("ready") and time.monotonic() < deadline:
        time.sleep(0.05)
    yield srv, loop_holder["port"], loop_holder["tcp_port"]


def _get(port, path):
    import urllib.request

    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


def test_debug_endpoints_and_tcp_listener(http_server):
    import base64
    import socket
    import urllib.request

    srv, port, tcp_port = http_server
    # write through the TCP OpenTSDB listener
    s = socket.create_connection(("127.0.0.1", tcp_port), timeout=5)
    s.sendall(b"put sys.load 1000 1.5 host=tcp1\n"
              b"put sys.load 2000 2.5 host=tcp1\nquit\n")
    s.close()
    deadline = time.monotonic() + 10

    def sql(q):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/sql?db=public", data=q.encode())
        req.add_header("Authorization",
                       "Basic " + base64.b64encode(b"root:").decode())
        req.add_header("Accept-Encoding", "gzip")
        with urllib.request.urlopen(req) as r:
            raw = r.read()
            if r.headers.get("Content-Encoding") == "gzip":
                import gzip as _gz

                raw = _gz.decompress(raw)
            return raw.decode()

    while time.monotonic() < deadline:
        try:
            out = sql('SELECT count(*) AS c FROM "sys.load"')
            if out.strip().splitlines()[-1] == "2":
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert out.strip().splitlines()[-1] == "2"
    # the sql call above created a span; /debug/traces shows it
    st, body = _get(port, "/debug/traces")
    assert st == 200 and b"http:sql" in body
    st, body = _get(port, "/debug/backtrace")
    assert st == 200 and b"thread" in body
    st, body = _get(port, "/debug/pprof?seconds=0.2")
    assert st == 200 and b"samples over" in body


def test_tls_config_loading(tmp_path):
    from cnosdb_tpu.config import Config

    cfg_path = tmp_path / "c.toml"
    cfg_path.write_text(
        '[security]\ntls_cert_path = "/x/cert.pem"\n'
        'tls_key_path = "/x/key.pem"\n')
    cfg = Config.load(str(cfg_path))
    assert cfg.security.enabled
    assert Config().security.enabled is False


def test_otlp_ingest_and_jaeger_query_api(http_server):
    """OTLP/HTTP JSON export → own-table storage → SQL AND jaeger API
    (reference otlp_to_jaeger.rs + http_service.rs jaeger endpoints)."""
    import json as _json
    import urllib.request

    srv, port, _tcp = http_server
    payload = {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "checkout"}}]},
            "scopeSpans": [{"spans": [
                {"traceId": "abc123", "spanId": "s1", "name": "GET /cart",
                 "kind": 2, "startTimeUnixNano": "1700000000000000000",
                 "endTimeUnixNano": "1700000000005000000",
                 "attributes": [{"key": "http.status_code",
                                 "value": {"intValue": "200"}}],
                 "status": {"code": 1}},
                {"traceId": "abc123", "spanId": "s2",
                 "parentSpanId": "s1", "name": "SELECT",
                 "kind": 3, "startTimeUnixNano": "1700000000001000000",
                 "endTimeUnixNano": "1700000000002000000"},
            ]}],
        }],
    }
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/traces?db=public",
        data=_json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        assert r.status == 200

    # stored spans are plain SQL rows
    sreq = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/sql?db=public",
        data=b"SELECT count(*) AS c FROM trace_spans",
        headers={"Accept": "application/json"})
    with urllib.request.urlopen(sreq) as r:
        body = r.read().decode()
    assert '"c": 2' in body or '"c":2' in body, body

    st, body = _get(port, "/api/services")
    assert st == 200 and _json.loads(body)["data"] == ["checkout"]
    st, body = _get(port, "/api/services/checkout/operations")
    assert st == 200
    assert sorted(_json.loads(body)["data"]) == ["GET /cart", "SELECT"]

    st, body = _get(port, "/api/traces?service=checkout")
    traces = _json.loads(body)["data"]
    assert st == 200 and len(traces) == 1
    tr = traces[0]
    assert tr["traceID"] == "abc123" and len(tr["spans"]) == 2
    child = next(s for s in tr["spans"] if s["spanID"] == "s2")
    assert child["references"] == [{"refType": "CHILD_OF",
                                    "traceID": "abc123", "spanID": "s1"}]
    assert child["startTime"] == 1700000000001000  # µs
    assert child["duration"] == 1000               # µs
    procs = tr["processes"]
    assert [p["serviceName"] for p in procs.values()] == ["checkout"]

    st, body = _get(port, "/api/traces/abc123")
    assert st == 200 and _json.loads(body)["data"][0]["traceID"] == "abc123"


def test_otlp_span_export():
    """Own spans export as OTLP/HTTP JSON batches (reference
    global_tracing.rs minitrace → opentelemetry-otlp). A stock OTLP
    collector accepts the JSON encoding on /v1/traces."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from cnosdb_tpu.server.trace import OtlpExporter, TraceCollector

    received = []

    class Recv(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, json.loads(body)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Recv)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        coll = TraceCollector()
        exp = OtlpExporter(f"http://127.0.0.1:{srv.server_port}", coll,
                           flush_interval_s=0.2)
        with coll.span("parent") as p:
            p.set_tag("db", "public")
            with coll.span("child"):
                pass
        exp.close()
        assert received, "no OTLP batch arrived"
        path, payload = received[0]
        assert path == "/v1/traces"
        rs = payload["resourceSpans"][0]
        svc = rs["resource"]["attributes"][0]
        assert svc["key"] == "service.name"
        spans = rs["scopeSpans"][0]["spans"]
        names = {s["name"] for s in spans}
        assert {"parent", "child"} <= names
        by_name = {s["name"]: s for s in spans}
        # ids are OTLP fixed-width hex; the child links to its parent
        assert len(by_name["parent"]["traceId"]) == 32
        assert len(by_name["parent"]["spanId"]) == 16
        assert by_name["child"]["parentSpanId"] == \
            by_name["parent"]["spanId"]
        assert by_name["child"]["traceId"] == by_name["parent"]["traceId"]
        pa = {a["key"]: a["value"]["stringValue"]
              for a in by_name["parent"]["attributes"]}
        assert pa.get("db") == "public"
        assert int(by_name["parent"]["endTimeUnixNano"]) >= \
            int(by_name["parent"]["startTimeUnixNano"])
        assert exp.exported == len(spans)
    finally:
        srv.shutdown()
