"""Multi-process cluster e2e: remote writes, remote scans, raft failover,
node rejoin/catch-up, and a chaos restart-while-writing loop.

Counterpart of the reference's e2e_test/src/independent/{coordinator_tests,
restart_tests,replica_test,chaos_tests}.rs, scaled to CI time budgets:
3 data processes + 1 meta process on localhost ports.
"""
import time

import pytest

from cluster_harness import Cluster

pytestmark = pytest.mark.cluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(str(tmp_path_factory.mktemp("cluster")), n_nodes=3).start()
    yield c
    c.stop()


def _csv_rows(out: str) -> list[list[str]]:
    lines = [l for l in out.strip().splitlines() if l]
    return [l.split(",") for l in lines[1:]]


def _count(node, table, db, where="") -> int:
    out = node.sql(f"SELECT count(*) FROM {table} {where}", db=db)
    rows = _csv_rows(out)
    return int(rows[0][0]) if rows else 0


def _wait_count(node, table, db, expect, timeout=20.0):
    deadline = time.monotonic() + timeout
    got = -1
    while time.monotonic() < deadline:
        try:
            got = _count(node, table, db)
            if got == expect:
                return got
        except Exception:
            pass
        time.sleep(0.3)
    return got


def test_remote_write_and_scan(cluster):
    """Writes through node 1 land on shards across nodes; node 2 serves the
    query by fanning out to remote vnodes (Arrow IPC plane)."""
    n1, n2 = cluster.nodes[0], cluster.nodes[1]
    n1.sql("CREATE DATABASE d1 WITH SHARD 4 REPLICA 1", db="public")
    lines = "\n".join(
        f"cpu,host=h{i} usage={i}.5 {1_700_000_000_000_000_000 + i * 1_000}"
        for i in range(64))
    n1.write_lp(lines, db="d1")
    # query through the OTHER node: requires remote fan-out
    assert _wait_count(n2, "cpu", "d1", 64) == 64
    out = n2.sql("SELECT host, usage FROM cpu WHERE host = 'h7'", db="d1")
    rows = _csv_rows(out)
    assert rows == [["h7", "7.5"]]
    # aggregate across shards/nodes
    out = n2.sql("SELECT sum(usage) FROM cpu", db="d1")
    assert abs(float(_csv_rows(out)[0][0]) - sum(i + 0.5 for i in range(64))) < 1e-6


def test_replicated_write_failover_and_rejoin(cluster):
    """REPLICA 3: writes survive killing a node (majority commit), the
    killed node rejoins and catches up (reference replica_test +
    restart_tests)."""
    n1, n2, n3 = cluster.nodes
    n1.sql("CREATE DATABASE d2 WITH SHARD 1 REPLICA 3", db="public")
    lines = "\n".join(
        f"mem,host=h{i % 4} used={i} {1_700_000_000_000_000_000 + i * 1_000}"
        for i in range(32))
    n1.write_lp(lines, db="d2")
    assert _wait_count(n1, "mem", "d2", 32) == 32
    # kill node 3; majority (2/3) keeps accepting writes and serving reads
    n3.kill()
    more = "\n".join(
        f"mem,host=h{i % 4} used={i} {1_700_000_000_000_000_000 + (32 + i) * 1_000}"
        for i in range(32))
    n1.write_lp(more, db="d2")
    assert _wait_count(n2, "mem", "d2", 64) == 64
    # restart node 3: raft replays/snapshots it back to parity
    n3.start().wait_ready()
    assert _wait_count(n3, "mem", "d2", 64, timeout=90.0) == 64


def test_killed_leaderless_shard_still_reads(cluster):
    """Single-replica shards owned by a killed node fail over for reads on
    OTHER shards; replicated data stays fully readable."""
    n1, n2 = cluster.nodes[0], cluster.nodes[1]
    # d2 from the previous test is replica-3: still readable from any node
    assert _count(n2, "mem", "d2") == 64


def test_chaos_restart_while_writing(cluster):
    """Chaos loop (reference chaos_tests.rs:75): restart a data node while
    writes keep flowing through the others; nothing acknowledged is lost."""
    n1, n2, n3 = cluster.nodes
    n1.sql("CREATE DATABASE d3 WITH SHARD 2 REPLICA 3", db="public")
    total = 0
    base = 1_700_000_000_000_000_000
    for round_i in range(6):
        if round_i == 2:
            n3.kill()
        if round_i == 4:
            n3.start()  # rejoin mid-traffic, don't wait
        writer = n1 if round_i % 2 == 0 else n2
        lines = "\n".join(
            f"evt,host=h{i % 8} v={i} {base + (total + i) * 1_000}"
            for i in range(25))
        writer.write_lp(lines, db="d3")
        total += 25
    assert _wait_count(n1, "evt", "d3", total, timeout=30.0) == total
    n3.wait_ready()
    assert _wait_count(n3, "evt", "d3", total, timeout=90.0) == total


def test_move_vnode_then_kill_source(cluster):
    """Elasticity (reference MOVE VNODE + DownloadFile): re-place a vnode
    onto another node, kill the original owner — scans still answer from
    the new placement."""
    n1, n2, n3 = cluster.nodes
    for n in (n1, n2, n3):
        if n.proc is None:
            n.start()
    for n in (n1, n2, n3):
        n.wait_ready()
    n1.sql("CREATE DATABASE dmv WITH SHARD 1 REPLICA 1", db="public")
    lines = "\n".join(
        f"mv,host=h{i % 3} v={i} {1_700_000_000_000_000_000 + i * 1_000}"
        for i in range(20))
    n1.write_lp(lines, db="dmv")
    assert _wait_count(n1, "mv", "dmv", 20) == 20
    # find the vnode and its owning node
    out = n1.sql("SELECT vnode_id, node_id FROM cluster_schema.vnodes "
                 "WHERE owner = 'cnosdb.dmv'", db="public")
    rows = _csv_rows(out)
    assert rows, out
    vid, owner_node = int(rows[0][0]), int(rows[0][1])
    target = next(n.node_id for n in (n1, n2, n3)
                  if n.node_id != owner_node)
    n1.sql(f"MOVE VNODE {vid} TO NODE {target}", db="public")
    # data fully served from the new node
    survivor = next(n for n in (n1, n2, n3) if n.node_id != owner_node)
    assert _wait_count(survivor, "mv", "dmv", 20) == 20
    # kill the ORIGINAL owner: the moved vnode must keep answering
    victim = next(n for n in (n1, n2, n3) if n.node_id == owner_node)
    victim.kill()
    assert _wait_count(survivor, "mv", "dmv", 20, timeout=30.0) == 20
    victim.start().wait_ready()
