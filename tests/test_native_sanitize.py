"""ASAN+UBSAN harness for the native codec kernels (SURVEY §5: the
reference relies on Rust's ownership guarantees; the rebuild's C++ surface
gets sanitizers). Builds `libcnosdb_codecs_asan.so` and drives codec
round-trips through it in a SUBPROCESS with the sanitizer runtime
preloaded — any heap overflow / UB aborts the child and fails the test."""
import os
import subprocess
import sys

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
ASAN_LIB = os.path.join(os.path.dirname(__file__), "..", "cnosdb_tpu",
                        "_native", "libcnosdb_codecs_asan.so")

CHILD = r"""
import os, sys
import numpy as np

# route the bindings at the sanitized build
os.environ["CNOSDB_NATIVE_LIB"] = sys.argv[1]
from cnosdb_tpu.storage import codecs, native
from cnosdb_tpu.models.schema import ValueType

assert native.available(), "sanitized native lib failed to load"

rng = np.random.default_rng(7)
# exercise every codec family through encode→decode round-trips at odd
# sizes (boundary conditions are where memory bugs live)
for n in (0, 1, 7, 63, 64, 65, 1000, 4097):
    ts = np.cumsum(rng.integers(1, 1000, max(n, 1)).astype(np.int64))[:n]
    out = codecs.decode_timestamps(codecs.encode_timestamps(ts))
    assert np.array_equal(out, ts), f"ts roundtrip n={n}"

    f = rng.normal(0, 1e6, n)
    out = codecs.decode(codecs.encode(f, ValueType.FLOAT), ValueType.FLOAT)
    assert np.array_equal(out, f), f"f64 roundtrip n={n}"

    i = rng.integers(-2**40, 2**40, max(n, 1)).astype(np.int64)[:n]
    out = codecs.decode(codecs.encode(i, ValueType.INTEGER),
                        ValueType.INTEGER)
    assert np.array_equal(out, i), f"i64 roundtrip n={n}"

# line-protocol parser under sanitizers: valid, malformed, and
# adversarial inputs (truncated escapes, unbalanced quotes, huge tokens)
from cnosdb_tpu.protocol import native_lp
assert native_lp.available()
cases = [
    "cpu,host=a usage=1.5,b=t,s=\"x\",c=3i,u=7u 1000\n" * 50,
    "m v=1",                       # no trailing newline
    "m \\",                        # trailing escape
    'm s="unterminated 5\n',
    "m,t=1 v=1 99999999999999999999999\n",   # ts overflow
    "m," + "k=v," * 500 + "z=1 v=1 5\n",
    "m v=" + "9" * 400 + "i 5\n",
    "\x00\xff bin=1 5\n",
    "#only comments\n\n\n",
    "",
]
for c in cases:
    native_lp.try_parse(c, 0, 1)   # must not crash; result may be None
rnd = np.random.default_rng(11)
for _ in range(200):               # random byte soup
    blob = rnd.integers(32, 127, rnd.integers(1, 300)).astype(np.uint8)
    native_lp.try_parse(blob.tobytes().decode("ascii"), 0, 1)
print("SANITIZED ROUNDTRIPS OK")
"""


@pytest.mark.skipif(not os.path.exists(os.path.join(NATIVE_DIR, "codecs.cpp")),
                    reason="native source absent")
def test_codecs_under_asan(tmp_path):
    build = subprocess.run(["make", "-C", NATIVE_DIR, "asan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"asan build unavailable: {build.stderr[-300:]}")
    # find the asan runtime to preload (python itself isn't instrumented)
    probe = subprocess.run(
        ["g++", "-print-file-name=libasan.so"], capture_output=True,
        text=True)
    asan_rt = probe.stdout.strip()
    cxx = subprocess.run(
        ["g++", "-print-file-name=libstdc++.so"], capture_output=True,
        text=True).stdout.strip()
    env = dict(os.environ)
    # libstdc++ after libasan: the __cxa_throw interceptor must find the
    # real symbol at init or sanitized C++ exceptions abort
    env["LD_PRELOAD"] = f"{asan_rt} {cxx}"
    env["ASAN_OPTIONS"] = "detect_leaks=0,abort_on_error=1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    child = subprocess.run(
        [sys.executable, "-c", CHILD, os.path.abspath(ASAN_LIB)],
        capture_output=True, text=True, env=env, timeout=300)
    assert child.returncode == 0, \
        f"sanitizer run failed:\n{child.stdout}\n{child.stderr[-2000:]}"
    assert "SANITIZED ROUNDTRIPS OK" in child.stdout
