"""ASAN+UBSAN harness for the native codec kernels (SURVEY §5: the
reference relies on Rust's ownership guarantees; the rebuild's C++ surface
gets sanitizers). Builds `libcnosdb_codecs_asan.so` and drives codec
round-trips through it in a SUBPROCESS with the sanitizer runtime
preloaded — any heap overflow / UB aborts the child and fails the test."""
import os
import subprocess
import sys

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
ASAN_LIB = os.path.join(os.path.dirname(__file__), "..", "cnosdb_tpu",
                        "_native", "libcnosdb_codecs_asan.so")

CHILD = r"""
import os, sys
import numpy as np

# route the bindings at the sanitized build
os.environ["CNOSDB_NATIVE_LIB"] = sys.argv[1]
from cnosdb_tpu.storage import codecs, native
from cnosdb_tpu.models.schema import ValueType

assert native.available(), "sanitized native lib failed to load"

rng = np.random.default_rng(7)
# exercise every codec family through encode→decode round-trips at odd
# sizes (boundary conditions are where memory bugs live)
for n in (0, 1, 7, 63, 64, 65, 1000, 4097):
    ts = np.cumsum(rng.integers(1, 1000, max(n, 1)).astype(np.int64))[:n]
    out = codecs.decode_timestamps(codecs.encode_timestamps(ts))
    assert np.array_equal(out, ts), f"ts roundtrip n={n}"

    f = rng.normal(0, 1e6, n)
    out = codecs.decode(codecs.encode(f, ValueType.FLOAT), ValueType.FLOAT)
    assert np.array_equal(out, f), f"f64 roundtrip n={n}"

    i = rng.integers(-2**40, 2**40, max(n, 1)).astype(np.int64)[:n]
    out = codecs.decode(codecs.encode(i, ValueType.INTEGER),
                        ValueType.INTEGER)
    assert np.array_equal(out, i), f"i64 roundtrip n={n}"
print("SANITIZED ROUNDTRIPS OK")
"""


@pytest.mark.skipif(not os.path.exists(os.path.join(NATIVE_DIR, "codecs.cpp")),
                    reason="native source absent")
def test_codecs_under_asan(tmp_path):
    build = subprocess.run(["make", "-C", NATIVE_DIR, "asan"],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"asan build unavailable: {build.stderr[-300:]}")
    # find the asan runtime to preload (python itself isn't instrumented)
    probe = subprocess.run(
        ["g++", "-print-file-name=libasan.so"], capture_output=True,
        text=True)
    asan_rt = probe.stdout.strip()
    env = dict(os.environ)
    env["LD_PRELOAD"] = asan_rt
    env["ASAN_OPTIONS"] = "detect_leaks=0,abort_on_error=1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    child = subprocess.run(
        [sys.executable, "-c", CHILD, os.path.abspath(ASAN_LIB)],
        capture_output=True, text=True, env=env, timeout=300)
    assert child.returncode == 0, \
        f"sanitizer run failed:\n{child.stdout}\n{child.stderr[-2000:]}"
    assert "SANITIZED ROUNDTRIPS OK" in child.stdout
