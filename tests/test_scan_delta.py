"""Incremental scan snapshots (delta tokens) + scan-cache accounting.

The coordinator's scan cache keys each vnode batch by a ScanToken
(TSM file-id set + memcache WAL seqno + destructive version). A stale
hit decodes only what the token doesn't cover and merges it into the
cached batch — these tests pin the perf counters (`delta_hit` /
`delta_rows` / `scan_miss`) AND bit-identical equivalence with a full
rescan across interleaved writes, flushes, compactions, deletes and
ALTERs.
"""
import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.schema import ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import DEFAULT_TENANT, MetaStore
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import stages


@pytest.fixture
def cluster(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    yield meta, engine, coord
    engine.close()


def _write(coord, host, ts_list, vals, table="cpu", db="public",
           field="usage"):
    wb = WriteBatch()
    wb.add_series(table, SeriesRows(
        SeriesKey(table, {"host": host}), list(ts_list),
        {field: (int(ValueType.FLOAT), list(vals))}))
    coord.write_points(DEFAULT_TENANT, db, wb)


def _counters(coord, *scan_args, **scan_kw):
    """Run one scan under a scoped profile → (batches, snapshot)."""
    prof = stages.QueryProfile()
    with stages.profile_scope(prof):
        bs = coord.scan_table(*scan_args, **scan_kw)
    return bs, prof.snapshot()


def _flat(batches):
    """Canonical row set: sorted (sid, ts, field, value, valid) tuples —
    order-independent equality across scans."""
    out = []
    for b in batches:
        sid = b.series_ids[b.sid_ordinal]
        for name, (_vt, v, valid) in sorted(b.fields.items()):
            vv = v.decode() if hasattr(v, "decode") else v
            vals = np.where(valid, vv, 0)
            out += list(zip(sid.tolist(), b.ts.tolist(),
                            [name] * len(b.ts),
                            np.asarray(vals).tolist(), valid.tolist()))
    return sorted(out)


def _fresh_scan(meta, engine, table="cpu", db="public"):
    """Forced full rescan ground truth: a new Coordinator over the SAME
    engine has an empty scan cache, so every batch decodes from scratch.
    (A second TsKv over the live data dir would race the first one's
    WAL/summary writes — same engine, fresh cache is the honest probe.)"""
    return _flat(Coordinator(meta, engine).scan_table(
        DEFAULT_TENANT, db, table))


# --------------------------------------------------------------- perf smoke

def test_rescan_after_one_write_is_delta_not_miss(cluster):
    """Acceptance: after 1 new row on a scanned vnode, the rescan reports
    delta_hit (not scan_miss) and decodes only the new row."""
    meta, engine, coord = cluster
    _write(coord, "a", range(500), [float(i) for i in range(500)])
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")

    _write(coord, "a", [10_000], [42.0])
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) >= 1, snap
    assert snap.get("scan_miss", 0) == 0, snap
    # only the delta decodes: 1 new row, not the 500 cached ones
    assert snap.get("delta_rows", 0) <= 2, snap
    assert sum(b.n_rows for b in bs) == 501
    row = {(s, t): v for s, t, _f, v, ok in _flat(bs) if ok}
    assert row[min(row)[0], 10_000] == 42.0


def test_second_scan_is_plain_hit(cluster):
    meta, engine, coord = cluster
    _write(coord, "a", range(50), [1.0] * 50)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    _, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("scan_hit", 0) >= 1 and "delta_hit" not in snap, snap


def test_memcache_only_delta(cluster):
    """Delta entirely from memcache rows (no flush): new series too."""
    meta, engine, coord = cluster
    _write(coord, "a", range(100), [1.0] * 100)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    _write(coord, "b", range(30), [2.0] * 30)   # new series, mem only
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) >= 1, snap
    assert sum(b.n_rows for b in bs) == 130
    assert _flat(bs) == _fresh_scan(meta, engine)


def test_overwrite_same_timestamp_delta_wins(cluster):
    meta, engine, coord = cluster
    _write(coord, "a", range(20), [1.0] * 20)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    _write(coord, "a", [7], [99.0])
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) >= 1, snap
    assert sum(b.n_rows for b in bs) == 20      # dedup, no double row
    rows = {(s, t): v for s, t, _f, v, ok in _flat(bs) if ok}
    assert list(rows[k] for k in rows if k[1] == 7) == [99.0]
    assert _flat(bs) == _fresh_scan(meta, engine)


def test_flush_then_rescan_stays_delta(cluster):
    """A flush turns memcache rows into a new L0 file: the rescan decodes
    that file as the delta and dedups the re-decoded rows."""
    meta, engine, coord = cluster
    _write(coord, "a", range(100), [1.0] * 100)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    engine.flush_all()
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) >= 1, snap
    assert snap.get("scan_miss", 0) == 0, snap
    assert sum(b.n_rows for b in bs) == 100
    assert _flat(bs) == _fresh_scan(meta, engine)


# ------------------------------------------------------------- invalidation

def test_compaction_invalidates_delta_tokens(cluster):
    """Regression: compaction rewrites the file set, so cached tokens no
    longer cover it → full rescan (scan_miss), never a bogus delta."""
    meta, engine, coord = cluster
    for i in range(4):
        _write(coord, "a", range(i * 10, i * 10 + 10), [float(i)] * 10)
        engine.flush_all()
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    v = next(iter(engine.vnodes.values()))
    before = v.scan_token().file_ids
    engine.compact_all()
    assert v.scan_token().file_ids != before, "compaction did not rewrite files"
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("scan_miss", 0) >= 1, snap
    assert snap.get("delta_hit", 0) == 0, snap
    assert _flat(bs) == _fresh_scan(meta, engine)


def test_delete_forces_full_rescan(cluster):
    """Tombstone-writing deletes bump destructive_version: a delta can't
    express removed rows, so the next scan is a full rescan."""
    meta, engine, coord = cluster
    _write(coord, "a", range(100), [1.0] * 100)
    engine.flush_all()
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    from cnosdb_tpu.models.predicate import ColumnDomains
    coord.delete_from_table(DEFAULT_TENANT, "public", "cpu",
                            ColumnDomains.all(), 0, 49)
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) == 0, snap
    assert sum(b.n_rows for b in bs) == 50
    assert _flat(bs) == _fresh_scan(meta, engine)


# ------------------------------------------------------------ property test

def test_delta_merge_equals_full_rescan_interleaved(cluster):
    """Property: after every step of an interleaved write/flush/compact/
    ALTER schedule, the (possibly delta-merged) cached scan is
    bit-identical to a forced full rescan of the same storage."""
    from cnosdb_tpu.sql.executor import QueryExecutor

    meta, engine, coord = cluster
    ex = QueryExecutor(meta, coord)
    rng = np.random.default_rng(7)

    _write(coord, "h0", range(10), rng.random(10).tolist())
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")

    next_ts = 1000
    for step in range(24):
        op = step % 6
        if op in (0, 1, 3):     # writes: old series, new series, overwrite
            host = f"h{rng.integers(0, 4)}"
            n = int(rng.integers(1, 8))
            base = next_ts if op != 3 else int(rng.integers(0, 10))
            next_ts += n
            _write(coord, host, range(base, base + n),
                   rng.random(n).tolist())
        elif op == 2:
            engine.flush_all()
        elif op == 4 and step == 10:
            ex.execute_one("ALTER TABLE cpu ADD FIELD extra DOUBLE")
        elif op == 5 and step == 17:
            engine.flush_all()
            engine.compact_all()
        got = _flat(coord.scan_table(DEFAULT_TENANT, "public", "cpu"))
        want = _fresh_scan(meta, engine)
        assert got == want, f"divergence after step {step} (op {op})"

    # the schedule must actually have exercised the delta path
    _write(coord, "h1", [99_999], [5.0])
    _, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("delta_hit", 0) >= 1, snap


def test_alter_table_isolates_cache_entries(cluster):
    """ALTER bumps schema_version which is part of the cache key: post-DDL
    scans never serve a pre-DDL batch (no delta across the ALTER)."""
    from cnosdb_tpu.sql.executor import QueryExecutor

    meta, engine, coord = cluster
    ex = QueryExecutor(meta, coord)
    _write(coord, "a", range(10), [1.0] * 10)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    ex.execute_one("ALTER TABLE cpu ADD FIELD extra DOUBLE")
    bs, snap = _counters(coord, DEFAULT_TENANT, "public", "cpu")
    assert snap.get("scan_miss", 0) >= 1, snap
    assert _flat(bs) == _fresh_scan(meta, engine)


# ---------------------------------------------------------- cache accounting

def test_scan_cache_byte_accounting_and_cap(cluster):
    meta, engine, coord = cluster
    _write(coord, "a", range(100), [1.0] * 100)
    coord.scan_table(DEFAULT_TENANT, "public", "cpu")
    entries, nbytes = coord.scan_cache_stats()
    assert entries == 1
    # ts(8) + usage vals(8) per row is the floor; keys/overhead add more
    assert nbytes >= 100 * 16

    # shrink the byte cap below one entry: storing evicts down to it
    old = coord.SCAN_CACHE_MAX_BYTES
    try:
        coord.SCAN_CACHE_MAX_BYTES = nbytes // 2
        _write(coord, "b", range(100), [2.0] * 100, table="mem")
        coord.scan_table(DEFAULT_TENANT, "public", "mem")
        entries2, nbytes2 = coord.scan_cache_stats()
        assert entries2 <= 1
    finally:
        coord.SCAN_CACHE_MAX_BYTES = old


def test_executor_pool_and_metrics_surface():
    from cnosdb_tpu.utils import executor

    assert executor.pool_size("scan") >= 1
    assert executor.pool_size("decode") >= 1
    sizes = executor.pool_sizes()
    assert sizes.get("scan", 0) >= 1 and sizes.get("decode", 0) >= 1
    active = executor.active_counts()
    assert active.get("scan", 0) >= 0
    # the pool actually runs work, in submission order
    assert executor.run_all("scan", lambda x: x * 2, [1, 2, 3]) == [2, 4, 6]


def test_scan_token_excludes_stored_but_unapplied_entries(tmp_path):
    """Under replication the vnode WAL doubles as the raft log: an entry
    is stored at replication time but only visible at apply time. A token
    captured in that window must NOT cover the entry's seq — a cached
    0-row result would otherwise revalidate as "delta empty" forever once
    the rows apply (seq > mem_seq filters them out)."""
    from cnosdb_tpu.storage.vnode import VnodeStorage
    from cnosdb_tpu.storage.wal import WalEntryType

    v = VnodeStorage(1, str(tmp_path / "v1"))
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": "a"}), [10, 20],
        {"usage": (int(ValueType.FLOAT), [1.0, 2.0])}))
    data = wb.encode()
    # replication layer stores the entry (append-time durability)...
    seq = v.wal.append(WalEntryType.WRITE, data)
    t0 = v.scan_token()
    assert t0.mem_seq < seq
    # ...and applies it once the quorum commits
    v.apply_entry(WalEntryType.WRITE, data, seq)
    t1 = v.scan_token()
    assert t1.mem_seq == seq
    assert t1.data_version > t0.data_version
    # the delta over the old token now surfaces the applied rows
    sv = v.active.suffix_view(t0.mem_seq)
    assert sv is not None and not sv.is_empty
