"""RENAME COLUMN lineage: reusing a renamed-away name must never
conflate two columns' data (TSM chunks resolve fields by column id —
storage/scan.py _resolve_chunk_col; buffered memcache rows re-key at
ALTER time — vnode.rename_mem_field). Reference behavior:
alter_table.rs rename_column keeps the column id stable."""
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex._engine = engine
    yield ex
    engine.close()


def _setup(db):
    db.execute_one("CREATE TABLE t (f1 BIGINT, f2 BIGINT, TAGS(tg))")
    db.execute_one(
        "INSERT INTO t (time, tg, f1, f2) VALUES "
        "(1000, 'a', 100, 200), (2000, 'a', 101, 201)")


def _rename_chain(db):
    db.execute_one("ALTER TABLE t RENAME COLUMN f1 TO g")
    db.execute_one("ALTER TABLE t RENAME COLUMN f2 TO f1")


def _check(db):
    rs = db.execute_one("SELECT time, g, f1 FROM t ORDER BY time")
    assert rs.columns[1].tolist() == [100, 101]   # g = historic f1
    assert rs.columns[2].tolist() == [200, 201]   # new f1 = historic f2


def test_rename_reuse_memcache(db):
    """Unflushed rows: the ALTER re-keys live memcache data."""
    _setup(db)
    _rename_chain(db)
    _check(db)


def test_rename_reuse_flushed(db):
    """Flushed chunks: id-based resolution picks the right column."""
    _setup(db)
    db._engine.flush_all()
    _rename_chain(db)
    _check(db)


def test_rename_reuse_flushed_with_filter(db):
    """Predicate page pruning must key constraints onto the id-resolved
    chunk column, not the same-named stale one."""
    _setup(db)
    db._engine.flush_all()
    _rename_chain(db)
    rs = db.execute_one("SELECT time, f1 FROM t WHERE f1 >= 201")
    assert rs.columns[1].tolist() == [201]
    rs = db.execute_one("SELECT time, g FROM t WHERE g <= 100")
    assert rs.columns[1].tolist() == [100]


def test_rename_reuse_across_compaction(db):
    """Compaction merges chunk columns by id and writes them back under
    the current schema names."""
    _setup(db)
    db._engine.flush_all()
    _rename_chain(db)
    db.execute_one(
        "INSERT INTO t (time, tg, g, f1) VALUES (3000, 'a', 102, 202)")
    db._engine.flush_all()
    db._engine.compact_all()
    rs = db.execute_one("SELECT time, g, f1 FROM t ORDER BY time")
    assert rs.columns[1].tolist() == [100, 101, 102]
    assert rs.columns[2].tolist() == [200, 201, 202]


def test_rename_then_add_fresh_column(db):
    """ADD COLUMN under a renamed-away name starts empty (lineage cut —
    models/schema.py add_column)."""
    _setup(db)
    db._engine.flush_all()
    db.execute_one("ALTER TABLE t RENAME COLUMN f1 TO g")
    db.execute_one("ALTER TABLE t ADD FIELD f1 BIGINT")
    rs = db.execute_one("SELECT time, g, f1 FROM t ORDER BY time")
    assert rs.columns[1].tolist() == [100, 101]
    assert rs.columns[2].tolist() == [None, None]


def test_rename_simple_follows_data(db):
    """Plain rename still reads historic chunks (no reuse involved)."""
    _setup(db)
    db._engine.flush_all()
    db.execute_one("ALTER TABLE t RENAME COLUMN f1 TO vis")
    rs = db.execute_one("SELECT time, vis FROM t ORDER BY time")
    assert rs.columns[1].tolist() == [100, 101]


def test_drop_then_rename_no_resurrection(db):
    """DROP COLUMN purges unflushed memcache chunks; renaming another
    column onto the dropped name must not resurrect the dropped values."""
    db.execute_one("CREATE TABLE t (a BIGINT, b BIGINT, TAGS(tg))")
    db.execute_one("INSERT INTO t (time, tg, b) VALUES (1000, 'x', 555)")
    db.execute_one("ALTER TABLE t DROP COLUMN b")
    db.execute_one("ALTER TABLE t RENAME COLUMN a TO b")
    rs = db.execute_one("SELECT time, b FROM t")
    assert rs.columns[1].tolist() == [None]


def test_drop_then_add_no_resurrection(db):
    """Same leftover-chunk hazard through ADD COLUMN instead of RENAME."""
    db.execute_one("CREATE TABLE t (a BIGINT, b BIGINT, TAGS(tg))")
    db.execute_one("INSERT INTO t (time, tg, b) VALUES (1000, 'x', 555)")
    db.execute_one("ALTER TABLE t DROP COLUMN b")
    db.execute_one("ALTER TABLE t ADD FIELD b BIGINT")
    rs = db.execute_one("SELECT time, b FROM t")
    assert rs.columns[1].tolist() == [None]


def test_rename_errors(db):
    _setup(db)
    with pytest.raises(Exception):
        db.execute_one("ALTER TABLE t RENAME COLUMN time TO t2")
    with pytest.raises(Exception):
        db.execute_one("ALTER TABLE t RENAME COLUMN f1 TO f2")
    with pytest.raises(Exception):
        db.execute_one("ALTER TABLE t RENAME COLUMN nope TO x")


# ---------------------------------------------------------------- crash replay
def _build(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"), background_compaction=False)
    coord = Coordinator(meta, engine)
    # coordinator BEFORE open_existing (mirrors server/http.py build_*):
    # its init hydrates the schema view WAL replay re-keys against
    engine.open_existing()
    ex = QueryExecutor(meta, coord)
    ex._engine = engine
    return ex, engine


def test_rename_reuse_crash_replay(tmp_path):
    """WAL entries written BEFORE a rename chain carry the old field names;
    post-crash replay must re-key them by column id (the WriteBatch schema
    stamp), or historic f1 rows would land under the reused name f1."""
    ex, engine = _build(tmp_path)
    _setup(ex)            # rows reach memcache + WAL under f1/f2
    _rename_chain(ex)     # f1 → g, f2 → f1 (live memcache re-keys; WAL keeps
    #                       the as-written names + schema-version stamp)
    # crash: WAL durable, process dies WITHOUT close() — close would flush
    # the memcache and empty the replay window this test exists to cover
    for v in engine.vnodes.values():
        v.wal.sync()
    engine._compactor.shutdown(wait=False)

    ex2, engine2 = _build(tmp_path)
    _check(ex2)           # g = historic f1 values, f1 = historic f2 values
    engine2.close()


def test_rename_drop_crash_replay_drops_rows(tmp_path):
    """A column DROPPED between write and crash must not resurrect at
    replay under a later same-named column (the stamp maps its id to a
    column the live schema no longer has)."""
    ex, engine = _build(tmp_path)
    ex.execute_one("CREATE TABLE t (f1 BIGINT, f2 BIGINT, TAGS(tg))")
    ex.execute_one(
        "INSERT INTO t (time, tg, f1, f2) VALUES (1000, 'a', 100, 200)")
    ex.execute_one("ALTER TABLE t DROP COLUMN f2")
    ex.execute_one("ALTER TABLE t ADD FIELD f2 BIGINT")
    for v in engine.vnodes.values():
        v.wal.sync()
    engine._compactor.shutdown(wait=False)

    ex2, engine2 = _build(tmp_path)
    rs = ex2.execute_one("SELECT time, f1, f2 FROM t ORDER BY time")
    assert rs.columns[1].tolist() == [100]
    assert rs.columns[2].tolist() == [None]   # dropped data stays dropped
    engine2.close()
