"""Materialized rollup plane: CREATE/SHOW/DROP lifecycle, subsumption
rewrite bit-identity, late-data watermark semantics, durable per-vnode
state across restart, and the crash/replay chaos oracle (slow-marked).

The fast suite runs everything in-process with CNOSDB_MATVIEW_AUTO=0 and
explicit ``now_ns`` so watermark advancement is deterministic against the
~1970 synthetic timestamps; the chaos test spawns a real node process and
injects a crash at the ``matview.persist`` fault site (power loss between
writing the tmp state file and the atomic rename).
"""
import glob
import json
import os
import time
import urllib.error

import pytest

from cnosdb_tpu.errors import QueryError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql import matview
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.sql.stream import WatermarkTracker
from cnosdb_tpu.storage.engine import TsKv


SEC = 10**9
MIN = 60 * SEC


@pytest.fixture
def db(tmp_path, monkeypatch):
    monkeypatch.setenv("CNOSDB_MATVIEW_AUTO", "0")
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    yield ex
    coord.close()


def _seed(db, n=200, start=0):
    # i.5 values: sums of halves stay exact in binary FP, so incremental
    # vs from-scratch aggregation must be bit-identical, not just close
    rows = ", ".join(f"({(start + i) * SEC}, 'h{(start + i) % 4}', "
                     f"{start + i}.5)" for i in range(n))
    db.execute_one(f"INSERT INTO m (time, h, v) VALUES {rows}")


def _mk_view(db, name="mv1", delay="10s"):
    db.execute_one(
        f"CREATE MATERIALIZED VIEW {name} WATERMARK DELAY '{delay}' AS "
        "SELECT date_bin(INTERVAL '1 minute', time) AS t, h, "
        "sum(v), count(v) FROM m GROUP BY t, h")


def _refresh(db, name="mv1", now_ns=None):
    return db.matview_engine().refresh(name, now_ns=now_ns)


def _both(db, q):
    """(rows with rewrite, rows without) — repr-compared for bit identity."""
    db.matview_rewrite_enabled = True
    a = db.execute_one(q).rows()
    db.matview_rewrite_enabled = False
    b = db.execute_one(q).rows()
    db.matview_rewrite_enabled = True
    return sorted(map(repr, a)), sorted(map(repr, b))


# ------------------------------------------------------------- lifecycle
def test_create_show_drop(db):
    _mk_view(db)
    rs = db.execute_one("SHOW MATERIALIZED VIEWS")
    rows = rs.rows()
    assert len(rows) == 1
    row = dict(zip(rs.names, rows[0]))
    assert row["view_name"] == "mv1" and row["table"] == "m"
    assert int(row["delay_ns"]) == 10 * SEC
    assert "sum(v)" in row["query"]

    with pytest.raises(QueryError):
        _mk_view(db)                     # duplicate
    db.execute_one(
        "CREATE MATERIALIZED VIEW IF NOT EXISTS mv1 AS "
        "SELECT date_bin(INTERVAL '1 minute', time) AS t, sum(v) "
        "FROM m GROUP BY t")             # no-op, keeps original def

    db.execute_one("DROP MATERIALIZED VIEW mv1")
    assert db.execute_one("SHOW MATERIALIZED VIEWS").rows() == []
    with pytest.raises(QueryError):
        db.execute_one("DROP MATERIALIZED VIEW mv1")
    db.execute_one("DROP MATERIALIZED VIEW IF EXISTS mv1")   # no-op


def test_ineligible_definitions_rejected(db):
    bad = [
        # WHERE: deltas would need the predicate re-applied to raw rows
        "CREATE MATERIALIZED VIEW b AS SELECT date_bin(INTERVAL '1 minute',"
        " time) AS t, sum(v) FROM m WHERE v > 1 GROUP BY t",
        # no time bucket: nothing ever seals
        "CREATE MATERIALIZED VIEW b AS SELECT h, sum(v) FROM m GROUP BY h",
        # median has no mergeable partial form
        "CREATE MATERIALIZED VIEW b AS SELECT date_bin(INTERVAL '1 minute',"
        " time) AS t, median(v) FROM m GROUP BY t",
        # count(DISTINCT) partials are not mergeable either
        "CREATE MATERIALIZED VIEW b AS SELECT date_bin(INTERVAL '1 minute',"
        " time) AS t, count(DISTINCT v) FROM m GROUP BY t",
        # LIMIT makes the state order-dependent
        "CREATE MATERIALIZED VIEW b AS SELECT date_bin(INTERVAL '1 minute',"
        " time) AS t, sum(v) FROM m GROUP BY t LIMIT 3",
    ]
    for sql in bad:
        with pytest.raises(QueryError):
            db.execute_one(sql)
    assert db.execute_one("SHOW MATERIALIZED VIEWS").rows() == []


# ------------------------------------------------------ subsumption rewrite
def test_rewrite_bit_identical_across_query_shapes(db):
    _seed(db)
    _mk_view(db)
    db.execute_one(
        "CREATE MATERIALIZED VIEW mv2 AS "
        "SELECT date_bin(INTERVAL '1 minute', time) AS t, h, max(v), "
        "min(v), first(time, v), last(time, v), avg(v) FROM m GROUP BY t, h")
    db.coord.engine.flush_all()
    now = 200 * SEC + 10 * SEC + 1
    _refresh(db, "mv1", now)
    _refresh(db, "mv2", now)
    queries = [
        # same grain, grouped
        "SELECT date_bin(INTERVAL '1 minute', time) AS t, h, sum(v) AS s, "
        "count(v) AS c FROM m GROUP BY t, h ORDER BY t, h",
        # coarser origin-congruent re-bucket
        "SELECT date_bin(INTERVAL '2 minutes', time) AS t, sum(v) AS s "
        "FROM m GROUP BY t ORDER BY t",
        # global (no bucket, no tags)
        "SELECT sum(v) AS s, count(v) AS c FROM m",
        # range: sealed span from the view + residual edges from raw
        f"SELECT h, sum(v) AS s FROM m WHERE time >= {30 * SEC} "
        f"AND time < {170 * SEC} GROUP BY h ORDER BY h",
        # residual tag filters, decided per sealed group
        "SELECT h, sum(v) AS s FROM m WHERE h = 'h1' GROUP BY h",
        "SELECT h, sum(v) AS s FROM m WHERE h != 'h0' GROUP BY h ORDER BY h",
        "SELECT sum(v) AS s FROM m WHERE h = 'h1' OR h = 'h2'",
        # the mv2 agg family
        "SELECT h, max(v) AS mx, min(v) AS mn FROM m GROUP BY h ORDER BY h",
        "SELECT h, first(time, v) AS f, last(time, v) AS l FROM m "
        "GROUP BY h ORDER BY h",
        "SELECT h, avg(v) AS a FROM m GROUP BY h ORDER BY h",
    ]
    for q in queries:
        before = matview.counters_snapshot().get("rewrite_hit", 0)
        a, b = _both(db, q)
        assert a == b, q
        assert matview.counters_snapshot().get("rewrite_hit", 0) \
            == before + 1, q


def test_rewrite_misses_when_ineligible(db):
    _seed(db)
    _mk_view(db)
    db.coord.engine.flush_all()
    _refresh(db, now_ns=200 * SEC + 10 * SEC + 1)
    misses = [
        # field predicate: must see raw rows
        "SELECT h, sum(v) AS s FROM m WHERE v > 50 GROUP BY h ORDER BY h",
        # finer bucket than the view's grain
        "SELECT date_bin(INTERVAL '30 seconds', time) AS t, sum(v) AS s "
        "FROM m GROUP BY t ORDER BY t",
        # agg the view does not carry
        "SELECT h, max(v) AS mx FROM m GROUP BY h ORDER BY h",
    ]
    for q in misses:
        before = matview.counters_snapshot().get("rewrite_hit", 0)
        a, b = _both(db, q)
        assert a == b, q
        assert matview.counters_snapshot().get("rewrite_hit", 0) \
            == before, q


def test_unsealed_tail_merges_with_sealed_buckets(db):
    _seed(db, n=120)
    _mk_view(db)
    db.coord.engine.flush_all()
    # seal only the first minute: hwm = align_down(90s - 10s) = 60s
    _refresh(db, now_ns=90 * SEC)
    assert db.matview_engine().status("mv1")["vnodes"]
    q = "SELECT h, sum(v) AS s, count(v) AS c FROM m GROUP BY h ORDER BY h"
    before = matview.counters_snapshot().get("rewrite_hit", 0)
    a, b = _both(db, q)
    assert a == b
    assert matview.counters_snapshot().get("rewrite_hit", 0) == before + 1


def test_late_data_within_watermark_delay(db):
    _seed(db, n=60)
    _mk_view(db, delay="30s")
    db.coord.engine.flush_all()
    # hwm = align_down(80s - 30s) = 0: nothing sealed yet
    _refresh(db, now_ns=80 * SEC)
    # rows 60..89 land "late" but inside the delay window — they are
    # still above the hwm, so the next refresh folds them exactly once
    _seed(db, n=30, start=60)
    db.coord.engine.flush_all()
    _refresh(db, now_ns=150 * SEC)       # seals [0, 120s)
    rep = db.matview_engine().verify("mv1")
    assert rep["equal"], rep
    a, b = _both(db, "SELECT h, sum(v) AS s, count(v) AS c FROM m "
                     "GROUP BY h ORDER BY h")
    assert a == b


def test_refresh_is_delta_only_and_idempotent(db):
    _seed(db, n=60)
    _mk_view(db)
    db.coord.engine.flush_all()
    c0 = matview.counters_snapshot().get("delta_rows", 0)
    _refresh(db, now_ns=80 * SEC)        # seals [0, 60s): 60 rows
    c1 = matview.counters_snapshot().get("delta_rows", 0)
    assert c1 - c0 == 60
    _refresh(db, now_ns=80 * SEC)        # same watermark: no delta
    assert matview.counters_snapshot().get("delta_rows", 0) == c1
    _seed(db, n=60, start=60)
    db.coord.engine.flush_all()
    _refresh(db, now_ns=140 * SEC)       # advances to 120s: 60 more rows
    assert matview.counters_snapshot().get("delta_rows", 0) - c1 == 60
    assert db.matview_engine().verify("mv1")["equal"]


def test_drop_cleans_persisted_state(db, tmp_path):
    _seed(db, n=60)
    _mk_view(db)
    db.coord.engine.flush_all()
    _refresh(db, now_ns=80 * SEC)
    pat = str(tmp_path / "data" / "**" / "matview" / "*")
    assert glob.glob(pat, recursive=True)
    tracker = db.matview_engine().tracker
    assert any(k.startswith("mv1@") for k in tracker.watermarks)
    db.execute_one("DROP MATERIALIZED VIEW mv1")
    assert glob.glob(pat, recursive=True) == []
    assert not any(k.startswith("mv1@") for k in tracker.watermarks)


def test_torn_state_file_degrades_to_raw_scan(db, tmp_path):
    _seed(db, n=60)
    _mk_view(db)
    db.coord.engine.flush_all()
    _refresh(db, now_ns=80 * SEC)
    paths = glob.glob(str(tmp_path / "data" / "**" / "matview" / "*.json"),
                      recursive=True)
    assert paths
    for p in paths:
        with open(p, "w") as f:
            f.write('{"hwm": 123, "rows": [[["h0",')   # torn mid-write
    me = db.matview_engine()
    with me._lock:
        me._states.clear()               # force reload from disk
    q = "SELECT h, sum(v) AS s FROM m GROUP BY h ORDER BY h"
    before = matview.counters_snapshot().get("rewrite_hit", 0)
    a, b = _both(db, q)
    assert a == b                        # correct, just slower
    assert matview.counters_snapshot().get("rewrite_hit", 0) == before


# ----------------------------------------------------- restart durability
def test_restart_restores_definition_and_state(tmp_path, monkeypatch):
    monkeypatch.setenv("CNOSDB_MATVIEW_AUTO", "0")
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    _seed(ex, n=120)
    _mk_view(ex)
    coord.engine.flush_all()
    _refresh(ex, now_ns=140 * SEC)
    hwm_before = ex.matview_engine().status("mv1")["vnodes"]
    assert any(v["hwm"] is not None for v in hwm_before.values())
    coord.close()

    meta2 = MetaStore(str(tmp_path / "meta.json"))
    engine2 = TsKv(str(tmp_path / "data"))
    coord2 = Coordinator(meta2, engine2)
    ex2 = QueryExecutor(meta2, coord2)
    try:
        ex2.restore_matviews()           # what build_server does on boot
        me = ex2.matview_engine()
        assert "mv1" in me.views
        assert me.status("mv1")["vnodes"] == hwm_before
        assert me.verify("mv1")["equal"]
        # delta maintenance resumes from the persisted hwm, not zero
        c0 = matview.counters_snapshot().get("delta_rows", 0)
        _seed(ex2, n=30, start=120)
        coord2.engine.flush_all()
        me.refresh("mv1", now_ns=200 * SEC)
        assert matview.counters_snapshot().get("delta_rows", 0) - c0 == 30
        db = ex2
        a, b = _both(db, "SELECT h, sum(v) AS s FROM m GROUP BY h "
                         "ORDER BY h")
        assert a == b
    finally:
        coord2.close()


# -------------------------------------------------------------- satellites
def test_watermark_tracker_persist_is_atomic(tmp_path):
    path = str(tmp_path / "wm.json")
    t = WatermarkTracker(path)
    t.set("mv1@t.db:1", 12345)
    assert not os.path.exists(path + ".tmp")     # fsync'd then renamed
    assert WatermarkTracker(path).watermarks["mv1@t.db:1"] == 12345
    with open(path) as f:
        json.load(f)                             # valid JSON on disk


def test_agg_memo_counters_exposed(db):
    from cnosdb_tpu.ops import tpu_exec
    snap = tpu_exec.memo_counters_snapshot()
    assert set(snap) == {"hit", "miss", "evict"}
    assert all(isinstance(v, int) and v >= 0 for v in snap.values())
    assert isinstance(tpu_exec.memo_bytes(), int)
    _seed(db, n=60)
    db.coord.engine.flush_all()
    db.execute_one("SELECT h, sum(v) FROM m GROUP BY h")
    after = tpu_exec.memo_counters_snapshot()
    assert sum(after.values()) >= sum(snap.values())
    # monotone: counters never go backwards
    assert all(after[k] >= snap[k] for k in snap)


# ------------------------------------------------------------ chaos (slow)
@pytest.mark.slow
@pytest.mark.cluster
def test_crash_during_persist_then_replay_is_exact(tmp_path):
    """Power loss between writing the tmp state file and the atomic
    rename: the tracker never ran ahead of the state, so after restart a
    refresh replays the delta and the incremental view must equal a
    from-scratch recompute bit-for-bit."""
    from cluster_harness import Cluster
    from cnosdb_tpu.parallel.net import rpc_call

    os.environ["CNOSDB_FAULTS"] = "seed=7"
    try:
        cluster = Cluster(str(tmp_path / "c"), n_nodes=1).start()
    finally:
        del os.environ["CNOSDB_FAULTS"]
    try:
        n = cluster.nodes[0]
        n.sql("CREATE TABLE c (v DOUBLE, TAGS(h))")
        lines = "\n".join(f"c,h=h{i % 3} v={i}.5 {i * SEC}"
                          for i in range(180))
        n.write_lp(lines)
        n.sql("CREATE MATERIALIZED VIEW cmv WATERMARK DELAY '10s' AS "
              "SELECT date_bin(INTERVAL '1 minute', time) AS t, h, "
              "sum(v), count(v) FROM c GROUP BY t, h")
        oracle = {f"h{h}": sum(i + 0.5 for i in range(180) if i % 3 == h)
                  for h in range(3)}

        rpc_call(f"127.0.0.1:{n.rpc_port}", "_faults",
                 {"spec": "matview.persist:crash:once"}, timeout=5.0)
        now = 180 * SEC + 10 * SEC + 1
        with pytest.raises(Exception):   # connection dies with the process
            n.http("GET", f"/debug/matview?name=cmv&refresh=1&now_ns={now}")
        deadline = time.monotonic() + 20
        while n.proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.1)
        assert n.proc.poll() is not None, "crash fault did not fire"
        n.proc = None

        n.start().wait_ready()
        out = json.loads(n.http(
            "GET",
            f"/debug/matview?name=cmv&refresh=1&verify=1&now_ns={now}"))
        assert out["verify"]["equal"], out["verify"]
        assert any(v["hwm"] == 180 * SEC
                   for v in out["status"]["vnodes"].values())
        rows = [l.split(",") for l in n.sql(
            "SELECT h, sum(v) FROM c GROUP BY h ORDER BY h"
        ).strip().splitlines()[1:]]
        assert {r[0]: float(r[1]) for r in rows} == oracle
    finally:
        cluster.stop()
