"""Device-placement CI coverage (round-3 verdict: the fused device path
shipped with zero test coverage — a broken fused.py would have gone green).

CNOSDB_TPU_FORCE_DEVICE_PATH=1 makes tpu_exec take the device placement on
the CPU backend: eligible queries run the fused DeviceBatch/launch_fused
program, ineligible ones the aggregate_column_host XLA wrapper. Every
query here executes twice — host placement then forced device placement —
and the results must agree bit-for-bit, so any defect in fused.py /
device_cache.py diverges from the host oracle and fails.
"""
import numpy as np
import pytest

from cnosdb_tpu.ops import fused
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    d = tmp_path_factory.mktemp("devpath")
    meta = MetaStore(str(d / "meta.json"))
    engine = TsKv(str(d / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE cpu (usage DOUBLE, load DOUBLE, "
                   "cnt BIGINT, flag BOOLEAN, TAGS(host, region))")
    rng = np.random.default_rng(7)
    rows = []
    t0 = 1_600_000_000_000_000_000
    for h in range(6):
        region = "eu" if h % 2 == 0 else "us"
        for k in range(200):
            ts = t0 + k * 30_000_000_000 + h  # 30s cadence, staggered
            u = round(float(rng.normal(50, 10)), 3)
            ld = round(float(rng.normal(1, 0.2)), 3)
            c = int(rng.integers(-100, 100))
            fields = f"usage={u},cnt={c}i,flag={'t' if k % 3 else 'f'}"
            if k % 5 != 0:      # load is nullable: every 5th row missing
                fields += f",load={ld}"
            rows.append(f"cpu,host=h{h},region={region} {fields} {ts}")
    from cnosdb_tpu.protocol.line_protocol import parse_lines

    wb = parse_lines("\n".join(rows))
    from cnosdb_tpu.parallel.meta import DEFAULT_TENANT

    coord.write_points(DEFAULT_TENANT, "public", wb)
    yield ex
    coord.close()


QUERIES = [
    # fused-eligible: numeric aggs, tag group-by, time buckets, filters
    "SELECT count(*) FROM cpu",
    "SELECT count(usage), sum(usage), min(usage), max(usage) FROM cpu",
    "SELECT avg(usage) FROM cpu",
    "SELECT host, sum(usage) FROM cpu GROUP BY host ORDER BY host",
    "SELECT host, region, count(*), max(cnt) FROM cpu "
    "GROUP BY host, region ORDER BY host, region",
    "SELECT time_bucket(time, '5m') AS b, avg(usage) FROM cpu "
    "GROUP BY b ORDER BY b",
    "SELECT host, time_bucket(time, '10m') AS b, min(usage), max(load) "
    "FROM cpu GROUP BY host, b ORDER BY host, b",
    "SELECT host, count(load), sum(load) FROM cpu GROUP BY host "
    "ORDER BY host",                                  # nullable column
    "SELECT count(*) FROM cpu WHERE usage > 50",
    "SELECT host, sum(cnt) FROM cpu WHERE usage > 40 AND load < 1.2 "
    "GROUP BY host ORDER BY host",
    "SELECT max(usage) FROM cpu WHERE cnt >= 0",
    "SELECT first(usage), last(usage) FROM cpu",      # rank selection
    "SELECT host, first(load), last(cnt) FROM cpu GROUP BY host "
    "ORDER BY host",
    "SELECT time_bucket(time, '1h') AS b, first(usage), last(usage) "
    "FROM cpu GROUP BY b ORDER BY b",
    "SELECT count(flag), sum(cnt) FROM cpu WHERE flag = true",
    # device-INELIGIBLE shapes (strings/tags in filter, IS NULL, time agg):
    # forced mode must still answer correctly via aggregate_column_host
    "SELECT count(*) FROM cpu WHERE host = 'h1'",
    "SELECT host, count(*) FROM cpu WHERE load IS NULL GROUP BY host "
    "ORDER BY host",
    "SELECT min(time), max(time) FROM cpu",
]


def _run(ex, sql):
    rs = ex.execute_one(sql, Session(database="public"))
    return rs.names, [tuple(col.tolist()) for col in rs.columns]


@pytest.mark.parametrize("sql", QUERIES)
def test_forced_device_path_matches_host(db, sql, monkeypatch):
    monkeypatch.setenv("CNOSDB_TPU_FORCE_DEVICE_PATH", "0")
    host = _run(db, sql)
    monkeypatch.setenv("CNOSDB_TPU_FORCE_DEVICE_PATH", "1")
    dev = _run(db, sql)
    assert host[0] == dev[0]
    for hc, dc in zip(host[1], dev[1]):
        for a, b in zip(hc, dc):
            if isinstance(a, float) and isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-12, nan_ok=True), sql
            else:
                assert a == b, sql


def test_fused_kernel_actually_launches(db, monkeypatch):
    """The forced run must go through launch_fused — guards against the
    override silently routing back to the host path."""
    monkeypatch.setenv("CNOSDB_TPU_FORCE_DEVICE_PATH", "1")
    before = fused.launch_count
    _run(db, "SELECT host, avg(usage) FROM cpu GROUP BY host ORDER BY host")
    assert fused.launch_count > before


def test_sqllogic_aggregates_forced_device(db, monkeypatch, tmp_path):
    """The aggregate slt matrix re-runs under the forced device placement
    (fresh database per file, same golden expectations)."""
    import os

    from tests.test_sqllogic import CASES_DIR, _parse_slt
    from cnosdb_tpu.server.http import format_csv

    monkeypatch.setenv("CNOSDB_TPU_FORCE_DEVICE_PATH", "1")
    agg_cases = sorted(
        f for f in os.listdir(CASES_DIR)
        if f.startswith(("gen_agg", "gen_group", "gen_time_bucket",
                         "dql_agg", "dql_time_bucket", "dql_filter")))
    assert len(agg_cases) >= 8
    for case in agg_cases:
        d = tmp_path / case
        meta = MetaStore(str(d / "meta.json"))
        engine = TsKv(str(d / "data"))
        coord = Coordinator(meta, engine)
        ex = QueryExecutor(meta, coord)
        session = Session()
        try:
            for kind, sql, expected, lineno in _parse_slt(
                    os.path.join(CASES_DIR, case)):
                if kind == "ok":
                    ex.execute_one(sql, session)
                elif kind == "error":
                    with pytest.raises(Exception):
                        ex.execute_one(sql, session)
                else:
                    rs = ex.execute_one(sql, session)
                    got = format_csv(rs)[:-1].split("\n")
                    expected = [ln.replace("\\N", "") for ln in expected]
                    assert got == expected, f"{case}:{lineno} {sql!r}"
        finally:
            coord.close()
