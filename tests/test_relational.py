"""JOIN / UNION / subqueries / window functions (reference gets these from
DataFusion — query_server/query/src/sql/planner.rs; here they run host-side
over columnar scan results, sql/relational.py)."""
import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE cpu (v DOUBLE, TAGS(host, region))")
    ex.execute_one(
        "INSERT INTO cpu (time, host, region, v) VALUES "
        "(1, 'a', 'eu', 1.0), (2, 'b', 'eu', 2.0), "
        "(3, 'c', 'us', 3.0), (4, 'a', 'us', 4.0)")
    ex.execute_one("CREATE TABLE hostinfo (owner STRING, TAGS(host))")
    ex.execute_one("INSERT INTO hostinfo (time, host, owner) VALUES "
                   "(1, 'a', 'alice'), (1, 'b', 'bob')")
    yield ex
    coord.close()


def rows(rs, *cols):
    return list(zip(*[rs.columns[c].tolist() for c in cols]))


def test_inner_join(db):
    rs = db.execute_one("SELECT c.host, c.v, h.owner FROM cpu c "
                        "JOIN hostinfo h ON c.host = h.host ORDER BY c.v")
    assert rs.columns[0].tolist() == ["a", "b", "a"]
    assert rs.columns[2].tolist() == ["alice", "bob", "alice"]


def test_left_join_null_fill_and_null_ordering(db):
    rs = db.execute_one(
        "SELECT c.host, h.owner FROM cpu c LEFT JOIN hostinfo h "
        "ON c.host = h.host ORDER BY c.host, h.owner")
    got = rows(rs, 0, 1)
    assert got == [("a", "alice"), ("a", "alice"), ("b", "bob"), ("c", None)]


def test_full_and_cross_join(db):
    db.execute_one("INSERT INTO hostinfo (time, host, owner) VALUES "
                   "(1, 'z', 'zed')")
    rs = db.execute_one("SELECT c.host, h.owner FROM cpu c "
                        "FULL JOIN hostinfo h ON c.host = h.host")
    pairs = set(rows(rs, 0, 1))
    assert (None, "zed") in pairs and ("c", None) in pairs
    rs = db.execute_one("SELECT count(*) FROM cpu c CROSS JOIN hostinfo h")
    assert rs.columns[0][0] == 12


def test_group_by_over_join(db):
    rs = db.execute_one(
        "SELECT h.owner, count(*), sum(c.v) FROM cpu c "
        "JOIN hostinfo h ON c.host = h.host GROUP BY h.owner ORDER BY h.owner")
    assert rows(rs, 0, 1, 2) == [("alice", 2, 5.0), ("bob", 1, 2.0)]


def test_group_by_order_by_aggregate(db):
    rs = db.execute_one(
        "SELECT c.region, count(*) FROM cpu c JOIN hostinfo h "
        "ON c.host = h.host GROUP BY c.region ORDER BY count(*) DESC")
    assert rows(rs, 0, 1) == [("eu", 2), ("us", 1)]


def test_having_over_join(db):
    rs = db.execute_one(
        "SELECT h.owner, sum(c.v) s FROM cpu c JOIN hostinfo h "
        "ON c.host = h.host GROUP BY h.owner HAVING sum(c.v) > 3")
    assert rows(rs, 0, 1) == [("alice", 5.0)]


def test_union_and_union_all(db):
    rs = db.execute_one("SELECT host FROM cpu WHERE region = 'eu' "
                        "UNION SELECT host FROM cpu WHERE host = 'a' "
                        "ORDER BY host")
    assert rs.columns[0].tolist() == ["a", "b"]
    rs = db.execute_one("SELECT host FROM cpu WHERE region = 'eu' "
                        "UNION ALL SELECT host FROM cpu WHERE host = 'a' "
                        "ORDER BY host")
    assert rs.columns[0].tolist() == ["a", "a", "a", "b"]


def test_scalar_subquery(db):
    rs = db.execute_one("SELECT host, v FROM cpu "
                        "WHERE v > (SELECT avg(v) FROM cpu) ORDER BY v")
    assert rs.columns[0].tolist() == ["c", "a"]


def test_in_subquery(db):
    rs = db.execute_one("SELECT count(*) FROM cpu "
                        "WHERE host IN (SELECT host FROM hostinfo)")
    assert rs.columns[0][0] == 3
    rs = db.execute_one("SELECT count(*) FROM cpu "
                        "WHERE host NOT IN (SELECT host FROM hostinfo)")
    assert rs.columns[0][0] == 1


def test_from_subquery(db):
    rs = db.execute_one(
        "SELECT t.host FROM (SELECT host, v FROM cpu WHERE v >= 2) t "
        "WHERE t.v < 4 ORDER BY t.host")
    assert rs.columns[0].tolist() == ["b", "c"]


def test_row_number_partitioned(db):
    rs = db.execute_one(
        "SELECT host, v, row_number() OVER "
        "(PARTITION BY region ORDER BY v DESC) rn FROM cpu ORDER BY host, v")
    got = set(rows(rs, 0, 1, 2))
    assert {("a", 1.0, 2), ("b", 2.0, 1), ("c", 3.0, 2),
            ("a", 4.0, 1)} <= got


def test_cumulative_sum_window(db):
    rs = db.execute_one(
        "SELECT v, sum(v) OVER (ORDER BY time) s FROM cpu ORDER BY time")
    assert rs.columns[1].tolist() == [1.0, 3.0, 6.0, 10.0]


def test_whole_partition_aggregate_window(db):
    rs = db.execute_one(
        "SELECT region, v, avg(v) OVER (PARTITION BY region) a "
        "FROM cpu ORDER BY time")
    assert rs.columns[2].tolist() == [1.5, 1.5, 3.5, 3.5]


def test_lag_lead(db):
    rs = db.execute_one(
        "SELECT v, lag(v) OVER (ORDER BY time) p, "
        "lead(v) OVER (ORDER BY time) n FROM cpu ORDER BY time")
    p, n = rs.columns[1].tolist(), rs.columns[2].tolist()
    assert np.isnan(p[0]) and p[1:] == [1.0, 2.0, 3.0]
    assert n[:3] == [2.0, 3.0, 4.0] and np.isnan(n[3])


def test_rank_dense_rank_ties(db):
    db.execute_one("INSERT INTO cpu (time, host, region, v) VALUES "
                   "(5, 'd', 'us', 3.0)")
    rs = db.execute_one(
        "SELECT host, rank() OVER (ORDER BY v) r, "
        "dense_rank() OVER (ORDER BY v) d FROM cpu ORDER BY v, host")
    assert rows(rs, 0, 1, 2) == [("a", 1, 1), ("b", 2, 2), ("c", 3, 3),
                                 ("d", 3, 3), ("a", 5, 4)]


def test_first_value(db):
    rs = db.execute_one(
        "SELECT host, first_value(v) OVER (PARTITION BY region "
        "ORDER BY time) f FROM cpu WHERE region = 'eu' ORDER BY time")
    assert rs.columns[1].tolist() == [1.0, 1.0]


def test_window_over_aggregate_subquery(db):
    """Windows over grouped results compose via FROM subquery."""
    rs = db.execute_one(
        "SELECT t.region, rank() OVER (ORDER BY t.s DESC) r FROM "
        "(SELECT region, sum(v) s FROM cpu GROUP BY region) t "
        "ORDER BY r")
    assert rows(rs, 0, 1) == [("us", 1), ("eu", 2)]
