"""JOIN / UNION / subqueries / window functions (reference gets these from
DataFusion — query_server/query/src/sql/planner.rs; here they run host-side
over columnar scan results, sql/relational.py)."""
import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE cpu (v DOUBLE, TAGS(host, region))")
    ex.execute_one(
        "INSERT INTO cpu (time, host, region, v) VALUES "
        "(1, 'a', 'eu', 1.0), (2, 'b', 'eu', 2.0), "
        "(3, 'c', 'us', 3.0), (4, 'a', 'us', 4.0)")
    ex.execute_one("CREATE TABLE hostinfo (owner STRING, TAGS(host))")
    ex.execute_one("INSERT INTO hostinfo (time, host, owner) VALUES "
                   "(1, 'a', 'alice'), (1, 'b', 'bob')")
    yield ex
    coord.close()


def rows(rs, *cols):
    return list(zip(*[rs.columns[c].tolist() for c in cols]))


def test_inner_join(db):
    rs = db.execute_one("SELECT c.host, c.v, h.owner FROM cpu c "
                        "JOIN hostinfo h ON c.host = h.host ORDER BY c.v")
    assert rs.columns[0].tolist() == ["a", "b", "a"]
    assert rs.columns[2].tolist() == ["alice", "bob", "alice"]


def test_left_join_null_fill_and_null_ordering(db):
    rs = db.execute_one(
        "SELECT c.host, h.owner FROM cpu c LEFT JOIN hostinfo h "
        "ON c.host = h.host ORDER BY c.host, h.owner")
    got = rows(rs, 0, 1)
    assert got == [("a", "alice"), ("a", "alice"), ("b", "bob"), ("c", None)]


def test_full_and_cross_join(db):
    db.execute_one("INSERT INTO hostinfo (time, host, owner) VALUES "
                   "(1, 'z', 'zed')")
    rs = db.execute_one("SELECT c.host, h.owner FROM cpu c "
                        "FULL JOIN hostinfo h ON c.host = h.host")
    pairs = set(rows(rs, 0, 1))
    assert (None, "zed") in pairs and ("c", None) in pairs
    rs = db.execute_one("SELECT count(*) FROM cpu c CROSS JOIN hostinfo h")
    assert rs.columns[0][0] == 12


def test_group_by_over_join(db):
    rs = db.execute_one(
        "SELECT h.owner, count(*), sum(c.v) FROM cpu c "
        "JOIN hostinfo h ON c.host = h.host GROUP BY h.owner ORDER BY h.owner")
    assert rows(rs, 0, 1, 2) == [("alice", 2, 5.0), ("bob", 1, 2.0)]


def test_group_by_order_by_aggregate(db):
    rs = db.execute_one(
        "SELECT c.region, count(*) FROM cpu c JOIN hostinfo h "
        "ON c.host = h.host GROUP BY c.region ORDER BY count(*) DESC")
    assert rows(rs, 0, 1) == [("eu", 2), ("us", 1)]


def test_having_over_join(db):
    rs = db.execute_one(
        "SELECT h.owner, sum(c.v) s FROM cpu c JOIN hostinfo h "
        "ON c.host = h.host GROUP BY h.owner HAVING sum(c.v) > 3")
    assert rows(rs, 0, 1) == [("alice", 5.0)]


def test_union_and_union_all(db):
    rs = db.execute_one("SELECT host FROM cpu WHERE region = 'eu' "
                        "UNION SELECT host FROM cpu WHERE host = 'a' "
                        "ORDER BY host")
    assert rs.columns[0].tolist() == ["a", "b"]
    rs = db.execute_one("SELECT host FROM cpu WHERE region = 'eu' "
                        "UNION ALL SELECT host FROM cpu WHERE host = 'a' "
                        "ORDER BY host")
    assert rs.columns[0].tolist() == ["a", "a", "a", "b"]


def test_scalar_subquery(db):
    rs = db.execute_one("SELECT host, v FROM cpu "
                        "WHERE v > (SELECT avg(v) FROM cpu) ORDER BY v")
    assert rs.columns[0].tolist() == ["c", "a"]


def test_in_subquery(db):
    rs = db.execute_one("SELECT count(*) FROM cpu "
                        "WHERE host IN (SELECT host FROM hostinfo)")
    assert rs.columns[0][0] == 3
    rs = db.execute_one("SELECT count(*) FROM cpu "
                        "WHERE host NOT IN (SELECT host FROM hostinfo)")
    assert rs.columns[0][0] == 1


def test_from_subquery(db):
    rs = db.execute_one(
        "SELECT t.host FROM (SELECT host, v FROM cpu WHERE v >= 2) t "
        "WHERE t.v < 4 ORDER BY t.host")
    assert rs.columns[0].tolist() == ["b", "c"]


def test_row_number_partitioned(db):
    rs = db.execute_one(
        "SELECT host, v, row_number() OVER "
        "(PARTITION BY region ORDER BY v DESC) rn FROM cpu ORDER BY host, v")
    got = set(rows(rs, 0, 1, 2))
    assert {("a", 1.0, 2), ("b", 2.0, 1), ("c", 3.0, 2),
            ("a", 4.0, 1)} <= got


def test_cumulative_sum_window(db):
    rs = db.execute_one(
        "SELECT v, sum(v) OVER (ORDER BY time) s FROM cpu ORDER BY time")
    assert rs.columns[1].tolist() == [1.0, 3.0, 6.0, 10.0]


def test_whole_partition_aggregate_window(db):
    rs = db.execute_one(
        "SELECT region, v, avg(v) OVER (PARTITION BY region) a "
        "FROM cpu ORDER BY time")
    assert rs.columns[2].tolist() == [1.5, 1.5, 3.5, 3.5]


def test_lag_lead(db):
    rs = db.execute_one(
        "SELECT v, lag(v) OVER (ORDER BY time) p, "
        "lead(v) OVER (ORDER BY time) n FROM cpu ORDER BY time")
    p, n = rs.columns[1].tolist(), rs.columns[2].tolist()
    # out-of-frame slots are NULL (None), not NaN
    assert p[0] is None and p[1:] == [1.0, 2.0, 3.0]
    assert n[:3] == [2.0, 3.0, 4.0] and n[3] is None


def test_rank_dense_rank_ties(db):
    db.execute_one("INSERT INTO cpu (time, host, region, v) VALUES "
                   "(5, 'd', 'us', 3.0)")
    rs = db.execute_one(
        "SELECT host, rank() OVER (ORDER BY v) r, "
        "dense_rank() OVER (ORDER BY v) d FROM cpu ORDER BY v, host")
    assert rows(rs, 0, 1, 2) == [("a", 1, 1), ("b", 2, 2), ("c", 3, 3),
                                 ("d", 3, 3), ("a", 5, 4)]


def test_first_value(db):
    rs = db.execute_one(
        "SELECT host, first_value(v) OVER (PARTITION BY region "
        "ORDER BY time) f FROM cpu WHERE region = 'eu' ORDER BY time")
    assert rs.columns[1].tolist() == [1.0, 1.0]


def test_window_over_aggregate_subquery(db):
    """Windows over grouped results compose via FROM subquery."""
    rs = db.execute_one(
        "SELECT t.region, rank() OVER (ORDER BY t.s DESC) r FROM "
        "(SELECT region, sum(v) s FROM cpu GROUP BY region) t "
        "ORDER BY r")
    assert rows(rs, 0, 1) == [("us", 1), ("eu", 2)]


def test_join_null_keys_never_match(db):
    """Vectorized equi-join semantics: NULL join keys match nothing
    (SQL), including NULL-vs-NULL; NaN float keys match nothing."""
    db.execute_one("CREATE TABLE lk (k BIGINT, x DOUBLE, TAGS(t))")
    db.execute_one("CREATE TABLE rk (k2 BIGINT, y DOUBLE, TAGS(t))")
    db.execute_one("INSERT INTO lk (time, t, k, x) VALUES "
                   "(1,'l',1,10.0),(2,'l',NULL,20.0),(3,'l',3,30.0)")
    db.execute_one("INSERT INTO rk (time, t, k2, y) VALUES "
                   "(1,'r',1,1.5),(2,'r',NULL,2.5),(3,'r',9,3.5)")
    rs = db.execute_one(
        "SELECT l.x, r.y FROM lk l JOIN rk r ON l.k = r.k2 ORDER BY l.x")
    assert rows(rs, 0, 1) == [(10.0, 1.5)]
    # left join: NULL-key left rows survive with NULL right columns
    # (float NULL renders as NaN in the columnar result)
    rs = db.execute_one(
        "SELECT l.x, r.y FROM lk l LEFT JOIN rk r ON l.k = r.k2 "
        "ORDER BY l.x")
    got = rows(rs, 0, 1)
    assert [x for x, _ in got] == [10.0, 20.0, 30.0]
    assert got[0][1] == 1.5
    assert all(y != y or y is None for _, y in got[1:])  # NaN/None = NULL


def test_join_string_keys_and_duplicates(db):
    db.execute_one("CREATE TABLE ls (k STRING, x DOUBLE, TAGS(t))")
    db.execute_one("CREATE TABLE rs_ (k2 STRING, y DOUBLE, TAGS(t))")
    db.execute_one("INSERT INTO ls (time, t, k, x) VALUES "
                   "(1,'l','a',1.0),(2,'l','b',2.0),(3,'l','a',3.0)")
    db.execute_one("INSERT INTO rs_ (time, t, k2, y) VALUES "
                   "(1,'r','a',10.0),(2,'r','a',20.0),(3,'r','c',30.0)")
    rs = db.execute_one(
        "SELECT l.x, r.y FROM ls l JOIN rs_ r ON l.k = r.k2 "
        "ORDER BY l.x, r.y")
    # 'a' x 'a' duplicates expand: (1,10),(1,20),(3,10),(3,20)
    assert rows(rs, 0, 1) == [(1.0, 10.0), (1.0, 20.0),
                              (3.0, 10.0), (3.0, 20.0)]


def test_join_int_float_key_equality(db):
    db.execute_one("CREATE TABLE li (k BIGINT, x DOUBLE, TAGS(t))")
    db.execute_one("CREATE TABLE rf (k2 DOUBLE, y DOUBLE, TAGS(t))")
    db.execute_one("INSERT INTO li (time, t, k, x) VALUES (1,'l',5,1.0)")
    db.execute_one("INSERT INTO rf (time, t, k2, y) VALUES (1,'r',5.0,9.0)")
    rs = db.execute_one(
        "SELECT l.x, r.y FROM li l JOIN rf r ON l.k = r.k2")
    assert rows(rs, 0, 1) == [(1.0, 9.0)]


def test_join_bigint_keys_above_2_53_stay_exact(db):
    big = 2**53
    db.execute_one("CREATE TABLE lb (k BIGINT, x DOUBLE, TAGS(t))")
    db.execute_one("CREATE TABLE rb (k2 BIGINT, y DOUBLE, TAGS(t))")
    db.execute_one(f"INSERT INTO lb (time, t, k, x) VALUES (1,'l',{big},1.0)")
    db.execute_one(
        f"INSERT INTO rb (time, t, k2, y) VALUES (1,'r',{big + 1},9.0)")
    rs = db.execute_one("SELECT l.x FROM lb l JOIN rb r ON l.k = r.k2")
    assert rs.n_rows == 0  # 2^53 and 2^53+1 must NOT alias through float64


def test_join_qualified_by_table_name_without_alias(db):
    """FROM o JOIN c ON o.cust = c.cust — unaliased tables are
    addressable by their own names (standard SQL)."""
    rs = db.execute_one(
        "SELECT hostinfo.owner, sum(cpu.v) AS s FROM cpu "
        "JOIN hostinfo ON cpu.host = hostinfo.host "
        "GROUP BY hostinfo.owner ORDER BY hostinfo.owner")
    assert rows(rs, 0, 1) == [("alice", 5.0), ("bob", 2.0)]


def test_duplicate_unaliased_table_rejected(db):
    from cnosdb_tpu.errors import CnosError
    with pytest.raises(CnosError, match="more than once"):
        db.execute_one("SELECT cpu.v FROM cpu JOIN cpu ON cpu.host = cpu.host")
    # aliasing both sides is fine (self-join)
    rs = db.execute_one(
        "SELECT a.host FROM cpu a JOIN cpu b ON a.host = b.host "
        "WHERE a.time < b.time")
    assert rs.columns[0].tolist() == ["a"]


# ---------------------------------------------------------------------------
# cost-based inner-join ordering (sql/join_order.py)
# ---------------------------------------------------------------------------
@pytest.fixture
def db3(tmp_path):
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.storage.engine import TsKv
    from cnosdb_tpu.sql.executor import QueryExecutor
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE f (amt BIGINT, TAGS(cust, prod))")
    ex.execute_one(
        "INSERT INTO f (time, cust, prod, amt) VALUES " + ", ".join(
            f"({i+1}, 'c{i % 7}', 'p{i % 5}', {i * 3})" for i in range(40)))
    ex.execute_one("CREATE TABLE dc (cname STRING, TAGS(cust))")
    ex.execute_one("INSERT INTO dc (time, cust, cname) VALUES " + ", ".join(
        f"({i+1}, 'c{i}', 'cust-{i}')" for i in range(7)))
    ex.execute_one("CREATE TABLE dp (pname STRING, TAGS(prod))")
    ex.execute_one("INSERT INTO dp (time, prod, pname) VALUES " + ", ".join(
        f"({i+1}, 'p{i}', 'prod-{i}')" for i in range(5)))
    yield ex
    coord.close()


def _written_order(ex, sql):
    """Execute with the optimizer disabled (written-order reference)."""
    orig = ex._join_optimized
    ex._join_optimized = lambda *a, **k: None
    try:
        return ex.execute_one(sql)
    finally:
        ex._join_optimized = orig


def test_join_reorder_identical_output(db3):
    """The reordered plan must reproduce written-order rows and columns
    bit for bit — no ORDER BY, so this pins the lexsort restoration."""
    for sql in [
        "SELECT f.cust, f.prod, f.amt, dc.cname, dp.pname FROM f "
        "JOIN dc ON f.cust = dc.cust JOIN dp ON f.prod = dp.prod",
        "SELECT dc.cname, count(f.amt), sum(f.amt) FROM dc "
        "JOIN f ON f.cust = dc.cust JOIN dp ON f.prod = dp.prod "
        "GROUP BY dc.cname ORDER BY dc.cname",
        "SELECT * FROM f JOIN dc ON f.cust = dc.cust "
        "JOIN dp ON f.prod = dp.prod",
        "SELECT f.amt, dp.pname FROM f JOIN dc ON f.cust = dc.cust "
        "JOIN dp ON f.prod = dp.prod AND dc.cname = 'cust-1'",
    ]:
        a = db3.execute_one(sql)
        b = _written_order(db3, sql)
        assert a.names == b.names, sql
        for ca, cb in zip(a.columns, b.columns):
            assert [str(x) for x in ca.tolist()] == \
                [str(x) for x in cb.tolist()], sql


def test_join_reorder_triggers(db3):
    """The optimizer actually runs on a 3-leaf inner chain."""
    from cnosdb_tpu.sql import join_order
    import cnosdb_tpu.sql.join_order as jo
    calls = []
    orig = jo.order_and_join
    jo.order_and_join = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    try:
        db3.execute_one(
            "SELECT f.amt FROM f JOIN dc ON f.cust = dc.cust "
            "JOIN dp ON f.prod = dp.prod")
    finally:
        jo.order_and_join = orig
    assert calls


def test_join_reorder_outer_falls_back(db3):
    """LEFT JOIN in the tree pins written order (optimizer must decline)."""
    import cnosdb_tpu.sql.join_order as jo
    calls = []
    orig = jo.order_and_join
    jo.order_and_join = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    sql = ("SELECT f.cust, dc.cname, dp.pname FROM f "
           "LEFT JOIN dc ON f.cust = dc.cust "
           "JOIN dp ON f.prod = dp.prod")
    try:
        a = db3.execute_one(sql)
    finally:
        jo.order_and_join = orig
    assert not calls, "optimizer must decline on outer joins"
    b = _written_order(db3, sql)
    for ca, cb in zip(a.columns, b.columns):
        assert [str(x) for x in ca.tolist()] == [str(x) for x in cb.tolist()]


# ---------------------------------------------------------------------------
# CASE integration across traversals (review findings, round 3)
# ---------------------------------------------------------------------------
def test_case_null_aware_in_where(db):
    """CASE WHEN i IS NULL THEN true END as a FILTER must keep NULL rows
    (post-hoc validity masking must skip CASE-referenced columns)."""
    db.execute_one("CREATE TABLE cw (i BIGINT, pad BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO cw (time, h, i, pad) VALUES "
                   "(1,'a',5,0),(2,'a',NULL,0),(3,'b',NULL,0)")
    rs = db.execute_one(
        "SELECT time FROM cw WHERE CASE WHEN i IS NULL THEN true "
        "ELSE false END ORDER BY time")
    assert rs.columns[0].tolist() == [2, 3]


def test_case_agg_inside(db):
    """An aggregate whose only appearance is inside CASE still makes the
    query an aggregate query."""
    db.execute_one("CREATE TABLE ca (i BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO ca (time, h, i) VALUES "
                   "(1,'a',5),(2,'a',3)")
    rs = db.execute_one(
        "SELECT CASE WHEN sum(i) > 5 THEN 'big' ELSE 'small' END AS s "
        "FROM ca")
    assert rs.columns[0].tolist() == ["big"]


def test_case_simple_null_operand_never_matches(db):
    """CASE i WHEN 0 THEN ... with NULL i must take ELSE (garbage in the
    typed NULL slot must not match)."""
    db.execute_one("CREATE TABLE cn (i BIGINT, pad BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO cn (time, h, i, pad) VALUES "
                   "(1,'a',0,0),(2,'a',NULL,0)")
    rs = db.execute_one(
        "SELECT time, CASE i WHEN 0 THEN 'zero' ELSE 'other' END AS s "
        "FROM cn ORDER BY time")
    assert rs.columns[1].tolist() == ["zero", "other"]


def test_case_guarded_arm_error(db):
    """An arm that errors on rows its WHEN excludes must not abort."""
    db.execute_one("CREATE TABLE cg (f DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO cg (time, h, f) VALUES "
                   "(1,'a',2.5),(2,'a',1.0/0)")
    rs = db.execute_one(
        "SELECT time, CASE WHEN f < 1000000 THEN CAST(f AS BIGINT) "
        "ELSE -1 END AS v FROM cg ORDER BY time")
    assert rs.columns[1].tolist() == [2, -1]


def test_int_sum_overflow_exact(db):
    """Integer SUM past int64 must be exact (python-int accumulation),
    not a silent wrap."""
    db.execute_one("CREATE TABLE ov (i BIGINT, TAGS(h))")
    big = 2**62
    db.execute_one(f"INSERT INTO ov (time, h, i) VALUES "
                   f"(1,'a',{big}),(2,'a',{big}),(3,'a',{big})")
    # relational path (join) to hit host_aggregate
    db.execute_one("CREATE TABLE ovd (pad BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO ovd (time, h, pad) VALUES (1,'a',0)")
    rs = db.execute_one(
        "SELECT sum(ov.i) FROM ov JOIN ovd ON ov.h = ovd.h")
    assert rs.columns[0].tolist() == [3 * big]


def test_case_in_analyzer_rewrites(db):
    """exact_count inside a CASE arm still rewrites to count."""
    db.execute_one("CREATE TABLE cr (i BIGINT, TAGS(h))")
    db.execute_one("INSERT INTO cr (time, h, i) VALUES (1,'a',5),(2,'a',7)")
    rs = db.execute_one(
        "SELECT CASE WHEN exact_count(i) = 2 THEN 'two' END AS s FROM cr")
    assert rs.columns[0].tolist() == ["two"]


# ---------------------------------------------------------------------------
# correlated EXISTS (decorrelated to semi/anti-join)
# ---------------------------------------------------------------------------
def test_correlated_exists_semi_join(db):
    """EXISTS with an equality correlation behaves as a semi-join."""
    rs = db.execute_one(
        "SELECT c.host, c.v FROM cpu c WHERE EXISTS "
        "(SELECT 1 FROM hostinfo h WHERE h.host = c.host) ORDER BY c.v")
    assert rows(rs, 0, 1) == [("a", 1.0), ("b", 2.0), ("a", 4.0)]


def test_correlated_not_exists_anti_join(db):
    """NOT EXISTS keeps outer rows with no match (anti-join)."""
    rs = db.execute_one(
        "SELECT c.host FROM cpu c WHERE NOT EXISTS "
        "(SELECT 1 FROM hostinfo h WHERE h.host = c.host) ORDER BY c.host")
    assert rs.columns[0].tolist() == ["c"]


def test_correlated_exists_with_local_predicate(db):
    """Local (non-correlated) conjuncts stay inside the subquery."""
    rs = db.execute_one(
        "SELECT c.host, c.v FROM cpu c WHERE EXISTS "
        "(SELECT 1 FROM hostinfo h WHERE h.host = c.host "
        "AND h.owner = 'alice') ORDER BY c.v")
    assert rows(rs, 0, 1) == [("a", 1.0), ("a", 4.0)]


def test_correlated_not_exists_null_outer_key(db):
    """Anti-join semantics: an outer row whose key is NULL has no match
    and must be KEPT by NOT EXISTS (NOT IN would drop it)."""
    db.execute_one("CREATE TABLE ev (k BIGINT, pad BIGINT, TAGS(t))")
    db.execute_one("INSERT INTO ev (time, t, k, pad) VALUES "
                   "(1,'x',1,0),(2,'x',NULL,0),(3,'x',9,0)")
    db.execute_one("CREATE TABLE kv (k2 BIGINT, TAGS(t))")
    db.execute_one("INSERT INTO kv (time, t, k2) VALUES (1,'y',1)")
    rs = db.execute_one(
        "SELECT e.time FROM ev e WHERE NOT EXISTS "
        "(SELECT 1 FROM kv x WHERE x.k2 = e.k) ORDER BY e.time")
    assert rs.columns[0].tolist() == [2, 3]
    rs = db.execute_one(
        "SELECT e.time FROM ev e WHERE EXISTS "
        "(SELECT 1 FROM kv x WHERE x.k2 = e.k) ORDER BY e.time")
    assert rs.columns[0].tolist() == [1]


def test_exists_aggregate_subquery_always_true(db):
    """EXISTS over an ungrouped aggregate subquery is unconditionally TRUE
    (the subquery yields exactly one row — count()=0 included), so every
    outer row survives; NOT EXISTS keeps none. Round-3 advisor finding:
    semi-join decorrelation must decline this shape."""
    rs = db.execute_one(
        "SELECT c.host FROM cpu c WHERE EXISTS "
        "(SELECT count(*) FROM hostinfo h WHERE h.host = c.host) "
        "ORDER BY c.v")
    assert rs.columns[0].tolist() == ["a", "b", "c", "a"]
    rs = db.execute_one(
        "SELECT c.host FROM cpu c WHERE NOT EXISTS "
        "(SELECT count(*) FROM hostinfo h WHERE h.host = c.host)")
    assert rs.n_rows == 0


def test_exists_aggregate_subquery_invalid_names_raise(db):
    """The aggregate short-circuit must not mask name-resolution errors:
    a bad table or column in the EXISTS body still raises."""
    from cnosdb_tpu.errors import CnosError
    for sql in (
        "SELECT c.host FROM cpu c WHERE EXISTS "
        "(SELECT count(*) FROM nosuch n WHERE n.x = c.host)",
        "SELECT c.host FROM cpu c WHERE EXISTS "
        "(SELECT count(h.bogus) FROM hostinfo h WHERE h.host = c.host)",
    ):
        with pytest.raises(CnosError):
            db.execute_one(sql)


def test_exists_exact_count_subquery_always_true(db):
    """exact_count desugars to count BEFORE the decorrelation guards run,
    so the aggregate short-circuit fires for it too."""
    rs = db.execute_one(
        "SELECT c.host FROM cpu c WHERE EXISTS "
        "(SELECT exact_count(*) FROM hostinfo h WHERE h.host = c.host) "
        "ORDER BY c.v")
    assert rs.columns[0].tolist() == ["a", "b", "c", "a"]


def test_exists_offset_not_decorrelated(db):
    """OFFSET skips the aggregate's single row (EXISTS → false) and makes
    semi-join decorrelation unsound; uncorrelated bodies evaluate exactly,
    correlated ones must decline (error) rather than answer wrongly."""
    rs = db.execute_one("SELECT host FROM cpu WHERE EXISTS "
                        "(SELECT count(*) FROM hostinfo OFFSET 1)")
    assert rs.n_rows == 0
    from cnosdb_tpu.errors import CnosError
    for sql in (
        "SELECT c.host FROM cpu c WHERE EXISTS (SELECT count(*) "
        "FROM hostinfo h WHERE h.host = c.host OFFSET 1)",
        "SELECT c.host FROM cpu c WHERE EXISTS (SELECT 1 "
        "FROM hostinfo h WHERE h.host = c.host OFFSET 1)",
    ):
        with pytest.raises(CnosError):
            db.execute_one(sql)


def test_coalesce_in_union_order_by(db):
    """Union-level ORDER BY is desugared by the analyzer (it is evaluated
    directly by _union, never re-entering per-branch analysis)."""
    rs = db.execute_one(
        "SELECT host FROM cpu UNION SELECT host FROM hostinfo "
        "ORDER BY coalesce(host, 'zz')")
    assert rs.columns[0].tolist() == ["a", "b", "c"]
    rs = db.execute_one(
        "SELECT * FROM (SELECT host FROM cpu UNION "
        "SELECT host FROM hostinfo ORDER BY coalesce(host, 'zz')) d")
    assert rs.columns[0].tolist() == ["a", "b", "c"]


def test_coalesce_in_join_on(db):
    """NULL-function desugaring must reach JOIN ON expressions
    (round-3 advisor finding: coalesce in ON failed with PlanError)."""
    rs = db.execute_one(
        "SELECT c.host, h.owner FROM cpu c JOIN hostinfo h "
        "ON coalesce(c.host, 'zz') = h.host ORDER BY c.v")
    assert rows(rs, 0, 1) == [("a", "alice"), ("b", "bob"), ("a", "alice")]


def test_in_list_isin_fast_path_exact(db):
    """Long integer IN lists use np.isin without losing exactness."""
    big = 2**53 + 1
    db.execute_one("CREATE TABLE bigt (v BIGINT, TAGS(t))")
    db.execute_one(f"INSERT INTO bigt (time, t, v) VALUES "
                   f"(1,'x',{big}),(2,'x',{big + 1}),(3,'x',5)")
    in_list = ", ".join(str(big + k) for k in range(0, 20, 2))
    rs = db.execute_one(
        f"SELECT time FROM bigt WHERE v IN ({in_list}) ORDER BY time")
    assert rs.columns[0].tolist() == [1]   # big+1 is NOT in (evens only)


def test_join_reorder_outer_join_regions(db3):
    """Inner regions AROUND an outer join reorder; the outer join pins
    its own position. Output must equal the written-order plan bit for
    bit (round-3 verdict item 8)."""
    ex = db3
    ex.execute_one("CREATE TABLE dx (xname STRING, TAGS(cust))")
    ex.execute_one("INSERT INTO dx (time, cust, xname) VALUES "
                   "(1, 'c0', 'x-0'), (2, 'c9', 'x-9')")
    for sql in [
        # LEFT JOIN leaf inside an inner region
        "SELECT f.cust, f.amt, dc.cname, dp.pname, dx.xname FROM f "
        "JOIN dc ON f.cust = dc.cust JOIN dp ON f.prod = dp.prod "
        "LEFT JOIN dx ON f.cust = dx.cust",
        # outer join subtree as a leaf of the inner region
        "SELECT f.amt, dc.cname, dx.xname, dp.pname FROM f "
        "JOIN dc ON f.cust = dc.cust "
        "JOIN dp ON f.prod = dp.prod "
        "RIGHT JOIN dx ON f.cust = dx.cust",
        # aggregates over the mixed tree
        "SELECT dc.cname, count(f.amt) AS c FROM f "
        "JOIN dc ON f.cust = dc.cust JOIN dp ON f.prod = dp.prod "
        "LEFT JOIN dx ON dc.cust = dx.cust "
        "GROUP BY dc.cname ORDER BY dc.cname",
    ]:
        want = _written_order(ex, sql)
        got = ex.execute_one(sql)
        assert got.names == want.names, sql
        for cg, cw in zip(got.columns, want.columns):
            assert cg.tolist() == cw.tolist(), sql


def test_join_reorder_multi_qualifier_leaf(db3):
    """A materialized outer-join subtree (multi-qualifier leaf) rides
    through the reorder with positional column addressing."""
    ex = db3
    sql = ("SELECT f.amt, dc.cname, dp.pname FROM "
           "f JOIN dc ON f.cust = dc.cust "
           "JOIN dp ON f.prod = dp.prod WHERE f.amt > 30")
    want = _written_order(ex, sql)
    got = ex.execute_one(sql)
    assert got.names == want.names
    for cg, cw in zip(got.columns, want.columns):
        assert cg.tolist() == cw.tolist()
