"""Correlated scalar / IN / multi-key EXISTS subquery decorrelation.

Reference surface: DataFusion's subquery optimizer rules
(query_server/query/src/sql/logical/optimizer.rs:66-108 —
decorrelate_predicate_subquery, scalar_subquery_to_join), which the
reference inherits wholesale. Here the executor splits the correlated
equality conjuncts, runs the body once grouped by its correlation
columns, and splices a lookup/membership expr (sql/expr.py CorrLookup /
CorrIn / KeyInSet)."""
import numpy as np
import pytest

from cnosdb_tpu.errors import PlanError, QueryError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    ex.execute_one("CREATE TABLE orders (amount DOUBLE, qty BIGINT, "
                   "TAGS(cust, region))")
    ex.execute_one(
        "INSERT INTO orders (time, cust, region, amount, qty) VALUES "
        "(1, 'a', 'eu', 10.0, 1), (2, 'a', 'eu', 20.0, 2), "
        "(3, 'b', 'eu', 5.0, 1), (4, 'c', 'us', 50.0, 5)")
    ex.execute_one("CREATE TABLE custs (score DOUBLE, TAGS(name, zone))")
    ex.execute_one(
        "INSERT INTO custs (time, name, zone, score) VALUES "
        "(1, 'a', 'eu', 1.0), (2, 'b', 'eu', 2.0), "
        "(3, 'c', 'us', 3.0), (4, 'd', 'us', 4.0)")
    yield ex
    coord.close()


def q(ex, sql):
    rs = ex.execute_one(sql)
    out = []
    for i in range(rs.n_rows):
        row = []
        for c in rs.columns:
            v = c[i]
            if hasattr(v, "item"):
                v = v.item()
            row.append(v)
        out.append(tuple(row))
    return out


# -- correlated scalar subqueries -------------------------------------------

def test_correlated_scalar_sum(db):
    rows = q(db, "SELECT c.name, "
                 "(SELECT sum(o.amount) FROM orders o WHERE o.cust = c.name)"
                 " AS total FROM custs c ORDER BY c.name")
    assert rows == [("a", 30.0), ("b", 5.0), ("c", 50.0), ("d", None)]


def test_correlated_scalar_count_defaults_zero(db):
    rows = q(db, "SELECT c.name, "
                 "(SELECT count(o.amount) FROM orders o "
                 "WHERE o.cust = c.name) AS n FROM custs c ORDER BY c.name")
    assert rows == [("a", 2), ("b", 1), ("c", 1), ("d", 0)]


def test_correlated_scalar_in_where(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE "
                 "(SELECT sum(o.amount) FROM orders o WHERE o.cust = c.name)"
                 " > 9 ORDER BY c.name")
    assert rows == [("a",), ("c",)]


def test_correlated_scalar_with_local_pred(db):
    rows = q(db, "SELECT c.name, "
                 "(SELECT max(o.amount) FROM orders o "
                 "WHERE o.cust = c.name AND o.qty >= 2) AS m "
                 "FROM custs c ORDER BY c.name")
    assert rows == [("a", 20.0), ("b", None), ("c", 50.0), ("d", None)]


def test_correlated_scalar_nonagg_unique(db):
    # b and c have exactly one order each; restricting to them keeps the
    # single-row guarantee for every probed key
    rows = q(db, "SELECT c.name, "
                 "(SELECT o.amount FROM orders o WHERE o.cust = c.name) "
                 "AS amt FROM custs c WHERE c.name IN ('b', 'c', 'd') "
                 "ORDER BY c.name")
    assert rows == [("b", 5.0), ("c", 50.0), ("d", None)]


def test_correlated_scalar_nonagg_dup_raises(db):
    with pytest.raises((PlanError, QueryError)):
        q(db, "SELECT c.name, "
              "(SELECT o.amount FROM orders o WHERE o.cust = c.name) "
              "FROM custs c")


def test_correlated_scalar_composite_key(db):
    rows = q(db, "SELECT c.name, "
                 "(SELECT sum(o.amount) FROM orders o "
                 "WHERE o.cust = c.name AND o.region = c.zone) AS t "
                 "FROM custs c ORDER BY c.name")
    assert rows == [("a", 30.0), ("b", 5.0), ("c", 50.0), ("d", None)]


# -- correlated IN subqueries -----------------------------------------------

def test_correlated_in(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE c.score IN "
                 "(SELECT o.qty FROM orders o WHERE o.cust = c.name) "
                 "ORDER BY c.name")
    # a: score 1.0 in {1,2} yes; b: 2.0 in {1} no; c: 3.0 in {5} no
    assert rows == [("a",)]


def test_correlated_not_in(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE c.score NOT IN "
                 "(SELECT o.qty FROM orders o WHERE o.cust = c.name) "
                 "ORDER BY c.name")
    # d has no orders: NOT IN over empty set is TRUE
    assert rows == [("b",), ("c",), ("d",)]


def test_correlated_in_empty_set_false(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE c.score IN "
                 "(SELECT o.qty FROM orders o WHERE o.cust = c.name) "
                 "AND c.name = 'd'")
    assert rows == []


# -- EXISTS with composite correlation keys ---------------------------------

def test_exists_composite_key(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE EXISTS "
                 "(SELECT 1 FROM orders o WHERE o.cust = c.name "
                 "AND o.region = c.zone) ORDER BY c.name")
    assert rows == [("a",), ("b",), ("c",)]


def test_not_exists_composite_key(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE NOT EXISTS "
                 "(SELECT 1 FROM orders o WHERE o.cust = c.name "
                 "AND o.region = c.zone) ORDER BY c.name")
    assert rows == [("d",)]


def test_exists_composite_with_local_pred(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE EXISTS "
                 "(SELECT 1 FROM orders o WHERE o.cust = c.name "
                 "AND o.region = c.zone AND o.amount > 15) ORDER BY c.name")
    assert rows == [("a",), ("c",)]


# -- still-working uncorrelated forms ---------------------------------------

def test_uncorrelated_scalar_still_works(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE c.score > "
                 "(SELECT avg(score) FROM custs) ORDER BY c.name")
    assert rows == [("c",), ("d",)]


def test_uncorrelated_in_still_works(db):
    rows = q(db, "SELECT c.name FROM custs c WHERE c.name IN "
                 "(SELECT cust FROM orders) ORDER BY c.name")
    assert rows == [("a",), ("b",), ("c",)]
