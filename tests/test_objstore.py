"""Object-store external tables + COPY (reference
spi/src/query/datasource/{s3,gcs,azure}.rs, logical_planner.rs:835-980):
the stores are driven against an in-process fake server — the same
endpoint-override path a minio/fake-gcs/azurite deployment uses."""
import base64
import datetime
import hashlib
import hmac
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import objstore


class _FakeStore(BaseHTTPRequestHandler):
    """One handler serving all three dialects: objects live in
    server.blobs; every request's auth material is recorded for
    assertions."""

    def log_message(self, *a):
        pass

    def _key(self):
        import urllib.parse

        return urllib.parse.unquote(self.path.split("?")[0])

    def do_GET(self):
        self.server.requests.append(
            ("GET", self.path, {k.lower(): v for k, v in self.headers.items()}))
        blob = self.server.blobs.get(self._key())
        if blob is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def do_PUT(self):
        self.server.requests.append(
            ("PUT", self.path, {k.lower(): v for k, v in self.headers.items()}))
        n = int(self.headers.get("Content-Length", 0))
        self.server.blobs[self._key()] = self.rfile.read(n)
        self.send_response(200)
        self.end_headers()

    def do_POST(self):  # GCS media upload
        self.server.requests.append(("POST", self.path, dict(self.headers)))
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if self.path.startswith("/upload/storage/v1/b/"):
            import urllib.parse

            qs = urllib.parse.parse_qs(self.path.split("?", 1)[1])
            name = qs["name"][0]
            bucket = self.path.split("/b/")[1].split("/o")[0]
            self.server.blobs[f"/storage/v1/b/{bucket}/o/{name}"] = body
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")


@pytest.fixture
def fake(request):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeStore)
    srv.blobs = {}
    srv.requests = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def _endpoint(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


# ---------------------------------------------------------------------------
# store primitives
# ---------------------------------------------------------------------------
def test_s3_roundtrip_and_sigv4_shape(fake):
    st = objstore.S3Store("bkt", region="eu-west-1",
                          endpoint_url=_endpoint(fake),
                          access_key_id="AKID", secret_key="SECRET")
    st.put("dir/a.txt", b"hello")
    assert st.get("dir/a.txt") == b"hello"
    method, path, hdrs = fake.requests[0]
    assert method == "PUT" and path == "/bkt/dir/a.txt"
    auth = hdrs["authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKID/")
    assert "/eu-west-1/s3/aws4_request" in auth
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth
    assert hdrs["x-amz-content-sha256"] == hashlib.sha256(b"hello").hexdigest()


def test_s3_signature_is_deterministic():
    st = objstore.S3Store("b", region="us-east-1",
                          endpoint_url="http://x", access_key_id="AK",
                          secret_key="SK")
    now = datetime.datetime(2026, 1, 2, 3, 4, 5,
                            tzinfo=datetime.timezone.utc)
    h1 = st._signed_headers("GET", "/b/k", b"", now=now)
    h2 = st._signed_headers("GET", "/b/k", b"", now=now)
    assert h1 == h2
    assert h1["Authorization"] != \
        st._signed_headers("GET", "/b/other", b"", now=now)["Authorization"]


def test_s3_anonymous_when_no_credentials(fake):
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("k", b"v")
    _, _, hdrs = fake.requests[0]
    assert "authorization" not in hdrs


def test_gcs_roundtrip_emulator_mode(fake):
    st = objstore.GcsStore("bkt", gcs_base_url=_endpoint(fake),
                           disable_oauth=True)
    st.put("data/x.bin", b"\x00\x01")
    assert st.get("data/x.bin") == b"\x00\x01"


def test_azblob_sharedkey_roundtrip(fake):
    key = base64.b64encode(b"storage-account-key").decode()
    st = objstore.AzblobStore("ctr", account="acct", access_key=key,
                              endpoint_url=_endpoint(fake))
    st.put("b.txt", b"azure!")
    assert st.get("b.txt") == b"azure!"
    _, path, hdrs = fake.requests[0]
    assert path == "/acct/ctr/b.txt"
    auth = hdrs["authorization"]
    assert auth.startswith("SharedKey acct:")
    # recompute the expected MAC with the documented canonical form, from
    # the headers as RECEIVED on the wire (catches signed-vs-sent drift,
    # e.g. urllib injecting its own Content-Type)
    to_sign = ("PUT\n\n\n6\n\n"
               + hdrs["content-type"] + "\n\n\n\n\n\n\n"
               + f"x-ms-blob-type:{hdrs['x-ms-blob-type']}\n"
               + f"x-ms-date:{hdrs['x-ms-date']}\n"
               + f"x-ms-version:{hdrs['x-ms-version']}\n"
               + "/acct/acct/ctr/b.txt")
    want = base64.b64encode(hmac.new(
        b"storage-account-key", to_sign.encode(),
        hashlib.sha256).digest()).decode()
    assert auth == f"SharedKey acct:{want}"


def test_uri_parsing_errors():
    assert objstore.parse_uri("s3://b/k/x.csv") == ("s3", "b", "k/x.csv")
    assert objstore.parse_uri("/tmp/x.csv")[0] == "local"
    with pytest.raises(objstore.ObjectStoreError):
        objstore.parse_uri("ftp://b/k")
    with pytest.raises(objstore.ObjectStoreError):
        objstore.parse_uri("s3:///nobucket")


# ---------------------------------------------------------------------------
# SQL surface: external tables + COPY against the fake s3
# ---------------------------------------------------------------------------
@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    yield ex
    engine.close()


def test_external_table_over_s3(db, fake):
    fake.blobs["/bkt/t.csv"] = b"a,b\n1,x\n2,y\n"
    db.execute_one(
        "CREATE EXTERNAL TABLE ext STORED AS csv WITH HEADER ROW "
        "LOCATION 's3://bkt/t.csv' "
        f"OPTIONS (endpoint_url = '{_endpoint(fake)}', "
        "access_key_id = 'AK', secret_key = 'SK')")
    rs = db.execute_one("SELECT a, b FROM ext ORDER BY a")
    assert [int(x) for x in rs.columns[0]] == [1, 2]
    assert list(rs.columns[1]) == ["x", "y"]
    # the request was signed with the stored credentials
    get = [r for r in fake.requests if r[0] == "GET"][0]
    assert get[2]["authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AK/")


def test_copy_export_import_s3(db, fake):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO m (time, h, v) VALUES (1,'a',1.5),(2,'b',2.5)")
    db.execute_one(
        "COPY INTO 's3://bkt/out.csv' FROM m "
        f"CONNECTION = (endpoint_url = '{_endpoint(fake)}') "
        "FILE_FORMAT = (TYPE = 'csv')")
    assert b"1.5" in fake.blobs["/bkt/out.csv"]
    db.execute_one("CREATE TABLE m2 (v DOUBLE, TAGS(h))")
    rs = db.execute_one(
        "COPY INTO m2 FROM 's3://bkt/out.csv' "
        f"CONNECTION = (endpoint_url = '{_endpoint(fake)}') "
        "FILE_FORMAT = (TYPE = 'csv')")
    assert int(rs.columns[0][0]) == 2
    rs = db.execute_one("SELECT v FROM m2 ORDER BY time")
    assert [float(x) for x in rs.columns[0]] == [1.5, 2.5]


def test_external_table_via_meta_client(tmp_path):
    """Cluster mode: CREATE EXTERNAL TABLE forwards options through the
    MetaClient RPC plane (was dropped before — regression pin)."""
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.parallel.meta_service import MetaClient, MetaService

    store = MetaStore(str(tmp_path / "m.json"), register_self=False)
    svc = MetaService(store, port=0).start()
    try:
        c = MetaClient(svc.addr, node_id=7, watch=False)
        c.create_external_table(
            "cnosdb", "public", "ext", "s3://bkt/t.csv", "csv", True,
            False, {"endpoint_url": "http://e", "access_key_id": "AK"})
        ext = c.external_opt("cnosdb", "public", "ext")
        assert ext["path"] == "s3://bkt/t.csv"
        assert ext["options"]["endpoint_url"] == "http://e"
    finally:
        svc.stop()
