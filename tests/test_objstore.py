"""Object-store external tables + COPY (reference
spi/src/query/datasource/{s3,gcs,azure}.rs, logical_planner.rs:835-980):
the stores are driven against an in-process fake server — the same
endpoint-override path a minio/fake-gcs/azurite deployment uses."""
import base64
import datetime
import hashlib
import hmac
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import objstore


class _FakeStore(BaseHTTPRequestHandler):
    """One handler serving all three dialects: objects live in
    server.blobs; every request's auth material is recorded for
    assertions."""

    def log_message(self, *a):
        pass

    def _key(self):
        import urllib.parse

        return urllib.parse.unquote(self.path.split("?")[0])

    def _query(self):
        import urllib.parse

        if "?" not in self.path:
            return {}
        qs = urllib.parse.parse_qs(self.path.split("?", 1)[1])
        return {k: v[0] for k, v in qs.items()}

    def _list(self, qs):
        """List endpoint for all three dialects, paginated at
        server.page_size keys per response (continuation-token /
        pageToken / marker are all a plain start index here)."""
        base = self._key().rstrip("/")
        names = sorted(k[len(base) + 1:] for k in self.server.blobs
                       if k.startswith(base + "/"))
        names = [n for n in names if n.startswith(qs.get("prefix", ""))]
        start = int(qs.get("continuation-token") or qs.get("pageToken")
                    or qs.get("marker") or 0)
        page = names[start:start + self.server.page_size]
        nxt = str(start + len(page)) \
            if start + len(page) < len(names) else ""
        if "list-type" in qs:                     # S3 ListObjectsV2
            keys = "".join(f"<Contents><Key>{n}</Key></Contents>"
                           for n in page)
            if nxt:
                keys += (f"<NextContinuationToken>{nxt}"
                         "</NextContinuationToken>")
            payload = f"<ListBucketResult>{keys}</ListBucketResult>".encode()
        elif qs.get("comp") == "list":            # Azure container listing
            keys = "".join(f"<Blob><Name>{n}</Name></Blob>" for n in page)
            payload = (f"<EnumerationResults><Blobs>{keys}</Blobs>"
                       f"<NextMarker>{nxt}</NextMarker>"
                       "</EnumerationResults>").encode()
        else:                                     # GCS JSON API
            d = {"items": [{"name": n} for n in page]}
            if nxt:
                d["nextPageToken"] = nxt
            payload = json.dumps(d).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        self.server.requests.append(
            ("GET", self.path, {k.lower(): v for k, v in self.headers.items()}))
        if self.server.fail_statuses:
            self.send_response(self.server.fail_statuses.pop(0))
            self.end_headers()
            return
        qs = self._query()
        path_noq = self.path.split("?")[0]
        if ("list-type" in qs or qs.get("comp") == "list"
                or (path_noq.startswith("/storage/v1/b/")
                    and path_noq.endswith("/o"))):
            self._list(qs)
            return
        blob = self.server.blobs.get(self._key())
        if blob is None:
            self.send_response(404)
            self.end_headers()
            return
        # S3/GCS send `Range`, Azure signs `x-ms-range`; both use the same
        # bytes=a-b grammar. ignore_range models a server that answers 200
        # with the whole object (clients must slice locally).
        rng = self.headers.get("Range") or self.headers.get("x-ms-range")
        if rng and not self.server.ignore_range:
            a, b = rng.split("=", 1)[1].split("-")
            chunk = blob[int(a):int(b) + 1]
            self.send_response(206)
            self.send_header(
                "Content-Range", f"bytes {a}-{int(a) + len(chunk) - 1}"
                                 f"/{len(blob)}")
            self.send_header("Content-Length", str(len(chunk)))
            self.end_headers()
            self.wfile.write(chunk)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def do_PUT(self):
        self.server.requests.append(
            ("PUT", self.path, {k.lower(): v for k, v in self.headers.items()}))
        n = int(self.headers.get("Content-Length", 0))
        self.server.blobs[self._key()] = self.rfile.read(n)
        self.send_response(200)
        self.end_headers()

    def do_DELETE(self):
        self.server.requests.append(
            ("DELETE", self.path,
             {k.lower(): v for k, v in self.headers.items()}))
        if self.server.blobs.pop(self._key(), None) is None:
            self.send_response(404)
        else:
            self.send_response(204)
        self.end_headers()

    def do_POST(self):  # GCS media upload
        self.server.requests.append(("POST", self.path, dict(self.headers)))
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if self.path.startswith("/upload/storage/v1/b/"):
            import urllib.parse

            qs = urllib.parse.parse_qs(self.path.split("?", 1)[1])
            name = qs["name"][0]
            bucket = self.path.split("/b/")[1].split("/o")[0]
            self.server.blobs[f"/storage/v1/b/{bucket}/o/{name}"] = body
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")


@pytest.fixture
def fake(request):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeStore)
    srv.blobs = {}
    srv.requests = []
    srv.fail_statuses = []
    srv.ignore_range = False
    srv.page_size = 1000
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def _endpoint(srv):
    return f"http://127.0.0.1:{srv.server_address[1]}"


# ---------------------------------------------------------------------------
# store primitives
# ---------------------------------------------------------------------------
def test_s3_roundtrip_and_sigv4_shape(fake):
    st = objstore.S3Store("bkt", region="eu-west-1",
                          endpoint_url=_endpoint(fake),
                          access_key_id="AKID", secret_key="SECRET")
    st.put("dir/a.txt", b"hello")
    assert st.get("dir/a.txt") == b"hello"
    method, path, hdrs = fake.requests[0]
    assert method == "PUT" and path == "/bkt/dir/a.txt"
    auth = hdrs["authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKID/")
    assert "/eu-west-1/s3/aws4_request" in auth
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" in auth
    assert hdrs["x-amz-content-sha256"] == hashlib.sha256(b"hello").hexdigest()


def test_s3_signature_is_deterministic():
    st = objstore.S3Store("b", region="us-east-1",
                          endpoint_url="http://x", access_key_id="AK",
                          secret_key="SK")
    now = datetime.datetime(2026, 1, 2, 3, 4, 5,
                            tzinfo=datetime.timezone.utc)
    h1 = st._signed_headers("GET", "/b/k", b"", now=now)
    h2 = st._signed_headers("GET", "/b/k", b"", now=now)
    assert h1 == h2
    assert h1["Authorization"] != \
        st._signed_headers("GET", "/b/other", b"", now=now)["Authorization"]


def test_s3_anonymous_when_no_credentials(fake):
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("k", b"v")
    _, _, hdrs = fake.requests[0]
    assert "authorization" not in hdrs


def test_gcs_roundtrip_emulator_mode(fake):
    st = objstore.GcsStore("bkt", gcs_base_url=_endpoint(fake),
                           disable_oauth=True)
    st.put("data/x.bin", b"\x00\x01")
    assert st.get("data/x.bin") == b"\x00\x01"


def test_azblob_sharedkey_roundtrip(fake):
    key = base64.b64encode(b"storage-account-key").decode()
    st = objstore.AzblobStore("ctr", account="acct", access_key=key,
                              endpoint_url=_endpoint(fake))
    st.put("b.txt", b"azure!")
    assert st.get("b.txt") == b"azure!"
    _, path, hdrs = fake.requests[0]
    assert path == "/acct/ctr/b.txt"
    auth = hdrs["authorization"]
    assert auth.startswith("SharedKey acct:")
    # recompute the expected MAC with the documented canonical form, from
    # the headers as RECEIVED on the wire (catches signed-vs-sent drift,
    # e.g. urllib injecting its own Content-Type)
    to_sign = ("PUT\n\n\n6\n\n"
               + hdrs["content-type"] + "\n\n\n\n\n\n\n"
               + f"x-ms-blob-type:{hdrs['x-ms-blob-type']}\n"
               + f"x-ms-date:{hdrs['x-ms-date']}\n"
               + f"x-ms-version:{hdrs['x-ms-version']}\n"
               + "/acct/acct/ctr/b.txt")
    want = base64.b64encode(hmac.new(
        b"storage-account-key", to_sign.encode(),
        hashlib.sha256).digest()).decode()
    assert auth == f"SharedKey acct:{want}"


def test_uri_parsing_errors():
    assert objstore.parse_uri("s3://b/k/x.csv") == ("s3", "b", "k/x.csv")
    assert objstore.parse_uri("/tmp/x.csv")[0] == "local"
    with pytest.raises(objstore.ObjectStoreError):
        objstore.parse_uri("ftp://b/k")
    with pytest.raises(objstore.ObjectStoreError):
        objstore.parse_uri("s3:///nobucket")


# ---------------------------------------------------------------------------
# byte-range reads (cold-tier page fetch path) + retry semantics
# ---------------------------------------------------------------------------
_BLOB = bytes(range(256)) * 4   # 1 KiB, every offset distinguishable


def _range_gets(srv):
    return [r for r in srv.requests if r[0] == "GET"
            and ("range" in r[2] or "x-ms-range" in r[2])]


def test_s3_get_range_sends_range_header_and_handles_206(fake):
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake),
                          access_key_id="AK", secret_key="SK")
    st.put("obj", _BLOB)
    assert st.get_range("obj", 100, 64) == _BLOB[100:164]
    (_, _, hdrs), = _range_gets(fake)
    assert hdrs["range"] == "bytes=100-163"
    # Range rides outside the SigV4 signature — the signed header set must
    # not change when it is added (a signer that folded it in would 403
    # against real S3)
    assert "SignedHeaders=host;x-amz-content-sha256;x-amz-date" \
        in hdrs["authorization"]


def test_gcs_get_range(fake):
    st = objstore.GcsStore("bkt", gcs_base_url=_endpoint(fake),
                           disable_oauth=True)
    st.put("d/x.bin", _BLOB)
    assert st.get_range("d/x.bin", 0, 16) == _BLOB[:16]
    assert st.get_range("d/x.bin", 1000, 64) == _BLOB[1000:1024]  # past EOF
    (_, _, h1), (_, _, h2) = _range_gets(fake)
    assert h1["range"] == "bytes=0-15" and h2["range"] == "bytes=1000-1063"


def test_azblob_get_range_signs_x_ms_range(fake):
    key = base64.b64encode(b"storage-account-key").decode()
    st = objstore.AzblobStore("ctr", account="acct", access_key=key,
                              endpoint_url=_endpoint(fake))
    st.put("b.bin", _BLOB)
    assert st.get_range("b.bin", 7, 9) == _BLOB[7:16]
    (_, _, hdrs), = _range_gets(fake)
    # Azure's ranged read uses x-ms-range (covered by the SharedKey MAC),
    # not the plain Range header
    assert hdrs["x-ms-range"] == "bytes=7-15"
    assert "range" not in hdrs
    assert hdrs["authorization"].startswith("SharedKey acct:")


def test_local_get_range(tmp_path):
    p = str(tmp_path / "obj.bin")
    st = objstore.LocalStore()
    st.put(p, _BLOB)
    assert st.get_range(p, 300, 12) == _BLOB[300:312]
    assert st.get_range(p, 1020, 100) == _BLOB[1020:]   # clamped at EOF


def test_get_range_falls_back_to_200_full_body(fake):
    # a server that ignores Range answers 200 with the whole object; the
    # client slices locally so callers still see exactly [offset, offset+n)
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("obj", _BLOB)
    fake.ignore_range = True
    assert st.get_range("obj", 33, 10) == _BLOB[33:43]


def test_http_5xx_retries_until_success(fake, monkeypatch):
    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "4")
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("k", b"v")
    fake.fail_statuses = [503, 500]
    assert st.get("k") == b"v"
    gets = [r for r in fake.requests if r[0] == "GET"]
    assert len(gets) == 3                         # 2 failures + 1 success


def test_http_retry_budget_exhausts(fake, monkeypatch):
    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "1")   # 2 attempts
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("k", b"v")
    fake.fail_statuses = [500, 500, 500]
    with pytest.raises(objstore.ObjectStoreError, match="after 2 attempts"):
        st.get("k")
    assert len([r for r in fake.requests if r[0] == "GET"]) == 2


def test_http_404_is_permanent_no_retry(fake, monkeypatch):
    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "4")
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    with pytest.raises(objstore.ObjectStoreError, match="404"):
        st.get("missing")
    assert len(fake.requests) == 1                # no second attempt


def test_injected_get_fault_retries_then_succeeds(fake, monkeypatch):
    from cnosdb_tpu import faults

    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "4")
    st = objstore.GcsStore("bkt", gcs_base_url=_endpoint(fake),
                           disable_oauth=True)
    st.put("x", b"payload")
    faults.configure("seed=1;objstore.get:fail:times=2")
    try:
        assert st.get("x") == b"payload"
        log = [f for f in faults.fired_log() if f[0] == "objstore.get"]
        assert len(log) == 2
    finally:
        faults.reset()


def test_local_store_get_fault_retries(tmp_path, monkeypatch):
    from cnosdb_tpu import faults

    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "4")
    p = str(tmp_path / "f.bin")
    st = objstore.LocalStore()
    st.put(p, b"data")
    faults.configure("seed=1;objstore.get:fail:times=2")
    try:
        assert st.get_range(p, 1, 2) == b"at"
    finally:
        faults.reset()


def test_injected_put_fault_exhausts_budget(tmp_path, monkeypatch):
    from cnosdb_tpu import faults

    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "1")
    st = objstore.LocalStore()
    faults.configure("seed=1;objstore.put:fail")       # every attempt fails
    try:
        with pytest.raises(objstore.ObjectStoreError, match="2 attempts"):
            st.put(str(tmp_path / "f.bin"), b"data")
    finally:
        faults.reset()


# ---------------------------------------------------------------------------
# prefix listing + bulk delete (WAL-archive / backup-catalog GC path)
# ---------------------------------------------------------------------------
def test_local_list_and_delete_prefix(tmp_path):
    st = objstore.LocalStore()
    base = str(tmp_path / "arch")
    for name in ("wal/1/a.log", "wal/1/b.log", "wal/2/c.log", "obj/x"):
        st.put(os.path.join(base, name), b"d")
    pfx = os.path.join(base, "wal", "1") + os.sep
    assert st.list_prefix(pfx) == sorted(
        os.path.join(base, n) for n in ("wal/1/a.log", "wal/1/b.log"))
    assert st.list_prefix(os.path.join(base, "nothing") + os.sep) == []
    assert st.delete_prefix(pfx) == 2
    assert st.list_prefix(pfx) == []
    assert st.get(os.path.join(base, "obj/x")) == b"d"   # sibling untouched


def test_s3_list_prefix_paginates(fake):
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake),
                          access_key_id="AK", secret_key="SK")
    keys = [f"wal/0/seg_{i:03d}.log" for i in range(5)]
    for k in keys:
        st.put(k, b"x")
    st.put("other/zzz", b"x")
    fake.page_size = 2
    assert st.list_prefix("wal/0/") == keys
    # 5 keys at 2/page → 3 signed GETs, continuation-token carried through
    lists = [r for r in fake.requests
             if r[0] == "GET" and "list-type=2" in r[1]]
    assert len(lists) == 3
    assert "continuation-token" in lists[1][1]
    assert all(h["authorization"].startswith("AWS4-HMAC-SHA256")
               for _, _, h in lists)


def test_s3_delete_prefix(fake):
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    for i in range(3):
        st.put(f"wal/0/{i}.log", b"x")
    st.put("keep", b"x")
    assert st.delete_prefix("wal/0/") == 3
    assert st.list_prefix("wal/0/") == []
    assert st.get("keep") == b"x"


def test_gcs_list_prefix_paginates(fake):
    st = objstore.GcsStore("bkt", gcs_base_url=_endpoint(fake),
                           disable_oauth=True)
    for i in range(4):
        st.put(f"m/{i}", b"x")
    st.put("n/0", b"x")
    fake.page_size = 3
    assert st.list_prefix("m/") == [f"m/{i}" for i in range(4)]
    lists = [r for r in fake.requests if r[0] == "GET" and "/o?" in r[1]]
    assert len(lists) == 2 and "pageToken" in lists[1][1]
    assert st.delete_prefix("m/") == 4
    assert st.list_prefix("m/") == []


def test_azblob_list_and_delete_prefix(fake):
    key = base64.b64encode(b"storage-account-key").decode()
    st = objstore.AzblobStore("ctr", account="acct", access_key=key,
                              endpoint_url=_endpoint(fake))
    for i in range(3):
        st.put(f"wal/{i}.log", b"x")
    st.put("keep.bin", b"x")
    fake.page_size = 2
    assert st.list_prefix("wal/") == [f"wal/{i}.log" for i in range(3)]
    lists = [r for r in fake.requests
             if r[0] == "GET" and "comp=list" in r[1]]
    assert len(lists) == 2 and "marker=" in lists[1][1]
    # the listing is signed (query params ride CanonicalizedResource)
    assert all(h["authorization"].startswith("SharedKey acct:")
               for _, _, h in lists)
    assert st.delete_prefix("wal/") == 3
    assert st.get("keep.bin") == b"x"
    with pytest.raises(objstore.ObjectStoreError, match="404"):
        st.get("wal/0.log")


def test_list_prefix_rides_get_retry_path(fake, monkeypatch):
    from cnosdb_tpu import faults

    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "4")
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    st.put("p/a", b"x")
    faults.configure("seed=1;objstore.get:fail:times=2")
    try:
        assert st.list_prefix("p/") == ["p/a"]
        log = [f for f in faults.fired_log() if f[0] == "objstore.get"]
        assert len(log) == 2
    finally:
        faults.reset()


def test_list_prefix_retries_5xx_mid_pagination(fake, monkeypatch):
    monkeypatch.setenv("CNOSDB_OBJSTORE_RETRIES", "2")
    st = objstore.S3Store("bkt", endpoint_url=_endpoint(fake))
    for i in range(3):
        st.put(f"p/{i}", b"x")
    fake.page_size = 2
    fake.fail_statuses = [503]       # first page throttled once
    assert st.list_prefix("p/") == ["p/0", "p/1", "p/2"]


# ---------------------------------------------------------------------------
# SQL surface: external tables + COPY against the fake s3
# ---------------------------------------------------------------------------
@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    yield ex
    engine.close()


def test_external_table_over_s3(db, fake):
    fake.blobs["/bkt/t.csv"] = b"a,b\n1,x\n2,y\n"
    db.execute_one(
        "CREATE EXTERNAL TABLE ext STORED AS csv WITH HEADER ROW "
        "LOCATION 's3://bkt/t.csv' "
        f"OPTIONS (endpoint_url = '{_endpoint(fake)}', "
        "access_key_id = 'AK', secret_key = 'SK')")
    rs = db.execute_one("SELECT a, b FROM ext ORDER BY a")
    assert [int(x) for x in rs.columns[0]] == [1, 2]
    assert list(rs.columns[1]) == ["x", "y"]
    # the request was signed with the stored credentials
    get = [r for r in fake.requests if r[0] == "GET"][0]
    assert get[2]["authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AK/")


def test_copy_export_import_s3(db, fake):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    db.execute_one("INSERT INTO m (time, h, v) VALUES (1,'a',1.5),(2,'b',2.5)")
    db.execute_one(
        "COPY INTO 's3://bkt/out.csv' FROM m "
        f"CONNECTION = (endpoint_url = '{_endpoint(fake)}') "
        "FILE_FORMAT = (TYPE = 'csv')")
    assert b"1.5" in fake.blobs["/bkt/out.csv"]
    db.execute_one("CREATE TABLE m2 (v DOUBLE, TAGS(h))")
    rs = db.execute_one(
        "COPY INTO m2 FROM 's3://bkt/out.csv' "
        f"CONNECTION = (endpoint_url = '{_endpoint(fake)}') "
        "FILE_FORMAT = (TYPE = 'csv')")
    assert int(rs.columns[0][0]) == 2
    rs = db.execute_one("SELECT v FROM m2 ORDER BY time")
    assert [float(x) for x in rs.columns[0]] == [1.5, 2.5]


def test_external_table_via_meta_client(tmp_path):
    """Cluster mode: CREATE EXTERNAL TABLE forwards options through the
    MetaClient RPC plane (was dropped before — regression pin)."""
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.parallel.meta_service import MetaClient, MetaService

    store = MetaStore(str(tmp_path / "m.json"), register_self=False)
    svc = MetaService(store, port=0).start()
    try:
        c = MetaClient(svc.addr, node_id=7, watch=False)
        c.create_external_table(
            "cnosdb", "public", "ext", "s3://bkt/t.csv", "csv", True,
            False, {"endpoint_url": "http://e", "access_key_id": "AK"})
        ext = c.external_opt("cnosdb", "public", "ext")
        assert ext["path"] == "s3://bkt/t.csv"
        assert ext["options"]["endpoint_url"] == "http://e"
    finally:
        svc.stop()
