"""Multi-process chaos soak (slow-marked, excluded from tier-1).

Drives the deterministic fault plane (cnosdb_tpu/faults.py) against a real
3-node cluster: every data-node subprocess inherits CNOSDB_FAULTS from the
harness env, which arms the `_faults` runtime-control RPC; the tests then
install per-node schedules (partitions, crashes) and assert the headline
invariants:

- no acknowledged write is lost across a leader partition + re-election
- an injected crash (os._exit inside the RPC server) behaves like a power
  loss: the cluster keeps serving on the majority and the node catches up
  after restart
- scans fail over to replica alternates when the primary's node is
  unreachable, and self-heal once the partition lifts
"""
import json
import os
import time

import pytest

from cluster_harness import Cluster, assert_lock_graph_acyclic
from cnosdb_tpu.parallel.net import RpcError, rpc_call

pytestmark = [pytest.mark.slow, pytest.mark.cluster]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    # Arm the fault-control plane in every spawned node: CNOSDB_FAULTS in
    # the inherited env (no rules yet — seed only) sets faults.CTL_ARMED in
    # each subprocess, exposing the `_faults` RPC. The test process itself
    # imported cnosdb_tpu.faults long ago with the var unset, so its own
    # RPC client stays injection-free.
    # CNOSDB_LOCKWATCH arms the lock-order watchdog in every node, so the
    # whole soak doubles as a deadlock detector: teardown asserts the
    # observed lock-order graph stayed acyclic on every surviving node.
    knobs = {"CNOSDB_FAULTS": "seed=1", "CNOSDB_LOCKWATCH": "1"}
    os.environ.update(knobs)
    try:
        c = Cluster(str(tmp_path_factory.mktemp("chaos")), n_nodes=3).start()
    finally:
        for k in knobs:
            del os.environ[k]
    yield c
    assert assert_lock_graph_acyclic(c) > 0
    c.stop()


def _set_faults(node, spec: str) -> dict:
    return rpc_call(f"127.0.0.1:{node.rpc_port}", "_faults",
                    {"spec": spec}, timeout=5.0)


def _csv_rows(out: str) -> list[list[str]]:
    lines = [l for l in out.strip().splitlines() if l]
    return [l.split(",") for l in lines[1:]]


def _count(node, table, db) -> int:
    rows = _csv_rows(node.sql(f"SELECT count(*) FROM {table}", db=db))
    return int(rows[0][0]) if rows else 0


def _wait_count(node, table, db, expect, timeout=30.0):
    deadline = time.monotonic() + timeout
    got = -1
    while time.monotonic() < deadline:
        try:
            got = _count(node, table, db)
            if got == expect:
                return got
        except Exception:
            pass
        time.sleep(0.3)
    return got


def test_fault_control_plane_is_armed(cluster):
    for n in cluster.nodes:
        out = _set_faults(n, "")
        assert out["ok"] and out["enabled"] is False


def test_no_acked_write_lost_across_partition_and_reelection(cluster):
    """Partition each node in turn (so one round provably isolates the
    raft leader), keep writing acked batches through the healthy majority,
    then heal — every acknowledged write must be readable everywhere."""
    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dpart WITH SHARD 1 REPLICA 3", db="public")
    base = 1_700_000_000_000_000_000
    total = 0

    def write_batch(writer, k):
        nonlocal total
        lines = "\n".join(
            f"pw,host=h{i % 4} v={i} {base + (total + i) * 1_000}"
            for i in range(k))
        writer.write_lp(lines, db="dpart")  # raising == not acked
        total += k

    write_batch(n1, 20)
    assert _wait_count(n1, "pw", "dpart", total) == total

    for victim in cluster.nodes:
        healthy = [n for n in cluster.nodes if n is not victim]
        # isolate `victim` at the RPC layer, both directions: it cannot
        # send to anyone, and the others cannot send to it
        _set_faults(victim, "rpc.send:fail")
        for n in healthy:
            _set_faults(n, f"rpc.send:fail:if=127.0.0.1:{victim.rpc_port}")
        try:
            # acked writes through the healthy majority; if the victim was
            # the leader this forces a re-election first (write_lp blocks
            # until the write is durably committed or raises)
            write_batch(healthy[0], 20)
        finally:
            for n in cluster.nodes:
                _set_faults(n, "")
        assert _wait_count(healthy[1], "pw", "dpart", total,
                           timeout=60.0) == total

    # after the last heal every node (including every ex-victim) converges
    for n in cluster.nodes:
        assert _wait_count(n, "pw", "dpart", total, timeout=90.0) == total


def test_injected_crash_and_catchup(cluster):
    """The crash action is a real os._exit inside the node's RPC server —
    indistinguishable from power loss. Majority keeps serving; the crashed
    node restarts, recovers its WAL, and catches up."""
    n1, n2, n3 = cluster.nodes
    n1.sql("CREATE DATABASE dcrash WITH SHARD 1 REPLICA 3", db="public")
    base = 1_700_000_000_000_000_000
    lines = "\n".join(
        f"cr,host=h{i % 4} v={i} {base + i * 1_000}" for i in range(30))
    n1.write_lp(lines, db="dcrash")
    assert _wait_count(n1, "cr", "dcrash", 30) == 30

    # the arming request installs the rule AFTER its own rpc.server hook
    # ran, so the NEXT _faults call is the one that dies mid-serve
    _set_faults(n3, "rpc.server:crash:once,if=_faults")
    with pytest.raises(Exception):
        _set_faults(n3, "")
    deadline = time.monotonic() + 15.0
    while n3.proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.1)
    assert n3.proc.poll() == 137  # the injected exit code
    n3.proc = None

    # majority continues to accept acked writes while n3 is down
    more = "\n".join(
        f"cr,host=h{i % 4} v={i} {base + (30 + i) * 1_000}"
        for i in range(30))
    n1.write_lp(more, db="dcrash")
    assert _wait_count(n2, "cr", "dcrash", 60) == 60

    n3.start().wait_ready(timeout=90.0)
    assert _wait_count(n3, "cr", "dcrash", 60, timeout=90.0) == 60


def test_scan_failover_to_alternates_and_self_heal(cluster):
    """With the querying node partitioned from its peers, scans must be
    served entirely from local replicas (remote primaries fail over down
    the alternate list); lifting the partition restores remote scanning
    and self-heals any replicas marked broken along the way."""
    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dscan WITH SHARD 1 REPLICA 3", db="public")
    base = 1_700_000_000_000_000_000
    lines = "\n".join(
        f"sc,host=h{i % 4} v={i} {base + i * 1_000}" for i in range(40))
    n1.write_lp(lines, db="dscan")
    assert _wait_count(n1, "sc", "dscan", 40) == 40

    others = [n for n in cluster.nodes if n is not n1]
    spec = ";".join(f"rpc.send:fail:if=127.0.0.1:{n.rpc_port}"
                    for n in others)
    _set_faults(n1, spec)
    try:
        # every remote target is unreachable from n1: REPLICA 3 guarantees
        # a local alternate, so the scan must still return everything
        assert _wait_count(n1, "sc", "dscan", 40, timeout=30.0) == 40
    finally:
        _set_faults(n1, "")
    # healed: scans keep answering (and broken marks self-heal on success)
    assert _wait_count(n1, "sc", "dscan", 40, timeout=30.0) == 40
    out = _set_faults(n1, "")
    assert out["ok"]


def _integrity_gauge(node, kind: str) -> float:
    needle = f'cnosdb_integrity_total{{kind="{kind}"}}'
    for line in node.http("GET", "/metrics").splitlines():
        if line.startswith(needle):
            return float(line.split()[-1])
    return -1.0


def test_bitflip_quarantine_failover_and_anti_entropy_repair(cluster):
    """End-to-end integrity loop on real at-rest bytes: a bit flip on one
    replica's TSM file is detected by that node's scrub sweep, the file is
    quarantined and the replica marked BROKEN (queries stay correct via
    scan failover), then the anti-entropy pass rebuilds the replica from a
    healthy majority peer and checksums re-converge."""
    n1, n2, _n3 = cluster.nodes
    n1.sql("CREATE DATABASE dintg WITH SHARD 1 REPLICA 3", db="public")
    base = 1_700_000_000_000_000_000
    lines = "\n".join(
        f"ig,host=h{i % 4} v={i} {base + i * 1_000}" for i in range(40))
    n1.write_lp(lines, db="dintg")
    for n in cluster.nodes:
        assert _wait_count(n, "ig", "dintg", 40) == 40
    # seal every replica's memcache into TSM files: the corruption below
    # must land on at-rest bytes, not in-memory rows
    for n in cluster.nodes:
        n.sql("FLUSH", db="dintg")

    # flip 2 bytes of the first dintg artifact n2's sweep reads (a TSM
    # file; the flip lands inside the crc-covered window) — the same sweep
    # must then detect and quarantine it
    _set_faults(n2, "scrub.read:corrupt(2):times=1,if=dintg")
    try:
        out = json.loads(n2.http("GET", "/debug/scrub"))
    finally:
        _set_faults(n2, "")
    corrupt = [p for p in out["scrub"]["corrupt"] if "dintg" in p]
    assert len(corrupt) == 1
    assert out["counters"]["corruptions_detected"] >= 1
    assert out["counters"]["files_quarantined"] >= 1
    assert _integrity_gauge(n2, "corruptions_detected") >= 1
    assert _integrity_gauge(n2, "files_quarantined") >= 1

    # the quarantined replica serves nothing, so a correct count proves
    # every node routes the scan around the BROKEN replica
    for n in cluster.nodes:
        assert _wait_count(n, "ig", "dintg", 40, timeout=30.0) == 40

    # anti-entropy: any node's coordinator can run the sweep; it must
    # rebuild the quarantined replica from a majority donor and verify
    # the repaired checksum against the donor's
    rep = json.loads(n1.http("GET", "/debug/scrub?repair=1"))["repair"]
    assert rep["checked"] >= 1
    assert len(rep["repaired"]) >= 1
    assert rep["failed"] == []
    assert _integrity_gauge(n1, "repairs_ok") >= 1

    # converged: every node (including the repaired one, BROKEN cleared)
    # answers correctly, and a second sweep finds nothing left to repair
    for n in cluster.nodes:
        assert _wait_count(n, "ig", "dintg", 40, timeout=30.0) == 40
    rep2 = json.loads(n1.http("GET", "/debug/scrub?repair=1"))["repair"]
    assert rep2["failed"] == []


# ---------------------------------------------------------------------------
# nemesis plane (PR 13): history-checked invariants under seeded schedules
# ---------------------------------------------------------------------------
NEM_BASE = 1_700_000_000_000_000_000


def _keys_on(node, table, db) -> set[str]:
    rows = _csv_rows(node.sql(f"SELECT DISTINCT k FROM {table}", db=db))
    return {r[0] for r in rows}


def _wait_keys(node, table, db, expect: set[str], timeout=60.0) -> set[str]:
    deadline = time.monotonic() + timeout
    got: set[str] = set()
    while time.monotonic() < deadline:
        try:
            got = _keys_on(node, table, db)
            if got == expect:
                return got
        except Exception:
            pass
        time.sleep(0.3)
    return got


class _Client:
    """History-recorded client: every write/read/delete lands in the
    recorder as invoke → ok/fail, so the checker can audit the run."""

    def __init__(self, rec, table: str, db: str):
        self.rec, self.table, self.db = rec, table, db
        self.n = 0

    def write(self, node, session: str, k: int) -> list[str]:
        keys = [f"k{self.n + i}" for i in range(k)]
        lines = "\n".join(
            f"{self.table},k={key} v=1 {NEM_BASE + (self.n + i) * 1_000}"
            for i, key in enumerate(keys))
        e = self.rec.invoke(session, "write", keys=keys)
        try:
            node.write_lp(lines, db=self.db)    # raising == not acked
        except Exception as ex:
            self.rec.fail(session, e, str(ex)[:200])
            return []
        self.rec.ok(session, e)
        self.n += k
        return keys

    def read(self, node, session: str) -> set[str] | None:
        e = self.rec.invoke(session, "read", durable=False, mono=True)
        try:
            keys = _keys_on(node, self.table, self.db)
        except Exception as ex:
            self.rec.fail(session, e, str(ex)[:200])
            return None
        self.rec.ok(session, e, keys=sorted(keys))
        return keys

    def delete_before(self, node, session: str, upto: int) -> list[str]:
        keys = [f"k{i}" for i in range(min(upto, self.n))]
        e = self.rec.invoke(session, "delete", keys=keys)
        try:
            node.sql(f"DELETE FROM {self.table} WHERE time < "
                     f"{NEM_BASE + upto * 1_000}", db=self.db)
        except Exception as ex:
            self.rec.fail(session, e, str(ex)[:200])
            return keys     # even an unacked delete may have applied
        self.rec.ok(session, e)
        return keys


def _assert_checks(history, observed: set[str], context: str):
    from cnosdb_tpu.chaos.checker import run_client_checks

    results = run_client_checks(history, observed)
    bad = [r for r in results if not r.ok]
    assert not bad, context + "\n" + "\n".join(
        f"{r.name}: {r.detail}" for r in bad)


def test_rolling_restart_no_lost_acked_writes(cluster, tmp_path):
    """Restart every node in turn while a recorded client keeps writing
    through the survivors: zero acknowledged writes may be lost, and the
    write path's unavailability window stays bounded (REPLICA 3 keeps a
    quorum up throughout)."""
    from cnosdb_tpu.chaos.history import History, HistoryRecorder

    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE droll WITH SHARD 1 REPLICA 3", db="public")
    rec = HistoryRecorder(str(tmp_path / "roll.jsonl"))
    cl = _Client(rec, "rr", "droll")

    acked: set[str] = set()
    acked.update(cl.write(n1, "w", 20))
    assert _wait_keys(n1, "rr", "droll", acked) == acked

    worst_gap = 0.0
    for victim in cluster.nodes:
        victim.kill()
        survivor = cluster.alive_node()
        # the write path may blip while leadership moves off the killed
        # node; time the outage from the first failed ack to the next
        # successful one
        gap_start = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            got = cl.write(survivor, "w", 5)
            if got:
                acked.update(got)
                break
            gap_start = gap_start or time.monotonic()
            time.sleep(0.5)
        if gap_start is not None:
            worst_gap = max(worst_gap, time.monotonic() - gap_start)
        got = cl.write(survivor, "w", 5)
        assert got, "write path did not recover while one node was down"
        acked.update(got)
        victim.start().wait_ready(timeout=90.0)
        assert _wait_keys(victim, "rr", "droll", acked, timeout=90.0) \
            == acked, f"node {victim.node_id} lost acked writes on restart"
    rec.close()

    assert worst_gap < 30.0, \
        f"write unavailability window {worst_gap:.1f}s exceeds bound"
    h = History.load(str(tmp_path / "roll.jsonl"))
    for n in cluster.nodes:
        _assert_checks(h, _wait_keys(n, "rr", "droll", acked),
                       f"rolling restart, node {n.node_id}")


def test_nemesis_mix_preserves_client_invariants(cluster, tmp_path):
    """A seeded nemesis schedule mixing partitions and crash-restarts over
    the 3-node cluster, with every client op recorded: afterwards the full
    history must satisfy no-lost-acked-write, no-resurrection and
    per-session monotonic reads on every node's final state. The printed
    seed reproduces the exact schedule."""
    from cnosdb_tpu.chaos import nemesis
    from cnosdb_tpu.chaos.history import History, HistoryRecorder

    seed = 5
    plan = nemesis.generate_plan(seed, n_nodes=3, steps=4,
                                 kinds=("partition", "crash_restart"))
    ctx = nemesis.describe(plan, seed)
    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dnem WITH SHARD 1 REPLICA 3", db="public")
    rec = HistoryRecorder(str(tmp_path / "nem.jsonl"))
    cl = _Client(rec, "nm", "dnem")

    acked: set[str] = set()
    deleted: set[str] = set()
    acked.update(cl.write(n1, "w", 20))
    assert _wait_keys(n1, "nm", "dnem", acked) == acked

    for ev in plan:
        victim = cluster.nodes[ev.node]
        healthy = [n for n in cluster.nodes if n is not victim]
        if ev.kind == "partition":
            vspec, ospec = nemesis.event_specs(
                ev, f"127.0.0.1:{victim.rpc_port}", seed)
            _set_faults(victim, vspec)
            for n in healthy:
                _set_faults(n, ospec)
            try:
                acked.update(cl.write(healthy[0], "w", 10))
                cl.read(healthy[1], f"r{healthy[1].node_id}")
            finally:
                for n in cluster.nodes:
                    _set_faults(n, nemesis.heal_spec(seed, ev))
        else:                              # crash_restart: a power loss
            victim.kill()
            survivor = cluster.alive_node()
            acked.update(cl.write(survivor, "w", 10))
            cl.read(survivor, f"r{survivor.node_id}")
            victim.start().wait_ready(timeout=90.0)
        live = acked - deleted
        for n in cluster.nodes:
            assert _wait_keys(n, "nm", "dnem", live, timeout=90.0) == live, \
                f"{ctx}\nstep #{ev.step} ({ev.kind}@n{ev.node}): " \
                f"node {n.node_id} diverged"
            cl.read(n, f"r{n.node_id}")
        if ev.step == 1:   # mid-schedule delete arms the resurrection check
            deleted.update(cl.delete_before(cluster.alive_node(), "w", 10))
    rec.close()

    h = History.load(str(tmp_path / "nem.jsonl"))
    live = acked - deleted
    for n in cluster.nodes:
        final = _wait_keys(n, "nm", "dnem", live, timeout=90.0)
        _assert_checks(h, final, f"{ctx}\nfinal state on node {n.node_id}")


def _hedges_fired(node) -> int:
    total = 0
    for line in node.http("GET", "/metrics").splitlines():
        if line.startswith("cnosdb_hedge_total") \
                and 'outcome="fired"' in line:
            total += int(float(line.rsplit(" ", 1)[1]))
    return total


def test_slow_replica_brownout_tail_bounded(cluster):
    """Gray failure (slow_replica nemesis): one replica holder keeps
    answering every RPC, just 120ms late. The hedged-scan plane on the
    querying coordinator must (a) fire zero hedges while the cluster is
    healthy, (b) engage during the brownout, and (c) hold the query p99
    within 3x the healthy p99 — while every answer stays correct before,
    during, and after (checker green)."""
    from cnosdb_tpu.chaos import nemesis

    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dgray WITH SHARD 1 REPLICA 2", db="public")
    base = 1_800_000_000_000_000_000
    rows = 400
    lines = "\n".join(
        f"gray,host=h{i % 7} v={float(i)} {base + i * 1_000_000}"
        for i in range(rows))
    n1.write_lp(lines, db="dgray")
    assert _wait_count(n1, "gray", "dgray", rows) == rows

    # REPLICA 2 on 3 nodes: exactly one node holds nothing locally — the
    # one whose scans go dark when all its outbound sends are dropped.
    # Query from THAT node, so every scan crosses the wire with two
    # replica candidates (local replicas always outrank remote ones).
    qnode = None
    for n in cluster.nodes:
        others = [o for o in cluster.nodes if o is not n]
        _set_faults(n, ";".join(f"rpc.send:fail:if=127.0.0.1:{o.rpc_port}"
                                for o in others))
        try:
            ok = _wait_count(n, "gray", "dgray", rows, timeout=5.0) == rows
        finally:
            _set_faults(n, "")
        if not ok:
            qnode = n
            break
    assert qnode is not None, "some node should hold no local replica"
    holders = [n for n in cluster.nodes if n is not qnode]

    q = "SELECT count(*), sum(v) FROM gray"
    baseline = _csv_rows(qnode.sql(q, db="dgray"))[0]
    assert int(baseline[0]) == rows

    def phase(n):
        lat = []
        for _ in range(n):
            t0 = time.monotonic()
            got = _csv_rows(qnode.sql(q, db="dgray"))[0]
            lat.append(time.monotonic() - t0)
            assert got == baseline     # correct under all conditions
        lat.sort()
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    phase(5)                           # warm caches + latency sketches
    fired0 = _hedges_fired(qnode)
    healthy_p99 = phase(30)
    assert _hedges_fired(qnode) == fired0, \
        "hedges fired on a healthy cluster — hedging must be tail-only"

    # brown out the holder the coordinator currently PREFERS (the one
    # whose scan lane carries the most samples): worst case, primary
    # traffic lands on the straggler until the plane reacts
    snap = json.loads(qnode.http("GET", "/debug/health"))["nodes"]
    def scan_samples(node):
        cell = snap.get(f"127.0.0.1:{node.rpc_port}", {})
        return cell.get("classes", {}).get("scan", {}).get("samples", 0)
    victim = max(holders, key=scan_samples)
    ev = nemesis.NemesisEvent(step=0, kind="slow_replica",
                              node=victim.node_id, param=120)
    vspec, peers = nemesis.event_specs(
        ev, f"127.0.0.1:{victim.rpc_port}", seed=11)
    assert peers == ""                 # gray failure: only the victim
    _set_faults(victim, vspec)
    try:
        phase(5)                       # adaptation: rescues + re-ranking
        browned_p99 = phase(30)
    finally:
        _set_faults(victim, nemesis.heal_spec(11, ev))
    assert _hedges_fired(qnode) > fired0, \
        "brownout never engaged the hedge lane"
    bound = max(3 * healthy_p99, 0.1)  # abs floor rides out CI jitter
    assert browned_p99 <= bound, \
        f"brownout p99 {browned_p99:.3f}s exceeds {bound:.3f}s " \
        f"(healthy p99 {healthy_p99:.3f}s)"
    # healed: same bytes, breaker-free path
    assert _csv_rows(qnode.sql(q, db="dgray"))[0] == baseline


def _memory_rpc(node, payload: dict) -> dict:
    return rpc_call(f"127.0.0.1:{node.rpc_port}", "_memory",
                    payload, timeout=5.0)


def test_memory_pressure_fails_writes_closed_then_heals(cluster, tmp_path):
    """memory_pressure nemesis: squeeze one node's memory broker to a
    1-byte budget over the `_memory` runtime RPC (the harness-direct
    action nemesis.event_specs prescribes for this kind). The squeezed
    node must degrade exactly as the ladder says — user-ingress writes
    fail CLOSED with a typed 413 (never hang, never ack-then-lose),
    while reads keep answering and raft replication from the healthy
    nodes continues ungated — and restoring the budget heals it: writes
    through the ex-victim succeed again and the recorded history passes
    the checker on every node's final state."""
    import urllib.error

    from cnosdb_tpu.chaos import nemesis
    from cnosdb_tpu.chaos.history import History, HistoryRecorder

    n1 = cluster.nodes[0]
    n1.sql("CREATE DATABASE dmemp WITH SHARD 1 REPLICA 3", db="public")
    rec = HistoryRecorder(str(tmp_path / "memp.jsonl"))
    cl = _Client(rec, "mp", "dmemp")

    acked: set[str] = set()
    acked.update(cl.write(n1, "w", 20))
    assert acked, "healthy-cluster write must ack"
    assert _wait_keys(n1, "mp", "dmemp", acked) == acked

    ev = nemesis.NemesisEvent(step=0, kind="memory_pressure", node=2,
                              param=1)
    assert nemesis.event_specs(ev, "unused", seed=13) == ("", ""), \
        "memory_pressure is harness-direct: no fault-spec injection"
    victim = cluster.nodes[ev.node]
    healthy = [n for n in cluster.nodes if n is not victim]

    # squeeze: total=1 byte → soft=hard=0, so after the ladder reclaims
    # everything it can, any write with a nonzero estimate lands on the
    # fail-closed branch — deterministic, no timing window
    out = _memory_rpc(victim, {"total_bytes": ev.param})
    assert out["ok"] and out["snapshot"]["total_bytes"] == ev.param
    try:
        # recorded writes through the victim bounce (fail == not acked)
        for _ in range(3):
            assert cl.write(victim, "w", 5) == [], \
                "write acked through a node above its hard watermark"
        # the rejection is typed at the HTTP edge: 413 MemoryExceeded
        with pytest.raises(urllib.error.HTTPError) as ei:
            victim.write_lp(f"mp,k=kx v=1 {NEM_BASE}", db="dmemp")
        assert ei.value.code == 413, \
            f"expected 413 fail-closed, got {ei.value.code}"
        # the healthy majority keeps acking; replication to the victim
        # rides the raft plane, which the broker never touches — the
        # victim still converges and still answers reads
        got = cl.write(healthy[0], "w", 10)
        assert got, "healthy node refused writes during peer's squeeze"
        acked.update(got)
        assert _wait_keys(victim, "mp", "dmemp", acked, timeout=60.0) \
            == acked, "squeezed node stopped applying replicated writes"
        assert cl.read(victim, "rv") == acked
        # the broker booked the degradation: fail-closed writes counted
        snap = _memory_rpc(victim, {})["snapshot"]
        assert snap["counters"].get("write/fail_hard", 0) >= 4
    finally:
        # heal: 0 = back to config/auto budget
        out = _memory_rpc(victim, {"total_bytes": 0})
    assert out["ok"] and out["snapshot"]["total_bytes"] > (1 << 20)

    # healed: the ex-victim acks user writes again, promptly
    t0 = time.monotonic()
    got = cl.write(victim, "w", 5)
    assert got, "ex-victim still refusing writes after heal"
    assert time.monotonic() - t0 < 30.0, "post-heal write did not recover"
    acked.update(got)
    rec.close()

    h = History.load(str(tmp_path / "memp.jsonl"))
    for n in cluster.nodes:
        final = _wait_keys(n, "mp", "dmemp", acked, timeout=90.0)
        _assert_checks(h, final, f"memory_pressure, node {n.node_id}")
