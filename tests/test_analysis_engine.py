"""Self-tests for the invariant lint engine (cnosdb_tpu/analysis).

Each rule is exercised against a known-bad fixture in
tests/analysis_fixtures/ (linted as data, never imported), then the
engine mechanics themselves: inline suppressions, the baseline ratchet
in both directions, and the CLI's exit codes.
"""
import json
import os
import subprocess
import sys

import pytest

from cnosdb_tpu import analysis
from cnosdb_tpu.analysis import interproc
from cnosdb_tpu.analysis import rules as rules_mod

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(analysis.__file__)))


def _fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


def _lint(filename, rule):
    return analysis.lint_files([_fx(filename)], rules=[rule],
                               ignore_scope=True)


# ------------------------------------------------------------- per-rule
# (fixture file, rule, expected finding lines)
_CASES = [
    ("bad_bare_except.py", rules_mod.NoBareExcept(), [7]),
    ("bad_rpc_timeout.py", rules_mod.RpcCallTimeout(), [6, 7]),
    ("bad_lock_blocking.py", rules_mod.LockBlocking(), [8, 9, 15]),
    ("bad_swallow.py", rules_mod.SwallowedException(), [7]),
    ("bad_jax_purity.py", rules_mod.JaxPurity(), [9, 16, 20]),
    ("bad_wallclock.py", rules_mod.WallclockDuration(), [8, 14]),
    ("bad_metrics.py", rules_mod.MetricsNaming(), [6, 7, 8]),
    ("bad_row_loop.py", rules_mod.RowLoop(), [7]),
    ("bad_row_loop.py", rules_mod.RowLoopFallback(), [21]),
    ("bad_stage_name.py", rules_mod.StageCatalog(), [6, 9, 12]),
    ("bad_device_decode.py", rules_mod.DeviceDecodeAccounting(), [9, 18]),
    ("bad_string_filter.py", rules_mod.StringFilterAccounting(), [10, 21]),
    ("bad_cold_tier.py", rules_mod.ColdTierAccounting(), [10, 20]),
    ("bad_serving.py", rules_mod.ServingAccounting(), [10, 20]),
    ("bad_backup.py", rules_mod.BackupAccounting(), [10, 20]),
    ("bad_fault_site.py", rules_mod.FaultSiteCoverage(), [10, 11]),
    ("bad_compressed_domain.py",
     rules_mod.CompressedDomainAccounting(), [9, 20]),
    ("bad_hedge.py", rules_mod.HedgeAccounting(), [12, 15]),
    ("bad_memory.py", rules_mod.MemoryAccounting(), [13, 15]),
    ("bad_mesh.py", rules_mod.MeshAccounting(), [12, 15]),
    # interprocedural rule family (cnosdb_tpu/analysis/interproc.py)
    ("bad_host_sync.py", interproc.HostSync(), [8, 9, 10, 11]),
    ("bad_recompile.py", interproc.RecompileHazard(), [8, 14]),
    ("bad_lock_dispatch.py", interproc.LockHeldDispatch(), [15, 16]),
    ("bad_deadline_drop.py", interproc.DeadlinePropagation(), [9]),
]


@pytest.mark.parametrize(
    "filename,rule,lines", _CASES,
    ids=[f"{rule.name}:{filename}" for filename, rule, lines in _CASES])
def test_rule_catches_fixture(filename, rule, lines):
    findings = _lint(filename, rule)
    assert [f.line for f in sorted(findings, key=lambda f: f.line)] == lines, \
        [f.render() for f in findings]
    assert all(f.rule == rule.name for f in findings)


def test_every_rule_has_a_fixture_and_motivation():
    covered = {rule.name for _fn, rule, _l in _CASES}
    for rule in rules_mod.all_rules():
        assert rule.name in covered, f"rule {rule.name} has no fixture case"
        assert rule.motivation, f"rule {rule.name} must name its incident"


# ----------------------------------------------- interprocedural passes
def test_cross_file_taint_flows_two_call_edges():
    # make_rows (device) -> passthrough -> consume: the host pull in
    # consume is three files of context away from the jnp call that
    # tainted it, across two resolved call-graph edges
    findings = analysis.lint_files(
        [_fx("device_chain_outer.py"), _fx("device_chain_inner.py")],
        rules=[interproc.HostSync()], ignore_scope=True)
    outer = analysis.norm_relpath(_fx("device_chain_outer.py"))
    assert [(f.path, f.line) for f in findings] == [(outer, 13)]


def test_cross_file_taint_needs_the_inner_file():
    # without the producer module the call cannot resolve, the value is
    # not provably device, and the conservative analyzer stays silent
    findings = analysis.lint_files([_fx("device_chain_outer.py")],
                                   rules=[interproc.HostSync()],
                                   ignore_scope=True)
    assert findings == []


def test_report_filter_mutes_findings_but_keeps_summaries():
    # the --changed contract: unchanged files are still indexed (the
    # taint below only exists because the inner file was parsed) but
    # only files in the filter may report
    inner, outer = _fx("device_chain_inner.py"), _fx("device_chain_outer.py")
    keep_outer = {analysis.norm_relpath(outer)}
    findings = analysis.lint_files([outer, inner],
                                   rules=[interproc.HostSync()],
                                   ignore_scope=True,
                                   report_filter=keep_outer)
    assert [f.line for f in findings] == [13]
    keep_inner = {analysis.norm_relpath(inner)}
    findings = analysis.lint_files([outer, inner],
                                   rules=[interproc.HostSync()],
                                   ignore_scope=True,
                                   report_filter=keep_inner)
    assert findings == []


def test_stale_suppression_audit(tmp_path):
    # a disable that absorbs nothing is flagged on full-registry runs;
    # marker text inside a string literal is NOT a suppression
    f = tmp_path / "dead.py"
    f.write_text("x = 1  # lint: disable=host-sync (debt long gone)\n"
                 "DOC = 'mentioning lint: disable=all is fine'\n")
    findings = analysis.lint_files([str(f)])
    assert [(x.rule, x.line) for x in findings] == [("stale-suppression", 1)]


# --------------------------------------------------------- suppressions
def test_inline_disable_silences_only_that_rule():
    # the two row-loop rules are structural (they report when their target
    # functions are absent), so scope-ignoring them over an unrelated
    # fixture is meaningless — every other rule runs
    rules = [r for r in rules_mod.all_rules()
             if not r.name.startswith("row-loop")]
    findings = analysis.lint_files([_fx("suppressed.py")], rules=rules,
                                   ignore_scope=True)
    assert findings == [], [f.render() for f in findings]


def test_disable_on_other_line_does_not_leak():
    # the suppression must sit on the finding's own line
    findings = _lint("bad_swallow.py", rules_mod.SwallowedException())
    assert len(findings) == 1


# ------------------------------------------------------ baseline ratchet
def _run_fixture(rule, baseline_path):
    return analysis.run([_fx("bad_swallow.py")], rules=[rule],
                        baseline_path=baseline_path, ignore_scope=True)


def test_baseline_ratchet_both_directions(tmp_path):
    rule = rules_mod.SwallowedException()
    bl = str(tmp_path / "baseline.json")
    relpath = analysis.norm_relpath(_fx("bad_swallow.py"))

    # no baseline: the finding is a hard violation
    rep = _run_fixture(rule, bl)
    assert not rep.ok and len(rep.violations) == 1

    # frozen at the current count: ok, finding rides the baseline
    analysis.write_baseline(rep.counts, bl)
    rep = _run_fixture(rule, bl)
    assert rep.ok and rep.findings and not rep.violations

    # over-generous baseline: stale — the ratchet only turns one way
    analysis.write_baseline({(rule.name, relpath): 5}, bl)
    rep = _run_fixture(rule, bl)
    assert not rep.ok
    assert rep.stale == [(rule.name, relpath, 5, 1)]


def test_baseline_roundtrip_drops_zero_cells(tmp_path):
    bl = str(tmp_path / "b.json")
    analysis.write_baseline({("r1", "a.py"): 2, ("r2", "b.py"): 0}, bl)
    assert analysis.load_baseline(bl) == {("r1", "a.py"): 2}


def test_stale_check_ignores_files_outside_the_run(tmp_path):
    # a subset run must not flag baseline cells for files it never read
    rule = rules_mod.SwallowedException()
    bl = str(tmp_path / "baseline.json")
    analysis.write_baseline({(rule.name, "cnosdb_tpu/other.py"): 3}, bl)
    rep = _run_fixture(rule, bl)
    assert rep.stale == []


# ----------------------------------------------------------------- CLI
def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "cnosdb_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, timeout=120)


def test_cli_nonzero_on_fixtures(tmp_path):
    empty = str(tmp_path / "empty_baseline.json")
    p = _cli(FIXTURES, "--all-rules", "--baseline", empty, "--json")
    assert p.returncode == 1, p.stdout + p.stderr
    rep = json.loads(p.stdout)
    assert not rep["ok"] and rep["violations"]
    rules_hit = {f["rule"] for f in rep["violations"]}
    assert {"no-bare-except", "swallowed-exception", "lock-blocking",
            "wallclock-duration", "metrics-naming",
            "jax-purity"} <= rules_hit


def test_cli_fix_baseline_requires_whole_tree(tmp_path):
    p = _cli(FIXTURES, "--fix-baseline",
             "--baseline", str(tmp_path / "b.json"))
    assert p.returncode == 2


def test_cli_fix_baseline_reports_pruned_cells(tmp_path):
    bl = str(tmp_path / "b.json")
    analysis.write_baseline(
        {("swallowed-exception", "cnosdb_tpu/long_gone.py"): 2}, bl)
    p = _cli("--fix-baseline", "--baseline", bl)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "pruned stale cell swallowed-exception:cnosdb_tpu/long_gone.py" \
        in p.stdout
    assert ("swallowed-exception", "cnosdb_tpu/long_gone.py") \
        not in analysis.load_baseline(bl)


def test_cli_changed_rejects_explicit_paths():
    p = _cli(FIXTURES, "--changed", "HEAD")
    assert p.returncode == 2


def test_cli_callgraph_dumps_summaries():
    p = _cli(_fx("device_chain_inner.py"), _fx("device_chain_outer.py"),
             "--callgraph")
    assert p.returncode == 0, p.stdout + p.stderr
    lines = {l.split(" ", 1)[0].rsplit(":", 1)[-1]: l
             for l in p.stdout.splitlines()}
    assert "returns-device" in lines["make_rows"]
    assert "returns-device" in lines["passthrough"]
    assert "device_chain_inner.py:make_rows" in lines["passthrough"]
