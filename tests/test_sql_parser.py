import pytest

from cnosdb_tpu.errors import ParserError
from cnosdb_tpu.sql import ast
from cnosdb_tpu.sql.expr import BinOp, Between, Column, Func, InList, IsNull, Literal
from cnosdb_tpu.sql.parser import parse_sql, parse_interval_string, parse_timestamp_string


def one(sql):
    stmts = parse_sql(sql)
    assert len(stmts) == 1
    return stmts[0]


def test_basic_select():
    s = one("SELECT usage_user, usage_system FROM cpu")
    assert isinstance(s, ast.SelectStmt)
    assert s.table == "cpu"
    assert [i.expr.name for i in s.items] == ["usage_user", "usage_system"]


def test_select_star_where_order_limit():
    s = one("SELECT * FROM cpu WHERE host = 'h1' AND usage_user > 50.5 "
            "ORDER BY time DESC LIMIT 10 OFFSET 5")
    assert s.items[0].expr == "*"
    assert isinstance(s.where, BinOp) and s.where.op == "and"
    assert s.order_by[0][1] is False
    assert s.limit == 10 and s.offset == 5


def test_aggregate_group_by():
    s = one("SELECT date_bin(INTERVAL '1 minute', time) AS t, avg(usage_user) "
            "FROM cpu GROUP BY t, hostname HAVING avg(usage_user) > 10")
    f = s.items[0].expr
    assert isinstance(f, Func) and f.name == "date_bin"
    assert f.args[0].value.ns == 60 * 10**9
    assert s.items[0].alias == "t"
    assert len(s.group_by) == 2
    assert s.having is not None


def test_count_star():
    s = one("SELECT count(*) FROM cpu")
    f = s.items[0].expr
    assert f.name == "count" and f.args[0].value == "*"


def test_in_between_isnull():
    s = one("SELECT * FROM t WHERE a IN (1, 2, 3) AND b NOT IN ('x') "
            "AND c BETWEEN 1 AND 5 AND d NOT BETWEEN 2 AND 3 AND e IS NOT NULL")
    # walk the and-chain
    preds = []
    def walk(e):
        if isinstance(e, BinOp) and e.op == "and":
            walk(e.left); walk(e.right)
        else:
            preds.append(e)
    walk(s.where)
    assert isinstance(preds[0], InList) and not preds[0].negated
    assert isinstance(preds[1], InList) and preds[1].negated
    assert isinstance(preds[2], Between) and not preds[2].negated
    assert isinstance(preds[3], Between) and preds[3].negated
    assert isinstance(preds[4], IsNull) and preds[4].negated


def test_operator_precedence():
    s = one("SELECT * FROM t WHERE a = 1 OR b = 2 AND c = 3")
    assert s.where.op == "or"
    assert s.where.right.op == "and"
    e = one("SELECT 1 + 2 * 3 FROM t").items[0].expr
    assert e.op == "+" and e.right.op == "*"


def test_create_database_options():
    s = one("CREATE DATABASE IF NOT EXISTS oceanic_station WITH TTL '30d' "
            "SHARD 4 VNODE_DURATION '1d' REPLICA 2 PRECISION 'ms'")
    assert s.if_not_exists
    assert s.options == {"ttl": "30d", "shard_num": 4, "vnode_duration": "1d",
                         "replica": 2, "precision": "ms"}


def test_create_table():
    s = one("CREATE TABLE air (visibility DOUBLE, temperature DOUBLE CODEC(GORILLA), "
            "presssure BIGINT, ok BOOLEAN, TAGS(station, region))")
    assert [f.name for f in s.fields] == ["visibility", "temperature", "presssure", "ok"]
    assert s.fields[1].codec == "GORILLA"
    assert s.tags == ["station", "region"]


def test_insert():
    s = one("INSERT INTO air (time, station, visibility) VALUES "
            "(1673591597000000000, 'XiaoMaiDao', 56), (1673591598000000000, 'DaMaiDao', 57.5)")
    assert s.table == "air"
    assert s.columns == ["time", "station", "visibility"]
    assert len(s.rows) == 2
    assert s.rows[1] == [1673591598000000000, "DaMaiDao", 57.5]


def test_delete_update():
    d = one("DELETE FROM cpu WHERE time < 100 AND host = 'h1'")
    assert d.table == "cpu"
    u = one("UPDATE cpu SET host = 'h2' WHERE host = 'h1'")
    assert "host" in u.assignments


def test_show_describe():
    assert one("SHOW DATABASES").kind == "databases"
    assert one("SHOW TABLES").kind == "tables"
    s = one("SHOW TAG VALUES FROM cpu WITH KEY = host LIMIT 5")
    assert s.kind == "tag_values" and s.table == "cpu" and s.tag_key == "host"
    d = one("DESCRIBE TABLE cpu")
    assert d.kind == "table" and d.name == "cpu"


def test_alter():
    s = one("ALTER TABLE cpu ADD FIELD temp DOUBLE CODEC(GORILLA)")
    assert s.action == "add_field" and s.column.codec == "GORILLA"
    s2 = one("ALTER TABLE cpu DROP temp")
    assert s2.action == "drop" and s2.drop_name == "temp"
    s3 = one("ALTER DATABASE db SET TTL '7d'")
    assert s3.options == {"ttl": "7d"}


def test_tenant_user():
    assert one("CREATE TENANT test").name == "test"
    u = one("CREATE USER u1 WITH PASSWORD = 'secret'")
    assert u.password == "secret"
    assert one("ALTER USER u1 SET PASSWORD = 'n'").changes == {
        "password": "n"}
    assert one("DROP TENANT IF EXISTS test").if_exists


def test_explain():
    s = one("EXPLAIN SELECT * FROM cpu")
    assert isinstance(s, ast.ExplainStmt)
    assert isinstance(s.inner, ast.SelectStmt)


def test_multi_statements_and_comments():
    stmts = parse_sql("SELECT 1; -- comment\nSELECT 2; /* block */ SELECT 3")
    assert len(stmts) == 3


def test_quoted_identifiers_and_strings():
    s = one('SELECT "weird col" FROM "my table" WHERE note = \'it\'\'s\'')
    assert s.items[0].expr.name == "weird col"
    assert s.table == "my table"
    assert s.where.right.value == "it's"


def test_intervals_and_timestamps():
    assert parse_interval_string("1 minute") == 60 * 10**9
    assert parse_interval_string("10m") == 600 * 10**9
    assert parse_interval_string("1 hour 30 minutes") == 5400 * 10**9
    assert parse_timestamp_string("1970-01-01T00:00:00Z") == 0
    assert parse_timestamp_string("1970-01-01 00:00:01") == 10**9
    ns = parse_timestamp_string("2022-01-01T00:00:00.000000123Z")
    assert ns % 1000 == 123


def test_errors():
    with pytest.raises(ParserError):
        parse_sql("SELEC * FROM t")
    with pytest.raises(ParserError):
        parse_sql("SELECT FROM t")
    with pytest.raises(ParserError):
        parse_sql("SELECT * FROM t WHERE a >")


def test_try_cast_is_per_element_and_cast_skips_null_slots(tmp_path):
    """TRY_CAST nulls only the failing elements; strict CAST must not
    abort on NULL slots whose garbage values look uncastable."""
    import numpy as np

    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor, Session
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    coord = Coordinator(meta, TsKv(str(tmp_path / "data")))
    ex = QueryExecutor(meta, coord)
    s = Session()
    ex.execute_one(
        "CREATE TABLE public.ct (f DOUBLE, pad BIGINT, TAGS(h))", s)
    ex.execute_one(
        "INSERT INTO public.ct (time, h, f, pad) VALUES "
        "(1,'x',1.9,0), (2,'x',1.0/0,0), (3,'x',NULL,0), (4,'x',-2.5,0)", s)
    rs = ex.execute_one(
        "SELECT TRY_CAST(f AS BIGINT) AS x FROM public.ct ORDER BY time", s)
    got = [None if v is None or (isinstance(v, float) and np.isnan(v))
           else int(v) for v in rs.columns[0].tolist()]
    assert got == [1, None, None, -2]
    # strict CAST over rows that exclude the Inf: NULL slot must not abort
    rs = ex.execute_one(
        "SELECT CAST(f AS BIGINT) AS x FROM public.ct "
        "WHERE time != 2 ORDER BY time", s)
    vals = rs.columns[0].tolist()
    assert int(vals[0]) == 1 and int(vals[2]) == -2
    coord.close()
