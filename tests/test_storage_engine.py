import os

import numpy as np
import pytest

from cnosdb_tpu.models.points import SeriesRows, WriteBatch
from cnosdb_tpu.models.predicate import ColumnDomains, SetDomain, TimeRange, TimeRanges
from cnosdb_tpu.models.schema import TskvTableSchema, ValueType
from cnosdb_tpu.models.series import SeriesKey
from cnosdb_tpu.storage.compaction import Picker
from cnosdb_tpu.storage.scan import scan_vnode
from cnosdb_tpu.storage.vnode import VnodeStorage


def _wb(table, host, ts_list, usage_list, n_list=None):
    fields = {"usage": (int(ValueType.FLOAT), list(usage_list))}
    if n_list is not None:
        fields["n"] = (int(ValueType.INTEGER), list(n_list))
    wb = WriteBatch()
    wb.add_series(table, SeriesRows(SeriesKey(table, {"host": host}),
                                    list(ts_list), fields))
    return wb


def _schema():
    return {"cpu": TskvTableSchema.new_measurement(
        "t", "db", "cpu", tags=["host"],
        fields=[("usage", ValueType.FLOAT), ("n", ValueType.INTEGER)])}


def test_write_scan_memory_only(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", [10, 20, 30], [1.0, 2.0, 3.0]))
    v.write(_wb("cpu", "h2", [15, 25], [4.0, 5.0]))
    b = scan_vnode(v, "cpu")
    assert b.n_series == 2 and b.n_rows == 5
    np.testing.assert_array_equal(np.sort(b.ts), [10, 15, 20, 25, 30])
    vt, vals, valid = b.fields["usage"]
    assert valid.all()
    # rows of series ordinal 0 (h1 by insertion) are ts 10/20/30
    h1_rows = b.sid_ordinal == 0
    np.testing.assert_array_equal(b.ts[h1_rows], [10, 20, 30])
    np.testing.assert_allclose(vals[h1_rows], [1.0, 2.0, 3.0])
    v.close()


def test_flush_and_scan_from_file(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", range(100), np.arange(100) * 1.5, range(100)))
    v.flush()
    assert len(v.summary.version.levels[0]) == 1
    assert v.active.is_empty and not v.immutables
    b = scan_vnode(v, "cpu")
    assert b.n_rows == 100
    vt, vals, valid = b.fields["usage"]
    np.testing.assert_allclose(vals, np.arange(100) * 1.5)
    v.close()


def test_merge_memory_over_file(tmp_engine_dir):
    """Memcache rows override file rows at equal ts (last-write-wins)."""
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", [1, 2, 3], [1.0, 2.0, 3.0]))
    v.flush()
    v.write(_wb("cpu", "h1", [2, 4], [20.0, 40.0]))
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(b.ts, [1, 2, 3, 4])
    np.testing.assert_allclose(b.fields["usage"][1], [1.0, 20.0, 3.0, 40.0])
    v.close()


def test_partial_field_merge_across_flushes(tmp_engine_dir):
    """Write usage at ts, flush, write only n at same ts → both fields live."""
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    wb1 = WriteBatch()
    wb1.add_series("cpu", SeriesRows(SeriesKey("cpu", {"host": "h1"}), [5],
                                     {"usage": (int(ValueType.FLOAT), [1.25])}))
    v.write(wb1)
    v.flush()
    wb2 = WriteBatch()
    wb2.add_series("cpu", SeriesRows(SeriesKey("cpu", {"host": "h1"}), [5],
                                     {"n": (int(ValueType.INTEGER), [7])}))
    v.write(wb2)
    b = scan_vnode(v, "cpu")
    assert b.n_rows == 1
    assert b.fields["usage"][1][0] == 1.25 and b.fields["usage"][2][0]
    assert b.fields["n"][1][0] == 7 and b.fields["n"][2][0]
    v.close()


def test_wal_recovery(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", [1, 2], [1.0, 2.0]))
    v.flush()
    v.write(_wb("cpu", "h1", [3, 4], [3.0, 4.0]))
    v.wal.sync()
    # crash: no flush/close
    v.wal.close(); v.index.close(); v.summary.close()
    v2 = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    b = scan_vnode(v2, "cpu")
    np.testing.assert_array_equal(b.ts, [1, 2, 3, 4])
    np.testing.assert_allclose(b.fields["usage"][1], [1.0, 2.0, 3.0, 4.0])
    # unflushed rows are in memcache, flushed ones not replayed twice
    assert len(v2.active.series) == 1
    v2.close()


def test_series_index_persistence(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", [1], [1.0]))
    v.write(_wb("cpu", "h2", [1], [1.0]))
    sid1 = v.index.get_series_id(SeriesKey("cpu", {"host": "h1"}))
    v.close()
    v2 = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    assert v2.index.get_series_id(SeriesKey("cpu", {"host": "h1"})) == sid1
    assert v2.index.series_count() == 2
    ids = v2.index.get_series_ids_by_domains(
        "cpu", ColumnDomains.of("host", SetDomain(["h2"])))
    assert len(ids) == 1
    v2.close()


def test_compaction_merges_l0(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema(),
                     picker=Picker(l0_trigger=3))
    for i in range(4):
        v.write(_wb("cpu", "h1", [i * 10 + 1, i * 10 + 2], [float(i), float(i) + .5]))
        v.flush()
    assert len(v.summary.version.levels[0]) == 4
    assert v.compact()
    assert len(v.summary.version.levels[0]) == 0
    assert len(v.summary.version.levels[1]) == 1
    b = scan_vnode(v, "cpu")
    assert b.n_rows == 8
    # data intact post-compaction
    np.testing.assert_array_equal(b.ts, [1, 2, 11, 12, 21, 22, 31, 32])
    v.close()


def test_compaction_dedup_overlapping(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema(),
                     picker=Picker(l0_trigger=2))
    v.write(_wb("cpu", "h1", [1, 2, 3], [1.0, 2.0, 3.0]))
    v.flush()
    v.write(_wb("cpu", "h1", [2, 3, 4], [20.0, 30.0, 40.0]))
    v.flush()
    assert v.compact()
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(b.ts, [1, 2, 3, 4])
    np.testing.assert_allclose(b.fields["usage"][1], [1.0, 20.0, 30.0, 40.0])
    v.close()


def test_time_range_scan(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", range(0, 100, 10), np.arange(10.0)))
    v.flush()
    b = scan_vnode(v, "cpu", time_ranges=TimeRanges([TimeRange(20, 50)]))
    np.testing.assert_array_equal(b.ts, [20, 30, 40, 50])
    v.close()


def test_delete_time_range_and_drop_table(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", range(10), np.arange(10.0)))
    v.flush()
    v.write(_wb("cpu", "h1", range(10, 15), np.arange(10.0, 15.0)))
    v.delete_time_range("cpu", None, 3, 11)
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(b.ts, [0, 1, 2, 12, 13, 14])
    v.drop_table("cpu")
    b2 = scan_vnode(v, "cpu")
    assert b2.n_rows == 0
    v.close()


def test_delete_survives_compaction(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema(),
                     picker=Picker(l0_trigger=2))
    v.write(_wb("cpu", "h1", range(10), np.arange(10.0)))
    v.flush()
    v.write(_wb("cpu", "h1", range(10, 20), np.arange(10.0, 20.0)))
    v.flush()
    v.delete_time_range("cpu", None, 5, 14)
    assert v.compact()
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(b.ts, [0, 1, 2, 3, 4, 15, 16, 17, 18, 19])
    v.close()


def test_null_fields_roundtrip(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    wb = WriteBatch()
    wb.add_series("cpu", SeriesRows(
        SeriesKey("cpu", {"host": "h1"}), [1, 2, 3],
        {"usage": (int(ValueType.FLOAT), [1.0, None, 3.0]),
         "n": (int(ValueType.INTEGER), [None, 5, None])}))
    v.write(wb)
    v.flush()
    b = scan_vnode(v, "cpu")
    _, uv, um = b.fields["usage"]
    _, nv, nm = b.fields["n"]
    np.testing.assert_array_equal(um, [True, False, True])
    np.testing.assert_array_equal(nm, [False, True, False])
    assert uv[0] == 1.0 and uv[2] == 3.0 and nv[1] == 5
    v.close()


def test_compaction_priority_l0_beats_l1(tmp_engine_dir):
    """Newer L0 data must survive a merge with an older L1 file."""
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema(), picker=Picker(l0_trigger=2))
    v.write(_wb("cpu", "h1", [1, 2], [1.0, 2.0]))
    v.flush()
    v.write(_wb("cpu", "h1", [3], [3.0]))
    v.flush()
    assert v.compact()  # → L1 file containing ts1..3
    assert len(v.summary.version.levels[1]) == 1
    v.write(_wb("cpu", "h1", [2], [200.0]))  # newer value for ts=2
    v.flush()
    v.write(_wb("cpu", "h1", [5], [5.0]))
    v.flush()
    assert v.compact()  # merges L0 {ts2=200, ts5} with L1 {ts1,2,3}
    b = scan_vnode(v, "cpu")
    np.testing.assert_array_equal(b.ts, [1, 2, 3, 5])
    np.testing.assert_allclose(b.fields["usage"][1], [1.0, 200.0, 3.0, 5.0])
    v.close()


def test_delete_time_range_survives_crash(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", range(10), np.arange(10.0)))
    v.delete_time_range("cpu", None, 3, 6)
    v.wal.sync()
    # crash without flush
    v.wal.close(); v.index.close(); v.summary.close()
    v2 = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    b = scan_vnode(v2, "cpu")
    np.testing.assert_array_equal(b.ts, [0, 1, 2, 7, 8, 9])
    v2.close()


def test_wal_purge_keeps_unreadable_segments(tmp_engine_dir):
    from cnosdb_tpu.storage.wal import Wal, WalEntryType
    d = os.path.join(tmp_engine_dir, "wal")
    w = Wal(d, max_segment_size=128)
    for i in range(40):
        w.append(WalEntryType.WRITE, b"y" * 32)
    segs = sorted(f for f in os.listdir(d) if f.startswith("wal_"))
    # corrupt the first segment's magic
    p0 = os.path.join(d, segs[0])
    raw = bytearray(open(p0, "rb").read())
    raw[0] ^= 0xFF
    open(p0, "wb").write(bytes(raw))
    w.purge_to(100)  # must NOT delete anything at/after the unreadable seg
    segs_after = sorted(f for f in os.listdir(d) if f.startswith("wal_"))
    assert segs_after == segs
    w.close()


def test_update_tags(tmp_engine_dir):
    v = VnodeStorage(1, tmp_engine_dir, schemas=_schema())
    v.write(_wb("cpu", "h1", [1], [1.0]))
    old = SeriesKey("cpu", {"host": "h1"})
    new = SeriesKey("cpu", {"host": "h1-renamed"})
    sid = v.index.get_series_id(old)
    v.update_tags("cpu", [old], [new])
    assert v.index.get_series_id(old) is None
    assert v.index.get_series_id(new) == sid
    v.close()


def test_checksum_invariant_across_flush_and_compaction(tmp_engine_dir):
    """The content checksum (reference check.rs ChecksumGroup) must not
    change as data moves memcache → L0 → compacted levels."""
    from cnosdb_tpu.storage.engine import TsKv
    from cnosdb_tpu.models.points import SeriesRows, WriteBatch
    from cnosdb_tpu.models.series import SeriesKey

    eng = TsKv(tmp_engine_dir)
    v = eng.open_vnode("t.db", 1)
    for chunk in range(4):
        wb = WriteBatch()
        for s in range(3):
            ts = [chunk * 100 + i for i in range(100)]
            wb.add_series("m", SeriesRows(
                SeriesKey("m", {"h": f"s{s}"}), ts,
                {"v": (1, [float(chunk * 100 + i) for i in range(100)])}))
        v.write(wb)
        cs_mem = v.checksum()
        v.flush()
        assert v.checksum() == cs_mem, "flush changed content checksum"
    before = v.checksum()
    v.compact_full()
    assert v.checksum() == before, "compaction changed content checksum"
    eng.close()
    # reopen: recovery preserves the checksum too
    eng2 = TsKv(tmp_engine_dir)
    v2 = eng2.open_vnode("t.db", 1)
    assert v2.checksum() == before
    eng2.close()


def test_compaction_concurrent_with_writes(tmp_engine_dir):
    """Interleaved writes + flushes + compactions from a second thread must
    neither crash nor lose rows (VERDICT round-1: no concurrency coverage
    for the compaction path)."""
    import threading

    from cnosdb_tpu.storage.engine import TsKv
    from cnosdb_tpu.models.points import SeriesRows, WriteBatch
    from cnosdb_tpu.models.series import SeriesKey
    from cnosdb_tpu.storage.scan import scan_vnode

    eng = TsKv(tmp_engine_dir)
    v = eng.open_vnode("t.db", 1)
    stop = threading.Event()
    errors = []

    def churn():
        try:
            while not stop.is_set():
                v.flush(sync=False)
                v.compact()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    t = threading.Thread(target=churn)
    t.start()
    total = 0
    try:
        for chunk in range(30):
            wb = WriteBatch()
            ts = [chunk * 50 + i for i in range(50)]
            wb.add_series("m", SeriesRows(
                SeriesKey("m", {"h": "a"}), ts,
                {"v": (1, [float(x) for x in ts])}))
            v.write(wb)
            total += 50
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors, errors
    v.flush()
    v.compact_full()
    b = scan_vnode(v, "m")
    assert b.n_rows == total
    assert sorted(b.ts.tolist()) == list(range(total))
    eng.close()


def test_install_file_snapshot_rejects_traversal(tmp_engine_dir):
    """Regression (security): snapshot paths arrive over the network and
    must never write outside the vnode dir."""
    import pytest as _pytest

    from cnosdb_tpu.errors import StorageError
    from cnosdb_tpu.storage.engine import TsKv

    eng = TsKv(tmp_engine_dir)
    v = eng.open_vnode("t.db", 1)
    for bad in ("../evil", "a/../../evil", "/etc/evil"):
        with _pytest.raises(StorageError):
            v.install_file_snapshot({"files": {bad: b"x"}})
    eng.close()
