"""Test harness: run all tests on a virtual 8-device CPU mesh.

Env must be set before jax (or anything importing jax) loads, so this sits
at the very top of conftest.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: env presets JAX_PLATFORMS=axon
# the axon plugin's sitecustomize registration dials the TPU relay at
# interpreter start when this is set; a degraded relay would stall the
# whole suite, and tests run on the virtual CPU mesh regardless
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize registers the axon TPU plugin before conftest runs, so env
# alone is too late; force the cpu backend via config (backends are lazy,
# XLA_FLAGS above still applies at first init).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def tmp_engine_dir(tmp_path):
    d = tmp_path / "engine"
    d.mkdir()
    return str(d)


@pytest.fixture(autouse=True)
def _isolate_health_state():
    """The gray-failure plane keeps process-global node state (latency
    scorer, slow-start ramps, hedge/breaker counters). Left standing, a
    breaker tripped in one test throttles RPCs in the next."""
    from cnosdb_tpu.parallel import health

    health.SCORER.reset()
    health.SLOW_START.reset()
    health.reset_counters()
    yield
