"""Unit tests for the runtime lock-order watchdog (utils/lockwatch.py).

These run in-process with the watchdog flipped on via enable() — the
cluster suites exercise the subprocess path (CNOSDB_LOCKWATCH=1 in the
node env) and assert the graph stays acyclic at teardown.
"""
import threading

import pytest

from cnosdb_tpu.utils import lockwatch as lw


@pytest.fixture(autouse=True)
def _watch():
    was = lw.enabled()
    lw.enable(True)
    lw.reset()
    yield
    lw.reset()
    lw.enable(was)


def _in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(5)
    assert not t.is_alive()


def test_disabled_factories_return_plain_primitives():
    lw.enable(False)
    assert type(lw.Lock("x")) is type(threading.Lock())
    # an RLock factory result must support reentrancy either way
    rl = lw.RLock("y")
    with rl:
        with rl:
            pass


def test_nesting_records_edges_and_consistent_order_is_acyclic():
    a, b = lw.Lock("A"), lw.Lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = lw.report()
    assert {(e["from"], e["to"]) for e in rep["edges"]} == {("A", "B")}
    assert rep["edges"][0]["count"] == 3
    assert rep["cycles"] == []
    assert rep["counters"]["order_edges"] == 1


def test_opposite_order_across_threads_is_a_cycle():
    a, b = lw.Lock("A"), lw.Lock("B")
    with a:
        with b:
            pass
    def rev():
        with b:
            with a:
                pass
    _in_thread(rev)
    rep = lw.report()
    assert rep["cycles"] == [["A", "B"]]
    assert rep["counters"]["order_cycles"] == 1


def test_three_lock_cycle_detected():
    a, b, c = lw.Lock("A"), lw.Lock("B"), lw.Lock("C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    def close_loop():
        with c:
            with a:
                pass
    _in_thread(close_loop)
    assert lw.cycles() == [["A", "B", "C"]]


def test_reentrant_rlock_is_not_a_self_cycle():
    r = lw.RLock("R")
    with r:
        with r:
            with r:
                pass
    rep = lw.report()
    assert rep["edges"] == []
    assert rep["cycles"] == []


def test_reentry_does_not_fabricate_edges_to_other_locks():
    r, x = lw.RLock("R"), lw.Lock("X")
    with r:
        with x:
            with r:   # re-acquire while X held: adds no ordering info
                pass
    edges = {(e["from"], e["to"]) for e in lw.report()["edges"]}
    assert edges == {("R", "X")}


def test_note_blocking_records_held_locks():
    a = lw.Lock("A")
    lw.note_blocking("rpc:early")   # nothing held: no record
    with a:
        lw.note_blocking("rpc:scan")
    rep = lw.report()
    assert rep["held_across_blocking"] == [
        {"lock": "A", "op": "rpc:scan", "count": 1}]
    assert rep["counters"]["held_across_blocking"] == 1


def test_longest_held_tracked():
    a = lw.Lock("A")
    with a:
        pass
    held = {h["lock"]: h["max_held_ms"] for h in lw.report()["longest_held"]}
    assert "A" in held and held["A"] >= 0


def test_condition_wait_keeps_bookkeeping_balanced():
    r = lw.RLock("CV")
    cv = threading.Condition(r)
    woke = []

    def waiter():
        with cv:
            cv.wait(5.0)
            woke.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # wait() must release CV (so this acquire succeeds) and the waiter's
    # re-acquire must rebalance its per-thread held stack
    import time
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with cv:
            cv.notify_all()
        if woke:
            break
        time.sleep(0.01)
    t.join(5)
    assert woke == [1]
    assert lw.report()["cycles"] == []


def test_acquire_release_api_and_locked():
    a = lw.Lock("A")
    assert a.acquire(True, 1.0)
    assert a.locked()
    a.release()
    assert not a.locked()
    # failed non-blocking acquire must not corrupt the held stack
    with a:
        def contender():
            assert not a.acquire(False)
        _in_thread(contender)
    assert lw.report()["cycles"] == []


def test_counters_snapshot_shape():
    snap = lw.counters_snapshot()
    assert {"watched_locks", "acquires", "order_edges",
            "held_across_blocking", "order_cycles"} <= set(snap)
    assert all(isinstance(v, int) for v in snap.values())
