"""Per-query profiling plane: scoped QueryProfile, cross-thread/RPC
propagation, EXPLAIN ANALYZE breakdowns, slow-query log, /debug/profile,
and the streaming /metrics histograms (reference query_sql_process_ms +
DataFusion EXPLAIN ANALYZE metrics)."""
import json
import re
import threading
import time

import pytest

from cnosdb_tpu.errors import DeadlineExceeded, QueryError
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import DEFAULT_TENANT, MetaStore
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv
from cnosdb_tpu.utils import deadline as deadline_mod
from cnosdb_tpu.utils import executor as pool_mod
from cnosdb_tpu.utils import stages


# ------------------------------------------------------------------ units
def test_stage_and_count_land_in_active_profile():
    prof = stages.QueryProfile(qid="q1")
    with stages.profile_scope(prof):
        with stages.stage("decode_ms"):
            time.sleep(0.002)
        stages.count("scan_hit")
        stages.count("upload_bytes", 4096)
    snap = prof.snapshot()
    assert snap["decode_ms"] >= 1.0
    assert snap["scan_hit"] == 1
    assert snap["upload_bytes"] == 4096
    # outside any scope both are no-ops, not errors
    with stages.stage("decode_ms"):
        pass
    stages.count("scan_hit")
    assert prof.snapshot() == snap


def test_profile_scope_nesting_and_clear():
    outer = stages.QueryProfile()
    with stages.profile_scope(outer):
        assert stages.current_profile() is outer
        with stages.profile_scope(None):   # background work: bill nobody
            assert stages.current_profile() is None
            stages.count("scan_hit")
        assert stages.current_profile() is outer
    assert stages.current_profile() is None
    assert outer.snapshot() == {}


def test_merge_child_and_node_stages():
    parent = stages.QueryProfile(node_id=1)
    child = stages.QueryProfile(node_id=1)
    child.add_ms("kernel_ms", 5.0)
    child.add_count("group_count", 7)
    child.merge_remote({"node": 2, "ms": {"rpc_scan_vnode_ms": 3.0},
                        "counts": {"scan_miss": 1}})
    parent.merge_child(child)
    nodes = parent.node_stages()
    assert nodes["1"]["kernel_ms"] == 5.0
    assert nodes["1"]["group_count"] == 7
    assert nodes["2"]["rpc_scan_vnode_ms"] == 3.0
    totals = parent.stage_totals()
    assert totals["kernel_ms"] == 5.0 and totals["scan_miss"] == 1


def test_profile_ring_is_bounded_and_queryable():
    ring = stages.ProfileRing(capacity=8)
    for i in range(20):
        ring.record(stages.QueryProfile(qid=str(i)).finish(wall_ms=float(i)))
    assert len(ring.recent(limit=256)) == 8
    assert ring.get("19")["wall_ms"] == 19.0
    assert ring.get("0") is None          # evicted
    assert ring.recent(limit=3)[-1]["qid"] == "19"


# ----------------------------------------------- cross-thread propagation
def test_profile_and_trace_cross_pool_workers():
    """The classic contextvar loss: work submitted to the shared pools
    must keep billing the submitting query's profile and trace."""
    from cnosdb_tpu.server.trace import GLOBAL_COLLECTOR, current_trace_header

    prof = stages.QueryProfile()
    seen = []

    def task(i):
        stages.count("scan_hit")
        with stages.stage("decode_ms"):
            time.sleep(0.001)
        seen.append((threading.current_thread().name,
                     stages.current_profile(), current_trace_header()))
        return i

    with GLOBAL_COLLECTOR.span("query") as span:
        with stages.profile_scope(prof):
            out = pool_mod.run_all("decode", task, list(range(8)))
    assert out == list(range(8))
    snap = prof.snapshot()
    assert snap["scan_hit"] == 8, "counts lost crossing the pool boundary"
    assert snap["decode_ms"] >= 8 * 1.0
    workers = {name for name, _p, _t in seen}
    assert any(n != threading.current_thread().name for n in workers)
    for _name, p, hdr in seen:
        assert p is prof, "profile did not cross the pool boundary"
        assert hdr and hdr.startswith(span.trace_id + ":"), \
            "trace context did not cross the pool boundary"


# ----------------------------------------------------------- RPC envelope
def test_rpc_subprofile_round_trip():
    from cnosdb_tpu.parallel.net import RpcServer, rpc_call

    handler_profiled = []

    def handler(p):
        handler_profiled.append(stages.current_profile() is not None)
        with stages.stage("decode_ms"):
            time.sleep(0.002)
        stages.count("scan_miss")
        return {"ok": True, "vnode_id": p.get("vnode_id")}

    srv = RpcServer("127.0.0.1", 0, {"scan_vnode": handler},
                    node_id=7).start()
    try:
        # no profile in scope: no marker sent, handler runs unprofiled
        reply = rpc_call(srv.addr, "scan_vnode", {"vnode_id": 3})
        assert handler_profiled == [False]
        assert "_profile" not in reply
        prof = stages.QueryProfile(node_id=1)
        with stages.profile_scope(prof):
            reply = rpc_call(srv.addr, "scan_vnode", {"vnode_id": 3})
        assert handler_profiled == [False, True]
        assert "_profile" not in reply, "envelope must be stripped"
        assert len(prof.subprofiles) == 1
        sub = prof.subprofiles[0]
        assert sub["node"] == 7
        assert sub["method"] == "scan_vnode" and sub["vnode"] == 3
        assert sub["counts"]["scan_miss"] == 1
        assert sub["ms"]["decode_ms"] >= 1.0
        assert sub["ms"]["rpc_scan_vnode_ms"] >= sub["ms"]["decode_ms"]
        assert prof.node_stages()["7"]["scan_miss"] == 1
    finally:
        srv.stop()


# --------------------------------------------------------- EXPLAIN ANALYZE
@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield ex
    coord.close()


def _seed(db, n=200):
    db.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))")
    rows = ", ".join(f"({i * 10**9}, 'h{i % 4}', {i}.5)" for i in range(n))
    db.execute_one(f"INSERT INTO m (time, h, v) VALUES {rows}")


def _stage_rows(rs):
    """Parse `stage node=<n> name=<s> value=<v>` result rows →
    [(node, name, value)]."""
    out = []
    for line in rs.columns[0]:
        m = re.match(r"stage node=(\S+) name=(\S+) value=(\S+)", str(line))
        if m:
            out.append((m.group(1), m.group(2), float(m.group(3))))
    return out


def test_explain_analyze_renders_stage_and_device_rows(db):
    _seed(db)
    rs = db.execute_one(
        "EXPLAIN ANALYZE SELECT h, count(*), max(v) FROM m GROUP BY h")
    text = "\n".join(str(x) for x in rs.columns[0])
    assert "Execution: 4 rows" in text
    assert "TpuAggregateExec" in text
    rows = _stage_rows(rs)
    names = {n for _node, n, _v in rows}
    assert "kernel_ms" in names and "group_count" in names
    for _node, name, value in rows:
        assert name in stages.STAGE_CATALOG \
            or name.startswith(stages.DYNAMIC_STAGE_PREFIXES)
        assert value >= 0
    assert "device pallas_enabled=" in text


def test_explain_analyze_reconciles_with_scoped_profile(db):
    """The rendered breakdown and the ambient (bench-style) profile must
    agree: the inner profile folds into the outer, so per-stage sums
    reconcile within 10%."""
    _seed(db)
    db.execute_one("SELECT h, count(*) FROM m GROUP BY h")   # warm caches
    outer = stages.QueryProfile()
    with stages.profile_scope(outer):
        rs = db.execute_one(
            "EXPLAIN ANALYZE SELECT h, count(*), max(v) FROM m GROUP BY h")
    rendered: dict[str, float] = {}
    for _node, name, value in _stage_rows(rs):
        rendered[name] = rendered.get(name, 0.0) + value
    totals = outer.stage_totals()
    assert rendered, "no stage rows rendered"
    for name, value in rendered.items():
        got = totals.get(name, 0.0)
        assert abs(got - value) <= max(0.1 * value, 0.5), \
            f"{name}: EXPLAIN={value} vs profile={got}"


def test_profile_sealed_by_executor_and_ring_recorded(db):
    _seed(db, n=50)
    prof = stages.QueryProfile()
    with stages.profile_scope(prof):
        db.execute_one("SELECT count(*) FROM m")
    assert prof.qid is not None
    assert prof.wall_ms is not None and prof.wall_ms > 0
    assert prof.sql == "SELECT count(*) FROM m"
    assert "pallas_enabled" in prof.device
    d = stages.PROFILES.get(prof.qid)
    assert d is not None and d["wall_ms"] == prof.wall_ms


# ---------------------------------------------------------- slow-query log
def _slow_rows(db):
    db.slow_query_threshold_ms = 0
    rs = db.execute_one(
        "SELECT error, qid, sql FROM usage_schema.slow_queries")
    return list(zip(*(list(c) for c in rs.columns))) if rs.n_rows else []


def test_slow_query_log_threshold(db):
    _seed(db, n=50)
    db.slow_query_threshold_ms = 10_000   # nothing is that slow
    db.execute_one("SELECT count(*) FROM m")
    db.slow_query_threshold_ms = 1
    orig = db.execute_statement

    def slow_stmt(stmt, session):
        time.sleep(0.01)
        return orig(stmt, session)

    db.execute_statement = slow_stmt
    try:
        db.execute_one("SELECT max(v) FROM m")
    finally:
        db.execute_statement = orig
    rows = _slow_rows(db)
    assert rows, "threshold-exceeding query did not reach usage_schema"
    assert any("max(v)" in r[2] for r in rows)
    assert all("count(*)" not in r[2] for r in rows), \
        "query under threshold must not be logged"


def test_killed_and_deadline_exceeded_queries_still_log(db):
    """_finish_profile runs in execute_sql's finally: a query unwound by
    KILL or deadline expiry still lands in the slow-query log, with its
    error recorded."""
    _seed(db, n=50)
    db.slow_query_threshold_ms = 1
    orig = db.execute_statement

    def killed_stmt(stmt, session):
        qid = db._tls.qid
        db.tracker.kill(qid)                 # KILLed mid-flight
        time.sleep(0.01)
        db.tracker.check_cancelled(qid)      # raises: query killed
        return orig(stmt, session)

    db.execute_statement = killed_stmt
    try:
        with pytest.raises(QueryError):
            db.execute_one("SELECT min(v) FROM m")
    finally:
        db.execute_statement = orig

    def expired_stmt(stmt, session):
        time.sleep(0.01)
        deadline_mod.check_current()         # raises DeadlineExceeded
        return orig(stmt, session)

    db.slow_query_threshold_ms = 1
    db.execute_statement = expired_stmt
    try:
        with pytest.raises(DeadlineExceeded):
            with deadline_mod.scope(deadline_mod.Deadline(0.001)):
                db.execute_one("SELECT sum(v) FROM m")
    finally:
        db.execute_statement = orig
    rows = _slow_rows(db)
    errors = [r[0] for r in rows]
    assert any("killed" in e.lower() or "cancel" in e.lower()
               for e in errors), errors
    assert any("DeadlineExceeded" in e for e in errors), errors


# --------------------------------------------------- HTTP plane + metrics
@pytest.fixture
def http(tmp_path):
    from test_deadline import _Harness

    h = _Harness(str(tmp_path / "srv"))
    yield h
    h.close()


def _seed_http(h, n=40):
    lines = "\n".join(
        f"cpu,host=h{i % 4} usage={i}.5 {1672531200000000000 + i * 10**9}"
        for i in range(n))
    status, body, _ = h.request("POST", "/api/v1/write?db=public", lines)
    assert status == 200, body


def test_http_profile_header_and_debug_profile(http):
    _seed_http(http)
    # without the header: no summary
    status, _body, hdrs = http.request(
        "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
    assert status == 200 and "X-CnosDB-Profile-Summary" not in hdrs
    # opt-in: compact summary on the response
    status, _body, hdrs = http.request(
        "POST", "/api/v1/sql?db=public",
        "SELECT host, max(usage) FROM cpu GROUP BY host",
        headers={"X-CnosDB-Profile": "1"})
    assert status == 200
    summary = json.loads(hdrs["X-CnosDB-Profile-Summary"])
    assert summary["wall_ms"] > 0
    assert summary["stages"].get("group_count") == 4
    qid = summary["qid"]
    # full profile from the bounded ring
    status, body, _ = http.request("GET", f"/debug/profile?qid={qid}")
    assert status == 200
    full = json.loads(body)
    assert full["qid"] == qid and full["counts"]["group_count"] == 4
    assert "pallas_enabled" in full["device"]
    status, body, _ = http.request("GET", "/debug/profile")
    recents = json.loads(body)
    assert any(d["qid"] == qid for d in recents)
    status, body, _ = http.request("GET", "/debug/profile?qid=nope")
    assert status == 404


# A strict (small) Prometheus text-format checker: every line must be a
# comment or `name{labels} value`; histograms must expose cumulative
# monotone buckets ending in +Inf == _count, plus _sum/_count.
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'
    r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$')


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def _check_prometheus(text: str):
    types: dict[str, str] = {}
    samples: list[tuple[str, str, float]] = []
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[0] == "#" and parts[1] in ("TYPE", "HELP"), line
            if parts[1] == "TYPE":
                assert parts[3] in ("counter", "gauge", "histogram",
                                    "summary", "untyped"), line
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        samples.append((m.group(1), m.group(2) or "", float(m.group(4))))
    # histogram families: cumulative buckets + _sum + _count per series
    for fam, t in types.items():
        if t != "histogram":
            continue
        by_series: dict[tuple, list] = {}
        sums, counts = {}, {}
        for name, labels, v in samples:
            pairs = dict(_LABEL_RE.findall(labels))
            le = pairs.pop("le", None)
            key = tuple(sorted(pairs.items()))
            if name == f"{fam}_bucket":
                assert le is not None, f"bucket sample without le: {labels}"
                by_series.setdefault(key, []).append((le, v))
            elif name == f"{fam}_sum":
                sums[key] = v
            elif name == f"{fam}_count":
                counts[key] = v
        assert by_series, f"histogram {fam} has no buckets"
        for key, buckets in by_series.items():
            values = [v for _le, v in buckets]
            assert values == sorted(values), \
                f"{fam}{key}: buckets not cumulative: {buckets}"
            assert buckets[-1][0] == "+Inf"
            assert buckets[-1][1] == counts.get(key), \
                f"{fam}{key}: +Inf bucket != _count"
            assert key in sums, f"{fam}{key}: missing _sum"
    return types, samples


def test_metrics_endpoint_full_prometheus_parse(http):
    _seed_http(http)
    for _ in range(3):
        status, _b, _h = http.request(
            "POST", "/api/v1/sql?db=public", "SELECT count(*) FROM cpu")
        assert status == 200
    status, text, _ = http.request("GET", "/metrics")
    assert status == 200
    types, samples = _check_prometheus(text)
    names = {n for n, _l, _v in samples}
    assert "cnosdb_http_queries_total" in names
    # the SQL latency histogram engaged and checks out strictly
    assert types.get("cnosdb_query_sql_process_ms") == "histogram"
    cnt = [v for n, _l, v in samples
           if n == "cnosdb_query_sql_process_ms_count"]
    assert cnt and cnt[0] >= 3


def test_histogram_memory_bounded_under_soak():
    """100k observations must not grow per-sample state (the old
    implementation appended every value to a list forever)."""
    from cnosdb_tpu.server.metrics import MetricsRegistry

    reg = MetricsRegistry()
    n = 100_000
    for i in range(n):
        reg.observe("cnosdb_soak_ms", (i % 1000) / 10.0, route="q")
    hists = list(reg._histograms.values())
    assert len(hists) == 1
    h = hists[0]
    assert not hasattr(h, "append"), "histogram state must not be a list"
    assert len(h.buckets) == len(reg._hist_bounds)
    assert h.count == n
    assert h.total == pytest.approx(sum((i % 1000) / 10.0
                                        for i in range(1000)) * (n // 1000))
    text = reg.prometheus_text()
    _check_prometheus(text)
    # spot-check one cumulative bucket against the definition
    m = re.search(r'cnosdb_soak_ms_bucket\{route="q",le="5"\} (\d+)', text)
    # values are (i % 1000)/10 ∈ [0, 99.9]; ≤5 → i%1000 ∈ [0, 50] → 51/1000
    assert m and int(m.group(1)) == 51 * (n // 1000)


# ------------------------------------------------------- cluster breakdown
@pytest.mark.cluster
def test_explain_analyze_cluster_per_node_breakdown(tmp_path):
    """EXPLAIN ANALYZE on a multi-vnode distributed query: stage rows for
    every participating node, reconciling with the request's profile
    totals within 10%."""
    import base64
    import urllib.request

    from cluster_harness import Cluster

    c = Cluster(str(tmp_path / "cl"), n_nodes=2).start()
    try:
        n1 = c.nodes[0]
        n1.sql("CREATE DATABASE d1 WITH SHARD 4 REPLICA 1", db="public")
        lines = "\n".join(
            f"cpu,host=h{i} usage={i}.5 {1_700_000_000_000_000_000 + i * 10**3}"
            for i in range(64))
        n1.write_lp(lines, db="d1")

        def sql_with_profile(q):
            req = urllib.request.Request(
                f"http://127.0.0.1:{n1.http_port}/api/v1/sql?db=d1",
                data=q.encode(), method="POST",
                headers={"Authorization": "Basic "
                         + base64.b64encode(b"root:").decode(),
                         "X-CnosDB-Profile": "1"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read().decode(), dict(resp.headers)

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                body, _h = sql_with_profile("SELECT count(*) FROM cpu")
                if body.strip().splitlines()[-1] == "64":
                    break
            except Exception:
                pass
            time.sleep(0.3)
        body, hdrs = sql_with_profile(
            "EXPLAIN ANALYZE SELECT host, max(usage) FROM cpu GROUP BY host")
        rows = []
        for line in body.splitlines():
            m = re.match(r'"?stage node=(\S+) name=(\S+) value=([\d.]+)"?',
                         line)
            if m:
                rows.append((m.group(1), m.group(2), float(m.group(3))))
        assert rows, f"no stage rows in:\n{body}"
        nodes = {node for node, _n, _v in rows}
        assert len(nodes) >= 2, \
            f"expected per-node attribution across the cluster, got {nodes}"
        remote = [n for n, name, _v in rows if name.startswith("rpc_")]
        assert remote, "remote nodes must report rpc_* handler stages"
        # reconcile the rendered rows against the request profile summary
        summary = json.loads(hdrs["X-CnosDB-Profile-Summary"])
        totals = summary["stages"]
        rendered: dict[str, float] = {}
        for _node, name, value in rows:
            rendered[name] = rendered.get(name, 0.0) + value
        for name, value in rendered.items():
            got = totals.get(name, 0.0)
            assert abs(got - value) <= max(0.1 * value, 0.5), \
                f"{name}: EXPLAIN={value} vs profile={got} ({totals})"
    finally:
        c.stop()
