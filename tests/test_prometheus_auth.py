"""Prometheus remote write, auth enforcement, query tracker, TTL expiry."""
import struct
import time

import numpy as np
import pytest

from cnosdb_tpu.models.schema import DatabaseOptions, DatabaseSchema, Duration
from cnosdb_tpu.parallel.coordinator import Coordinator
from cnosdb_tpu.parallel.meta import MetaStore, DEFAULT_TENANT
from cnosdb_tpu.protocol import prometheus as prom
from cnosdb_tpu.sql.executor import QueryExecutor, Session
from cnosdb_tpu.storage.engine import TsKv


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(field_no: int, payload: bytes) -> bytes:
    return _varint((field_no << 3) | 2) + _varint(len(payload)) + payload


def _label(name: str, value: str) -> bytes:
    return _ld(1, name.encode()) + _ld(2, value.encode())


def _sample(value: float, ts_ms: int) -> bytes:
    return (_varint((1 << 3) | 1) + struct.pack("<d", value)
            + _varint(2 << 3) + _varint(ts_ms & (2**64 - 1)))


def _write_request() -> bytes:
    ts1 = (_ld(1, _label("__name__", "node_cpu")) + _ld(1, _label("host", "a"))
           + _ld(2, _sample(0.5, 1000)) + _ld(2, _sample(0.7, 2000)))
    ts2 = (_ld(1, _label("__name__", "node_mem")) + _ld(1, _label("host", "a"))
           + _ld(2, _sample(100.0, 1000)))
    return _ld(1, ts1) + _ld(1, ts2)


def test_prom_parse_remote_write():
    if not prom.snappy_available():
        pytest.skip("libsnappy not present")
    body = prom.snappy_compress(_write_request())
    wb = prom.parse_remote_write(body)
    assert set(wb.tables) == {"node_cpu", "node_mem"}
    sr = wb.tables["node_cpu"][0]
    assert sr.key.tag_value("host") == "a"
    assert sr.timestamps == [1000 * 10**6, 2000 * 10**6]
    assert sr.fields["value"][1] == [0.5, 0.7]


@pytest.fixture
def db(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    coord = Coordinator(meta, engine)
    ex = QueryExecutor(meta, coord)
    yield meta, coord, ex
    coord.close()


def test_prom_end_to_end(db):
    meta, coord, ex = db
    if not prom.snappy_available():
        pytest.skip("libsnappy not present")
    wb = prom.parse_remote_write(prom.snappy_compress(_write_request()))
    coord.write_points(DEFAULT_TENANT, "public", wb)
    rs = ex.execute_one("SELECT count(*) AS c, max(value) AS m FROM node_cpu")
    assert rs.rows()[0] == (2, 0.7)


def test_show_queries_and_kill(db):
    meta, coord, ex = db
    # a registered query shows up while running: simulate by registering
    qid = ex.tracker.register("SELECT 1", Session())
    rs = ex.execute_one("SHOW QUERIES")
    assert qid in rs.columns[0].tolist()
    ok = ex.execute_one(f"KILL QUERY {qid}")
    assert ok.columns[0][0] == "ok"
    with pytest.raises(Exception):
        ex.tracker.check_cancelled(qid)
    ex.tracker.finish(qid)


def test_ttl_bucket_expiry(db):
    meta, coord, ex = db
    meta.create_database(DatabaseSchema(
        DEFAULT_TENANT, "short", DatabaseOptions(
            ttl=Duration.parse("1d"), vnode_duration=Duration.parse("1h"))))
    s = Session(database="short")
    ex.execute_one("CREATE TABLE m (v DOUBLE, TAGS(h))", s)
    now = int(time.time() * 1e9)
    # writes below now - ttl are REJECTED at bucket creation (reference
    # "create expired bucket"), so build two buckets inside the TTL and
    # age one out by advancing the expiry clock instead
    old = now - 12 * 3_600_000_000_000   # 12h ago, within the 1d TTL
    ex.execute_one(f"INSERT INTO m (time, h, v) VALUES ({old}, 'a', 1), ({now}, 'a', 2)", s)
    assert len(meta.buckets_for(DEFAULT_TENANT, "short")) == 2
    expired = meta.expire_buckets(DEFAULT_TENANT, "short",
                                  now + 86_400_000_000_000)
    assert len(expired) == 1
    owner = f"{DEFAULT_TENANT}.short"
    for rs_ in expired[0].shard_group:
        for v in rs_.vnodes:
            coord.engine.drop_vnode(owner, v.id)
    rs = ex.execute_one("SELECT count(*) AS c FROM m", s)
    assert rs.columns[0][0] == 1  # old bucket gone, recent row remains


def test_http_auth_enforced(tmp_path):
    import sys
    sys.path.insert(0, "tests")
    from test_protocols_http import _HttpHarness

    h = _HttpHarness.__new__(_HttpHarness)
    import asyncio, socket, threading
    from cnosdb_tpu.server.http import build_server

    h.server = build_server(str(tmp_path / "srv"), auth_enabled=True)
    h.server.meta.create_user("alice", "pw123")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        h.port = s.getsockname()[1]
    h._loop = asyncio.new_event_loop()
    h._started = threading.Event()

    def run():
        asyncio.set_event_loop(h._loop)

        async def boot():
            h._runner = await h.server.start("127.0.0.1", h.port)
            h._started.set()
        h._loop.create_task(boot())
        h._loop.run_forever()

    h._thread = threading.Thread(target=run, daemon=True)
    h._thread.start()
    assert h._started.wait(10)
    try:
        import base64

        status, _ = h.request("POST", "/api/v1/sql?db=public", "SELECT 1")
        assert status == 401
        tok = base64.b64encode(b"alice:wrong").decode()
        status, _ = h.request("POST", "/api/v1/sql?db=public", "SELECT 1",
                              headers={"Authorization": f"Basic {tok}"})
        assert status == 401
        tok = base64.b64encode(b"alice:pw123").decode()
        status, _ = h.request("POST", "/api/v1/sql?db=public", "SELECT 1",
                              headers={"Authorization": f"Basic {tok}"})
        assert status == 200
    finally:
        h.close()
