"""Tier-1 invariant gate: the unified lint engine over the whole package.

Replaces the old ad-hoc AST tests (test_no_bare_except.py,
test_no_row_loops.py) with one entry point. The engine runs ONCE at
collection; each (rule, file) cell that carries findings or baseline
budget gets its own test id, so a regression reads as e.g.

    test_invariants.py::test_cell[swallowed-exception:cnosdb_tpu/parallel/raft.py]

Fixing baselined debt also fails (stale baseline) until the fix is
locked in with `python -m cnosdb_tpu.analysis --fix-baseline`.
"""
import json
import os
import subprocess
import sys

import pytest

from cnosdb_tpu import analysis
from cnosdb_tpu.analysis import rules as rules_mod

_REPORT = analysis.run()
_RULES = sorted(r.name for r in rules_mod.all_rules())

# every (rule, file) cell with current findings or baseline budget gets
# a stable test id; rules with neither get one "(clean)" id
_CELLS = sorted(set(_REPORT.counts) | set(_REPORT.baseline))
_PARAMS = []
for rule in _RULES:
    files = [p for (r, p) in _CELLS if r == rule]
    for p in files or ["(clean)"]:
        _PARAMS.append((rule, p))


@pytest.mark.parametrize("rule,path", _PARAMS,
                         ids=[f"{r}:{p}" for r, p in _PARAMS])
def test_cell(rule, path):
    if path == "(clean)":
        hits = [f for f in _REPORT.findings if f.rule == rule]
        assert hits == [], [f.render() for f in hits]
        return
    found = _REPORT.counts.get((rule, path), 0)
    allowed = _REPORT.baseline.get((rule, path), 0)
    cell = [f.render() for f in _REPORT.findings
            if f.rule == rule and f.path == path]
    assert found <= allowed, (
        f"{found} finding(s), baseline allows {allowed}:\n" + "\n".join(cell))
    assert found >= allowed, (
        f"baseline stale: {allowed} allowed but {found} found — lock the "
        f"fix in with `python -m cnosdb_tpu.analysis --fix-baseline`")


def test_whole_tree_ok():
    assert _REPORT.ok, (
        [f.render() for f in _REPORT.violations],
        _REPORT.stale)


def test_no_unknown_rules_in_baseline():
    known = set(_RULES)
    assert {r for (r, _p) in _REPORT.baseline} <= known


def test_cli_json_gate(tmp_path):
    """The CI entry point: `python -m cnosdb_tpu.analysis --json` must
    exit 0 on the tree, report machine-readable state, and write the run
    artifact carrying the cnosdb_analysis_findings_total{rule} gauge."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        analysis.__file__)))
    artifact = str(tmp_path / "analysis_report.json")
    p = subprocess.run([sys.executable, "-m", "cnosdb_tpu.analysis",
                        "--json", "--artifact", artifact],
                       capture_output=True, text=True, cwd=repo, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    rep = json.loads(p.stdout)
    assert rep["ok"] is True
    assert rep["violations"] == []
    with open(artifact, encoding="utf-8") as f:
        art = json.load(f)
    totals = art["metrics"]["cnosdb_analysis_findings_total"]
    # zero-filled per-rule gauge: every registered rule gets a label so
    # CI diffs are one-line readable even when a rule is clean
    assert set(_RULES) <= set(totals)
    for rule in ("host-sync", "recompile-hazard", "lock-held-dispatch",
                 "deadline-propagation"):
        assert totals[rule] == 0, (rule, totals)
    assert art["metrics"]["cnosdb_analysis_wall_ms"] > 0
