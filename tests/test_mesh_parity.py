"""Mesh execution plane (ops/mesh_exec.py + parallel/distributed_agg.py).

Parity contract: everything the mesh lane merges through XLA collectives
must be BIT-identical to the legacy per-batch kernel fan-out +
`_merge_results_vec` host merge — including f64 sum association (the
run-aware reduceat staging), NULL/NaN propagation, first/last tie-breaks
and output dtypes. The suite A/Bs whole queries against `CNOSDB_MESH=0`
on the 8-virtual-device CPU mesh the conftest forces, checks a numpy
oracle on the order-insensitive aggregates, and drives the nemesis
`device_loss` fault through the lane's transparent host-merge fallback.

Counters double as the no-host-hops proof: an engaged query must book
(merge, collective) and nothing else — any msgpack host merge would
surface as a decline reason instead.
"""
import numpy as np
import pytest

from cnosdb_tpu import faults
from cnosdb_tpu.parallel import mesh

BASE = 1_700_000_000_000_000_000
MINUTE = 60_000_000_000


@pytest.fixture
def db(tmp_path, monkeypatch):
    """4-shard database so scans produce multiple mesh-local batches;
    thresholds opened so small test tables engage; serving cache off so
    every execute_one actually runs the lane."""
    monkeypatch.setenv("CNOSDB_SERVING", "0")
    monkeypatch.setenv("CNOSDB_MESH", "1")
    monkeypatch.setenv("CNOSDB_MESH_MIN_ROWS", "0")
    monkeypatch.setenv("CNOSDB_MESH_MIN_DEVICES", "2")
    from cnosdb_tpu.parallel.coordinator import Coordinator
    from cnosdb_tpu.parallel.meta import MetaStore
    from cnosdb_tpu.sql.executor import QueryExecutor, Session
    from cnosdb_tpu.storage.engine import TsKv

    meta = MetaStore(str(tmp_path / "meta.json"))
    engine = TsKv(str(tmp_path / "data"))
    ex = QueryExecutor(meta, Coordinator(meta, engine))
    ex.execute_one("CREATE DATABASE mesh WITH SHARD 4 REPLICA 1")
    yield ex, Session(database="mesh")
    engine.close()


def _run_all(ex, s, queries):
    """repr-compare columns so NaN/-0.0/dtype differences all surface."""
    outs = []
    for q in queries:
        rs = ex.execute_one(q, s)
        outs.append((rs.names, [repr(c.tolist()) for c in rs.columns],
                     [str(c.dtype) for c in rs.columns]))
    return outs


def _ab(ex, s, queries, monkeypatch, expect_engaged=None):
    """Mesh pass first (counters asserted), then CNOSDB_MESH=0 oracle;
    every query must match byte-for-byte."""
    mesh.reset_counters()
    got = _run_all(ex, s, queries)
    snap = mesh.outcomes_snapshot()
    engaged = snap.get(("exec", "engaged"), 0)
    if expect_engaged is not None:
        assert engaged == expect_engaged, snap
    else:
        assert engaged > 0, snap
    # the no-host-hops proof: every engaged merge went collective
    assert snap.get(("merge", "collective"), 0) == engaged, snap
    assert snap.get(("merge", "host"), 0) == 0, snap
    monkeypatch.setenv("CNOSDB_MESH", "0")
    legacy = _run_all(ex, s, queries)
    monkeypatch.setenv("CNOSDB_MESH", "1")
    for q, a, b in zip(queries, got, legacy):
        assert a == b, q
    return got


@pytest.fixture
def seeded(db):
    """2000 rows, 16 hosts x 3 regions, normal floats + small ints."""
    ex, s = db
    ex.execute_one("CREATE TABLE m (v DOUBLE, i BIGINT, "
                   "TAGS(host, region))", s)
    rng = np.random.default_rng(7)
    rows = []
    for i in range(2000):
        rows.append((BASE + i * MINUTE, f"h{i % 16}", f"r{i % 3}",
                     float(rng.standard_normal()),
                     int(rng.integers(0, 100))))
    vals = ", ".join(f"({t}, '{h}', '{r}', {v!r}, {iv})"
                     for t, h, r, v, iv in rows)
    ex.execute_one(f"INSERT INTO m (time, host, region, v, i) "
                   f"VALUES {vals}", s)
    return ex, s, rows


TAG_QUERIES = [
    "SELECT host, count(*) AS c, sum(v) AS sv, min(v) AS mn, "
    "max(i) AS mx FROM m GROUP BY host",
    "SELECT host, region, first(v) AS f, last(v) AS l FROM m "
    "GROUP BY host, region",
    "SELECT date_bin(INTERVAL '1 hour', time) AS t, sum(v) AS sv, "
    "count(i) AS c FROM m GROUP BY t",
    "SELECT host, date_bin(INTERVAL '2 hour', time) AS t, sum(v) sv, "
    "first(i) f FROM m GROUP BY host, t",
    "SELECT count(*) AS c, sum(v) AS sv FROM m",
    "SELECT host, sum(v) sv FROM m WHERE v > 0 GROUP BY host",
    "SELECT host, avg(v) a FROM m GROUP BY host",
    "SELECT host, min(i) mn, max(v) mx, last(i) l FROM m "
    "WHERE region = 'r1' GROUP BY host",
    "SELECT host, sum(v) sv, first(v) f FROM m GROUP BY host",
]


def test_tag_groupby_bit_parity(seeded, monkeypatch):
    """Every shape the lane owns engages and matches the legacy merge
    byte-for-byte: tag group-by, date_bin buckets, global aggregates,
    filters, avg rewrite, f64 sums, first/last."""
    ex, s, _rows = seeded
    _ab(ex, s, TAG_QUERIES, monkeypatch,
        expect_engaged=len(TAG_QUERIES))


def test_numpy_oracle_order_insensitive_aggs(seeded, monkeypatch):
    """count / integer sum / min / max / first / last per host against a
    pure-python+numpy oracle over the inserted rows — these aggregates
    are association-free, so the oracle equality is exact, not approx."""
    ex, s, rows = seeded
    mesh.reset_counters()
    rs = ex.execute_one(
        "SELECT host, count(*) c, sum(i) si, min(v) mn, max(v) mx, "
        "first(v) f, last(v) l FROM m GROUP BY host ORDER BY host", s)
    assert mesh.outcomes_snapshot().get(("exec", "engaged")) == 1
    by_host: dict = {}
    for t, h, _r, v, iv in rows:
        by_host.setdefault(h, []).append((t, v, iv))
    got = list(zip(*[c.tolist() for c in rs.columns]))
    assert [g[0] for g in got] == sorted(by_host)
    for h, c, si, mn, mx, f, last in got:
        ent = by_host[h]
        assert c == len(ent)
        assert si == sum(iv for _t, _v, iv in ent)
        assert mn == min(v for _t, v, _iv in ent)
        assert mx == max(v for _t, v, _iv in ent)
        assert f == min(ent)[1]      # value at earliest timestamp
        assert last == max(ent)[1]   # value at latest timestamp


def test_null_nan_string_dictionary_parity(db, monkeypatch):
    """NULL runs in values, real NaN payloads (0.0/0.0), NULL string
    group keys through the dictionary path (CNOSDB_MESH_FIELDS=1 with
    ORDER BY pinning row order), DISTINCT declining to the legacy lane,
    and a single-vnode filter falling back — all byte-identical."""
    monkeypatch.setenv("CNOSDB_MESH_FIELDS", "1")
    ex, s = db
    ex.execute_one("CREATE TABLE m (v DOUBLE, i BIGINT, w DOUBLE, "
                   "s STRING, TAGS(host))", s)
    rng = np.random.default_rng(11)
    parts = []
    for i in range(1200):
        t = BASE + i * MINUTE
        v = "NULL" if i % 5 == 0 else repr(float(rng.standard_normal()))
        w = "(0.0/0.0)" if i % 7 == 0 else \
            repr(float(rng.standard_normal()))
        iv = "NULL" if i % 11 == 0 else str(int(rng.integers(-5, 5)))
        sv = "NULL" if i % 13 == 0 else f"'s{i % 3}'"
        parts.append(f"({t}, 'h{i % 8}', {v}, {iv}, {w}, {sv})")
    ex.execute_one("INSERT INTO m (time, host, v, i, w, s) VALUES "
                   + ", ".join(parts), s)
    queries = [
        "SELECT host, count(v) c, sum(v) sv, min(v) mn, max(v) mx "
        "FROM m GROUP BY host",
        "SELECT host, sum(w) sw, min(w) mn, max(w) mx FROM m "
        "GROUP BY host",
        "SELECT host, first(v) f, last(v) l, sum(i) si FROM m "
        "GROUP BY host",
        "SELECT s, sum(v) sv, count(*) c FROM m GROUP BY s ORDER BY s",
        "SELECT host, s, avg(v) a FROM m GROUP BY host, s "
        "ORDER BY host, s",
        "SELECT host, sum(v) sv FROM m WHERE i > 100 GROUP BY host",
        "SELECT host, sum(v) sv FROM m WHERE host = 'h3' GROUP BY host",
        "SELECT host, count(DISTINCT s) cd FROM m GROUP BY host",
        "SELECT host, sum(v) sv, first(w) fw FROM m "
        "WHERE v IS NOT NULL GROUP BY host",
    ]
    _ab(ex, s, queries, monkeypatch)


def test_mesh_off_books_disabled_and_never_engages(seeded, monkeypatch):
    """CNOSDB_MESH=0 is the byte-identical legacy path: the lane books
    only `disabled` declines, and repeated runs are bytewise stable."""
    ex, s, _rows = seeded
    monkeypatch.setenv("CNOSDB_MESH", "0")
    mesh.reset_counters()
    a = _run_all(ex, s, TAG_QUERIES[:3])
    b = _run_all(ex, s, TAG_QUERIES[:3])
    snap = mesh.outcomes_snapshot()
    assert a == b
    assert snap.get(("exec", "engaged"), 0) == 0, snap
    assert snap.get(("exec", "disabled"), 0) == 6, snap


def test_device_loss_falls_back_bit_identical(seeded, monkeypatch):
    """The nemesis `device_loss` injection (mesh.collective:fail) kills
    the merge kernel mid-collective: the lane must book device_loss,
    answer through the legacy host merge byte-identically, and re-engage
    once healed."""
    ex, s, _rows = seeded
    q = TAG_QUERIES[0]
    mesh.reset_counters()
    base = _run_all(ex, s, [q])
    assert mesh.outcomes_snapshot().get(("exec", "engaged")) == 1
    faults.configure("seed=1;mesh.collective:fail")
    try:
        mesh.reset_counters()
        faulted = _run_all(ex, s, [q])
        snap = mesh.outcomes_snapshot()
        assert snap.get(("exec", "device_loss")) == 1, snap
        assert snap.get(("exec", "engaged"), 0) == 0, snap
        assert faulted == base
    finally:
        faults.configure("seed=1")
    mesh.reset_counters()
    healed = _run_all(ex, s, [q])
    assert mesh.outcomes_snapshot().get(("exec", "engaged")) == 1
    assert healed == base


def test_nemesis_device_loss_plan_and_specs():
    """device_loss is a first-class nemesis kind: seeded plans include
    it, its spec arms the mesh.collective fault point on the victim only,
    and heal keeps the control surface armed (bare seed, not "")."""
    from cnosdb_tpu.chaos import nemesis

    plan = nemesis.generate_plan(31, n_nodes=3, steps=6,
                                 kinds=("device_loss",))
    assert plan == nemesis.generate_plan(31, n_nodes=3, steps=6,
                                         kinds=("device_loss",))
    assert all(ev.kind == "device_loss" for ev in plan)
    ev = plan[0]
    vspec, ospec = nemesis.event_specs(ev, "127.0.0.1:9999", 31)
    assert vspec == f"seed={31 + ev.step};mesh.collective:fail"
    assert ospec == ""
    assert nemesis.heal_spec(31, ev) == f"seed={31 + ev.step}"
    assert "device_loss" in nemesis.KINDS
