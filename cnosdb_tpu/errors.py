"""Error taxonomy.

Mirrors the reference's per-crate error enums (e.g. tskv/src/error.rs,
meta/src/error.rs, query_server/spi/src/lib.rs QueryError) collapsed into a
single hierarchy with stable error codes, matching the numbered error-code
scheme the reference derives via derive_traits/error_code.
"""
from __future__ import annotations


class CnosError(Exception):
    """Base error. `code` is a stable string like the reference's 010001."""

    code = "000000"

    def __init__(self, message: str = "", **ctx):
        self.message = message
        self.ctx = ctx
        super().__init__(message)

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        if self.ctx:
            kv = ", ".join(f"{k}={v!r}" for k, v in self.ctx.items())
            return f"[{self.code}] {self.message} ({kv})"
        return f"[{self.code}] {self.message}"


class ConfigError(CnosError):
    code = "010001"


class MetaError(CnosError):
    code = "020001"


class TenantNotFound(MetaError):
    code = "020002"


class DatabaseNotFound(MetaError):
    code = "020003"


class DatabaseAlreadyExists(MetaError):
    code = "020004"


class TableNotFound(MetaError):
    code = "020005"


class TableAlreadyExists(MetaError):
    code = "020006"


class BucketNotFound(MetaError):
    code = "020007"


class StorageError(CnosError):
    code = "030001"


class WalError(StorageError):
    code = "030002"


class TsmError(StorageError):
    code = "030003"


class ChecksumMismatch(StorageError):
    code = "030004"


class CodecError(StorageError):
    code = "030005"


class IndexError_(StorageError):
    code = "030006"


class SchemaError(CnosError):
    code = "040001"


class FieldTypeMismatch(SchemaError):
    code = "040002"


class ColumnNotFound(SchemaError):
    code = "040003"


class QueryError(CnosError):
    code = "050001"


class ParserError(QueryError):
    code = "050002"


class PlanError(QueryError):
    code = "050003"


class ExecutionError(QueryError):
    code = "050004"


class FunctionError(QueryError):
    code = "050005"


class CoordinatorError(CnosError):
    code = "060001"


class ReplicationError(CnosError):
    code = "070001"


class AuthError(CnosError):
    code = "080001"


class LimiterError(CnosError):
    """Per-tenant rate/quota budget exhausted. HTTP 429 + Retry-After:
    only THIS tenant needs to back off (contrast AdmissionRejected)."""

    code = "090001"

    def __init__(self, message: str = "", retry_after: float = 1.0, **ctx):
        super().__init__(message, **ctx)
        self.retry_after = retry_after


class DeadlineExceeded(CnosError):
    """Request ran past its deadline budget (header or config timeout).

    Deliberately NOT a QueryError subclass: retry/failover loops that
    swallow query- or RPC-level errors must not absorb it — once the
    budget is gone the only correct move is to unwind to the client
    (HTTP 504)."""

    code = "100001"


class AdmissionRejected(CnosError):
    """Shed by the per-node admission gate (queue full, or queue wait
    would outlive the request's own deadline). HTTP 503 + Retry-After —
    distinct from the per-tenant LimiterError 429."""

    code = "100002"

    def __init__(self, message: str = "", retry_after: float = 1.0, **ctx):
        super().__init__(message, **ctx)
        self.retry_after = retry_after


class MemoryExceeded(CnosError):
    """A single request outgrew its memory budget (per-query kill), or
    the node is above its hard memory watermark and must fail closed.

    Deliberately NOT a QueryError subclass, for the same reason as
    DeadlineExceeded: retry/failover loops must not absorb it — the
    request itself is the problem and retrying it elsewhere just moves
    the OOM. HTTP 413 (payload too large — the request, not the node,
    is oversized), so clients can tell it apart from the node-saturated
    503."""

    code = "100003"


class WriteBackpressure(AdmissionRejected):
    """Write shed by memory backpressure: the broker delayed the write
    waiting for flush progress, the delay budget ran out, and the node
    is still above its soft watermark. HTTP 503 + Retry-After (derived
    from flush progress) like its parent, but counted separately
    (cnosdb_requests_backpressured_total) so dashboards can tell a
    memory squeeze from an admission-queue overflow."""

    code = "100004"
