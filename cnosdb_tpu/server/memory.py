"""Memory-governance plane: one broker arbitrating every byte pool.

The reference bounds memory with a single GreedyMemoryPool
(common/memory_pool) gating writes and DataFusion queries; everything
else — caches, memcaches, group state — trusts its own independent cap
and the node OOMs when the caps add up past physical RAM under a burst
of wide group-bys plus an ingest spike. This module is the rebuild's
arbitration layer (the Taurus shared-node argument, PAPERS.md
2506.20010): a process-global :class:`MemoryBroker` with named,
accounted pools registered by each subsystem —

  ==============  =======================================  ==========
  pool            feeder (usage_fn / book)                 reclaim
  ==============  =======================================  ==========
  memcache        engine vnodes: active+immutable caches   flush
                  (dtype-aware bytes, unflushed WAL rows)
  scan_cache      coordinator ScanToken-keyed snapshots    LRU evict
  block_cache     cold-tier decoded block cache            clear
  serving         plan cache + result cache                evict
  agg_memo        per-batch partial-agg memos (tpu_exec)   clear
  query_groups    live aggregation accumulators (booked    (spills
                  by executor group spillers)               itself)
  device_uploads  live DeviceBatch uploads (die with       —
                  their scan batch)
  ==============  =======================================  ==========

and a deterministic degradation ladder over a soft/hard watermark pair:

  1. above **soft** — reclaim evictable pools via their callbacks,
     largest usage first, until back under soft;
  2. still above soft — shed *queued* (never running) queries through
     the admission gate with 503 + Retry-After;
  3. write path — bounded delay below the hard watermark (waiting for
     flush progress; sheds WriteBackpressure/503 when the delay budget
     runs out), fail-closed MemoryExceeded above it. The raft /
     heartbeat plane is NEVER touched: backpressure applies at
     `Coordinator.write_points` (user ingress) only, so replication and
     elections keep making progress while clients back off.

Per-query accounting rides the existing Deadline plumbing (PR 4): a
:class:`QueryMemory` hangs off the ambient deadline, every large
materialization site (scan assembly, RPC result buffers, group state)
charges it, and crossing the per-query budget raises a typed
MemoryExceeded (HTTP 413) that kills only the oversized query.

Master gate: CNOSDB_MEMORY=0 disables the whole plane — no pool reads,
no ladder, byte-identical legacy behavior. Below the soft watermark the
plane only *observes* (usage_fn reads), so untriggered behavior is
bit-identical by construction.

Observability: cnosdb_memory_total{pool,action} counters + a bounded
ring of recent reclaim/shed events, folded into /metrics and served by
GET /debug/memory.
"""
from __future__ import annotations

import os
import time
from collections import deque

from ..errors import MemoryExceeded, WriteBackpressure
from ..utils import lockwatch
from ..utils import deadline as deadline_mod

# ---------------------------------------------------------------- knobs
# ([query] memory_* config; configure() applies a loaded QueryConfig.
# Env overrides CNOSDB_QUERY_MEMORY_* ride the config loader; the
# bare ones below let harness subprocesses inherit without a file.)
TOTAL_BYTES = int(os.environ.get("CNOSDB_QUERY_MEMORY_TOTAL_BYTES", "0"))
SOFT_PCT = int(os.environ.get("CNOSDB_QUERY_MEMORY_SOFT_PCT", "70"))
HARD_PCT = int(os.environ.get("CNOSDB_QUERY_MEMORY_HARD_PCT", "90"))
PER_QUERY_BYTES = int(os.environ.get(
    "CNOSDB_QUERY_MEMORY_PER_QUERY_BYTES", "0"))
GROUP_BYTES = int(os.environ.get(
    "CNOSDB_QUERY_MEMORY_GROUP_BYTES", str(64 * 1024 * 1024)))
WRITE_DELAY_MS = int(os.environ.get(
    "CNOSDB_QUERY_MEMORY_WRITE_DELAY_MS", "2000"))

_REBALANCE_INTERVAL_S = 0.05   # ladder re-evaluation throttle
_EVENT_RING = 64


def enabled() -> bool:
    """Master gate: CNOSDB_MEMORY=0 restores byte-identical legacy
    behavior (no pools read, no ladder, no per-query accounting).
    Read per call — harness processes flip it via env."""
    return os.environ.get("CNOSDB_MEMORY", "1") != "0"


def configure(query_cfg) -> None:
    """Apply [query] memory_* knobs (called from server wiring)."""
    global TOTAL_BYTES, SOFT_PCT, HARD_PCT, PER_QUERY_BYTES
    global GROUP_BYTES, WRITE_DELAY_MS
    for attr, glob in (("memory_total_bytes", "TOTAL_BYTES"),
                       ("memory_soft_pct", "SOFT_PCT"),
                       ("memory_hard_pct", "HARD_PCT"),
                       ("memory_per_query_bytes", "PER_QUERY_BYTES"),
                       ("memory_group_bytes", "GROUP_BYTES"),
                       ("memory_write_delay_ms", "WRITE_DELAY_MS")):
        v = getattr(query_cfg, attr, None)
        if v is not None:
            globals()[glob] = int(v)
    BROKER.resize(TOTAL_BYTES)


def _auto_total() -> int:
    """0 = auto: a quarter of physical RAM, floored at 1 GiB."""
    try:
        phys = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        phys = 8 << 30
    return max(1 << 30, phys // 4)


# ------------------------------------------------------------- counters
_ctr_lock = lockwatch.Lock("memory.counters")
_counters: dict[tuple[str, str], int] = {}


def count(pool: str, action: str, n: int = 1) -> None:
    with _ctr_lock:
        _counters[(pool, action)] = _counters.get((pool, action), 0) + n


def counters_snapshot() -> dict[tuple[str, str], int]:
    with _ctr_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _ctr_lock:
        _counters.clear()


class _Pool:
    """One accounted pool: pull-style (usage_fn) or push-style (booked
    via book/unbook). `reclaim` takes a byte target and returns bytes
    freed (best effort)."""

    __slots__ = ("name", "usage_fn", "reclaim", "booked")

    def __init__(self, name, usage_fn=None, reclaim=None):
        self.name = name
        self.usage_fn = usage_fn
        self.reclaim = reclaim
        self.booked = 0

    def usage(self) -> int:
        if self.usage_fn is not None:
            try:
                return int(self.usage_fn())
            except Exception:
                # a dying subsystem (closed engine, torn-down cache)
                # must not take the broker with it
                count(self.name, "usage_error")
                return 0
        return self.booked


class MemoryBroker:
    """Process-global arbiter. Registration is idempotent (latest
    instance of a subsystem wins — tests open engines repeatedly in one
    process). Reclaim callbacks run OUTSIDE the broker lock: they take
    their own subsystem locks and must never need ours."""

    def __init__(self):
        self._lock = lockwatch.Lock("memory.broker")
        self._pools: dict[str, _Pool] = {}
        self._events: deque = deque(maxlen=_EVENT_RING)
        self._last_rebalance = 0.0
        self._total_override = 0

    # ------------------------------------------------------ registration
    def register_pool(self, name: str, usage_fn=None, reclaim=None) -> None:
        with self._lock:
            prev = self._pools.get(name)
            p = _Pool(name, usage_fn, reclaim)
            if prev is not None:
                p.booked = prev.booked
            self._pools[name] = p

    def book(self, name: str, n: int, action: str = "book") -> None:
        with self._lock:
            p = self._pools.get(name)
            if p is None:
                p = self._pools[name] = _Pool(name)
            p.booked += int(n)
        count(name, action)

    def unbook(self, name: str, n: int) -> None:
        with self._lock:
            p = self._pools.get(name)
            if p is not None:
                p.booked = max(0, p.booked - int(n))

    # ------------------------------------------------------------ budget
    def resize(self, total_bytes: int) -> None:
        """Runtime budget change (config apply / memory_pressure
        nemesis). 0 = back to auto."""
        with self._lock:
            self._total_override = int(total_bytes)
            self._last_rebalance = 0.0   # force the next ladder pass

    def total(self) -> int:
        with self._lock:
            override = self._total_override
        return override or TOTAL_BYTES or _auto_total()

    def watermarks(self) -> tuple[int, int]:
        t = self.total()
        return t * SOFT_PCT // 100, t * HARD_PCT // 100

    # ------------------------------------------------------------- state
    def usage(self) -> dict[str, int]:
        with self._lock:
            pools = list(self._pools.values())
        return {p.name: p.usage() for p in pools}

    def used(self) -> int:
        return sum(self.usage().values())

    def _event(self, pool: str, action: str, nbytes: int) -> None:
        with self._lock:
            self._events.append({"pool": pool, "action": action,
                                 "bytes": int(nbytes),
                                 "t_mono": time.monotonic()})

    def events_snapshot(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            evs = list(self._events)
        return [{"pool": e["pool"], "action": e["action"],
                 "bytes": e["bytes"],
                 "age_s": round(now - e["t_mono"], 2)} for e in evs]

    # ------------------------------------------------------------ ladder
    def rebalance(self, force: bool = False) -> int:
        """Run the degradation ladder if due; → current used bytes.

        Step 1: reclaim evictable pools (largest usage first) down to
        the soft watermark. Step 2: still over soft — shed QUEUED
        queries through the admission gate (running queries and the
        raft plane are never touched)."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_rebalance \
                    < _REBALANCE_INTERVAL_S:
                due = False
            else:
                self._last_rebalance = now
                due = True
        usage = self.usage()
        used = sum(usage.values())
        if not due:
            return used
        soft, _hard = self.watermarks()
        if used <= soft:
            return used
        # step 1: evictable pools, largest first
        with self._lock:
            pools = dict(self._pools)
        for name in sorted(usage, key=lambda n: usage[n], reverse=True):
            p = pools.get(name)
            if p is None or p.reclaim is None:
                continue
            need = used - soft
            if need <= 0:
                break
            try:
                freed = int(p.reclaim(need) or 0)
            except Exception:
                count(name, "reclaim_error")
                freed = 0
            if freed > 0:
                count(name, "reclaim")
                self._event(name, "reclaim", freed)
                used = self.used()
        if used <= soft:
            return used
        # step 2: shed queued queries (admission gate hook, wired by
        # the http server; embedded processes simply have no queue)
        gate = _GATE.get("gate")
        if gate is not None:
            shed = gate.shed_queued(retry_after=_retry_after(used, soft))
            if shed:
                count("admission", "shed_queued", shed)
                self._event("admission", "shed_queued", 0)
        return used

    # -------------------------------------------------------- write path
    def write_admit(self, est_bytes: int = 0) -> None:
        """Gate one user-ingress write (Coordinator.write_points).

        Below soft: free. Above hard: fail closed (MemoryExceeded —
        accepting the write would grow the memcache pool the node
        already cannot flush fast enough). Between: bounded delay
        polling for flush progress; sheds WriteBackpressure with a
        Retry-After derived from the observed drain rate when the
        delay budget runs out."""
        used = self.rebalance()
        soft, hard = self.watermarks()
        if used + est_bytes <= soft:
            return
        if used >= hard:
            count("write", "fail_hard")
            self._event("write", "fail_hard", est_bytes)
            raise MemoryExceeded(
                f"node above hard memory watermark "
                f"({used}/{hard} bytes) — write failed closed",
                used=used, hard=hard)
        # bounded delay: wait for the flush/reclaim machinery to drain
        # the pools below soft, never past the request's own deadline
        budget = deadline_mod.cap_current(max(WRITE_DELAY_MS, 0) / 1e3)
        t0 = time.monotonic()
        used0 = used
        while time.monotonic() - t0 < budget:
            time.sleep(min(0.02, budget))
            used = self.rebalance(force=True)
            if used + est_bytes <= soft:
                count("write", "delayed")
                return
            if used >= hard:
                count("write", "fail_hard")
                self._event("write", "fail_hard", est_bytes)
                raise MemoryExceeded(
                    f"node crossed hard memory watermark during "
                    f"write delay ({used}/{hard} bytes)",
                    used=used, hard=hard)
        # delay budget exhausted: derive Retry-After from the drain
        # rate actually observed while we waited (flush progress)
        elapsed = max(time.monotonic() - t0, 1e-3)
        rate = (used0 - used) / elapsed          # bytes/s, may be <= 0
        over = used + est_bytes - soft
        eta = over / rate if rate > 0 else _retry_after(used, soft)
        count("write", "backpressure_shed")
        self._event("write", "backpressure_shed", est_bytes)
        raise WriteBackpressure(
            f"write shed by memory backpressure ({used} bytes in use, "
            f"soft watermark {soft})",
            retry_after=round(min(max(eta, 0.5), 10.0), 2))


def _retry_after(used: int, soft: int) -> float:
    """Fallback Retry-After when no drain rate is observable: scale
    with the overage fraction, clamped to [0.5, 5] seconds."""
    over = max(used - soft, 0) / max(soft, 1)
    return round(min(0.5 + 4.5 * min(over, 1.0), 5.0), 2)


BROKER = MemoryBroker()

# admission-gate hook (server/http.py wires the process gate in; a dict
# so embedded tests can install/remove a fake without import dances)
_GATE: dict = {}


def set_admission_gate(gate) -> None:
    _GATE["gate"] = gate


# ---------------------------------------------------- per-query accounts
class QueryMemory:
    """Byte account for ONE request, hung off its Deadline. Charges are
    cumulative-live (charge/release); crossing the budget kills the
    query with a typed MemoryExceeded — concurrent in-budget queries
    are untouched. No lock: a query's charges happen on its own worker
    threads with the deadline already safely published, and a lost
    race on `used` skews one estimate, never corrupts a result."""

    __slots__ = ("budget", "used", "peak")

    def __init__(self, budget: int):
        self.budget = int(budget)
        self.used = 0
        self.peak = 0

    def charge(self, n: int, site: str, qid=None) -> None:
        self.used += int(n)
        if self.used > self.peak:
            self.peak = self.used
        if self.budget and self.used > self.budget:
            count("query", "killed")
            BROKER._event("query", "killed", self.used)
            raise MemoryExceeded(
                f"query memory budget exceeded at {site} "
                f"({self.used} > {self.budget} bytes)",
                qid=qid, site=site)

    def release(self, n: int) -> None:
        self.used = max(0, self.used - int(n))


def query_mem() -> QueryMemory | None:
    """The ambient request's memory account (created on first use), or
    None when the plane is off / no deadline context is installed."""
    if not enabled():
        return None
    dl = deadline_mod.current()
    if dl is None:
        return None
    qm = dl.mem
    if qm is None:
        qm = dl.mem = QueryMemory(PER_QUERY_BYTES)
    return qm


def charge_query(n: int, site: str) -> None:
    """Charge `n` bytes to the ambient query (no-op when the plane is
    off or the caller has no request context)."""
    if n <= 0:
        return
    qm = query_mem()
    if qm is None:
        return
    dl = deadline_mod.current()
    count("query", "charge")
    qm.charge(n, site, qid=dl.qid if dl is not None else None)


def release_query(n: int) -> None:
    if n <= 0:
        return
    qm = query_mem()
    if qm is not None:
        qm.release(n)


# ------------------------------------------------------- module facades
def register_pool(name: str, usage_fn=None, reclaim=None) -> None:
    BROKER.register_pool(name, usage_fn, reclaim)


def book(name: str, n: int, action: str = "book") -> None:
    if enabled():
        BROKER.book(name, n, action)


def unbook(name: str, n: int) -> None:
    if enabled():
        BROKER.unbook(name, n)


def write_admit(est_bytes: int = 0) -> None:
    if enabled():
        BROKER.write_admit(est_bytes)


def maybe_rebalance() -> None:
    """Cheap ladder checkpoint for non-write entry points (query
    ingress): throttled internally, reads only counters when idle."""
    if enabled():
        BROKER.rebalance()


def debug_snapshot() -> dict:
    """GET /debug/memory payload."""
    soft, hard = BROKER.watermarks()
    usage = BROKER.usage()
    return {
        "enabled": enabled(),
        "total_bytes": BROKER.total(),
        "soft_bytes": soft,
        "hard_bytes": hard,
        "used_bytes": sum(usage.values()),
        "pools": usage,
        "per_query_budget_bytes": PER_QUERY_BYTES,
        "group_budget_bytes": GROUP_BYTES,
        "recent_events": BROKER.events_snapshot(),
        "counters": {f"{p}/{a}": v
                     for (p, a), v in sorted(counters_snapshot().items())},
    }


def control(payload: dict) -> dict:
    """Runtime control behind the `_memory` RPC (chaos memory_pressure
    nemesis): {"total_bytes": N} squeezes/restores the broker budget
    (0 = back to config/auto); {} just reads the snapshot back."""
    out: dict = {"ok": True}
    if "total_bytes" in payload:
        BROKER.resize(int(payload["total_bytes"]))
        count("broker", "resize")
    out["snapshot"] = debug_snapshot()
    return out
