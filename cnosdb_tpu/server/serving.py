"""High-QPS serving plane: plan cache, result cache, fused micro-batches.

Millions of users means thousands of *small* concurrent queries, not one
big scan — and without this plane every request re-parses, re-plans and
pays its own scan + dispatch. Three layers compose (each independently
sound, each skippable):

  1. **Fingerprint + prepared-plan cache.** `fingerprint()` normalizes a
     SELECT at the token level — number/string literals hoist into a
     parameter vector, everything else renders canonically — so every
     member of a dashboard/point-query family shares one fingerprint.
     The plan cache keys analyzed statements + plans on
     ``(tenant, db, fingerprint, params)``; an exact hit skips
     parse+analyze+plan entirely, and a *template* hit (same fingerprint,
     new params) re-binds the literals into the cached analyzed AST and
     pays only `plan_select`.
  2. **Result cache.** Keyed on ``(tenant, db, fingerprint, params)``
     with the table's ScanToken map (`Coordinator.table_tokens`) captured
     BEFORE execution — the same conservative token-before-decode
     ordering the coordinator scan cache uses, so a racing write makes a
     stored entry miss, never serve stale. A probe revalidates the
     current token map: any flush / delete / compaction / tier / DDL
     event bumps a token (or the schema version) and the entry dies — no
     TTL guessing. Destructive write paths additionally push eager
     eviction through :func:`invalidate` (fault point
     ``serving.invalidate``); correctness never depends on that push,
     only hygiene does.
  3. **Fused micro-batching.** Under admission-gate pressure, compatible
     concurrent point queries (same table / schema / scanned columns /
     time ranges — filter-only differences) rendezvous in
     :class:`MicroBatcher`: one shared scan, one stacked-mask filter
     evaluation (`ops.tpu_exec.stacked_filter_masks`), then per-member
     demux under each member's own deadline + QueryProfile so EXPLAIN
     ANALYZE inside a fused batch still reports honestly and a member
     whose deadline dies mid-batch sheds alone.

``CNOSDB_SERVING=0`` disables all three layers (the executor then never
constructs a ServingPlane — byte-identical legacy behavior). Telemetry:
``cnosdb_serving_total{layer,outcome}`` + cache entry/byte gauges on
/metrics, ``serving.*`` stage-catalog counters in per-query profiles.
"""
from __future__ import annotations

import dataclasses
import os
import re
import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from .. import faults
from ..utils import deadline as deadline_mod
from ..utils import lockwatch, stages

faults.register_point(
    "serving.invalidate", __name__,
    desc="between a destructive mutation committing and the serving "
         "result cache evicting its entries (eviction lost = crash "
         "analog; token revalidation must still prevent stale reads)")

# --------------------------------------------------------------- telemetry
# process-global {(layer, outcome): n} counters behind cnosdb_serving_total
_counters_lock = lockwatch.Lock("serving.counters")
_COUNTERS: dict[tuple[str, str], int] = {}
_WIDTHS: dict[int, int] = {}          # fused-batch width histogram


def _count_serving(layer: str, outcome: str, n: int = 1) -> None:
    with _counters_lock:
        k = (layer, outcome)
        _COUNTERS[k] = _COUNTERS.get(k, 0) + n


def counters_snapshot() -> dict[tuple[str, str], int]:
    with _counters_lock:
        return dict(_COUNTERS)


def width_histogram() -> dict[int, int]:
    with _counters_lock:
        return dict(_WIDTHS)


def reset_counters() -> None:
    """Test/bench isolation for the process-global serving counters."""
    with _counters_lock:
        _COUNTERS.clear()
        _WIDTHS.clear()


# planes register here so storage/DDL-side invalidation hooks (which have
# no executor reference) can fan eviction in
_PLANES: "weakref.WeakSet" = weakref.WeakSet()


def cache_stats() -> dict[str, tuple[int, int]]:
    """{cache: (entries, bytes)} across registered planes, for /metrics."""
    plan_e = plan_b = res_e = res_b = 0
    for p in list(_PLANES):
        e, b = p.plan_cache.stats()
        plan_e += e
        plan_b += b
        e, b = p.result_cache.stats()
        res_e += e
        res_b += b
    return {"plan_cache": (plan_e, plan_b),
            "result_cache": (res_e, res_b)}


def invalidate(tenant: str, db: str, table: str | None = None) -> int:
    """Push eager eviction for a destructive event (DDL / DELETE /
    matview refresh / compaction / tiering). Correctness does NOT depend
    on this call — result-cache probes revalidate ScanTokens — so a
    crash or injected fault here loses only hygiene, which is exactly
    what the ``serving.invalidate`` fault point exists to prove."""
    if faults.ENABLED:
        faults.fire("serving.invalidate",
                    tenant=tenant, db=db, table=table or "")
    n = 0
    for p in list(_PLANES):
        n += p.result_cache.invalidate(tenant, db, table)
        n += p.plan_cache.invalidate(tenant, db, table)
    if n:
        _count_serving("result_cache", "invalidate", n)
    return n


def invalidate_owner(owner: str, table: str | None = None) -> int:
    """Owner-string (``tenant.db``) entry point for storage-side hooks
    (compaction, tiering) that never see tenant/db separately."""
    tenant, _, db = owner.partition(".")
    return invalidate(tenant, db, table)


def _serving_bytes_used() -> int:
    s = cache_stats()
    return s["plan_cache"][1] + s["result_cache"][1]


def _serving_reclaim(target_bytes: int) -> int:
    """Broker reclaim: shrink result caches LRU-first across every
    registered plane — plan caches are entry-capped and tiny, results
    hold the bytes."""
    freed = 0
    for p in list(_PLANES):
        if freed >= target_bytes:
            break
        freed += p.result_cache.reclaim(target_bytes - freed)
    return freed


def _register_serving_pool() -> None:
    from . import memory as _memory

    _memory.register_pool("serving",
                          usage_fn=_serving_bytes_used,
                          reclaim=_serving_reclaim)


_register_serving_pool()


# ------------------------------------------------------------ fingerprint
# scalars whose value depends on call time / session — a cached plan or
# result would freeze them (the executor folds the current_* family at
# plan time, and now() bakes into plan-time time ranges)
_UNCACHEABLE_FUNCS = frozenset({
    "now", "current_timestamp", "current_time", "current_date", "today",
    "current_user", "current_tenant", "current_database", "current_role",
    "random", "uuid", "sleep"})

_SELECT_RE = re.compile(r"^\s*select\b", re.IGNORECASE)


def fingerprint(sql: str):
    """→ ``(fingerprint, params)`` or None when not fingerprintable.

    Token-level normalization over `sql.parser.tokenize`: number/string
    literals become placeholders (values collected in token order),
    idents render lowercased (quoted idents keep their quotes so
    ``"a b"`` can never collide with ``a b``). Declined shapes — anything
    that isn't a single SELECT, session variables, and the
    time/session-dependent scalar family — return None and take the
    legacy path."""
    if not _SELECT_RE.match(sql):
        return None
    from ..sql.parser import tokenize

    try:
        toks = tokenize(sql)
    except Exception:
        return None     # the real parser will produce the real error
    parts: list[str] = []
    params: list = []
    it = iter(range(len(toks)))
    for i in it:
        t = toks[i]
        if t.kind == "eof":
            break
        if t.kind == "op" and t.value == ";":
            # a single trailing ';' is fine; anything after it means a
            # multi-statement request — not fingerprintable
            if any(toks[j].kind != "eof" for j in range(i + 1, len(toks))):
                return None
            break
        if t.kind == "number":
            parts.append("?")
            params.append(_num_value(t.value))
        elif t.kind == "string":
            parts.append("?s")
            params.append(t.value)
        elif t.kind == "sysvar":
            return None     # session-scoped variable
        elif t.kind == "ident":
            if t.value in _UNCACHEABLE_FUNCS:
                return None
            if sql[t.pos] in "\"`":
                parts.append(f'"{t.value}"')
            else:
                parts.append(t.value)
        else:
            parts.append(str(t.value))
    return " ".join(parts), tuple(params)


def _num_value(text: str):
    if re.fullmatch(r"\d+", text):
        return int(text)
    return float(text)


def _vkey(v):
    """Type-tagged equality key: 1, 1.0 and True must not unify when
    matching token params against AST literal values."""
    return (type(v).__name__, v)


# --------------------------------------------------- AST literal rebinding
def _walk_literals(node, out: list) -> None:
    from ..sql.expr import Literal

    if isinstance(node, Literal):
        out.append(node)
        return
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            _walk_literals(getattr(node, f.name), out)
        return
    if isinstance(node, (list, tuple)):
        for v in node:
            _walk_literals(v, out)


def _rebuild_literals(node, repl: dict[int, object], idx: list):
    """Structural copy of `node` with literal ordinal i replaced by
    Literal(repl[i]); untouched subtrees are shared, and the walk order
    is identical to `_walk_literals` so ordinals line up."""
    from ..sql.expr import Literal

    if isinstance(node, Literal):
        i = idx[0]
        idx[0] += 1
        if i in repl:
            return Literal(repl[i])
        return node
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = _rebuild_literals(v, repl, idx)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    if isinstance(node, list):
        nl = [_rebuild_literals(v, repl, idx) for v in node]
        if any(a is not b for a, b in zip(nl, node)):
            return nl
        return node
    if isinstance(node, tuple):
        nt = tuple(_rebuild_literals(v, repl, idx) for v in node)
        if any(a is not b for a, b in zip(nt, node)):
            return nt
        return node
    return node


def _template_slots(stmt, params: tuple):
    """→ (slots, n_ast_literals) mapping the analyzed statement's literal
    positions (AST literals in walk order, then LIMIT, then OFFSET) onto
    token-param indices, or None when the statement is not rebindable —
    param values must be pairwise distinct (else a value→slot map is
    ambiguous) and the literal multiset must equal the param multiset
    (parser constant-folding / interval+timestamp transforms break the
    literal↔token correspondence, which this check detects)."""
    lits: list = []
    _walk_literals(stmt, lits)
    values = [lit.value for lit in lits]
    n_ast = len(values)
    if stmt.limit is not None:
        values.append(stmt.limit)
    if stmt.offset is not None:
        values.append(stmt.offset)
    pkeys = [_vkey(p) for p in params]
    if len(set(pkeys)) != len(pkeys):
        return None
    if sorted(map(repr, pkeys)) != sorted(repr(_vkey(v)) for v in values):
        return None
    index = {k: i for i, k in enumerate(pkeys)}
    slots = [index[_vkey(v)] for v in values]
    return slots, n_ast


def _rebind(entry: "_PlanEntry", new_params: tuple):
    """Template hit → a new analyzed statement with `new_params` bound.
    Returns None (caller re-parses) when a param changed python type —
    the analyzer's type checks were only run for the template's types."""
    for old, new in zip(entry.params, new_params):
        if type(old) is not type(new):
            return None
    slots, n_ast = entry.slots
    repl = {}
    limit = offset = None
    for j, slot in enumerate(slots):
        if j < n_ast:
            repl[j] = new_params[slot]
        elif j == n_ast and entry.stmt.limit is not None:
            limit = new_params[slot]
        else:
            offset = new_params[slot]
    if (limit is not None and not isinstance(limit, int)) \
            or (offset is not None and not isinstance(offset, int)):
        return None
    stmt = _rebuild_literals(entry.stmt, repl, [0])
    changes = {}
    if limit is not None:
        changes["limit"] = limit
    if offset is not None:
        changes["offset"] = offset
    return dataclasses.replace(stmt, **changes) if changes else stmt


# ------------------------------------------------------------- plan cache
class _PlanEntry:
    __slots__ = ("stmt", "plan", "tenant", "db", "table", "schema_version",
                 "params", "slots")

    def __init__(self, stmt, plan, tenant, db, table, schema_version,
                 params, slots):
        self.stmt = stmt
        self.plan = plan
        self.tenant = tenant
        self.db = db
        self.table = table
        self.schema_version = schema_version
        self.params = params
        self.slots = slots      # (slot list, n_ast_literals) or None


class PlanCache:
    """Bounded LRU of analyzed+planned SELECTs keyed
    ``(tenant, db, fingerprint, params)`` plus one rebindable template
    per fingerprint. Entries pin nothing mutable: execution revalidates
    the schema version and re-runs the privilege check."""

    def __init__(self, max_entries: int = 512):
        self.max_entries = max(8, int(max_entries))
        self._lock = lockwatch.Lock("serving.plan_cache")
        self._entries: OrderedDict = OrderedDict()
        self._templates: dict = {}    # (tenant, db, fp) -> _PlanEntry

    def get_exact(self, key) -> "_PlanEntry | None":
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
            return e

    def get_template(self, tenant, db, fp) -> "_PlanEntry | None":
        with self._lock:
            return self._templates.get((tenant, db, fp))

    def store(self, key, entry: "_PlanEntry") -> None:
        tenant, db, fp, _params = key
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                old_key, old = self._entries.popitem(last=False)
                _count_serving("plan_cache", "evict")
                tk = (old_key[0], old_key[1], old_key[2])
                if self._templates.get(tk) is old:
                    del self._templates[tk]
            if entry.slots is not None:
                self._templates[(tenant, db, fp)] = entry

    def evict(self, key) -> None:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                _count_serving("plan_cache", "evict")
            tk = (key[0], key[1], key[2])
            if self._templates.get(tk) is e and e is not None:
                del self._templates[tk]

    def invalidate(self, tenant, db, table=None) -> int:
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if e.tenant == tenant and e.db == db
                    and (table is None or e.table == table)]
            for k in dead:
                e = self._entries.pop(k)
                tk = (k[0], k[1], k[2])
                if self._templates.get(tk) is e:
                    del self._templates[tk]
            return len(dead)

    def stats(self) -> tuple[int, int]:
        with self._lock:
            return len(self._entries), 0


# ----------------------------------------------------------- result cache
class _ResultEntry:
    __slots__ = ("rs", "tokens", "stmt", "tenant", "db", "table", "nbytes")

    def __init__(self, rs, tokens, stmt, tenant, db, table, nbytes):
        self.rs = rs
        self.tokens = tokens
        self.stmt = stmt
        self.tenant = tenant
        self.db = db
        self.table = table
        self.nbytes = nbytes


def _rs_nbytes(rs) -> int:
    n = 256
    for c in rs.columns:
        n += int(getattr(c, "nbytes", 0) or 0)
        if getattr(c, "dtype", None) == object:
            n += 64 * len(c)    # boxed-object estimate
    return n


class ResultCache:
    """Byte-capped LRU of finished ResultSets keyed
    ``(tenant, db, fingerprint, params)``; every entry carries the
    ScanToken map captured before its execution and is revalidated
    against the live map on probe. Errors are never stored (negative-
    entry suppression) — a failing query re-executes every time."""

    def __init__(self, max_bytes: int, max_entries: int = 4096):
        self.max_bytes = max(1 << 20, int(max_bytes))
        self.max_entries = max(16, int(max_entries))
        self._lock = lockwatch.Lock("serving.result_cache")
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0

    def get(self, key) -> "_ResultEntry | None":
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
            return e

    def store(self, key, entry: "_ResultEntry") -> bool:
        if entry.nbytes > self.max_bytes // 8:
            return False    # one giant result must not wipe the cache
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._entries and (
                    len(self._entries) >= self.max_entries
                    or self._bytes + entry.nbytes > self.max_bytes):
                _k, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                _count_serving("result_cache", "evict")
            self._entries[key] = entry
            self._bytes += entry.nbytes
        return True

    def evict(self, key) -> None:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._bytes -= e.nbytes

    def reclaim(self, target_bytes: int) -> int:
        """Memory-broker shrink: pop LRU entries until `target_bytes`
        are freed — a lost entry is just a cache miss."""
        freed = 0
        with self._lock:
            while self._entries and freed < target_bytes:
                _k, ev = self._entries.popitem(last=False)
                freed += ev.nbytes
                _count_serving("result_cache", "evict")
            self._bytes = max(0, self._bytes - freed)
        return freed

    def invalidate(self, tenant, db, table=None) -> int:
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if e.tenant == tenant and e.db == db
                    and (table is None or e.table == table)]
            for k in dead:
                self._bytes -= self._entries.pop(k).nbytes
            return len(dead)

    def stats(self) -> tuple[int, int]:
        with self._lock:
            return len(self._entries), self._bytes


# -------------------------------------------------------- fused batching
class _Member:
    __slots__ = ("plan", "field_names", "prof", "ctx", "result", "error")

    def __init__(self, plan, field_names):
        self.plan = plan
        self.field_names = field_names
        self.prof = stages.current_profile()
        self.ctx = deadline_mod.current()
        self.result = None
        self.error = None


class _Group:
    __slots__ = ("key", "members", "closed", "done", "failed")

    def __init__(self, key):
        self.key = key
        self.members: list[_Member] = []
        self.closed = False
        self.done = threading.Event()
        self.failed = False


class MicroBatcher:
    """Group-commit rendezvous for compatible point queries.

    The first submitter of a compatibility key becomes leader, holds a
    ~`window_ms` collection window, then executes ONE shared scan and
    demuxes per-member results (`QueryExecutor._exec_raw_batches` with
    precomputed stacked masks). Followers joining an open group are free;
    opening a NEW group only happens under admission-gate pressure (or
    ``CNOSDB_SERVING_BATCH_FORCE=1``), so an idle node never pays the
    window latency. A group-level failure falls every member back to its
    solo path — fusion is an optimization, never a new failure mode."""

    def __init__(self, plane, window_ms: float = 2.0, max_width: int = 32):
        self._plane = plane
        self.window_s = max(0.0, float(window_ms)) / 1e3
        self.max_width = max(2, int(max_width))
        self.force = os.environ.get(
            "CNOSDB_SERVING_BATCH_FORCE", "0") == "1"
        self._lock = lockwatch.Lock("serving.batcher")
        self._groups: dict = {}
        self._gate = None

    def attach_gate(self, gate) -> None:
        self._gate = gate

    def _pressure(self) -> bool:
        g = self._gate
        if g is None:
            return False
        running, queued = g.pressure()
        return queued > 0 or running >= g.max_concurrent

    def decline(self, reason: str) -> None:
        """Book an unfusable shape — only while batching is engaged, so
        idle traffic doesn't drown the decline counters."""
        if self.force or self._pressure():
            _count_serving("batch", f"declined_{reason}")

    def submit(self, executor, plan, tenant: str, db: str,
               field_names: list[str]):
        """→ the member's ResultSet, or None = run the solo path."""
        key = (tenant, db, plan.table,
               getattr(plan.schema, "schema_version", None),
               tuple(field_names),
               tuple((r.min_ts, r.max_ts) for r in plan.time_ranges.ranges))
        m = _Member(plan, list(field_names))
        g = None
        leader = False
        with self._lock:
            open_g = self._groups.get(key)
            if open_g is not None and not open_g.closed \
                    and len(open_g.members) < self.max_width:
                open_g.members.append(m)
                g = open_g
            elif self.force or self._pressure():
                g = _Group(key)
                g.members.append(m)
                self._groups[key] = g
                leader = True
        if g is None:
            _count_serving("batch", "solo")
            stages.count("serving.solo")
            return None
        if leader:
            _count_serving("batch", "leader_open")
            return self._lead(executor, g, m, tenant, db)
        return self._await_member(g, m)

    def _lead(self, executor, g: _Group, m: _Member, tenant, db):
        if self.window_s:
            time.sleep(self.window_s)
        with self._lock:
            g.closed = True
            if self._groups.get(g.key) is g:
                del self._groups[g.key]
            members = list(g.members)
        if len(members) == 1:
            g.done.set()
            _count_serving("batch", "solo")
            stages.count("serving.solo")
            return None     # nobody joined: run the plain solo path
        try:
            _fused_exec(executor, members, tenant, db)
            with _counters_lock:
                _WIDTHS[len(members)] = _WIDTHS.get(len(members), 0) + 1
            _count_serving("batch", "fused", len(members))
        except BaseException:
            g.failed = True
            _count_serving("batch", "declined_leader_error", len(members))
            raise
        finally:
            g.done.set()
        if m.error is not None:
            raise m.error
        return m.result

    def _await_member(self, g: _Group, m: _Member):
        while not g.done.wait(0.05):
            if m.ctx is not None:
                # shed ONLY this member: leader results for it are
                # discarded, the deadline error propagates now
                m.ctx.check()
        if g.failed:
            self.decline("leader_error")
            return None     # leader-side failure: fall back to solo
        if m.error is not None:
            raise m.error
        return m.result


def _fused_exec(executor, members: list[_Member], tenant: str, db: str):
    """Leader body: one shared scan (widened tag domains when members
    disagree — each member's residual filter re-checks its own tags),
    one stacked-mask evaluation per batch, then per-member projection
    under that member's own deadline scope + QueryProfile."""
    from contextlib import nullcontext

    from ..models.predicate import ColumnDomains
    from ..ops.tpu_exec import stacked_filter_masks
    from ..sql.executor import _batches_bytes, _schema_padding

    plan0 = members[0].plan
    doms = plan0.tag_domains
    for m in members[1:]:
        if m.plan.tag_domains is not doms \
                and repr(m.plan.tag_domains) != repr(doms):
            doms = ColumnDomains.all()
            break
    # hedged members under fused batches: the shared scan runs ONCE under
    # the leader's deadline, so any hedges its remote splits fire serve
    # every member of the group — book the delta to the leader's profile
    # (process-wide counters, so concurrent queries' hedges can bleed in;
    # the count is attribution telemetry, not an exact invariant)
    from ..parallel import health as health_mod

    h0 = sum(v for (o, _r), v in health_mod.counters_snapshot()[0].items()
             if o == "fired")
    with stages.stage("serving.fused_scan_ms"):
        batches = executor.coord.scan_table(
            tenant, db, plan0.table, time_ranges=plan0.time_ranges,
            tag_domains=doms, field_names=members[0].field_names)
    h1 = sum(v for (o, _r), v in health_mod.counters_snapshot()[0].items()
             if o == "fired")
    if h1 > h0:
        stages.count("serving.fused_hedges", h1 - h0)
    filters = [m.plan.filter for m in members]
    filter_cols = set()
    for f in filters:
        if f is not None:
            filter_cols |= f.columns()
    with executor.memory_pool.reservation(
            _batches_bytes(batches), f"fused scan of {plan0.table}"):
        shared = []
        for b in batches:
            env = executor._raw_batch_env(plan0.schema, b)
            for c in filter_cols:
                if c not in env:
                    env[c] = _schema_padding(plan0.schema, c, b.n_rows)
                    env[f"__valid__:{c}"] = np.zeros(b.n_rows, dtype=bool)
            masks = stacked_filter_masks(env, filters, b.n_rows,
                                         set(b.fields))
            shared.append((b.n_rows, env, masks))
        for i, m in enumerate(members):
            scope = (stages.profile_scope(m.prof)
                     if m.prof is not stages.current_profile()
                     else nullcontext())
            with scope:
                try:
                    if m.ctx is not None:
                        m.ctx.check()    # shed only this member
                    stages.count("serving.fused")
                    prepared = [(env, masks[i], n)
                                for (n, env, masks) in shared]
                    m.result = executor._exec_raw_batches(
                        m.plan, None, prepared=prepared)
                except BaseException as e:
                    m.error = e


# ------------------------------------------------------------ the plane
class ServingPlane:
    """Per-executor serving tier; all state process-local. Constructed by
    QueryExecutor unless ``CNOSDB_SERVING=0``."""

    def __init__(self, executor):
        self._executor = weakref.ref(executor)
        self.plan_cache = PlanCache(max_entries=int(os.environ.get(
            "CNOSDB_SERVING_PLAN_ENTRIES", "512")))
        self.result_cache = ResultCache(max_bytes=int(float(os.environ.get(
            "CNOSDB_SERVING_RESULT_MB", "64")) * (1 << 20)))
        self.batcher = MicroBatcher(self, window_ms=float(os.environ.get(
            "CNOSDB_SERVING_BATCH_WINDOW_MS", "2")))
        self._tls = threading.local()
        self._fp_lock = lockwatch.Lock("serving.fp_memo")
        self._fp_memo: OrderedDict = OrderedDict()
        _PLANES.add(self)

    def attach_gate(self, gate) -> None:
        self.batcher.attach_gate(gate)

    # ---------------------------------------------------------- fingerprint
    def _fingerprint(self, sql: str):
        if not _SELECT_RE.match(sql):
            return None     # DML/DDL: not even worth a memo slot
        with self._fp_lock:
            hit = self._fp_memo.get(sql)
            if hit is not None:
                self._fp_memo.move_to_end(sql)
                return None if hit == "uncacheable" else hit
        fpp = fingerprint(sql)
        with self._fp_lock:
            self._fp_memo[sql] = fpp if fpp is not None else "uncacheable"
            self._fp_memo.move_to_end(sql)
            while len(self._fp_memo) > 1024:
                self._fp_memo.popitem(last=False)
        return fpp

    # ------------------------------------------------------------- serving
    def try_execute(self, sql: str, session):
        """Serving-plane fast path for one request; → list[ResultSet] or
        None = take the legacy parse/plan/execute path. Every early None
        books an outcome (serving-accounting lint rule)."""
        ex = self._executor()
        if ex is None:
            _count_serving("result_cache", "bypass")
            return None
        # same kill window the legacy loop has before each statement — a
        # KILLed query must not be answered from cache
        ex.tracker.check_cancelled(ex._tls.qid)
        if not _SELECT_RE.match(sql):
            # DML/DDL: invisible to the serving plane by design — kept a
            # separate outcome so SELECT bypasses stay a useful signal
            _count_serving("result_cache", "non_select")
            return None
        fpp = self._fingerprint(sql)
        if fpp is None:
            # non-fingerprintable SELECT (session-dependent scalar,
            # multi-statement, session var): invisible to all three layers
            _count_serving("result_cache", "bypass")
            return None
        fp, params = fpp
        key = (session.tenant, session.database, fp, params)
        ent = self.result_cache.get(key)
        if ent is not None:
            rs = self._probe_result(ex, ent, key, session)
            if rs is not None:
                _count_serving("result_cache", "hit")
                stages.count("serving.result_hit")
                return [rs]
        else:
            _count_serving("result_cache", "miss")
            stages.count("serving.result_miss")
        return self._execute_miss(ex, key, sql, session)

    def _probe_result(self, ex, ent: _ResultEntry, key, session):
        cur = ex.coord.table_tokens(ent.tenant, ent.db, ent.table)
        if cur is None or cur != ent.tokens:
            self.result_cache.evict(key)
            _count_serving("result_cache", "invalidate")
            _count_serving("result_cache", "miss")
            stages.count("serving.result_miss")
            return None
        ex._check_privilege(ent.stmt, session)   # may raise: never cached
        return ent.rs

    def _execute_miss(self, ex, key, sql: str, session):
        from ..sql import ast
        from ..sql.parser import parse_sql

        tenant, db0, fp, params = key
        state = {"key": key, "tenant": tenant, "db": db0,
                 "tokens": None, "bypass": None, "stmt": None}
        # ---- plan cache
        pe = self.plan_cache.get_exact(key)
        how = "hit"
        if pe is None:
            tpl = self.plan_cache.get_template(tenant, db0, fp)
            if tpl is not None:
                pe = self._rebind_template(ex, tpl, params, key)
                how = "rebind"
        if pe is not None:
            rs = self._exec_planned(ex, pe, key, session, state, how)
            if rs is not None:
                return rs
            # schema drift / stale template: fall through to a full parse
        _count_serving("plan_cache", "miss")
        stages.count("serving.plan_miss")
        # ---- full path, instrumented: parse here (once), let _select's
        # observation hook capture the analyzed stmt + plan + tokens
        try:
            stmts = parse_sql(sql)
        except Exception:
            _count_serving("result_cache", "bypass")
            raise               # same error the legacy path would raise
        if len(stmts) != 1 or not isinstance(stmts[0], ast.SelectStmt):
            _count_serving("result_cache", "bypass")
            return None         # UNION etc: legacy path re-parses
        stmt = stmts[0]
        # through execute_statement (not _select directly): it owns the
        # privilege check and honors instance-level instrumentation, so
        # the serving full path stays behaviorally identical to legacy
        self._tls.state = state
        self._tls.fp = fp
        try:
            rs = ex.execute_statement(stmt, session)
        finally:
            self._tls.state = None   # errors are never cached
            self._tls.fp = None
        self._store_result(key, rs, state)
        return [rs]

    def _rebind_template(self, ex, tpl: _PlanEntry, params, key):
        """Template fingerprint hit with new params → a fresh exact
        entry, or None when rebinding is unsound for these params."""
        from ..errors import PlanError
        from ..sql.planner import plan_select

        stmt = _rebind(tpl, params)
        if stmt is None:
            self.decline_rebind("param_type")
            return None
        schema = ex.meta.table_opt(tpl.tenant, tpl.db, tpl.table)
        if schema is None or getattr(schema, "schema_version", None) \
                != tpl.schema_version:
            self.decline_rebind("schema_drift")
            return None
        try:
            plan = plan_select(stmt, schema)
        except PlanError:
            self.decline_rebind("plan_error")
            return None
        pe = _PlanEntry(stmt, plan, tpl.tenant, tpl.db, tpl.table,
                        tpl.schema_version, params, tpl.slots and
                        _template_slots(stmt, params))
        self.plan_cache.store(key, pe)
        _count_serving("plan_cache", "hit_rebind")
        stages.count("serving.plan_rebind")
        return pe

    def decline_rebind(self, reason: str) -> None:
        _count_serving("plan_cache", f"rebind_declined_{reason}")

    def _exec_planned(self, ex, pe: _PlanEntry, key, session, state, how):
        """Execute a cached plan: revalidate schema version, re-run the
        privilege check, capture invalidation tokens BEFORE the scan,
        then dispatch straight to the executor's batch methods."""
        from ..sql.planner import AggregatePlan

        schema = ex.meta.table_opt(pe.tenant, pe.db, pe.table)
        if schema is None or getattr(schema, "schema_version", None) \
                != pe.schema_version:
            self.plan_cache.evict(key)
            return None     # caller books plan_cache miss + reparses
        ex._check_privilege(pe.stmt, session)
        if how == "hit":
            _count_serving("plan_cache", "hit")
            stages.count("serving.plan_hit")
        state["tokens"] = ex.coord.table_tokens(pe.tenant, pe.db, pe.table)
        state["stmt"] = pe.stmt
        state["table"] = pe.table
        state["db"] = pe.db
        self._tls.fp = key[2]
        try:
            if isinstance(pe.plan, AggregatePlan):
                rs = ex._exec_aggregate(pe.plan, pe.tenant, pe.db)
            else:
                rs = ex._exec_raw(pe.plan, pe.tenant, pe.db)
        finally:
            self._tls.fp = None
        self._store_result(key, rs, state)
        return [rs]

    # ----------------------------------------------- _select observation
    def claim(self):
        """Consume-once TLS handoff: armed by `_execute_miss` for the
        OUTER statement only — nested _select calls (subquery
        resolution) claim nothing and stay invisible to the caches."""
        state = getattr(self._tls, "state", None)
        self._tls.state = None
        return state

    def current_fp(self) -> str | None:
        """Fingerprint of the serving-instrumented request executing on
        THIS thread, if any — tags remote scan RPCs for cluster-wide
        cache attribution."""
        return getattr(self._tls, "fp", None)

    def observe_plan(self, state, stmt, plan, session, db, table,
                     schema) -> None:
        """_select hook, fired right after `plan_select` on the claimed
        outer statement: learn the plan + capture result-cache tokens
        (pre-scan, so a racing write causes a miss, never staleness)."""
        if session.tenant != state["tenant"] or db == "usage_schema":
            # tenant-swapped system view: the analyzed stmt embeds the
            # caller's tenant filter — never reusable across sessions
            state["bypass"] = "tenant_view"
            _count_serving("result_cache", "bypass")
            return
        tenant, db0, fp, params = state["key"]
        slots = _template_slots(stmt, params)
        pe = _PlanEntry(stmt, plan, tenant, db, table,
                        getattr(schema, "schema_version", None),
                        params, slots)
        self.plan_cache.store(state["key"], pe)
        state["stmt"] = stmt
        state["table"] = table
        state["db"] = db
        state["tokens"] = self._executor().coord.table_tokens(
            session.tenant, db, table)
        if state["tokens"] is None:
            state["bypass"] = "remote_vnodes"
            _count_serving("result_cache", "bypass")

    def _store_result(self, key, rs, state) -> None:
        if state.get("tokens") is None:
            if state.get("bypass") is None:
                # never reached the plan hook (relational/system/constant
                # path): the result is not token-invalidatable
                _count_serving("result_cache", "bypass")
                stages.count("serving.result_bypass")
            return
        ent = _ResultEntry(rs, state["tokens"], state["stmt"],
                           state["tenant"], state["db"], state["table"],
                           _rs_nbytes(rs))
        if not self.result_cache.store(key, ent):
            _count_serving("result_cache", "bypass")
            stages.count("serving.result_bypass")
