"""Distributed tracing: spans, context propagation, in-process collection.

Role-parity with the reference's trace crate (common/trace/src/
global_tracing.rs minitrace + OTLP export, span_ext.rs Span helpers,
http/http_ctx.rs header propagation; consumed by TraceCollectorBatcher
ReaderProxy tskv/src/reader/trace.rs): spans carry (trace_id, span_id,
parent_id, name, tags, start/duration) and propagate across processes via
a `cnos-trace-id` header on both the user HTTP API and the node-to-node
RPC plane. Collection is an in-memory ring per process, queryable through
`GET /debug/traces` and the `information_schema.traces` virtual table —
the reference's jaeger-store role collapsed to the embedded case (OTLP
export is a config hook away: the collector interface takes any sink).
"""
from __future__ import annotations

import contextvars
import secrets
import threading
import time
from ..utils import lockwatch

TRACE_HEADER = "cnos-trace-id"

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "cnos_current_span", default=None)


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "tags",
                 "start_ns", "duration_ns", "_collector", "_token")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, collector: "TraceCollector"):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.tags: dict = {}
        self.start_ns = time.time_ns()
        self.duration_ns = 0
        self._collector = collector
        self._token = None

    def set_tag(self, key: str, value):
        self.tags[key] = value
        return self

    def __enter__(self):
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_ns = time.time_ns() - self.start_ns
        if exc is not None:
            self.tags["error"] = str(exc)
        if self._token is not None:
            _current_span.reset(self._token)
        self._collector.record(self)
        return False

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "tags": dict(self.tags), "start_ns": self.start_ns,
                "duration_ns": self.duration_ns}


class TraceCollector:
    """Bounded ring of finished spans (reference keeps them in minitrace's
    collector until OTLP flush; embedded deployments query them back)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._spans: list[dict] = []
        self._lock = lockwatch.Lock("trace.collector")
        self.sinks: list = []   # extra consumers (OTLP exporter)

    def record(self, span: Span):
        d = span.to_dict()
        with self._lock:
            self._spans.append(d)
            if len(self._spans) > self.capacity:
                del self._spans[:self.capacity // 4]
        for sink in self.sinks:
            try:
                sink(d)
            except Exception:
                pass   # a broken exporter must never fail the traced work

    def spans(self, trace_id: str | None = None,
              limit: int = 500) -> list[dict]:
        with self._lock:
            out = self._spans if trace_id is None else \
                [s for s in self._spans if s["trace_id"] == trace_id]
            return list(out[-limit:])

    # ------------------------------------------------------------- spans
    def span(self, name: str, trace_id: str | None = None,
             parent_id: str | None = None) -> Span:
        """Start a child of the context span, or a root with the given (or
        a fresh) trace id — `Span::from_context` in the reference."""
        cur = _current_span.get()
        if trace_id is None and cur is not None:
            trace_id = cur.trace_id
            parent_id = cur.span_id
        if trace_id is None:
            trace_id = secrets.token_hex(8)
        return Span(trace_id, secrets.token_hex(4), parent_id, name, self)

    def from_headers(self, headers, name: str) -> Span:
        """Continue a trace propagated over HTTP/RPC: header value is
        `trace_id[:parent_span_id]` (reference http_ctx.rs)."""
        raw = headers.get(TRACE_HEADER, "") if headers else ""
        trace_id = parent = None
        if raw:
            trace_id, _, parent = raw.partition(":")
            parent = parent or None
        return self.span(name, trace_id=trace_id, parent_id=parent)


GLOBAL_COLLECTOR = TraceCollector()


def current_span() -> Span | None:
    """The context-active span, if any (profile plane attaches per-stage
    timings to the root span it finds here)."""
    return _current_span.get()


def current_trace_header() -> str | None:
    """Outgoing propagation value for the active span, if any."""
    cur = _current_span.get()
    if cur is None:
        return None
    return f"{cur.trace_id}:{cur.span_id}"


class OtlpExporter:
    """Background OTLP/HTTP JSON exporter for this process's own spans
    (reference: minitrace → opentelemetry-otlp in global_tracing.rs:14-60).
    Registers as a collector sink; a daemon thread batches spans and POSTs
    {endpoint}/v1/traces. OTLP/HTTP officially supports the JSON encoding,
    so any stock collector accepts these without protobuf codegen."""

    def __init__(self, endpoint: str, collector: TraceCollector,
                 service_name: str = "cnosdb-tpu", batch_size: int = 256,
                 flush_interval_s: float = 2.0):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._queue: list[dict] = []
        self._lock = lockwatch.Lock("trace.otlp_queue")
        self._wake = threading.Event()
        self._stop = False
        self.exported = 0
        self.errors = 0
        collector.sinks.append(self._enqueue)
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True)
        self._thread.start()

    def _enqueue(self, span: dict):
        with self._lock:
            self._queue.append(span)
            if len(self._queue) >= self.batch_size:
                self._wake.set()

    def _run(self):
        while not self._stop:
            self._wake.wait(timeout=self.flush_interval_s)
            self._wake.clear()
            self.flush()

    def flush(self):
        with self._lock:
            batch, self._queue = self._queue, []
        if not batch:
            return
        import json
        import urllib.request

        body = json.dumps(self._to_otlp(batch)).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5) as resp:
                resp.read()
            self.exported += len(batch)
        except Exception:
            self.errors += 1   # drop the batch; tracing is best-effort

    def _to_otlp(self, batch: list[dict]) -> dict:
        spans = []
        for s in batch:
            attrs = [{"key": str(k),
                      "value": {"stringValue": str(v)}}
                     for k, v in (s.get("tags") or {}).items()]
            span = {
                # OTLP ids are fixed-width hex: 16-byte trace, 8-byte span
                "traceId": s["trace_id"].rjust(32, "0"),
                "spanId": s["span_id"].rjust(16, "0"),
                "name": s["name"],
                "kind": 1,   # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(s["start_ns"]),
                "endTimeUnixNano": str(s["start_ns"] + s["duration_ns"]),
                "attributes": attrs,
            }
            if s.get("parent_id"):
                span["parentSpanId"] = s["parent_id"].rjust(16, "0")
            spans.append(span)
        return {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{"scope": {"name": "cnosdb_tpu"},
                            "spans": spans}],
        }]}

    def close(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)
        self.flush()
