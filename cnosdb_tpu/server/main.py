"""`cnosdb-tpu` server entry point (reference: main/src/main.rs `cnosdb run`).

The HTTP/SQL service is attached here as the service layer lands; this
module always exists so the console script resolves.
"""
from __future__ import annotations

import argparse
import sys


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cnosdb-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd")
    run = sub.add_parser("run", help="run a data/query/meta node")
    run.add_argument("--config", default=None, help="TOML config path")
    run.add_argument("--data-dir", default="./cnosdb-data")
    run.add_argument("--http-port", type=int, default=8902)
    run.add_argument("-M", "--mode", default="singleton",
                     choices=["singleton", "query_tskv", "tskv", "query",
                              "meta"])
    run.add_argument("--meta", default=None,
                     help="meta service address host:port (cluster modes)")
    run.add_argument("--node-id", type=int, default=1)
    run.add_argument("--rpc-port", type=int, default=0,
                     help="node-to-node RPC port (0 = ephemeral)")
    run.add_argument("--meta-port", type=int, default=8901,
                     help="meta service port (mode=meta)")
    run.add_argument("--meta-peers", default=None,
                     help="replicated meta group members as "
                          "'1@host:port,2@host:port,...' (mode=meta)")
    run.add_argument("--meta-host", default="127.0.0.1",
                     help="meta RPC bind host; set 0.0.0.0 for multi-host "
                          "groups (the RPC plane is unauthenticated)")
    cfg = sub.add_parser("config", help="print default config")
    check = sub.add_parser("check", help="validate a config file")
    check.add_argument("path")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.cmd in (None, "run"):
        _guard_degraded_relay()
        from .http import run_server

        return run_server(args)
    if args.cmd == "config":
        from ..config import Config

        print(Config().to_toml())
        return 0
    if args.cmd == "check":
        from ..config import Config

        Config.load(args.path)
        print("config ok")
        return 0
    return 1


def _guard_degraded_relay() -> None:
    """A degraded TPU relay hangs `import jax` itself (the axon plugin
    dials it at import when PALLAS_AXON_POOL_IPS is set) — which would
    freeze the SERVER at its first query's placement probe. Probe in a
    subprocess before any jax import; on a hang, re-exec the server on
    CPU jax with the relay var stripped (same guard as bench.py and
    __graft_entry__; cnosdb_tpu/utils/relay.py)."""
    import os

    if os.environ.get("CNOSDB_SERVER_REEXEC"):
        return
    from ..utils.relay import cleaned_cpu_env, probe_jax_importable

    verdict = probe_jax_importable(timeout=30.0)
    if verdict is None:
        return
    print(f"# {verdict}\n# re-exec server on CPU jax", file=sys.stderr)
    os.execve(sys.executable, [sys.executable, "-m", "cnosdb_tpu.server.main",
                               *(sys.argv[1:])],
              cleaned_cpu_env({"CNOSDB_SERVER_REEXEC": "1"}))


if __name__ == "__main__":
    sys.exit(main())
