"""Third-party trace ingest + Jaeger query API.

Role-parity with the reference's OTLP→Jaeger pipeline
(main/src/opentelemetry/otlp_to_jaeger.rs, 920 LoC;
main/src/http/http_service.rs:1673-2407 jaeger HTTP endpoints): OTLP/HTTP
trace export lands in the database's own storage (a `trace_spans`
measurement — service name as the tag, span identity/timing/attributes
as fields), and Jaeger's HTTP query API is answered by SQL over that
table, so stored traces are ALSO queryable like any other data.

The ingest accepts the OTLP/HTTP JSON encoding (the `otlphttp` exporter's
json mode); protobuf-encoded OTLP is rejected with 415 — the JSON
encoding is part of the OTLP spec and needs no generated bindings.
"""
from __future__ import annotations

import json

TRACE_TABLE = "trace_spans"


def _attr_value(v: dict):
    for k in ("stringValue", "intValue", "doubleValue", "boolValue"):
        if k in v:
            val = v[k]
            return int(val) if k == "intValue" else val
    if "arrayValue" in v:
        return [_attr_value(x) for x in v["arrayValue"].get("values", [])]
    return None


def _attrs(lst) -> dict:
    return {a["key"]: _attr_value(a.get("value", {})) for a in (lst or [])}


_KINDS = {0: "unspecified", 1: "internal", 2: "server", 3: "client",
          4: "producer", 5: "consumer"}


def parse_otlp_json(body: bytes) -> list[dict]:
    """OTLP/HTTP JSON ExportTraceServiceRequest → span row dicts."""
    req = json.loads(body)
    rows: list[dict] = []
    for rs in req.get("resourceSpans", []):
        rattrs = _attrs(rs.get("resource", {}).get("attributes"))
        service = str(rattrs.get("service.name", "unknown"))
        for ss in rs.get("scopeSpans", []) + rs.get("instrumentationLibrarySpans", []):
            for sp in ss.get("spans", []):
                start = int(sp.get("startTimeUnixNano", 0))
                end = int(sp.get("endTimeUnixNano", start))
                kind = sp.get("kind", 0)
                if isinstance(kind, str):   # "SPAN_KIND_SERVER" form
                    kind = {f"SPAN_KIND_{v.upper()}": k
                            for k, v in _KINDS.items()}.get(kind, 0)
                status = sp.get("status", {}).get("code", 0)
                if isinstance(status, str):
                    status = {"STATUS_CODE_UNSET": 0, "STATUS_CODE_OK": 1,
                              "STATUS_CODE_ERROR": 2}.get(status, 0)
                rows.append({
                    "time": start,
                    "service_name": service,
                    "trace_id": str(sp.get("traceId", "")),
                    "span_id": str(sp.get("spanId", "")),
                    "parent_span_id": str(sp.get("parentSpanId", "") or ""),
                    "operation_name": str(sp.get("name", "")),
                    "span_kind": _KINDS.get(int(kind), "unspecified"),
                    "duration_ns": max(0, end - start),
                    "status_code": int(status),
                    "attributes": json.dumps(
                        {**rattrs, **_attrs(sp.get("attributes"))},
                        sort_keys=True),
                })
    return rows


# ------------------------------------------------------------- jaeger out
def jaeger_tags(attr_json: str) -> list[dict]:
    try:
        attrs = json.loads(attr_json) if attr_json else {}
    except Exception:
        attrs = {}
    out = []
    for k, v in sorted(attrs.items()):
        t = ("bool" if isinstance(v, bool)
             else "int64" if isinstance(v, int)
             else "float64" if isinstance(v, float) else "string")
        out.append({"key": k, "type": t,
                    "value": v if t != "string" else str(v)})
    return out


def spans_to_jaeger_traces(rows: list[dict]) -> list[dict]:
    """Engine rows (dicts with the trace_spans columns) → jaeger /api
    trace objects, spans grouped by trace id."""
    by_trace: dict[str, list[dict]] = {}
    for r in rows:
        by_trace.setdefault(r["trace_id"], []).append(r)
    out = []
    for trace_id, spans in by_trace.items():
        procs: dict[str, str] = {}
        jspans = []
        for r in spans:
            svc = r["service_name"]
            pid = procs.setdefault(svc, f"p{len(procs) + 1}")
            refs = []
            if r.get("parent_span_id"):
                refs.append({"refType": "CHILD_OF", "traceID": trace_id,
                             "spanID": r["parent_span_id"]})
            jspans.append({
                "traceID": trace_id,
                "spanID": r["span_id"],
                "operationName": r["operation_name"],
                "references": refs,
                "startTime": int(r["time"]) // 1000,        # µs
                "duration": int(r["duration_ns"]) // 1000,  # µs
                "tags": jaeger_tags(r.get("attributes", ""))
                + [{"key": "span.kind", "type": "string",
                    "value": r.get("span_kind", "unspecified")},
                   {"key": "otel.status_code", "type": "int64",
                    "value": int(r.get("status_code", 0))}],
                "processID": pid,
            })
        out.append({
            "traceID": trace_id,
            "spans": jspans,
            "processes": {pid: {"serviceName": svc, "tags": []}
                          for svc, pid in procs.items()},
        })
    return out
