"""Arrow Flight SQL service.

Role-parity with the reference's Flight SQL server (main/src/flight_sql/
flight_sql_server.rs, 1,255 LoC): implements the actual FlightSQL command
set over gRPC — FlightDescriptor.cmd carries a protobuf `Any` wrapping
arrow.flight.protocol.sql messages:

  CommandStatementQuery  → GetFlightInfo executes the statement, returns
                           the REAL result schema + a TicketStatementQuery
                           endpoint; DoGet streams the cached result
  CommandGetCatalogs / CommandGetDbSchemas / CommandGetTables
                         → catalog browsing per the FlightSQL spec

The three messages involved are tiny, so their protobuf wire format is
encoded/decoded directly (varint + length-delimited fields) — no protoc
dependency. A legacy raw ticket (b"<db>\\x00<sql>" or plain SQL bytes)
remains accepted for simple `do_get(Ticket(sql))` clients.

Clients authenticate with basic auth middleware, as in the reference.
"""
from __future__ import annotations

import base64
import secrets
import threading

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.flight as fl

    FLIGHT_AVAILABLE = True
except Exception:  # pragma: no cover - pyarrow always present in this env
    FLIGHT_AVAILABLE = False

from ..sql.executor import QueryExecutor, ResultSet, Session
from ..utils import lockwatch

# ---------------------------------------------------------------- protobuf
_SQL_NS = "type.googleapis.com/arrow.flight.protocol.sql."


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_bytes_field(field_no: int, payload: bytes) -> bytes:
    return _pb_varint((field_no << 3) | 2) + _pb_varint(len(payload)) + payload


def _pb_parse(data: bytes) -> dict[int, list]:
    """Minimal protobuf reader: varint (0) and length-delimited (2)."""
    out: dict[int, list] = {}
    i, n = 0, len(data)
    while i < n:
        key = 0
        shift = 0
        while True:
            b = data[i]
            i += 1
            key |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        field, wt = key >> 3, key & 7
        if wt == 0:
            val = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            val = data[i:i + ln]
            i += ln
        else:  # pragma: no cover - the sql messages only use wt 0/2
            raise ValueError(f"unsupported protobuf wire type {wt}")
        out.setdefault(field, []).append(val)
    return out


def _any_pack(type_name: str, payload: bytes) -> bytes:
    return (_pb_bytes_field(1, (_SQL_NS + type_name).encode())
            + _pb_bytes_field(2, payload))


def _any_unpack(raw: bytes) -> tuple[str, bytes] | None:
    """→ (short type name, value bytes) for arrow.flight.protocol.sql
    messages, else None (legacy raw-SQL descriptors)."""
    try:
        fields = _pb_parse(raw)
        url = fields.get(1, [b""])[0]
        if not isinstance(url, bytes) or b"arrow.flight.protocol.sql." \
                not in url:
            return None
        val = fields.get(2, [b""])[0]
        return url.rsplit(b".", 1)[-1].decode(), \
            val if isinstance(val, bytes) else b""
    except Exception:
        return None


def command_statement_query(sql: str) -> bytes:
    """Client-side helper: a standard FlightSQL statement descriptor
    (what adbc/JDBC drivers send)."""
    return _any_pack("CommandStatementQuery",
                     _pb_bytes_field(1, sql.encode()))


def command_get_tables(include_schema: bool = False) -> bytes:
    payload = b""
    if include_schema:
        payload += _pb_varint((5 << 3) | 0) + _pb_varint(1)
    return _any_pack("CommandGetTables", payload)


def command_get_catalogs() -> bytes:
    return _any_pack("CommandGetCatalogs", b"")


def command_get_db_schemas() -> bytes:
    return _any_pack("CommandGetDbSchemas", b"")


def action_create_prepared_statement(sql: str) -> bytes:
    """Client-side body for the CreatePreparedStatement action."""
    return _any_pack("ActionCreatePreparedStatementRequest",
                     _pb_bytes_field(1, sql.encode()))


def action_close_prepared_statement(handle: bytes) -> bytes:
    return _any_pack("ActionClosePreparedStatementRequest",
                     _pb_bytes_field(1, handle))


def command_prepared_statement_query(handle: bytes) -> bytes:
    return _any_pack("CommandPreparedStatementQuery",
                     _pb_bytes_field(1, handle))


def command_statement_update(sql: str) -> bytes:
    return _any_pack("CommandStatementUpdate",
                     _pb_bytes_field(1, sql.encode()))


def command_prepared_statement_update(handle: bytes) -> bytes:
    return _any_pack("CommandPreparedStatementUpdate",
                     _pb_bytes_field(1, handle))


# -------------------------------------------------------------- binding
def _sql_literal(v) -> str:
    """Render one bound parameter as a SQL literal. Strings quote with ''
    doubling; a bound value can never escape its literal position."""
    if v is None:
        return "NULL"
    if isinstance(v, (bool, np.bool_)):
        return "true" if v else "false"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        f = float(v)
        if f != f or f in (float("inf"), float("-inf")):
            raise ValueError("non-finite float parameters are unsupported")
        return repr(f)
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise ValueError(f"unsupported parameter type {type(v).__name__}")


def count_placeholders(sql: str) -> int:
    """Number of bindable `?` positions (same quote scan as bind_sql)."""
    count = 0
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in ("'", '"'):
            q = c
            j = i + 1
            while j < n:
                if sql[j] == q:
                    if j + 1 < n and sql[j + 1] == q:
                        j += 2
                        continue
                    break
                j += 1
            i = j + 1
            continue
        if c == "?":
            count += 1
        i += 1
    return count


def bind_sql(sql: str, params: list) -> str:
    """Substitute `?` placeholders with literals, skipping quoted strings
    and quoted identifiers (the reference returns unimplemented here; this
    engine binds). Raises on placeholder/parameter count mismatch."""
    out = []
    i, n = 0, len(sql)
    it = iter(params)
    used = 0
    while i < n:
        c = sql[i]
        if c in ("'", '"'):
            q = c
            j = i + 1
            while j < n:
                if sql[j] == q:
                    if j + 1 < n and sql[j + 1] == q:   # doubled quote
                        j += 2
                        continue
                    break
                j += 1
            out.append(sql[i:min(j + 1, n)])
            i = j + 1
            continue
        if c == "?":
            try:
                out.append(_sql_literal(next(it)))
            except StopIteration:
                raise ValueError("more placeholders than parameters")
            used += 1
            i += 1
            continue
        out.append(c)
        i += 1
    if used != len(params):
        raise ValueError(f"{len(params)} parameters for {used} placeholders")
    return "".join(out)


# ---------------------------------------------------------------- arrow
def result_to_arrow(rs: ResultSet) -> "pa.Table":
    arrays, names = [], []
    for name, col in zip(rs.names, rs.columns):
        names.append(name)
        if col.dtype == object:
            arrays.append(pa.array([None if v is None else v for v in col]))
        elif np.issubdtype(col.dtype, np.floating):
            arrays.append(pa.array(col, from_pandas=True))  # NaN → null
        else:
            arrays.append(pa.array(col))
    return pa.table(arrays, names=names)


if FLIGHT_AVAILABLE:

    class _DbHeaderMiddleware(fl.ServerMiddleware):
        def __init__(self, db: str):
            self.db = db

    class _DbHeaderFactory(fl.ServerMiddlewareFactory):
        """FlightSQL has no database field in CommandStatementQuery;
        drivers select one via a connection header (adbc:
        `adbc.flight.sql.rpc.call_header.database`, surfaced as a
        `database` gRPC header here)."""

        def start_call(self, info, headers):
            db = None
            for k, v in headers.items():
                if k.lower() in ("database", "db", "x-cnosdb-database"):
                    db = v[0] if isinstance(v, (list, tuple)) else v
            return _DbHeaderMiddleware(db or "public")

    class _BasicAuthMiddlewareFactory(fl.ServerMiddlewareFactory):
        def __init__(self, server):
            self.server = server

        def start_call(self, info, headers):
            if not self.server.auth_enabled:
                return None
            auth = None
            for k, v in headers.items():
                if k.lower() == "authorization":
                    auth = v[0]
            if not auth or not auth.startswith("Basic "):
                raise fl.FlightUnauthenticatedError("basic auth required")
            try:
                user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
            except Exception:
                raise fl.FlightUnauthenticatedError("bad authorization")
            if self.server.meta.check_user(user, pw) is None:
                raise fl.FlightUnauthenticatedError("invalid credentials")
            return None

    class FlightSqlServer(fl.FlightServerBase):
        def __init__(self, executor: QueryExecutor, location: str,
                     auth_enabled: bool = False):
            self.executor = executor
            self.meta = executor.meta
            self.auth_enabled = auth_enabled
            super().__init__(
                location,
                middleware={"auth": _BasicAuthMiddlewareFactory(self),
                            "db": _DbHeaderFactory()})
            self.location = location
            # statement_handle → executed Table (one do_get consumes it)
            self._results: dict[bytes, "pa.Table"] = {}
            self._results_lock = lockwatch.Lock("flight.results")
            # prepared handle → last bound parameter row (DoPut with a
            # CommandPreparedStatementQuery descriptor binds; the next
            # get_flight_info on that handle consumes the binding)
            self._bound_params: dict[bytes, list] = {}

        # ------------------------------------------------------ execution
        def _execute(self, db: str, sql: str) -> "pa.Table":
            session = Session(database=db or "public")
            rs = self.executor.execute_one(sql, session)
            return result_to_arrow(rs)

        def _catalog_table(self, kind: str, include_schema: bool):
            dbs = sorted({o.split(".", 1)[1]
                          for o in self.meta.databases})
            if kind == "CommandGetCatalogs":
                return pa.table({"catalog_name": ["cnosdb"]})
            if kind == "CommandGetDbSchemas":
                return pa.table({
                    "catalog_name": ["cnosdb"] * len(dbs),
                    "db_schema_name": dbs})
            rows = {"catalog_name": [], "db_schema_name": [],
                    "table_name": [], "table_type": []}
            schemas = []
            for owner, tables in sorted(self.meta.tables.items()):
                db = owner.split(".", 1)[1]
                for tname, ts in sorted(tables.items()):
                    rows["catalog_name"].append("cnosdb")
                    rows["db_schema_name"].append(db)
                    rows["table_name"].append(tname)
                    rows["table_type"].append("TABLE")
                    if include_schema:
                        cols = {c: pa.array([], pa.float64())
                                for c in ts.field_names()}
                        schemas.append(
                            pa.table(cols).schema.serialize().to_pybytes()
                            if cols else b"")
            if include_schema:
                rows["table_schema"] = schemas
            return pa.table(rows)

        def _info_for(self, descriptor, table: "pa.Table",
                      handle: bytes) -> "fl.FlightInfo":
            with self._results_lock:
                if len(self._results) > 64:
                    self._results.clear()   # dropped handles re-execute
                self._results[handle] = table
            ticket = fl.Ticket(_any_pack(
                "TicketStatementQuery", _pb_bytes_field(1, handle)))
            endpoint = fl.FlightEndpoint(ticket, [self.location])
            return fl.FlightInfo(table.schema, descriptor, [endpoint],
                                 table.num_rows, table.nbytes)

        # ------------------------------------------------------ protocol
        def get_flight_info(self, context, descriptor):
            raw = descriptor.command or b""
            parsed = _any_unpack(raw)
            if parsed is not None:
                kind, val = parsed
                if kind == "CommandStatementQuery":
                    fields = _pb_parse(val)
                    sql = fields.get(1, [b""])[0].decode()
                    db = "public"
                    try:
                        db = context.get_middleware("db").db
                    except Exception:
                        pass
                    # statement handle doubles as a re-execution recipe;
                    # the uniqueness suffix is hex so it can never contain
                    # the \x00 separators
                    handle = db.encode() + b"\x00" + sql.encode() \
                        + b"\x00" + secrets.token_hex(8).encode()
                    return self._info_for(
                        descriptor, self._execute(db, sql), handle)
                if kind == "CommandPreparedStatementQuery":
                    handle = _pb_parse(val).get(1, [b""])[0]
                    db, _, rest = handle.partition(b"\x00")
                    sql = rest.rsplit(b"\x00", 1)[0]
                    if not sql:
                        raise fl.FlightServerError(
                            "unknown prepared statement handle")
                    run_sql = sql.decode()
                    with self._results_lock:
                        params = self._bound_params.get(handle)
                    if params is not None:
                        try:
                            run_sql = bind_sql(run_sql, params)
                        except ValueError as e:
                            raise fl.FlightServerError(str(e))
                        # the ticket handle embeds the BOUND sql so a
                        # cache-evicted do_get re-derives the same rows
                        handle = (db + b"\x00" + run_sql.encode() + b"\x00"
                                  + secrets.token_hex(8).encode())
                    return self._info_for(
                        descriptor, self._execute(db.decode(), run_sql),
                        handle)
                if kind in ("CommandGetCatalogs", "CommandGetDbSchemas",
                            "CommandGetTables"):
                    include_schema = False
                    if kind == "CommandGetTables":
                        include_schema = bool(
                            _pb_parse(val).get(5, [0])[0])
                    table = self._catalog_table(kind, include_schema)
                    # the handle recipe keeps the include_schema flag so a
                    # cache-evicted re-derivation matches the advertised
                    # schema exactly
                    recipe = f"{kind}|{int(include_schema)}"
                    return self._info_for(
                        descriptor, table,
                        b"\x00" + recipe.encode() + b"\x00"
                        + secrets.token_hex(8).encode())
                raise fl.FlightServerError(
                    f"unsupported FlightSQL command {kind}")
            # legacy: descriptor.command is raw (db\x00)sql — execute and
            # advertise the true schema the same way
            db, sep, sql = raw.partition(b"\x00")
            if not sep:
                db, sql = b"public", raw
            handle = db + b"\x00" + sql + b"\x00" \
                + secrets.token_hex(8).encode()
            return self._info_for(
                descriptor, self._execute(db.decode(), sql.decode()), handle)

        def do_action(self, context, action):
            """FlightSQL actions (reference flight_sql_server.rs:933
            do_action_create_prepared_statement /
            do_action_close_prepared_statement). The prepared handle is a
            replayable (db, sql) recipe; `?` placeholders bind via DoPut
            (bind_sql — the reference returns unimplemented there);
            preparing a READ statement runs it once to advertise the TRUE
            dataset schema (JDBC drivers prepare even ad-hoc statements);
            preparing DML/DDL is side-effect free."""
            body = action.body.to_pybytes() if action.body else b""
            parsed = _any_unpack(body)
            val = parsed[1] if parsed else body
            if action.type == "CreatePreparedStatement":
                sql = _pb_parse(val).get(1, [b""])[0].decode()
                db = "public"
                try:
                    db = context.get_middleware("db").db
                except Exception:
                    pass
                handle = db.encode() + b"\x00" + sql.encode() + b"\x00" \
                    + secrets.token_hex(8).encode()
                # only READ statements run at prepare, and only for their
                # SCHEMA: a LIMIT-0 wrapper avoids paying the full query
                # twice (get_flight_info re-executes); preparing DML/DDL
                # must not apply side effects — JDBC prepares an INSERT
                # before running it
                first_kw = (sql.lstrip().split(None, 1) or [""])[0].lower()
                if first_kw in ("select", "show", "describe", "explain",
                                "union"):
                    # parameterized statements probe with NULL bindings —
                    # same shape, no rows needed; an unprobeable form
                    # (e.g. LIMIT ?) advertises schema at execute time
                    n_params = count_placeholders(sql)
                    probe_sql = bind_sql(sql, [None] * n_params) \
                        if n_params else sql
                    try:
                        try:
                            probe = (f"SELECT * FROM ({probe_sql}) __prep "
                                     "LIMIT 0"
                                     if first_kw in ("select", "union")
                                     else probe_sql)
                            table = self._execute(db, probe)
                        except Exception:
                            table = self._execute(db, probe_sql)
                        schema_ipc = table.schema.serialize().to_pybytes()
                    except Exception:
                        if not n_params:
                            raise
                        schema_ipc = pa.schema([]).serialize().to_pybytes()
                else:
                    schema_ipc = pa.schema([]).serialize().to_pybytes()
                result = (_pb_bytes_field(1, handle)
                          + _pb_bytes_field(2, schema_ipc)
                          + _pb_bytes_field(3, b""))
                yield fl.Result(_any_pack(
                    "ActionCreatePreparedStatementResult", result))
                return
            if action.type == "ClosePreparedStatement":
                handle = _pb_parse(val).get(1, [b""])[0]
                with self._results_lock:
                    self._results.pop(handle, None)
                    self._bound_params.pop(handle, None)
                return
            raise fl.FlightServerError(
                f"unsupported action {action.type!r}")

        def list_actions(self, context):
            return [("CreatePreparedStatement",
                     "plan a SQL statement, return handle + schema"),
                    ("ClosePreparedStatement",
                     "release a prepared statement handle")]

        def _read_param_rows(self, reader) -> list[list]:
            """Drain the DoPut stream → list of parameter rows (positional
            python values); empty stream → []."""
            rows: list[list] = []
            try:
                while True:
                    chunk = reader.read_chunk()
                    batch = chunk.data
                    if batch is None or batch.num_rows == 0:
                        continue
                    cols = [batch.column(i).to_pylist()
                            for i in range(batch.num_columns)]
                    for r in range(batch.num_rows):
                        rows.append([c[r] for c in cols])
            except StopIteration:
                pass
            return rows

        def _affected(self, rs) -> int:
            # DML returns a 1-row count cell (the real affected count);
            # DDL returns a message row → 0 affected
            if rs.names and rs.n_rows == 1:
                v = rs.columns[0][0]
                if isinstance(v, (int, np.integer)):
                    return int(v)
            elif rs.names:
                return rs.n_rows
            return 0

        def do_put(self, context, descriptor, reader, writer):
            """CommandStatementUpdate / CommandPreparedStatementUpdate →
            execute (once per bound parameter row — JDBC executeBatch),
            reply DoPutUpdateResult{record_count} in the metadata stream;
            CommandPreparedStatementQuery → bind parameters for the next
            get_flight_info on that handle (the reference returns
            unimplemented for this one; here it binds)."""
            parsed = _any_unpack(descriptor.command or b"")
            if parsed is None:
                raise fl.FlightServerError("unsupported DoPut descriptor")
            kind, val = parsed
            fields = _pb_parse(val)
            if kind == "CommandPreparedStatementQuery":
                handle = fields.get(1, [b""])[0]
                rows = self._read_param_rows(reader)
                if len(rows) > 1:
                    raise fl.FlightServerError(
                        "one parameter row expected for a query binding")
                with self._results_lock:
                    self._bound_params[handle] = rows[0] if rows else []
                result = _any_pack("DoPutPreparedStatementResult",
                                   _pb_bytes_field(1, handle))
                writer.write(pa.py_buffer(result))
                return
            if kind == "CommandStatementUpdate":
                sql = fields.get(1, [b""])[0].decode()
                db = "public"
                try:
                    db = context.get_middleware("db").db
                except Exception:
                    pass
                param_rows = self._read_param_rows(reader)
            elif kind == "CommandPreparedStatementUpdate":
                handle = fields.get(1, [b""])[0]
                dbb, _, rest = handle.partition(b"\x00")
                db, sql = dbb.decode(), rest.rsplit(b"\x00", 1)[0].decode()
                param_rows = self._read_param_rows(reader)
            else:
                raise fl.FlightServerError(
                    f"unsupported DoPut command {kind}")
            affected = 0
            try:
                if param_rows:
                    for row in param_rows:
                        rs = self.executor.execute_one(
                            bind_sql(sql, row), Session(database=db))
                        affected += self._affected(rs)
                else:
                    rs = self.executor.execute_one(sql, Session(database=db))
                    affected = self._affected(rs)
            except ValueError as e:
                raise fl.FlightServerError(str(e))
            update_result = _pb_varint((1 << 3) | 0) + _pb_varint(affected)
            writer.write(pa.py_buffer(update_result))

        def do_get(self, context, ticket):
            raw = ticket.ticket
            parsed = _any_unpack(raw)
            if parsed is not None and parsed[0] == "TicketStatementQuery":
                handle = _pb_parse(parsed[1]).get(1, [b""])[0]
                with self._results_lock:
                    table = self._results.pop(handle, None)
                if table is None:
                    # evicted / different process: re-derive from the
                    # recipe embedded in the handle
                    db, _, rest = handle.partition(b"\x00")
                    sql = rest.rsplit(b"\x00", 1)[0]
                    if not sql:
                        raise fl.FlightServerError("stale statement handle")
                    if db == b"":   # catalog command handle: kind|flag
                        kind, _, flag = sql.decode().partition("|")
                        table = self._catalog_table(kind, flag == "1")
                    else:
                        table = self._execute(db.decode(), sql.decode())
                return fl.RecordBatchStream(table)
            # legacy ticket payload: b"<db>\x00<sql>" (db optional)
            db, sep, sql = raw.partition(b"\x00")
            if not sep:
                db, sql = b"public", raw
            return fl.RecordBatchStream(
                self._execute(db.decode(), sql.decode()))

    def start_flight_server(executor: QueryExecutor, port: int,
                            auth_enabled: bool = False) -> "FlightSqlServer":
        server = FlightSqlServer(executor, f"grpc://0.0.0.0:{port}",
                                 auth_enabled=auth_enabled)
        t = threading.Thread(target=server.serve, daemon=True)
        t.start()
        return server
else:  # pragma: no cover
    def start_flight_server(*a, **k):
        raise RuntimeError("pyarrow.flight not available")
