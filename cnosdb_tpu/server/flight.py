"""Arrow Flight SQL service.

Role-parity with the reference's Flight SQL server (main/src/flight_sql/
flight_sql_server.rs): clients authenticate with basic auth, submit SQL via
GetFlightInfo/DoGet (the simplified Flight pattern pyarrow clients use:
`flight.connect(...).do_get(Ticket(sql))`), and receive Arrow record
batches. Results convert from the engine's numpy columns zero-copy where
possible.
"""
from __future__ import annotations

import base64
import threading

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.flight as fl

    FLIGHT_AVAILABLE = True
except Exception:  # pragma: no cover - pyarrow always present in this env
    FLIGHT_AVAILABLE = False

from ..sql.executor import QueryExecutor, ResultSet, Session


def result_to_arrow(rs: ResultSet) -> "pa.Table":
    arrays, names = [], []
    for name, col in zip(rs.names, rs.columns):
        names.append(name)
        if col.dtype == object:
            arrays.append(pa.array([None if v is None else v for v in col]))
        elif np.issubdtype(col.dtype, np.floating):
            arrays.append(pa.array(col, from_pandas=True))  # NaN → null
        else:
            arrays.append(pa.array(col))
    return pa.table(arrays, names=names)


if FLIGHT_AVAILABLE:

    class _BasicAuthMiddlewareFactory(fl.ServerMiddlewareFactory):
        def __init__(self, server):
            self.server = server

        def start_call(self, info, headers):
            if not self.server.auth_enabled:
                return None
            auth = None
            for k, v in headers.items():
                if k.lower() == "authorization":
                    auth = v[0]
            if not auth or not auth.startswith("Basic "):
                raise fl.FlightUnauthenticatedError("basic auth required")
            try:
                user, _, pw = base64.b64decode(auth[6:]).decode().partition(":")
            except Exception:
                raise fl.FlightUnauthenticatedError("bad authorization")
            if self.server.meta.check_user(user, pw) is None:
                raise fl.FlightUnauthenticatedError("invalid credentials")
            return None

    class FlightSqlServer(fl.FlightServerBase):
        def __init__(self, executor: QueryExecutor, location: str,
                     auth_enabled: bool = False):
            self.executor = executor
            self.meta = executor.meta
            self.auth_enabled = auth_enabled
            super().__init__(
                location,
                middleware={"auth": _BasicAuthMiddlewareFactory(self)})
            self.location = location

        # ticket payload: b"<db>\x00<sql>" (db optional)
        def do_get(self, context, ticket):
            raw = ticket.ticket
            db, sep, sql = raw.partition(b"\x00")
            if not sep:
                db, sql = b"public", raw
            session = Session(database=db.decode() or "public")
            rs = self.executor.execute_one(sql.decode(), session)
            table = result_to_arrow(rs)
            return fl.RecordBatchStream(table)

        def get_flight_info(self, context, descriptor):
            sql = descriptor.command or b""
            ticket = fl.Ticket(sql)
            endpoint = fl.FlightEndpoint(ticket, [self.location])
            # execute lazily at do_get; advertise unknown schema cheaply
            schema = pa.schema([])
            return fl.FlightInfo(schema, descriptor, [endpoint], -1, -1)

    def start_flight_server(executor: QueryExecutor, port: int,
                            auth_enabled: bool = False) -> "FlightSqlServer":
        server = FlightSqlServer(executor, f"grpc://0.0.0.0:{port}",
                                 auth_enabled=auth_enabled)
        t = threading.Thread(target=server.serve, daemon=True)
        t.start()
        return server
else:  # pragma: no cover
    def start_flight_server(*a, **k):
        raise RuntimeError("pyarrow.flight not available")
