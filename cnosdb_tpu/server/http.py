"""HTTP service: the primary user-facing API.

Role-parity with the reference's HttpService (main/src/http/
http_service.rs): /api/v1/write (line protocol), /api/v1/sql, /api/v1/ping,
/api/v1/opentsdb/write, /metrics (Prometheus text), with basic auth and
per-request db / precision / pretty parameters, csv|json result encoding
via the Accept header (main/src/http/response.rs, result_format.rs).
"""
from __future__ import annotations

import asyncio
import base64
import json
import sys
import time

import numpy as np
from aiohttp import web

from .. import __version__
from ..errors import CnosError, DeadlineExceeded, ParserError, QueryError
from ..models.schema import Precision
from ..parallel.coordinator import Coordinator
from ..parallel.meta import MetaStore, DEFAULT_TENANT
from ..protocol.line_protocol import parse_lines
from ..sql.executor import QueryExecutor, ResultSet, Session
from ..storage.engine import TsKv
from ..utils import deadline as deadline_mod
from ..utils import stages
from .admission import AdmissionGate
from .metrics import MetricsRegistry

# per-request deadline override (milliseconds of budget from ingress);
# absent → the config [query] read_timeout_ms / write_timeout_ms defaults
DEADLINE_HEADER = "X-CnosDB-Deadline-Ms"
# opt-in per-query profiling: any truthy value on the request installs a
# QueryProfile at ingress; the response then carries a compact JSON
# summary header, and the full profile is at /debug/profile?qid=
PROFILE_HEADER = "X-CnosDB-Profile"
PROFILE_SUMMARY_HEADER = "X-CnosDB-Profile-Summary"


class HttpServer:
    def __init__(self, meta: MetaStore, coord: Coordinator,
                 executor: QueryExecutor, auth_enabled: bool = False,
                 query_cfg=None):
        from ..config import QueryConfig

        self.meta = meta
        self.coord = coord
        self.executor = executor
        self.auth_enabled = auth_enabled
        self.metrics = MetricsRegistry()
        qc = query_cfg or QueryConfig()
        self.read_timeout_ms = int(qc.read_timeout_ms)
        self.write_timeout_ms = int(qc.write_timeout_ms)
        # slow-query log: [query] slow_query_threshold_ms (0 = off);
        # enforced in the executor so KILLed/expired queries still log
        executor.slow_query_threshold_ms = \
            int(getattr(qc, "slow_query_threshold_ms", 0) or 0)
        # gray-failure plane: push [query] hedge knobs into the
        # process-global health scorer (the coordinator reads the module
        # globals at hedge time, so late configure() is fine)
        from ..parallel import health as _health

        _health.configure(qc)
        self.gate = AdmissionGate(qc.max_concurrent_queries,
                                  qc.max_queued_queries)
        # memory-governance plane: push [query] memory_* knobs into the
        # broker and hand it the gate so ladder step 2 can shed QUEUED
        # queries (server/memory.py)
        from . import memory as _memory

        _memory.configure(qc)
        _memory.set_admission_gate(self.gate)
        # the serving plane's micro-batcher keys its fuse-or-solo decision
        # off this gate's pressure (queued > 0 / running at the cap)
        sv = getattr(executor, "serving", None)
        if sv is not None:
            sv.attach_gate(self.gate)
        from ..parallel.limiter import TenantLimiters

        self.limiters = TenantLimiters(meta)
        self.app = web.Application(client_max_size=512 * 1024 * 1024)
        self.app.add_routes([
            web.post("/api/v1/write", self.handle_write),
            web.post("/api/v1/sql", self.handle_sql),
            web.get("/api/v1/ping", self.handle_ping),
            web.post("/api/v1/opentsdb/write", self.handle_opentsdb_write),
            web.post("/api/v1/prom/write", self.handle_prom_write),
            web.post("/api/v1/prom/read", self.handle_prom_read),
            web.post("/api/v1/es/_bulk", self.handle_es_bulk),
            # OTLP trace ingest + jaeger query API (reference
            # http_service.rs:1673-2407, otlp_to_jaeger.rs)
            web.post("/api/v1/traces", self.handle_otlp_traces),
            web.post("/v1/traces", self.handle_otlp_traces),
            web.get("/api/services", self.handle_jaeger_services),
            web.get("/api/services/{service}/operations",
                    self.handle_jaeger_operations),
            web.get("/api/traces", self.handle_jaeger_traces),
            web.get("/api/traces/{trace_id}", self.handle_jaeger_trace),
            web.get("/metrics", self.handle_metrics),
            web.get("/debug/health", self.handle_health),
            web.get("/debug/traces", self.handle_traces),
            web.get("/debug/profile", self.handle_profile),
            web.get("/debug/backtrace", self.handle_backtrace),
            web.get("/debug/pprof", self.handle_pprof),
            web.get("/debug/scrub", self.handle_scrub),
            web.get("/debug/backup", self.handle_backup),
            web.get("/debug/matview", self.handle_matview),
            web.get("/debug/lockgraph", self.handle_lockgraph),
            web.get("/debug/memory", self.handle_memory),
        ])
        # background integrity scrubber (storage/scrub.py), attached by
        # run_server when cfg.storage.scrub_interval > 0
        self.scrubber = None

    # ------------------------------------------------------------- helpers
    def _auth(self, request) -> tuple[str, str]:
        """→ (user, tenant); raises 401 on failure."""
        hdr = request.headers.get("Authorization", "")
        user, password = "root", ""
        if hdr.startswith("Basic "):
            try:
                dec = base64.b64decode(hdr[6:]).decode()
                user, _, password = dec.partition(":")
            except Exception:
                raise web.HTTPUnauthorized(text="bad authorization header")
        elif self.auth_enabled:
            raise web.HTTPUnauthorized(text="authorization required")
        if self.auth_enabled:
            if self.meta.check_user(user, password) is None:
                raise web.HTTPUnauthorized(text="invalid user or password")
        tenant = request.query.get("tenant", DEFAULT_TENANT)
        if self.auth_enabled and not self.meta.user_can_access(user, tenant):
            raise web.HTTPForbidden(
                text=f"user {user!r} is not a member of tenant {tenant!r}")
        return user, tenant

    def _session(self, request) -> Session:
        user, tenant = self._auth(request)
        db = request.query.get("db", "public")
        return Session(tenant=tenant, database=db, user=user)

    def _request_deadline(self, request, default_ms: int) -> deadline_mod.Deadline:
        """Per-request lifecycle context, created once at ingress. The
        client may shrink (or extend) the config default via the
        X-CnosDB-Deadline-Ms header; 0 or a negative value means
        unbounded (kill/disconnect cancellation still applies)."""
        raw = request.headers.get(DEADLINE_HEADER)
        ms = default_ms
        if raw is not None:
            try:
                ms = int(float(raw))
            except ValueError:
                raise web.HTTPBadRequest(
                    text=f"bad {DEADLINE_HEADER} header: {raw!r}")
        return deadline_mod.Deadline(ms / 1000.0 if ms > 0 else None)

    def _authorize_read(self, session: Session):
        if not self.auth_enabled:
            return
        if not self.meta.check_db_privilege(session.user, session.tenant,
                                            session.database, "read"):
            raise web.HTTPForbidden(
                text=f"user {session.user!r} lacks read privilege on "
                     f"{session.tenant}.{session.database}")

    def _authorize_write(self, session: Session):
        """RBAC write gate for the ingest endpoints — line-protocol /
        OpenTSDB / prom / ES writes must clear the same bar as SQL INSERT
        (reference http_service.rs privilege checks per route)."""
        if not self.auth_enabled:
            return
        if not self.meta.check_db_privilege(session.user, session.tenant,
                                            session.database, "write"):
            raise web.HTTPForbidden(
                text=f"user {session.user!r} lacks write privilege on "
                     f"{session.tenant}.{session.database}")

    # ------------------------------------------------------------- handlers
    async def handle_ping(self, request):
        return web.json_response({"version": __version__, "status": "healthy"})

    async def handle_write(self, request):
        session = self._session(request)
        self._authorize_write(session)
        precision = request.query.get("precision", "ns")
        try:
            prec = Precision.parse(precision)
        except Exception:
            return _err_response(400, ParserError(f"bad precision {precision!r}"))
        body = await request.text()
        dl = self._request_deadline(request, self.write_timeout_ms)

        def run():
            with deadline_mod.scope(dl):
                self.coord.write_points(session.tenant, session.database,
                                        batch)

        try:
            batch = parse_lines(body, prec)
            self.limiters.check_write(session.tenant, batch.n_rows())
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, run)
        except asyncio.CancelledError:
            dl.cancel("client disconnected")
            raise
        except CnosError as e:
            self.metrics.incr("cnosdb_http_write_errors_total")
            if isinstance(e, DeadlineExceeded):
                self.metrics.incr("cnosdb_requests_deadline_exceeded_total")
            from ..errors import MemoryExceeded, WriteBackpressure

            # memory-ladder outcomes get their own counters: 503-with-
            # Retry-After (flushes draining, retry helps) vs 413 (the
            # write itself is too big / node fail-closed over hard)
            if isinstance(e, WriteBackpressure):
                self.metrics.incr("cnosdb_requests_backpressured_total")
            elif isinstance(e, MemoryExceeded):
                self.metrics.incr("cnosdb_requests_memory_exceeded_total")
            return _err_response(_status_for(e), e)
        self.metrics.incr("cnosdb_http_writes_total")
        self.metrics.incr("cnosdb_http_points_written_total", batch.n_rows())
        self._record_http_usage(request, session, "http_data_in",
                                len(body))
        self._record_http_usage(request, session, "http_writes", 1)
        return web.Response(status=200)

    def _record_http_usage(self, request, session, table: str, value: int):
        """usage_schema HTTP-plane counters (reference http reporters):
        cumulative per (tenant, db, api, user), 1s-throttled."""
        try:
            self.coord.record_usage(
                table,
                {"tenant": session.tenant, "database": session.database,
                 "node_id": str(self.coord.node_id),
                 "api": request.path, "host": request.host,
                 "user": session.user},
                value, throttle=True, cumulative=True)
        except Exception:
            pass

    async def handle_sql(self, request):
        session = self._session(request)
        sql = (await request.text()).strip()
        if not sql:
            return _err_response(400, QueryError("empty sql"))
        accept = request.headers.get("Accept", "application/csv")
        from .trace import GLOBAL_COLLECTOR

        span = GLOBAL_COLLECTOR.from_headers(request.headers, "http:sql")
        span.set_tag("sql", sql[:200]).set_tag("tenant", session.tenant)
        dl = self._request_deadline(request, self.read_timeout_ms)
        # opt-in per-query profile summary: X-CnosDB-Profile: 1 installs
        # the profile at ingress so the response can carry its totals
        # (the full profile stays fetchable at /debug/profile?qid=)
        want_profile = request.headers.get(PROFILE_HEADER, "") \
            not in ("", "0", "false")
        prof = stages.QueryProfile() if want_profile else None

        def run():
            # on the executor worker thread: one thread per in-flight
            # request, so blocking in the admission gate is safe
            # profile_scope(None) is a harmless clear, so no conditional
            with deadline_mod.scope(dl), stages.profile_scope(prof):
                self.gate.acquire(dl)   # AdmissionRejected → 503
                try:
                    with span:
                        return self.executor.execute_sql(sql, session)
                except CnosError:
                    if dl.qid and dl.remote_nodes:
                        # deadline expiry / kill / disconnect unwound the
                        # query while remote vnodes may still be working:
                        # best-effort cancel fan-out frees their workers
                        try:
                            self.coord.cancel_remote_scans(dl)
                        except Exception:
                            pass
                    raise
                finally:
                    self.gate.release()

        t0 = time.monotonic()
        try:
            self.limiters.check_query(session.tenant)
            loop = asyncio.get_running_loop()
            results = await loop.run_in_executor(None, run)
        except asyncio.CancelledError:
            # aiohttp cancels the handler when the client disconnects;
            # flip the cancel flag so the (uninterruptible) worker thread
            # unwinds at its next checkpoint and fans cancels out itself
            dl.cancel("client disconnected")
            raise
        except CnosError as e:
            self.metrics.incr("cnosdb_http_sql_errors_total")
            if isinstance(e, DeadlineExceeded):
                self.metrics.incr("cnosdb_requests_deadline_exceeded_total")
            from ..errors import MemoryExceeded

            if isinstance(e, MemoryExceeded):
                self.metrics.incr("cnosdb_requests_memory_exceeded_total")
            return _err_response(_status_for(e), e)
        self.metrics.incr("cnosdb_http_queries_total")
        # reference query_sql_process_ms: end-to-end SQL latency histogram
        self.metrics.observe("cnosdb_query_sql_process_ms",
                             (time.monotonic() - t0) * 1e3)
        self._record_http_usage(request, session, "http_queries", 1)
        self._record_http_usage(request, session, "http_data_in", len(sql))
        rs = results[-1] if results else ResultSet.empty()
        if "json" in accept:
            resp = web.Response(text=format_json(rs),
                                content_type="application/json")
        elif "table" in accept:
            resp = web.Response(text=format_table(rs),
                                content_type="text/plain")
        else:
            resp = web.Response(text=format_csv(rs), content_type="text/csv")
        if prof is not None:
            import json as _json

            summary = {"qid": prof.qid, "wall_ms": prof.wall_ms,
                       "stages": prof.stage_totals()}
            resp.headers[PROFILE_SUMMARY_HEADER] = _json.dumps(
                summary, separators=(",", ":"))[:4096]
        # gzip negotiation (reference http_service gzip layer)
        if "gzip" in request.headers.get("Accept-Encoding", ""):
            resp.enable_compression()
        return resp

    def _require_admin(self, request):
        """Debug surfaces expose cross-tenant internals (query text, stack
        frames): admin-only when auth is on."""
        if not self.auth_enabled:
            return
        user, _tenant = self._auth(request)
        u = self.meta.users.get(user)
        if u is None or not u.get("admin"):
            raise web.HTTPForbidden(text="debug endpoints are admin-only")

    @staticmethod
    def _query_number(request, name, default, lo, hi):
        try:
            v = float(request.query.get(name, default))
        except ValueError:
            raise web.HTTPBadRequest(text=f"bad {name!r} parameter")
        return min(max(v, lo), hi)

    async def handle_traces(self, request):
        """Collected spans (reference stores traces queryably via its
        jaeger-query API; embedded form returns them directly)."""
        self._require_admin(request)
        from .trace import GLOBAL_COLLECTOR

        tid = request.query.get("trace_id")
        limit = int(self._query_number(request, "limit", 500, 1, 10_000))
        return web.json_response(GLOBAL_COLLECTOR.spans(tid, limit))

    async def handle_profile(self, request):
        """Recent per-query profiles (bounded ring, like traces):
        `?qid=<n>` returns one full profile — stage timings, per-node
        sub-profiles, device telemetry; without qid, summaries of the
        most recent queries."""
        self._require_admin(request)
        qid = request.query.get("qid")
        if qid:
            d = stages.PROFILES.get(qid)
            if d is None:
                raise web.HTTPNotFound(text=f"no profile for qid {qid!r}")
            return web.json_response(d)
        limit = int(self._query_number(request, "limit", 50, 1, 256))
        return web.json_response(stages.PROFILES.recent(limit))

    async def handle_backtrace(self, request):
        """Live thread stacks (reference /debug/backtrace,
        http_service.rs:332)."""
        self._require_admin(request)
        import traceback

        frames = sys._current_frames()
        out = []
        import threading as _th

        names = {t.ident: t.name for t in _th.enumerate()}
        for tid, frame in frames.items():
            out.append(f"--- thread {tid} ({names.get(tid, '?')}):\n"
                       + "".join(traceback.format_stack(frame)))
        return web.Response(text="\n".join(out), content_type="text/plain")

    _pprof_lock = asyncio.Lock()

    async def handle_pprof(self, request):
        """Whole-process sampling CPU profile for ?seconds=N (reference
        /debug/pprof flamegraph, http_service.rs:1045). A sampler over
        sys._current_frames() sees EVERY thread — executor query threads
        and RPC handlers included — unlike cProfile, which instruments
        only the calling thread."""
        self._require_admin(request)
        import traceback

        seconds = self._query_number(request, "seconds", 2, 0.1, 30.0)
        if self._pprof_lock.locked():
            raise web.HTTPConflict(text="a profile is already running")
        async with self._pprof_lock:
            counts: dict[str, int] = {}
            deadline = asyncio.get_running_loop().time() + seconds
            n_samples = 0
            while asyncio.get_running_loop().time() < deadline:
                for tid, frame in sys._current_frames().items():
                    stack = traceback.extract_stack(frame, limit=12)
                    key = ";".join(f"{f.name}@{f.filename.rsplit('/', 1)[-1]}"
                                   f":{f.lineno}" for f in stack[-6:])
                    counts[key] = counts.get(key, 0) + 1
                n_samples += 1
                await asyncio.sleep(0.01)
        lines = [f"# {n_samples} samples over {seconds}s "
                 f"(collapsed stacks, hottest first)"]
        for key, c in sorted(counts.items(), key=lambda kv: -kv[1])[:80]:
            lines.append(f"{c:6d}  {key}")
        return web.Response(text="\n".join(lines), content_type="text/plain")

    async def handle_scrub(self, request):
        """Trigger one synchronous integrity sweep over every local vnode
        (CRC-verify TSM files, index checkpoints, sealed WAL segments;
        corrupt files are quarantined). `?repair=1` additionally runs the
        coordinator's anti-entropy pass so minority-divergent replicas are
        rebuilt from healthy peers before the response returns."""
        self._require_admin(request)
        from ..storage import scrub

        repair = request.query.get("repair", "0") not in ("0", "", "false")

        def run():
            if self.scrubber is not None:
                res = self.scrubber.sweep_once()
            else:
                res = scrub.scrub_engine(
                    self.coord.engine,
                    on_corruption=self.coord.on_scrub_corruption)
            out = {"scrub": res}
            if repair:
                out["repair"] = self.coord.anti_entropy_sweep()
            out["counters"] = scrub.counters_snapshot()
            return out

        loop = asyncio.get_running_loop()
        return web.json_response(await loop.run_in_executor(None, run))

    async def handle_backup(self, request):
        """Disaster-recovery plane status: archive config, per-vnode
        archiver watermarks + lag, counters, and the meta backup catalog.
        `?catchup=1` forces a synchronous seal + archive pass (the manual
        RPO-flush lever; BACKUP DATABASE does this per cut anyway)."""
        self._require_admin(request)
        from ..storage import backup

        catchup = request.query.get("catchup", "0") not in \
            ("0", "", "false")

        def run():
            out = {"enabled": backup.archive_enabled(),
                   "archivers": [], "catalog": {}}
            if not out["enabled"]:
                return out
            if catchup:
                for a in backup.archivers():
                    a.wal.seal_active()
                    a.catch_up()
            for a in backup.archivers():
                out["archivers"].append(
                    {"owner": a.owner, "vnode_id": a.vnode_id,
                     "watermark": a.watermark(),
                     "lag_seconds": a.lag_seconds()})
            out["lag_seconds"] = backup.archive_lag_seconds()
            out["counters"] = {f"{op}.{outcome}": n for (op, outcome), n
                               in backup.backup_snapshot().items()}
            for owner, entries in getattr(self.meta, "backups",
                                          {}).items():
                out["catalog"][owner] = [e["id"] for e in entries]
            return out

        loop = asyncio.get_running_loop()
        return web.json_response(await loop.run_in_executor(None, run))

    async def handle_matview(self, request):
        """Materialized-rollup admin surface: per-vnode watermarks and
        group counts for `?name=`, every registered view without it.
        `?refresh=1` forces a synchronous delta refresh first (with an
        optional deterministic `?now_ns=`), `?verify=1` compares the
        incremental state against a from-scratch recompute — the
        crash/replay chaos oracle."""
        self._require_admin(request)
        me = self.executor.matview_engine()
        name = request.query.get("name")
        refresh = request.query.get("refresh", "0") not in ("0", "", "false")
        verify = request.query.get("verify", "0") not in ("0", "", "false")
        now_ns = request.query.get("now_ns")

        def run():
            me.sync_from_meta()
            if name is None:
                return {"views": sorted(me.views)}
            out = {"name": name}
            if refresh:
                out["refreshed_vnodes"] = me.refresh(
                    name, now_ns=int(now_ns) if now_ns else None)
            out["status"] = me.status(name)
            if verify:
                out["verify"] = me.verify(name)
            return out

        loop = asyncio.get_running_loop()
        try:
            return web.json_response(await loop.run_in_executor(None, run))
        except QueryError as e:
            raise web.HTTPNotFound(text=str(e))

    async def handle_opentsdb_write(self, request):
        """OpenTSDB telnet-style put lines over HTTP (reference
        tcp_service + opentsdb parser)."""
        session = self._session(request)
        self._authorize_write(session)
        body = await request.text()
        from ..protocol.opentsdb import parse_opentsdb, parse_opentsdb_json

        try:
            # the reference serves telnet put lines AND the OpenTSDB
            # JSON body shape; sniff the leading character
            lead = body.lstrip()[:1]
            batch = (parse_opentsdb_json(body) if lead in ("[", "{")
                     else parse_opentsdb(body))
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, lambda: self.coord.write_points(
                    session.tenant, session.database, batch))
        except CnosError as e:
            return _err_response(_status_for(e), e)
        return web.Response(status=200)

    async def handle_prom_write(self, request):
        """Prometheus remote write: snappy + prompb (reference
        prom/remote_server.rs remote_write)."""
        session = self._session(request)
        self._authorize_write(session)
        from ..protocol.prometheus import parse_remote_write, snappy_available

        if not snappy_available():
            return _err_response(501, QueryError("snappy library unavailable"))
        body = await request.read()
        try:
            batch = parse_remote_write(body)
        except CnosError as e:
            return _err_response(_status_for(e), e)
        except Exception as e:
            # malformed prompb must be 4xx: prometheus retries 5xx forever
            return _err_response(400, ParserError(f"bad remote-write body: {e}"))
        try:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, lambda: self.coord.write_points(
                    session.tenant, session.database, batch))
        except CnosError as e:
            return _err_response(_status_for(e), e)
        self.metrics.incr("cnosdb_prom_write_points_total", batch.n_rows())
        return web.Response(status=204)

    async def handle_prom_read(self, request):
        """Prometheus remote read (reference prom/remote_server.rs:478
        remote_read → SQL over the same storage): decode prompb
        ReadRequest, scan per query, stream back a ReadResponse."""
        session = self._session(request)
        self._authorize_read(session)  # same bar as SQL SELECT
        from ..protocol.prometheus import (
            parse_read_request, encode_read_response, snappy_available,
        )

        if not snappy_available():
            return _err_response(501, QueryError("snappy library unavailable"))
        body = await request.read()
        try:
            queries = parse_read_request(body)
        except CnosError as e:
            return _err_response(_status_for(e), e)
        except Exception as e:
            return _err_response(400, ParserError(f"bad remote-read body: {e}"))
        import re as _re

        loop = asyncio.get_running_loop()
        try:
            per_query = await loop.run_in_executor(
                None, lambda: [self._prom_read_query(session, q)
                               for q in queries])
        except _re.error as e:
            # malformed matcher regex must be 4xx — prometheus retries 5xx
            return _err_response(400, ParserError(f"bad matcher regex: {e}"))
        except CnosError as e:
            return _err_response(_status_for(e), e)
        raw = encode_read_response(per_query)
        return web.Response(body=raw,
                            content_type="application/x-protobuf",
                            headers={"Content-Encoding": "snappy"})

    def _prom_read_query(self, session: Session, q: dict) -> list:
        """One prompb Query → [(labels, [(ts_ms, value)])]."""
        import re as _re

        from ..models.predicate import (
            ColumnDomains, SetDomain, TimeRange, TimeRanges,
        )
        from ..protocol.prometheus import (
            MATCH_EQ, MATCH_NEQ, MATCH_NRE, MATCH_RE,
        )

        metric = None
        eq_tags: dict[str, str] = {}
        # post predicates see the ABSENT label as "" (prometheus semantics:
        # a missing label equals the empty string)
        post = []
        for mtype, name, value in q["matchers"]:
            if name == "__name__":
                if mtype == MATCH_EQ:
                    metric = value
                elif mtype == MATCH_RE:
                    metric = None  # regex metric: unsupported → no result
                continue
            if mtype == MATCH_EQ:
                if value == "":
                    post.append((name, lambda v: (v or "") == ""))
                else:
                    eq_tags[name] = value
            elif mtype == MATCH_NEQ:
                post.append((name, lambda v, x=value: (v or "") != x))
            elif mtype == MATCH_RE:
                rx = _re.compile(value)
                post.append((name, lambda v, r=rx:
                             r.fullmatch(v or "") is not None))
            elif mtype == MATCH_NRE:
                rx = _re.compile(value)
                post.append((name, lambda v, r=rx:
                             r.fullmatch(v or "") is None))
        if metric is None:
            return []
        doms = ColumnDomains({k: SetDomain([v]) for k, v in eq_tags.items()}) \
            if eq_tags else ColumnDomains.all()
        trs = TimeRanges([TimeRange(q["start_ms"] * 1_000_000,
                                    q["end_ms"] * 1_000_000)])
        from ..errors import TableNotFound

        try:
            batches = self.coord.scan_table(
                session.tenant, session.database, metric,
                time_ranges=trs, tag_domains=doms, field_names=["value"])
        except TableNotFound:
            return []   # unknown metric = no data; real errors propagate
        series: dict[tuple, list] = {}
        labels_of: dict[tuple, dict] = {}
        for b in batches:
            if "value" not in b.fields:
                continue
            _vt, vals, valid = b.fields["value"]
            for i in range(b.n_rows):
                if not valid[i]:
                    continue
                key = b.series_keys[b.sid_ordinal[i]]
                if key is None:
                    continue
                tags = key.tag_dict()
                if any(not pred(tags.get(name)) for name, pred in post):
                    continue
                sk = tuple(sorted(tags.items()))
                series.setdefault(sk, []).append(
                    (int(b.ts[i]) // 1_000_000, float(vals[i])))
                labels_of.setdefault(sk, {"__name__": metric, **tags})
        out = []
        for sk in sorted(series):
            samples = sorted(series[sk])
            out.append((labels_of[sk], samples))
        return out

    async def handle_es_bulk(self, request):
        """ES-style log ingest (reference `_bulk` json_protocol API)."""
        session = self._session(request)
        self._authorize_write(session)
        table = request.query.get("table", "logs")
        tag_keys = tuple(t for t in request.query.get("tags", "").split(",") if t)
        from ..protocol.es_bulk import parse_es_bulk

        body = await request.text()
        try:
            batch = parse_es_bulk(body, table, tag_keys)
        except CnosError as e:
            return _err_response(_status_for(e), e)
        except Exception as e:
            # valid-JSON-but-wrong-shape lines must be 4xx, not 500
            return _err_response(400, ParserError(f"bad bulk body: {e}"))
        try:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, lambda: self.coord.write_points(
                    session.tenant, session.database, batch))
        except CnosError as e:
            self.metrics.incr("cnosdb_es_bulk_errors_total")
            return _err_response(_status_for(e), e)
        self.metrics.incr("cnosdb_es_bulk_writes_total")
        self.metrics.incr("cnosdb_es_bulk_points_written_total", batch.n_rows())
        return web.json_response({"errors": False, "items": batch.n_rows()})

    # --------------------------------------------------- traces (OTLP in)
    async def handle_otlp_traces(self, request):
        """OTLP/HTTP trace export → the `trace_spans` measurement: spans
        become rows queryable by SQL AND by the jaeger API below."""
        from ..models.points import WriteBatch
        from ..models.schema import ValueType
        from .otlp import TRACE_TABLE, parse_otlp_json

        session = self._session(request)
        self._authorize_write(session)
        ctype = request.headers.get("Content-Type", "")
        if "protobuf" in ctype:
            return web.Response(
                status=415,
                text="OTLP/HTTP protobuf encoding not supported; send the "
                     "OTLP JSON encoding (otlphttp exporter: encoding=json)")
        body = await request.read()
        try:
            rows = parse_otlp_json(body)
        except Exception as e:
            return web.Response(status=400, text=f"bad OTLP JSON: {e}")
        if rows:
            wb = WriteBatch.from_rows(
                TRACE_TABLE, rows,
                tag_names=["service_name", "span_id"],
                field_types={
                    "trace_id": ValueType.STRING,
                    "parent_span_id": ValueType.STRING,
                    "operation_name": ValueType.STRING,
                    "span_kind": ValueType.STRING,
                    "duration_ns": ValueType.INTEGER,
                    "status_code": ValueType.INTEGER,
                    "attributes": ValueType.STRING,
                })
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None, lambda: self.coord.write_points(
                    session.tenant, session.database, wb))
        return web.json_response({"partialSuccess": {}})

    # --------------------------------------------------- jaeger query API
    def _trace_rows(self, session, where: str, limit: int | None = None):
        from .otlp import TRACE_TABLE

        sql = (f"SELECT time, service_name, span_id, trace_id, "
               f"parent_span_id, operation_name, span_kind, duration_ns, "
               f"status_code, attributes FROM {TRACE_TABLE}")
        if where:
            sql += f" WHERE {where}"
        sql += " ORDER BY time DESC"
        if limit:
            sql += f" LIMIT {int(limit)}"
        rs = self.executor.execute_one(sql, session)
        return [dict(zip(rs.names, row)) for row in rs.rows()]

    async def handle_jaeger_services(self, request):
        from .otlp import TRACE_TABLE

        session = self._session(request)
        self._authorize_read(session)

        def run():
            try:
                rs = self.executor.execute_one(
                    f"SELECT DISTINCT service_name FROM {TRACE_TABLE} "
                    f"ORDER BY service_name", session)
                return [str(v) for v in rs.columns[0]]
            except CnosError:
                return []   # no traces ingested yet
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, run)
        return web.json_response({"data": data, "total": len(data)})

    async def handle_jaeger_operations(self, request):
        from .otlp import TRACE_TABLE

        session = self._session(request)
        self._authorize_read(session)
        svc = request.match_info["service"].replace("'", "''")

        def run():
            try:
                rs = self.executor.execute_one(
                    f"SELECT DISTINCT operation_name FROM {TRACE_TABLE} "
                    f"WHERE service_name = '{svc}' ORDER BY operation_name",
                    session)
                return [str(v) for v in rs.columns[0]]
            except CnosError:
                return []
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, run)
        return web.json_response({"data": data, "total": len(data)})

    async def handle_jaeger_traces(self, request):
        from .otlp import spans_to_jaeger_traces

        session = self._session(request)
        self._authorize_read(session)
        svc = request.query.get("service", "").replace("'", "''")
        op = request.query.get("operation", "").replace("'", "''")
        try:
            limit = int(request.query.get("limit", 20))
            start_us = int(request.query["start"]) \
                if "start" in request.query else None
            end_us = int(request.query["end"]) \
                if "end" in request.query else None
        except ValueError as e:
            return web.Response(status=400,
                                text=f"bad numeric query parameter: {e}")

        def run():
            try:
                where = []
                if svc:
                    where.append(f"service_name = '{svc}'")
                if op:
                    where.append(f"operation_name = '{op}'")
                if start_us is not None:   # µs, jaeger convention
                    where.append(f"time >= {start_us * 1000}")
                if end_us is not None:
                    where.append(f"time <= {end_us * 1000}")
                probe = self._trace_rows(session, " AND ".join(where),
                                         limit=limit * 50)
                ids: list[str] = []
                for r in probe:
                    if r["trace_id"] not in ids:
                        ids.append(r["trace_id"])
                    if len(ids) >= limit:
                        break
                if not ids:
                    return []
                idlist = ", ".join(
                    "'" + i.replace("'", "''") + "'" for i in ids)
                rows = self._trace_rows(session, f"trace_id IN ({idlist})")
                return spans_to_jaeger_traces(rows)
            except CnosError:
                return []
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, run)
        return web.json_response({"data": data, "total": len(data)})

    async def handle_jaeger_trace(self, request):
        from .otlp import spans_to_jaeger_traces

        session = self._session(request)
        self._authorize_read(session)
        tid = request.match_info["trace_id"].replace("'", "''")

        def run():
            try:
                rows = self._trace_rows(session, f"trace_id = '{tid}'")
                return spans_to_jaeger_traces(rows)
            except CnosError:
                return []
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(None, run)
        if not data:
            return web.json_response(
                {"data": [], "errors": [{"code": 404,
                                         "msg": "trace not found"}]},
                status=404)
        return web.json_response({"data": data, "total": len(data)})

    async def handle_lockgraph(self, request):
        """Runtime lock-order watchdog state (utils/lockwatch.py): the
        observed (held → acquired) graph, any order cycles (potential
        deadlocks), longest-held locks, and locks held across an RPC hop.
        Reports `enabled: false` with empty tables unless the process was
        started with CNOSDB_LOCKWATCH=1 (chaos/cluster suites do this)."""
        self._require_admin(request)
        from ..utils import lockwatch

        return web.json_response(lockwatch.report())

    async def handle_memory(self, request):
        """Memory-governance plane (server/memory.py): broker budget +
        watermarks, live per-pool bytes, per-(pool, action) ladder
        counters and the recent reclaim/shed/spill event ring. Reports
        `enabled: false` when the node runs with CNOSDB_MEMORY=0."""
        self._require_admin(request)
        from . import memory as _memory

        return web.json_response(_memory.debug_snapshot())

    async def handle_health(self, request):
        """Gray-failure tolerance plane (parallel/health.py): per-node
        health scores (state, err/burn EWMAs, per-method-class latency
        quantiles), the coordinator's circuit-breaker table, slow-start
        ramps in progress, and the hedge/breaker transition counters.
        All zeros/empty until this node has coordinated remote work."""
        self._require_admin(request)
        from ..parallel import health

        hedge, breaker = health.counters_snapshot()
        now = time.monotonic()
        cb = {}
        for node_id, st in list(self.coord._cb.items()):
            open_for = st[1] - now
            cb[str(node_id)] = {
                "consecutive_failures": st[0],
                "state": "open" if open_for > 0 else "closed",
                "open_remaining_s": round(max(0.0, open_for), 3),
            }
        # raft-member introspection: a gray failure often looks like "the
        # follower silently stopped applying" — surface every local
        # member's role/term/log/commit/applied so that is one curl away
        raft = {}
        mgr = self.coord._replica_mgr
        if mgr is not None:
            for (gid, vid), node in list(mgr.transport.nodes.items()):
                raft[f"{gid}#{vid}"] = {
                    "role": node.role, "term": node.term,
                    "leader_id": node.leader_id, "alive": node.alive,
                    "last_index": node.log.last_index(),
                    "commit": node.commit_index,
                    "applied": node.last_applied,
                }
        return web.json_response({
            "hedging_enabled": health.enabled(),
            "hedge_delay_ms_floor": health.HEDGE_DELAY_FLOOR_MS,
            "hedge_max_inflight": health.HEDGE_MAX_INFLIGHT,
            "hedge_inflight": self.coord._hedge_limiter.inflight(),
            "raft_members": raft,
            "nodes": health.SCORER.snapshot(),
            "breakers": cb,
            "slow_start": health.SLOW_START.ramping(),
            "counters": {
                "hedge": {f"{o}:{r}" if r else o: n
                          for (o, r), n in sorted(hedge.items())},
                "breaker": {f"{node}:{state}": n
                            for (node, state), n in sorted(breaker.items())},
            },
        })

    async def handle_metrics(self, request):
        from ..utils import executor, stages

        # fold the always-on failure counters (RPC handler errors etc.) in
        # as gauges at render time — set_gauge is idempotent, so repeated
        # scrapes see the current cumulative totals
        for name, n in stages.errors_snapshot().items():
            area, _, what = name.partition(".")
            self.metrics.set_gauge("cnosdb_errors_total", n,
                                   area=area, kind=what or area)
        # shared scan/decode pool health: live task counts + pool widths
        for name, n in executor.active_counts().items():
            self.metrics.set_gauge("cnosdb_scan_executor_active", n,
                                   pool=name)
        for name, n in executor.pool_sizes().items():
            self.metrics.set_gauge("cnosdb_scan_executor_threads", n,
                                   pool=name)
        entries, nbytes = self.coord.scan_cache_stats()
        self.metrics.set_gauge("cnosdb_scan_cache_entries", entries)
        self.metrics.set_gauge("cnosdb_scan_cache_bytes", nbytes)
        # request-lifecycle plane: admission gate counters + queue gauges
        # (cnosdb_requests_deadline_exceeded_total is a true counter,
        # incremented where the 504 is returned)
        g = self.gate.stats()
        self.metrics.set_gauge("cnosdb_requests_admitted_total",
                               g["admitted_total"])
        self.metrics.set_gauge("cnosdb_requests_queued_total",
                               g["queued_total"])
        self.metrics.set_gauge("cnosdb_requests_shed_total", g["shed_total"])
        self.metrics.set_gauge("cnosdb_requests_running", g["running"])
        self.metrics.set_gauge("cnosdb_requests_queue_depth", g["queued"])
        self.metrics.set_gauge("cnosdb_requests_queue_wait_ms",
                               g["queue_wait_ms_avg"], stat="avg")
        self.metrics.set_gauge("cnosdb_requests_queue_wait_ms",
                               g["queue_wait_ms_max"], stat="max")
        # cancellation fan-out + shed-before-decode observability
        for name, n in deadline_mod.counters_snapshot().items():
            self.metrics.set_gauge("cnosdb_deadline_total", n, kind=name)
        # integrity plane: scrub progress + corruption/quarantine/repair
        # totals (storage/scrub.py counters are always on)
        from ..storage import scrub

        for name, n in scrub.counters_snapshot().items():
            self.metrics.set_gauge("cnosdb_integrity_total", n, kind=name)
        # decode plane: pages that missed the native pagedec fast lane,
        # by reason — a hot reason here is a concrete decode regression.
        # These are monotonic process totals: set_counter (not set_gauge)
        # so PromQL rate()/increase() work on them
        from ..storage import scan as _scan

        for name, n in _scan.decode_fallback_snapshot().items():
            self.metrics.set_counter("cnosdb_decode_fallback_total", n,
                                     reason=name)
        # aggregation plane: factorize/distinct path totals
        from ..ops import group_agg as _group_agg

        for name, n in _group_agg.counters_snapshot().items():
            self.metrics.set_gauge("cnosdb_group_agg_total", n, kind=name)
        # memory-governance plane: per-(pool, action) ladder totals +
        # live pool bytes (see /debug/memory for the full snapshot)
        from . import memory as _memory

        if _memory.enabled():
            for (pool, action), n in _memory.counters_snapshot().items():
                self.metrics.set_counter("cnosdb_memory_total", n,
                                         pool=pool, action=action)
            for pool, b in _memory.BROKER.usage().items():
                self.metrics.set_gauge("cnosdb_memory_pool_bytes", b,
                                       pool=pool)
            self.metrics.set_gauge("cnosdb_memory_budget_bytes",
                                   _memory.BROKER.total())
        # invariant plane: lock-order watchdog counters (all zero unless
        # the node runs with CNOSDB_LOCKWATCH=1; order_cycles > 0 means a
        # potential deadlock was observed — see /debug/lockgraph)
        from ..utils import lockwatch

        for name, n in lockwatch.counters_snapshot().items():
            self.metrics.set_gauge("cnosdb_lockwatch_total", n, kind=name)
        # warm-agg memo + materialized rollups: only when the jax exec /
        # matview modules are already resident — a metrics scrape must
        # never be the thing that drags the kernel stack in
        import sys as _sys

        _tx = _sys.modules.get("cnosdb_tpu.ops.tpu_exec")
        if _tx is not None:
            self.metrics.set_gauge("cnosdb_agg_memo_bytes",
                                   _tx.memo_bytes())
            for name, n in _tx.memo_counters_snapshot().items():
                self.metrics.set_gauge("cnosdb_agg_memo_total", n,
                                       kind=name)
        # device-decode plane: per-(lane, reason) page outcomes — only
        # when the lane module is resident (same no-jax-on-scrape rule)
        _dd = _sys.modules.get("cnosdb_tpu.ops.device_decode")
        if _dd is not None:
            for (lane, reason), n in _dd.outcomes_snapshot().items():
                self.metrics.set_counter("cnosdb_device_decode_total", n,
                                         lane=lane, reason=reason)
        # string/search plane: per-(path, reason) predicate outcomes
        _sk = _sys.modules.get("cnosdb_tpu.ops.strkernels")
        if _sk is not None:
            for (path, reason), n in _sk.outcomes_snapshot().items():
                self.metrics.set_counter("cnosdb_string_filter_total", n,
                                         path=path, reason=reason)
        # compressed-domain lane: per-(lane, reason) page outcomes —
        # answered/skipped/masked/materialized and why
        _cd = _sys.modules.get("cnosdb_tpu.storage.compressed_domain")
        if _cd is not None:
            for (lane, reason), n in _cd.outcomes_snapshot().items():
                self.metrics.set_counter("cnosdb_compressed_domain_total",
                                         n, lane=lane, reason=reason)
        # mesh exec lane: per-(lane, reason) engage/decline outcomes —
        # ("merge", "collective") counting is the zero-host-msgpack-hop
        # witness for on-mesh partial merges
        _mx = _sys.modules.get("cnosdb_tpu.parallel.mesh")
        if _mx is not None:
            for (lane, reason), n in _mx.outcomes_snapshot().items():
                self.metrics.set_counter("cnosdb_mesh_total", n,
                                         lane=lane, reason=reason)
        _mv = _sys.modules.get("cnosdb_tpu.sql.matview")
        if _mv is not None:
            for name, n in _mv.counters_snapshot().items():
                self.metrics.set_gauge("cnosdb_matview_total", n,
                                       kind=name)
        # cold-tier plane: per-(lane, reason) tier/fetch/prune/cache
        # outcomes plus the block cache's live size — only when the
        # tiering module is resident (nothing cold has happened otherwise)
        _ct = _sys.modules.get("cnosdb_tpu.storage.tiering")
        if _ct is not None:
            for (lane, reason), n in _ct.cold_tier_snapshot().items():
                self.metrics.set_counter("cnosdb_cold_tier_total", n,
                                         lane=lane, reason=reason)
            bc = _ct.block_cache_stats()
            self.metrics.set_gauge("cnosdb_cold_block_cache_bytes",
                                   bc["bytes"])
            self.metrics.set_gauge("cnosdb_cold_block_cache_entries",
                                   bc["entries"])
        # serving plane: per-(layer, outcome) cache/batch counters plus
        # live cache sizes — only when the plane is resident
        # (CNOSDB_SERVING=0 never imports it)
        _sv = _sys.modules.get("cnosdb_tpu.server.serving")
        if _sv is not None:
            for (layer, outcome), n in _sv.counters_snapshot().items():
                self.metrics.set_counter("cnosdb_serving_total", n,
                                         layer=layer, outcome=outcome)
            for cache, (entries, nbytes) in _sv.cache_stats().items():
                self.metrics.set_gauge(f"cnosdb_serving_{cache}_entries",
                                       entries)
                if cache == "result_cache":
                    self.metrics.set_gauge(
                        f"cnosdb_serving_{cache}_bytes", nbytes)
            for width, n in _sv.width_histogram().items():
                self.metrics.set_counter("cnosdb_serving_batch_width_total",
                                         n, width=str(width))
        # disaster-recovery plane: per-(op, outcome) archive/backup/
        # restore counters plus the RPO gauge (age of the oldest sealed-
        # but-unarchived WAL segment) — resident only once configured
        _bk = _sys.modules.get("cnosdb_tpu.storage.backup")
        if _bk is not None and _bk.archive_enabled():
            for (op, outcome), n in _bk.backup_snapshot().items():
                self.metrics.set_counter("cnosdb_backup_total", n,
                                         op=op, outcome=outcome)
            self.metrics.set_gauge("cnosdb_backup_archive_lag_seconds",
                                   _bk.archive_lag_seconds())
        # gray-failure plane: hedge outcomes (fired/won/lost/cancelled/
        # suppressed, with suppression reason) and breaker state
        # transitions per node. True counters so rate() catches a node
        # flapping open/closed or a hedge storm.
        from ..parallel import health as _health

        _hedge, _breaker = _health.counters_snapshot()
        for (outcome, reason), n in _hedge.items():
            self.metrics.set_counter("cnosdb_hedge_total", n,
                                     outcome=outcome, reason=reason or "-")
        for (node, state), n in _breaker.items():
            self.metrics.set_counter("cnosdb_breaker_total", n,
                                     node=node, state=state)
        # nemesis plane: checker verdicts + recovery timings — resident
        # only when a chaos suite has run in this process
        _ch = _sys.modules.get("cnosdb_tpu.chaos")
        if _ch is not None:
            for (check, verdict), n in _ch.chaos_snapshot().items():
                self.metrics.set_counter("cnosdb_chaos_total", n,
                                         check=check, verdict=verdict)
            for kind, secs in _ch.recovery_snapshot().items():
                self.metrics.set_gauge("cnosdb_chaos_recovery_seconds",
                                       secs, kind=kind)
        return web.Response(text=self.metrics.prometheus_text(),
                            content_type="text/plain")

    # ------------------------------------------------------------- lifecycle
    async def start(self, host: str = "0.0.0.0", port: int = 8902,
                    ssl_context=None):
        runner = web.AppRunner(self.app)
        await runner.setup()
        site = web.TCPSite(runner, host, port, ssl_context=ssl_context)
        await site.start()
        return runner

    async def start_tcp_opentsdb(self, host: str = "0.0.0.0",
                                 port: int = 8905):
        """OpenTSDB telnet `put` listener (reference main/src/tcp/
        tcp_service.rs:36-106): newline-delimited put lines per
        connection, written through the normal coordinator path."""
        from ..protocol.opentsdb import parse_opentsdb

        async def on_conn(reader, writer):
            loop = asyncio.get_running_loop()
            try:
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    text = line.decode(errors="replace").strip()
                    if not text:
                        continue
                    if text.lower() == "quit":
                        break
                    try:
                        batch = parse_opentsdb(text)
                        await loop.run_in_executor(
                            None, lambda b=batch: self.coord.write_points(
                                DEFAULT_TENANT, "public", b))
                        self.metrics.incr("cnosdb_tcp_opentsdb_points_total",
                                          batch.n_rows())
                    except CnosError as e:
                        writer.write(f"error: {e}\n".encode())
                        await writer.drain()
            finally:
                writer.close()

        return await asyncio.start_server(on_conn, host, port)


# ---------------------------------------------------------------------------
# result formatting (reference main/src/http/result_format.rs)
# ---------------------------------------------------------------------------
def _cell(v):
    if v is None:
        return ""
    from ..sql.tsfuncs import IntervalNs, format_interval_ns, \
        render_composite

    if isinstance(v, IntervalNs):
        return format_interval_ns(int(v))
    if isinstance(v, dict):
        return render_composite(v)   # gauge/window struct Display
    if isinstance(v, (bytes, bytearray)):
        return v.hex()   # WKB and other binary render as lowercase hex
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return "NaN"   # NaN is a VALUE; NULL is the empty cell
    if isinstance(v, (float, np.floating)) and v == 0.0:
        return repr(0.0)   # normalize -0.0 (arrow renders 0.0)
    if isinstance(v, np.float32):
        return str(v)     # shortest f32 repr ('1.5707964', '6e-06') —
        # the reference's Float32 results (log/atan2 over ints) render
        # at f32 precision
    if isinstance(v, np.floating):
        return repr(float(v))
    if isinstance(v, (np.integer,)):
        return str(int(v))
    if isinstance(v, np.bool_):
        return "true" if v else "false"
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def format_csv(rs: ResultSet) -> str:
    lines = [",".join(rs.names)]
    for row in rs.rows():
        lines.append(",".join(_csv_escape(_cell(v)) for v in row))
    return "\n".join(lines) + "\n"


def _csv_escape(s: str) -> str:
    if "," in s or '"' in s or "\n" in s:
        return '"' + s.replace('"', '""') + '"'
    return s


def _json_value(v):
    if v is None:
        return None
    if isinstance(v, (np.floating, float)):
        f = float(v)
        return None if np.isnan(f) else f
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, (np.bool_, bool)):
        return bool(v)
    return str(v) if not isinstance(v, (int, str)) else v


def format_json(rs: ResultSet) -> str:
    out = [
        {n: _json_value(v) for n, v in zip(rs.names, row)}
        for row in rs.rows()
    ]
    return json.dumps(out)


def format_table(rs: ResultSet) -> str:
    rows = [[_cell(v) for v in row] for row in rs.rows()]
    widths = [max(len(n), *(len(r[i]) for r in rows)) if rows else len(n)
              for i, n in enumerate(rs.names)]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    def fmt_row(cells):
        return "| " + " | ".join(c.ljust(w) for c, w in zip(cells, widths)) + " |"
    lines = [sep, fmt_row(rs.names), sep]
    for r in rows:
        lines.append(fmt_row(r))
    lines.append(sep)
    return "\n".join(lines) + "\n"


def _status_for(e: CnosError) -> int:
    from ..errors import (
        AdmissionRejected, AuthError, DatabaseNotFound, LimiterError,
        MemoryExceeded, ParserError, PlanError, TableNotFound,
    )

    if isinstance(e, AuthError):
        return 403
    if isinstance(e, LimiterError):
        return 429          # per-tenant budget — THIS tenant backs off
    if isinstance(e, AdmissionRejected):
        return 503          # node saturated for everyone — shed load
    if isinstance(e, MemoryExceeded):
        return 413          # request over its byte budget — not retryable
    if isinstance(e, DeadlineExceeded):
        return 504          # request outlived its budget
    if isinstance(e, (ParserError, PlanError, DatabaseNotFound, TableNotFound)):
        return 422
    return 500


def _err_response(status: int, e: CnosError):
    headers = {}
    if status in (429, 503):
        # both shed classes are retryable; tell clients when
        headers["Retry-After"] = str(
            max(1, int(round(float(getattr(e, "retry_after", 1.0))))))
    return web.json_response(
        {"error_code": getattr(e, "code", "000000"), "error_message": str(e)},
        status=status, headers=headers)


def build_server(data_dir: str, auth_enabled: bool = False,
                 wal_sync: bool = False, query_cfg=None):
    """Wire meta + engine + coordinator + executor (reference
    server.rs ServiceBuilder::build_query_storage)."""
    import os

    meta = MetaStore(os.path.join(data_dir, "meta", "meta.json"))
    engine = TsKv(os.path.join(data_dir, "db"), wal_sync=wal_sync)
    # coordinator BEFORE open_existing: its init hydrates the engine's
    # schema view from the catalog, which WAL replay needs to re-key
    # replayed fields by column id across a pre-crash RENAME/DROP
    coord = Coordinator(meta, engine)
    engine.open_existing()
    executor = QueryExecutor(meta, coord)
    executor.restore_streams()  # persisted streams resume at their watermark
    executor.restore_matviews()  # rollups resume flush-driven maintenance
    return HttpServer(meta, coord, executor, auth_enabled=auth_enabled,
                      query_cfg=query_cfg)


def build_cluster_node(data_dir: str, meta_addr: str, node_id: int,
                       rpc_host: str = "127.0.0.1", rpc_port: int = 0,
                       auth_enabled: bool = False, wal_sync: bool = False,
                       query_cfg=None):
    """Wire a cluster data/query node: MetaClient cache + node RPC service
    + local engine + distributed coordinator (reference server.rs
    build_query_storage in cluster deployment: AdminMeta::new +
    add_data_node + grpc TSKVService)."""
    import os

    from ..parallel.meta_service import MetaClient
    from ..parallel.net import wait_rpc_ready
    from ..parallel.node_service import DataNodeService

    wait_rpc_ready(meta_addr, timeout=30.0)
    meta = MetaClient(meta_addr, node_id=node_id)
    engine = TsKv(os.path.join(data_dir, "db"), wal_sync=wal_sync)
    coord = Coordinator(meta, engine, node_id=node_id)
    engine.open_existing()
    node_svc = DataNodeService(coord, host=rpc_host, port=rpc_port).start()
    meta.register_node(node_id, grpc_addr=node_svc.addr)
    meta.start_heartbeat()
    executor = QueryExecutor(meta, coord)
    executor.restore_matviews()  # rollups resume flush-driven maintenance
    server = HttpServer(meta, coord, executor, auth_enabled=auth_enabled,
                        query_cfg=query_cfg)
    server.node_service = node_svc
    return server


def run_server(args) -> int:
    import asyncio
    import time as _time

    from ..config import Config

    # Config.load with no path still applies CNOSDB_* env overrides
    cfg = Config.load(getattr(args, "config", None))
    from ..utils import executor
    executor.configure(cfg.query)
    mode = getattr(args, "mode", "singleton")
    if mode == "meta":
        return run_meta_server(args)
    if getattr(args, "meta", None):
        server = build_cluster_node(
            args.data_dir, args.meta, getattr(args, "node_id", 1) or 1,
            rpc_port=getattr(args, "rpc_port", 0) or 0,
            auth_enabled=cfg.query.auth_enabled, wal_sync=cfg.wal.sync,
            query_cfg=cfg.query)
        print(f"node rpc on {server.node_service.addr}")
    else:
        server = build_server(args.data_dir,
                              auth_enabled=cfg.query.auth_enabled,
                              wal_sync=cfg.wal.sync,
                              query_cfg=cfg.query)
    flight_port = cfg.service.flight_rpc_listen_port

    if cfg.storage.scrub_interval > 0:
        from ..storage.scrub import Scrubber

        server.scrubber = Scrubber(
            server.coord.engine, cfg.storage.scrub_interval,
            mb_per_sec=cfg.storage.scrub_mb_per_sec,
            on_corruption=server.coord.on_scrub_corruption)
        server.scrubber.start()
        print(f"integrity scrubber every {cfg.storage.scrub_interval}s "
              f"at {cfg.storage.scrub_mb_per_sec} MB/s")

    if cfg.storage.tiering_uri:
        from ..storage import tiering

        tiering.configure(cfg.storage.tiering_uri)
        if cfg.storage.tiering_interval > 0:
            server.tiering_job = tiering.TieringJob(
                server.coord.engine, cfg.storage.tiering_interval,
                cfg.storage.tiering_cold_after_s)
            server.tiering_job.start()
            print(f"cold tiering → {cfg.storage.tiering_uri} every "
                  f"{cfg.storage.tiering_interval}s "
                  f"(cold after {cfg.storage.tiering_cold_after_s}s)")
        else:
            print(f"cold tier configured → {cfg.storage.tiering_uri} "
                  f"(no background sweep)")

    if cfg.storage.wal_archive_uri:
        from ..config import ConfigError
        from ..storage import backup

        arch_opts = None
        if cfg.storage.wal_archive_options:
            try:
                arch_opts = json.loads(cfg.storage.wal_archive_options)
            except ValueError as e:
                raise ConfigError(
                    f"bad [storage] wal_archive_options JSON: {e}")
        backup.configure_archive(cfg.storage.wal_archive_uri, arch_opts)
        # vnodes opened before this point (engine boot replay) missed the
        # __init__ attach hook: wire them now so fence + catch_up cover
        # every WAL in the process
        for v in list(server.coord.engine.vnodes.values()):
            backup.attach_vnode(v)
        print(f"WAL archive → {cfg.storage.wal_archive_uri} "
              f"(continuous archiving + BACKUP/RESTORE enabled)")

    if cfg.trace.otlp_endpoint:
        from .trace import GLOBAL_COLLECTOR, OtlpExporter

        OtlpExporter(cfg.trace.otlp_endpoint, GLOBAL_COLLECTOR,
                     batch_size=cfg.trace.batch_size,
                     flush_interval_s=cfg.trace.flush_interval_s)
        print(f"otlp export → {cfg.trace.otlp_endpoint}/v1/traces")

    async def ttl_job():
        """Bucket TTL expiry (reference meta_admin.rs:848 + ResourceManager):
        drop vnodes of expired buckets. Also reclaims the DROP recycle
        bin once entries outlive the recovery window."""
        trash_retention_s = 24 * 3600.0
        while True:
            await asyncio.sleep(60)
            now = int(_time.time() * 1e9)
            for owner in list(server.meta.databases):
                tenant, db = owner.split(".", 1)
                try:
                    for bucket in server.meta.expire_buckets(tenant, db, now):
                        for rs in bucket.shard_group:
                            for v in rs.vnodes:
                                # tier-then-expire: expired vnodes also
                                # release their cold-tier objects
                                server.coord.engine.drop_vnode(
                                    owner, v.id, purge_cold=True)
                except Exception:
                    pass
            try:
                server.meta.purge_trash(older_than_s=trash_retention_s)
            except Exception:
                pass

    ssl_context = None
    if cfg.security.enabled:
        import ssl as _ssl

        ssl_context = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
        ssl_context.load_cert_chain(cfg.security.tls_cert_path,
                                    cfg.security.tls_key_path)

    async def main():
        await server.start(port=args.http_port, ssl_context=ssl_context)
        if cfg.query.auth_enabled:
            # the telnet put protocol carries no credentials; exposing it
            # on an authenticated server would bypass RBAC entirely
            print("opentsdb tcp disabled: auth_enabled (telnet has no auth)")
        else:
            try:
                main._tcp = await server.start_tcp_opentsdb(
                    port=cfg.service.tcp_listen_port)
                print(f"opentsdb tcp on :{cfg.service.tcp_listen_port}")
            except Exception as e:
                print(f"opentsdb tcp disabled: {e}")
        try:
            from .flight import start_flight_server

            start_flight_server(server.executor, flight_port,
                                auth_enabled=cfg.query.auth_enabled)
            print(f"flight sql on :{flight_port}")
        except Exception as e:
            print(f"flight sql disabled: {e}")
        # hold a strong reference: the loop keeps only weak refs to tasks
        main._ttl_task = asyncio.get_running_loop().create_task(ttl_job())
        print(f"cnosdb-tpu listening on :{args.http_port} "
              f"(data dir {args.data_dir}, mode {getattr(args, 'mode', 'singleton')})")
        while True:
            await asyncio.sleep(3600)

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        if server.scrubber is not None:
            server.scrubber.stop()
        server.coord.close()
    return 0


def run_meta_server(args) -> int:
    """Standalone meta service process (reference cnosdb-meta binary,
    meta/src/bin/main.rs + service/http.rs). With --meta-peers it joins a
    replicated meta raft group."""
    import os
    import time as _time

    from ..parallel.meta_service import MetaService

    store = MetaStore(os.path.join(args.data_dir, "meta", "meta.json"),
                      register_self=False)
    peers = {}
    for spec in (getattr(args, "meta_peers", None) or "").split(","):
        if "@" in spec:
            nid, _, addr = spec.partition("@")
            peers[int(nid)] = addr
    # loopback by default: the msgpack RPC surface carries no auth, so
    # exposing it beyond the host is an explicit operator decision
    svc = MetaService(store, host=getattr(args, "meta_host", None)
                      or "127.0.0.1",
                      port=getattr(args, "meta_port", 8901) or 8901,
                      node_id=getattr(args, "node_id", None) if peers else None,
                      peers=peers or None,
                      raft_dir=os.path.join(args.data_dir, "meta", "raft"))
    svc.start()
    print(f"cnosdb-tpu meta listening on {svc.addr} "
          f"(data dir {args.data_dir}"
          + (f", raft member {args.node_id} of {sorted(peers)}" if peers
             else "") + ")")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()
    return 0
