"""Metrics registry with Prometheus text exposition.

Role-parity with common/metrics (metric_register.rs, prom_reporter.rs):
typed counters/gauges/histograms exported at GET /metrics.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from ..utils import lockwatch


class _Hist:
    """Streaming histogram: per-bucket counts + sum + count, O(1) memory
    per series regardless of observation volume (the previous sample-list
    representation grew without bound on long-lived servers)."""

    __slots__ = ("buckets", "total", "count")

    def __init__(self, n_bounds: int):
        self.buckets = [0] * n_bounds   # non-cumulative, per bound
        self.total = 0.0
        self.count = 0


class MetricsRegistry:
    def __init__(self):
        self._lock = lockwatch.Lock("metrics.registry")
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hist_bounds = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60]
        self._histograms: dict[tuple[str, tuple], _Hist] = {}

    def incr(self, name: str, value: float = 1, **labels):
        with self._lock:
            self._counters[(name, _lk(labels))] += value

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[(name, _lk(labels))] = value

    def set_counter(self, name: str, value: float, **labels):
        """Export an externally-accumulated monotonic total as a counter
        series. For sources that keep their own running sum (e.g. the
        scan planes' fallback/outcome tallies): `incr` would re-add the
        whole total on every scrape, `set_gauge` would mistype it and
        break rate() — this assigns, and exposition stays `counter`."""
        with self._lock:
            self._counters[(name, _lk(labels))] = value

    def observe(self, name: str, value: float, **labels):
        with self._lock:
            h = self._histograms.get((name, _lk(labels)))
            if h is None:
                h = self._histograms[(name, _lk(labels))] = \
                    _Hist(len(self._hist_bounds))
            for i, b in enumerate(self._hist_bounds):
                if value <= b:
                    h.buckets[i] += 1
                    break
            h.total += value
            h.count += 1

    def prometheus_text(self) -> str:
        out = []
        with self._lock:
            for (name, labels), v in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name}{_fmt(labels)} {v}")
            for (name, labels), h in sorted(self._histograms.items()):
                out.append(f"# TYPE {name} histogram")
                cum = 0
                for i, b in enumerate(self._hist_bounds):
                    cum += h.buckets[i]
                    out.append(f'{name}_bucket{_fmt(labels, le=b)} {cum}')
                out.append(f'{name}_bucket{_fmt(labels, le="+Inf")} {h.count}')
                out.append(f"{name}_sum{_fmt(labels)} {h.total}")
                out.append(f"{name}_count{_fmt(labels)} {h.count}")
        return "\n".join(out) + "\n"


def _lk(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt(labels: tuple, **extra) -> str:
    items = list(labels) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"
