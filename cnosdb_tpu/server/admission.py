"""Per-node query admission gate.

The reference bounds concurrent query execution with its dispatcher's
`query_limit` semaphore (query_server dispatcher/manager.rs) on top of
the per-tenant request limiters. This is the rebuild's equivalent: a
bounded running-set plus a bounded FIFO-ish wait queue in front of the
SQL endpoint.

  * up to `max_concurrent` queries execute at once;
  * up to `max_queued` more wait, each for at most its own request
    deadline (a queued request that cannot finish in time is shed NOW,
    not after burning its whole budget in line);
  * everything beyond that is shed immediately with AdmissionRejected,
    which the HTTP layer maps to 503 + Retry-After — deliberately
    distinct from the per-tenant token-bucket LimiterError (429): 429
    means "you specifically are over YOUR budget", 503 means "the node
    is saturated for everyone, back off and retry".

Rejection taxonomy (each failure names its actor and its remedy):

  ====  ==========================  =================================
  code  error (counter)             meaning / client remedy
  ====  ==========================  =================================
  429   LimiterError                this tenant exceeded ITS bucket
        (rate_limited)              — slow down, others unaffected
  503   AdmissionRejected           node saturated for everyone —
        (shed)                      back off per Retry-After
  503   WriteBackpressure           memory broker shedding WRITES
        (backpressured)             while flushes drain; Retry-After
                                    derives from observed flush
                                    progress (server/memory.py)
  504   DeadlineExceeded            the request ran out of ITS OWN
        (deadline)                  time budget mid-flight
  413   MemoryExceeded              this query/write is too big for
        (memory)                    its byte budget — shrink it;
                                    retrying unchanged cannot help
  ====  ==========================  =================================

The memory broker's degradation ladder (server/memory.py) also sheds
QUEUED — never running — queries via `shed_queued()` when reclaiming
caches alone cannot get back under the soft watermark: a queued query
holds no partial state yet, so shedding it frees future memory at zero
wasted work.

Acquisition happens on the executor worker thread (one thread per
in-flight HTTP request), so waiting here blocks no event loop. Counters
and queue-depth/wait gauges feed /metrics via `stats()`.
"""
from __future__ import annotations

import threading
import time

from ..errors import AdmissionRejected
from ..utils import deadline as deadline_mod
from ..utils import lockwatch


class AdmissionGate:
    def __init__(self, max_concurrent: int = 64, max_queued: int = 128):
        self.max_concurrent = max(1, int(max_concurrent))
        self.max_queued = max(0, int(max_queued))
        self._cond = threading.Condition(lockwatch.RLock("admission.gate"))
        self._running = 0
        self._queued = 0
        # memory-pressure shed generation: shed_queued() bumps the epoch
        # and every waiter queued BEFORE the bump sheds itself
        self._shed_epoch = 0
        self._shed_retry_after = 1.0
        # cumulative counters (cnosdb_requests_*_total)
        self.admitted_total = 0
        self.queued_total = 0
        self.shed_total = 0
        # wait-time accounting for the queue-wait gauge
        self._wait_sum_ms = 0.0
        self._wait_max_ms = 0.0

    def acquire(self, dl: deadline_mod.Deadline | None = None) -> float:
        """Block until admitted; returns seconds spent queued.

        Raises AdmissionRejected when the queue is full, or when the
        caller's deadline dies while waiting in line."""
        with self._cond:
            if self._running < self.max_concurrent and self._queued == 0:
                self._running += 1
                self.admitted_total += 1
                return 0.0
            if self._queued >= self.max_queued:
                self.shed_total += 1
                raise AdmissionRejected(
                    f"admission queue full "
                    f"({self._running} running, {self._queued} queued)",
                    retry_after=1.0)
            self._queued += 1
            self.queued_total += 1
            start = time.monotonic()
            epoch = self._shed_epoch
            try:
                while True:
                    if self._shed_epoch > epoch:
                        self.shed_total += 1
                        raise AdmissionRejected(
                            "shed while queued: node over memory "
                            "watermark (queued queries shed first, "
                            "running queries finish)",
                            retry_after=self._shed_retry_after)
                    if dl is not None and dl.dead():
                        self.shed_total += 1
                        raise AdmissionRejected(
                            "shed while queued: request deadline "
                            f"{'cancelled' if dl.cancelled else 'expired'} "
                            f"after {time.monotonic() - start:.2f}s in line",
                            retry_after=1.0)
                    if self._running < self.max_concurrent:
                        self._running += 1
                        self.admitted_total += 1
                        waited = time.monotonic() - start
                        self._wait_sum_ms += waited * 1000.0
                        self._wait_max_ms = max(self._wait_max_ms,
                                                waited * 1000.0)
                        return waited
                    rem = dl.remaining() if dl is not None else None
                    self._cond.wait(timeout=min(rem, 0.1)
                                    if rem is not None else 0.1)
            finally:
                self._queued -= 1

    def release(self) -> None:
        with self._cond:
            self._running -= 1
            self._cond.notify()

    def shed_queued(self, retry_after: float = 1.0) -> int:
        """Memory-broker ladder step 2: shed every currently QUEUED
        query with 503 + `retry_after` (the waiters raise on wakeup).
        Running queries are untouched. Returns how many were shed."""
        with self._cond:
            n = self._queued
            if n:
                self._shed_epoch += 1
                self._shed_retry_after = float(retry_after)
                self._cond.notify_all()
            return n

    def pressure(self) -> tuple[int, int]:
        """Dirty-read ``(running, queued)`` for the serving-plane micro-
        batcher's fuse-or-solo decision. Deliberately lock-free: it runs
        on every admitted point query, and a momentarily torn pair only
        mis-sizes one batching window — never correctness."""
        return self._running, self._queued

    def stats(self) -> dict:
        with self._cond:
            n_adm = self.admitted_total
            avg = self._wait_sum_ms / n_adm if n_adm else 0.0
            return {
                "running": self._running,
                "queued": self._queued,
                "admitted_total": n_adm,
                "queued_total": self.queued_total,
                "shed_total": self.shed_total,
                "queue_wait_ms_avg": avg,
                "queue_wait_ms_max": self._wait_max_ms,
            }
