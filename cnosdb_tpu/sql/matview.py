"""Materialized rollup plane: durable incremental views + planner rewrite.

Role-parity with CnosDB's downsampling/stream-rollup story, built from
parts this engine already has: a ``CREATE MATERIALIZED VIEW name AS
SELECT <aggs> FROM t GROUP BY time_bucket(...), tags`` statement
registers a rollup whose per-bucket PARTIAL aggregates (the same
wire-compatible partials ``ops/group_agg.py`` / ``_merge_partial``
already merge across vnodes) are persisted beside each vnode's TSM data
and advanced delta-only:

  * **Delta protocol** — per (view, vnode) a state file holds
    ``{hwm, groups}`` where ``groups`` maps (tag values..., bucket_ts)
    to the partial dict ``_merge_partial`` produces. A refresh scans
    only ``[hwm, new_hwm)`` (TSM time pruning keeps that delta-sized),
    folds the kernel partials in, then atomically replaces the state
    file (tmp + fsync + rename) BEFORE advancing the durable
    ``WatermarkTracker`` entry — so the tracker never runs ahead of the
    state and a crash between the two never double-counts a row.
  * **Watermark** — ``new_hwm = now - delay_ns`` aligned DOWN to the
    view's bucket grid (sql/stream.py WatermarkTracker semantics): late
    rows within the delay are still raw when their bucket seals.
  * **Subsumption rewrite** — an aggregate query over the same table is
    rewritten when its group tags ⊆ the view's, its physical partials
    are a subset of the view's, its bucket is a multiple of the view's
    (origin-congruent) or absent, its residual filter is empty and any
    tag constraints touch only view group tags. Sealed view buckets
    seed the executor's accumulator; only the unsealed tail plus
    non-bucket-aligned range edges are scanned raw and merged through
    the existing partial-merge path — bit-identical to a full scan.
  * **Failure model** — the state file is the unit of truth; an
    unrefreshed or torn vnode degrades that vnode to hwm = -inf, which
    disables the rewrite (correct, just slower). Rows acked into the
    WAL but folded from the memcache before a crash replay into raw
    storage and are NOT re-folded (delta starts at the persisted hwm).
    Rows arriving later than the watermark delay never enter sealed
    buckets — the same contract streaming rollups have.

Definitions live in the meta catalog (raft-replicated like stream
definitions); every node maintains the views for its LOCAL vnodes on
flush, and the coordinator-side rewrite fans out ``matview_partials``
RPCs for remote vnodes.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .. import faults
from ..errors import QueryError
from ..models.predicate import I64_MIN, ColumnDomains, TimeRange, TimeRanges
from ..utils import lockwatch, stages
from .planner import AggregatePlan, plan_select
from .stream import WatermarkTracker

log = logging.getLogger("cnosdb.matview")

faults.register_point("matview.persist", __name__,
                      desc="matview state persist, between fsync and rename")

# partial functions a view can persist and the rewrite can merge — the
# same set the vectorized cross-vnode merge supports (executor
# _VEC_MERGE_FUNCS); anything else (collect/distinct payloads) is not a
# fixed-size partial and disqualifies the view/query
MERGEABLE_FUNCS = ("count", "sum", "min", "max", "first", "last")

_LOCK = lockwatch.Lock("matview.counters")
_COUNTERS: dict[str, int] = {}


def _count(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters_snapshot() -> dict[str, int]:
    with _LOCK:
        return dict(sorted(_COUNTERS.items()))


def _now_ns() -> int:
    # event-time watermark: a cross-process timestamp compared against
    # row timestamps, so wall clock is the correct clock here
    return int(time.time() * 1e9)


def _align_down(ts: int, origin: int, interval: int) -> int:
    return origin + (int(ts) - origin) // interval * interval


def _align_up(ts: int, origin: int, interval: int) -> int:
    return origin - (origin - int(ts)) // interval * interval


def _py(v):
    """numpy scalar → JSON-serializable Python value."""
    if isinstance(v, np.generic):
        return v.item()
    return v


@dataclass
class MatViewDef:
    """A compiled view: the durable definition plus the derived plan
    bits the maintainer and the rewrite need."""

    name: str
    tenant: str
    database: str
    table: str
    select_sql: str
    delay_ns: int
    user: str
    group_tags: list[str] = field(default_factory=list)
    bucket: tuple[int, int] = (0, 1)
    phys_aggs: list = field(default_factory=list)      # AggSpec partials
    field_names: list[str] = field(default_factory=list)

    @property
    def owner(self) -> str:
        return f"{self.tenant}.{self.database}"

    def definition(self) -> dict:
        return {"tenant": self.tenant, "database": self.database,
                "select_sql": self.select_sql, "delay_ns": self.delay_ns,
                "user": self.user}


def compile_view(name: str, select, select_sql: str, delay_ns: int,
                 tenant: str, database: str, meta) -> MatViewDef:
    """Validate eligibility at CREATE time (not at first refresh): the
    SELECT must decompose into mergeable per-bucket partials."""
    from .executor import _decompose_aggs

    schema = meta.table(tenant, database, select.table)
    plan = plan_select(select, schema)
    if not isinstance(plan, AggregatePlan) or plan.bucket is None:
        raise QueryError(
            "materialized view requires an aggregate SELECT grouped by "
            "a time bucket (date_bin/time_window)")
    if select.where is not None:
        raise QueryError("materialized view SELECT cannot have WHERE — "
                         "filters belong on the querying side")
    if plan.group_fields:
        raise QueryError("materialized view can only group by tags and "
                         "the time bucket (field group keys change "
                         "identity on ALTER)")
    if plan.gapfill or plan.having is not None or plan.order_by \
            or plan.limit is not None or plan.offset is not None:
        raise QueryError("materialized view SELECT cannot use gapfill/"
                         "HAVING/ORDER BY/LIMIT")
    phys_aggs, _finalize = _decompose_aggs(plan.aggs)
    bad = [a.func for a in phys_aggs if a.func not in MERGEABLE_FUNCS]
    if bad:
        raise QueryError(
            f"aggregate partial {bad[0]!r} is not incrementally "
            f"mergeable; materialized views support "
            f"count/sum/mean/min/max/first/last")
    return MatViewDef(
        name=name, tenant=tenant, database=database, table=plan.table,
        select_sql=select_sql, delay_ns=int(delay_ns), user="",
        group_tags=list(plan.group_tags), bucket=plan.bucket,
        phys_aggs=phys_aggs,
        field_names=sorted({a.column for a in phys_aggs if a.column}))


class _FoldPlan:
    """The minimal plan surface ``executor._merge_partial`` reads."""

    __slots__ = ("group_tags", "group_fields", "bucket")

    def __init__(self, group_tags: list[str], bucket):
        self.group_tags = group_tags
        self.group_fields = []
        self.bucket = bucket


@dataclass
class Rewrite:
    """One subsumed query: accumulator seeded from sealed view buckets
    plus the raw time ranges still to scan."""

    view: str
    acc: dict
    scan_ranges: TimeRanges
    seal: int


def _fold_parts(dst: dict, src: dict, mapping) -> None:
    """Merge one persisted partial dict into an accumulator entry —
    mirror of the per-row branch in ``executor._merge_partial``, keyed
    by (view alias → query alias, func)."""
    for valias, qalias, func in mapping:
        if valias not in src:
            continue
        v = src[valias]
        cur = dst.get(qalias)
        if func == "count":
            dst[qalias] = (cur or 0) + int(v)
        elif func == "sum":
            dst[qalias] = v if cur is None else cur + v
        elif func == "min":
            dst[qalias] = v if cur is None else min(cur, v)
        elif func == "max":
            dst[qalias] = v if cur is None else max(cur, v)
        else:  # first / last
            ts = src.get(valias + "__ts", 0)
            cur_ts = dst.get(qalias + "__ts")
            if cur is None or cur_ts is None \
                    or (func == "first" and ts < cur_ts) \
                    or (func == "last" and ts > cur_ts):
                dst[qalias] = v
                dst[qalias + "__ts"] = ts


class MatviewEngine:
    """Per-node maintainer + query-rewrite engine.

    Owns the in-memory state cache for this node's local vnodes, the
    durable watermark registry, and the flush-triggered background
    refresh thread. Registered as ``coord.matview_maintainer`` so the
    ``matview_partials`` RPC and remote rewrites can reach it.
    """

    def __init__(self, executor, state_dir: str):
        self.executor = executor
        self.coord = executor.coord
        self.state_dir = state_dir
        self.tracker = WatermarkTracker(
            os.path.join(state_dir, "watermarks.json"))
        self.views: dict[str, MatViewDef] = {}
        self._states: dict[tuple, dict] = {}   # (name, owner, vid) → state
        self._lock = lockwatch.Lock("matview.state")
        # refresh mutual exclusion is per view and guards only the
        # in-flight set — scan/aggregate work never runs under it, so a
        # slow device refresh of one view cannot stall the others
        self._refresh_cv = threading.Condition()
        self._refreshing: set[str] = set()
        self._dirty: set[tuple] = set()        # (owner, vnode_id) flushed
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._meta_seen: dict | None = None
        self.coord.matview_maintainer = self
        engine = getattr(self.coord, "engine", None)
        if engine is not None:
            engine.flush_listener = self.notify_flush

    # --------------------------------------------------------- registration
    def register(self, vdef: MatViewDef) -> None:
        with self._lock:
            self.views[vdef.name] = vdef
        self._ensure_thread()

    def drop(self, name: str) -> None:
        """Unregister + remove every local persisted partial and
        watermark entry (DROP must not leak state files)."""
        with self._lock:
            vdef = self.views.pop(name, None)
            for key in [k for k in self._states if k[0] == name]:
                self._states.pop(key)
        prefix = f"{name}@"
        wkeys = [k for k in list(self.tracker.watermarks)
                 if k.startswith(prefix)]
        owners = {k.split("@", 1)[1].rsplit(":", 1)[0] for k in wkeys}
        if vdef is not None:
            owners.add(vdef.owner)
        for wkey in wkeys:
            self.tracker.remove(wkey)
        engine = self.coord.engine
        for owner in owners:
            for (o, vid) in list(engine.vnodes):
                if o != owner:
                    continue
                path = self._state_path(name, owner, vid)
                if os.path.exists(path):
                    os.remove(path)
        _count("drop")

    def sync_from_meta(self) -> None:
        """Reconcile the local registry with the replicated catalog —
        how a CREATE/DROP issued on another node reaches this one."""
        try:
            defs = dict(self.executor.meta.matviews)
        except Exception:
            stages.count_error("matview.meta_sync")
            return
        if defs == self._meta_seen:
            return
        self._meta_seen = defs
        from .parser import parse_sql

        for name, d in defs.items():
            if name in self.views:
                continue
            try:
                sel = parse_sql(d["select_sql"])[0]
                self.register(compile_view(
                    name, sel, d["select_sql"], d.get("delay_ns", 0),
                    d.get("tenant", "cnosdb"), d.get("database", "public"),
                    self.executor.meta))
            except Exception:
                log.exception("failed to restore materialized view %s", name)
        for name in [n for n in self.views if n not in defs]:
            self.drop(name)

    # ------------------------------------------------------------- triggers
    def notify_flush(self, owner: str, vnode_id: int) -> None:
        """Flush hook (storage/vnode.py): cheap mark-dirty + wake; the
        refresh itself runs on the background thread, never on the
        write path."""
        with self._lock:
            if not self.views and self._meta_seen is not None:
                return
            self._dirty.add((owner, int(vnode_id)))
        self._wake.set()

    def _ensure_thread(self) -> None:
        if self._thread is not None \
                or os.environ.get("CNOSDB_MATVIEW_AUTO", "1") == "0":
            return
        t = threading.Thread(target=self._run, daemon=True,
                             name="matview-maintainer")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=5.0)
            if self._stop.is_set():
                return
            self._wake.clear()
            with self._lock:
                dirty = self._dirty
                self._dirty = set()
            if not dirty:
                continue
            try:
                self.sync_from_meta()
                owners = {o for (o, _vid) in dirty}
                for name, vdef in list(self.views.items()):
                    if vdef.owner in owners:
                        self.refresh(name)
            except Exception:
                log.exception("matview background refresh failed")
                stages.count_error("matview.refresh")

    # -------------------------------------------------------------- refresh
    def refresh(self, name: str, now_ns: int | None = None) -> int:
        """Advance every LOCAL vnode of the view to the watermark;
        returns the number of vnodes refreshed. Explicit ``now_ns``
        keeps tests and the debug endpoint deterministic."""
        vdef = self.views.get(name)
        if vdef is None:
            raise QueryError(f"unknown materialized view {name!r}")
        now = _now_ns() if now_ns is None else int(now_ns)
        done = 0
        with self._refresh_cv:
            while name in self._refreshing:   # two racers would double-
                self._refresh_cv.wait()       # apply deltas past the hwm
            self._refreshing.add(name)
        try:
            for split in self._placed_splits(vdef):
                if self.coord.distributed \
                        and split.node_id != self.coord.node_id:
                    continue
                if self._refresh_vnode(vdef, split.vnode_id, now):
                    done += 1
        finally:
            with self._refresh_cv:
                self._refreshing.discard(name)
                self._refresh_cv.notify_all()
        if done:
            # a refreshed rollup changes what matview-rewritten aggregates
            # read: drop the serving plane's cached results for the base
            # table (hygiene — probes revalidate tokens regardless)
            try:
                from ..server import serving

                serving.invalidate(vdef.tenant, vdef.database, vdef.table)
            except Exception:
                stages.count_error("serving.invalidate")
        return done

    def _placed_splits(self, vdef: MatViewDef):
        try:
            return self.coord.table_vnodes(
                vdef.tenant, vdef.database, vdef.table,
                TimeRanges.all(), ColumnDomains.all())
        except Exception:
            stages.count_error("matview.placement")
            return []

    def _refresh_vnode(self, vdef: MatViewDef, vnode_id: int,
                       now: int) -> bool:
        origin, interval = vdef.bucket
        end = _align_down(now - vdef.delay_ns, origin, interval)
        st = self._get_state(vdef.name, vdef.owner, vnode_id)
        hwm = st["hwm"] if st is not None else I64_MIN
        if end <= hwm:
            return False
        v = self.coord.engine.vnode(vdef.owner, vnode_id)
        if v is None:
            return False
        from ..ops.tpu_exec import (TpuQuery, finish_scan_aggregate,
                                    launch_scan_aggregate)
        from ..storage.scan import scan_vnode

        t0 = time.perf_counter()
        batch = scan_vnode(
            v, vdef.table,
            time_ranges=TimeRanges([TimeRange(hwm, end - 1)]),
            field_names=vdef.field_names)
        result = None
        if batch is not None and batch.n_rows:
            q = TpuQuery(group_tags=vdef.group_tags,
                         time_bucket=vdef.bucket, aggs=vdef.phys_aggs)
            result = finish_scan_aggregate(launch_scan_aggregate(batch, q))
            _count("delta_rows", int(batch.n_rows))
        from .executor import _merge_partial

        key = (vdef.name, vdef.owner, vnode_id)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = {"hwm": I64_MIN, "groups": {}}
            if result is not None:
                _merge_partial(st["groups"], result,
                               _FoldPlan(vdef.group_tags, vdef.bucket),
                               vdef.phys_aggs)
            st["hwm"] = end
            payload = self._wire_state(st)
        self._persist_state(vdef.name, vdef.owner, vnode_id, payload)
        # tracker AFTER the state file: the durable watermark must never
        # run ahead of the partials it describes
        self.tracker.set(f"{vdef.name}@{vdef.owner}:{vnode_id}", end)
        _count("refresh")
        stages.count("matview.delta_rows",
                     int(batch.n_rows) if batch is not None else 0)
        prof = stages.current_profile()
        if prof is not None:
            prof.add_ms("matview.refresh_ms",
                        (time.perf_counter() - t0) * 1e3)
        return True

    # ------------------------------------------------------- state storage
    def _state_path(self, name: str, owner: str, vnode_id: int) -> str:
        return os.path.join(self.coord.engine.vnode_dir(owner, vnode_id),
                            "matview", f"{name}.json")

    @staticmethod
    def _wire_state(st: dict) -> dict:
        rows = [[[_py(k) for k in key],
                 {a: _py(v) for a, v in parts.items()}]
                for key, parts in st["groups"].items()]
        return {"hwm": int(st["hwm"]), "rows": rows}

    def _persist_state(self, name: str, owner: str, vnode_id: int,
                       payload: dict) -> None:
        path = self._state_path(name, owner, vnode_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        faults.fire("matview.persist", view=name, path=path)
        os.replace(tmp, path)

    def _get_state(self, name: str, owner: str, vnode_id: int) -> dict | None:
        key = (name, owner, vnode_id)
        with self._lock:
            st = self._states.get(key)
        if st is not None:
            return st
        st = self._load_state(name, owner, vnode_id)
        if st is None:
            return None
        with self._lock:
            return self._states.setdefault(key, st)

    def _load_state(self, name: str, owner: str, vnode_id: int) -> dict | None:
        path = self._state_path(name, owner, vnode_id)
        if not os.path.exists(path):
            return None
        from .executor import _canon_group_key

        try:
            with open(path) as f:
                d = json.load(f)
            groups = {tuple(_canon_group_key(k) for k in key): parts
                      for key, parts in d["rows"]}
            return {"hwm": int(d["hwm"]), "groups": groups}
        except Exception:
            # a torn/corrupt state file degrades this vnode to
            # "unrefreshed" (rewrite disabled, raw scans still correct)
            stages.count_error("matview.state_load")
            return None

    def partials_for(self, name: str, owner: str, vnode_id: int) -> dict:
        """RPC surface: one vnode's sealed partial set, wire form."""
        st = self._get_state(name, owner, vnode_id)
        if st is None:
            return {"hwm": None, "rows": []}
        with self._lock:
            return self._wire_state(st)

    # -------------------------------------------------------------- rewrite
    def rewrite(self, plan: AggregatePlan, phys_aggs, tenant: str,
                db: str) -> Rewrite | None:
        """Subsumption check + seed construction; None → raw scan."""
        self.sync_from_meta()
        with self._lock:
            cands = [v for v in self.views.values()
                     if v.tenant == tenant and v.database == db
                     and v.table == plan.table]
        if not cands:
            return None
        for vdef in cands:
            rw = self._try_rewrite(vdef, plan, phys_aggs)
            if rw is not None:
                _count("rewrite_hit")
                stages.count("matview.hit")
                stages.count("matview.seed_groups", len(rw.acc))
                return rw
        _count("rewrite_miss")
        stages.count("matview.miss")
        return None

    def _subsumes(self, vdef: MatViewDef, plan: AggregatePlan,
                  phys_aggs) -> list | None:
        """→ alias mapping [(view_alias, query_alias, func)] or None."""
        if plan.group_fields or plan.gapfill:
            return None
        if plan.filter is not None and not (
                set(plan.filter.columns()) <= set(vdef.group_tags)):
            # a residual filter over view group tags is decidable per
            # sealed group (all its rows share those exact tag values);
            # anything touching fields/time must see raw rows
            return None
        if not set(plan.group_tags) <= set(vdef.group_tags):
            return None
        if plan.tag_domains.is_none or not set(
                plan.tag_domains.domains) <= set(vdef.group_tags):
            return None
        vo, vi = vdef.bucket
        if plan.bucket is not None:
            qo, qi = plan.bucket
            if qi % vi != 0 or (qo - vo) % vi != 0:
                return None
        by_sig = {(a.func, a.column, repr(a.param)): a.alias
                  for a in vdef.phys_aggs}
        mapping = []
        for a in phys_aggs:
            if a.func not in MERGEABLE_FUNCS:
                return None
            valias = by_sig.get((a.func, a.column, repr(a.param)))
            if valias is None:
                return None
            mapping.append((valias, a.alias, a.func))
        return mapping

    def _try_rewrite(self, vdef: MatViewDef, plan: AggregatePlan,
                     phys_aggs) -> Rewrite | None:
        mapping = self._subsumes(vdef, plan, phys_aggs)
        if mapping is None:
            return None
        splits = self._placed_splits(vdef)
        if not splits:
            return None
        # gather per-vnode (hwm, rows): local under the state lock,
        # remote via RPC fan-out (outside any lock)
        entries, remote = [], []
        with self._lock:
            for split in splits:
                if self.coord.distributed \
                        and split.node_id != self.coord.node_id:
                    remote.append(split)
                    continue
                st = self._get_state_locked(vdef.name, vdef.owner,
                                            split.vnode_id)
                if st is None:
                    return None   # unrefreshed vnode → raw scan
                entries.append((st["hwm"],
                                [(k, dict(p))
                                 for k, p in st["groups"].items()]))
        for split in remote:
            wire = self._remote_partials(vdef, split)
            if wire is None or wire.get("hwm") is None:
                return None
            from .executor import _canon_group_key

            entries.append((int(wire["hwm"]),
                            [(tuple(_canon_group_key(k) for k in key), parts)
                             for key, parts in wire.get("rows", [])]))
        vo, vi = vdef.bucket
        seal = _align_down(min(hwm for hwm, _ in entries), vo, vi)
        # usable view-bucket spans per query range + residual raw ranges
        spans, residual = [], []
        for r in plan.time_ranges.ranges:
            lo = _align_up(r.min_ts, vo, vi)
            hi = _align_down(min(r.max_ts + 1, seal), vo, vi)
            if hi <= lo:
                residual.append(r)
                continue
            spans.append((lo, hi))
            if lo > r.min_ts:
                residual.append(TimeRange(r.min_ts, lo - 1))
            if hi <= r.max_ts:
                residual.append(TimeRange(hi, r.max_ts))
        if not spans:
            return None
        tag_idx = {t: i for i, t in enumerate(vdef.group_tags)}
        domain_items = [(tag_idx[c], dom) for c, dom
                        in plan.tag_domains.domains.items()]
        qb = plan.bucket
        acc: dict = {}
        for _hwm, rows in entries:
            for key, parts in rows:
                vts = key[-1]
                if not any(lo <= vts < hi for lo, hi in spans):
                    continue
                if domain_items and not all(
                        dom.contains_value(key[i])
                        for i, dom in domain_items):
                    continue
                if plan.filter is not None:
                    # tags-only residual (checked in _subsumes): every
                    # raw row in this sealed group carries exactly these
                    # tag values, so one eval decides the group. Expr
                    # eval expects array operands (e.g. != is ~(a == b),
                    # and ~ on a Python bool yields a truthy int), so
                    # feed 1-element object arrays — the same code path
                    # the raw scan drives with column arrays.
                    env = {t: np.asarray([key[i]], dtype=object)
                           for t, i in tag_idx.items()}
                    try:
                        if not bool(np.asarray(
                                plan.filter.eval(env, np)).reshape(-1)[0]):
                            continue
                    except Exception:
                        stages.count_error("matview.filter_eval")
                        return None  # degrade to raw scan
                qkey = tuple(key[tag_idx[t]] for t in plan.group_tags)
                if qb is not None:
                    qkey += (qb[0] + (vts - qb[0]) // qb[1] * qb[1],)
                _fold_parts(acc.setdefault(qkey, {}), parts, mapping)
        return Rewrite(view=vdef.name, acc=acc,
                       scan_ranges=TimeRanges(residual), seal=seal)

    def _get_state_locked(self, name, owner, vnode_id):
        """_get_state variant for callers already holding self._lock."""
        key = (name, owner, vnode_id)
        st = self._states.get(key)
        if st is None:
            st = self._load_state(name, owner, vnode_id)
            if st is not None:
                self._states[key] = st
        return st

    def _remote_partials(self, vdef: MatViewDef, split) -> dict | None:
        try:
            _count("remote_fetch")
            return self.coord._rpc(split.node_id, "matview_partials",
                                   {"view": vdef.name, "owner": vdef.owner,
                                    "vnode_id": split.vnode_id})
        except Exception:
            stages.count_error("matview.remote_partials")
            return None

    # ---------------------------------------------------------- inspection
    def status(self, name: str) -> dict:
        vdef = self.views.get(name)
        if vdef is None:
            raise QueryError(f"unknown materialized view {name!r}")
        out = {"table": vdef.table, "delay_ns": vdef.delay_ns,
               "bucket": list(vdef.bucket), "group_tags": vdef.group_tags,
               "vnodes": {}}
        for split in self._placed_splits(vdef):
            if self.coord.distributed \
                    and split.node_id != self.coord.node_id:
                continue
            st = self._get_state(name, vdef.owner, split.vnode_id)
            out["vnodes"][str(split.vnode_id)] = {
                "hwm": None if st is None else int(st["hwm"]),
                "groups": 0 if st is None else len(st["groups"]),
                "watermark": self.tracker.watermarks.get(
                    f"{name}@{vdef.owner}:{split.vnode_id}")}
        return out

    def verify(self, name: str) -> dict:
        """Compare every local vnode's incremental state against a
        from-scratch recompute over the same sealed row set — the
        crash/replay chaos oracle."""
        vdef = self.views.get(name)
        if vdef is None:
            raise QueryError(f"unknown materialized view {name!r}")
        from ..ops.tpu_exec import (TpuQuery, finish_scan_aggregate,
                                    launch_scan_aggregate)
        from ..storage.scan import scan_vnode
        from .executor import _merge_partial

        out = {"equal": True, "vnodes": 0, "mismatches": []}
        for split in self._placed_splits(vdef):
            if self.coord.distributed \
                    and split.node_id != self.coord.node_id:
                continue
            st = self._get_state(name, vdef.owner, split.vnode_id)
            if st is None:
                continue
            out["vnodes"] += 1
            v = self.coord.engine.vnode(vdef.owner, split.vnode_id)
            fresh: dict = {}
            if v is not None and st["hwm"] > I64_MIN:
                batch = scan_vnode(
                    v, vdef.table,
                    time_ranges=TimeRanges(
                        [TimeRange(I64_MIN, st["hwm"] - 1)]),
                    field_names=vdef.field_names)
                if batch is not None and batch.n_rows:
                    r = finish_scan_aggregate(launch_scan_aggregate(
                        batch, TpuQuery(group_tags=vdef.group_tags,
                                        time_bucket=vdef.bucket,
                                        aggs=vdef.phys_aggs)))
                    _merge_partial(fresh, r,
                                   _FoldPlan(vdef.group_tags, vdef.bucket),
                                   vdef.phys_aggs)
            with self._lock:
                have = {k: dict(p) for k, p in st["groups"].items()}
            for bad in _diff_states(have, fresh):
                out["equal"] = False
                if len(out["mismatches"]) < 8:
                    out["mismatches"].append(
                        {"vnode": split.vnode_id, "detail": bad})
        return out


def _diff_states(have: dict, fresh: dict):
    for key in set(have) | set(fresh):
        a, b = have.get(key), fresh.get(key)
        if a is None or b is None:
            yield f"group {key!r} only in " \
                  f"{'state' if b is None else 'recompute'}"
            continue
        for alias in set(a) | set(b):
            x, y = a.get(alias), b.get(alias)
            if x is None or y is None:
                yield f"group {key!r} part {alias} only on one side"
            elif isinstance(x, float) or isinstance(y, float):
                if not np.isclose(float(x), float(y), rtol=1e-9, atol=0):
                    yield f"group {key!r} part {alias}: {x} != {y}"
            elif _py(x) != _py(y):
                yield f"group {key!r} part {alias}: {x} != {y}"
