"""SQL statement AST.

Role-parity with the reference's ExtStatement
(query_server/spi/src/query/ast.rs:16-73): standard SELECT/INSERT/DELETE
plus CnosDB DDL (databases with TTL/SHARD/REPLICA/VNODE_DURATION/PRECISION,
tables with CODEC and TAGS(...)), SHOW/DESCRIBE, tenants/users, and admin
statements. Expressions reuse sql.expr's dual-target IR.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .expr import Expr


@dataclass
class SelectItem:
    expr: Any               # Expr | "*"
    alias: str | None = None


@dataclass
class SelectStmt:
    items: list[SelectItem]
    table: str | None
    where: Optional[Expr] = None
    group_by: list = field(default_factory=list)    # Expr | int (1-based) | str
    having: Optional[Expr] = None
    order_by: list = field(default_factory=list)    # (Expr|str, asc: bool)
    limit: int | None = None
    offset: int | None = None
    distinct: bool = False
    database: str | None = None   # explicit db qualifier (FROM db.table)
    # non-trivial FROM (joins / subquery relations); when set, `table` is
    # only populated for the single-plain-table fast path
    from_item: Any = None


@dataclass
class TableRef:
    """One named relation in FROM (reference ast.rs TableFactor::Table)."""

    name: str
    alias: str | None = None
    database: str | None = None


@dataclass
class SubqueryRef:
    """FROM (SELECT ...) alias [(col, ...)] — a derived relation."""

    select: Any                    # SelectStmt | UnionStmt
    alias: str
    col_aliases: list = field(default_factory=list)   # positional renames


@dataclass
class ValuesRef:
    """FROM (VALUES (...), (...)) [AS alias (col, ...)] — an inline
    constant relation (reference via DataFusion's values plan; default
    column names column1..columnN)."""

    rows: list                     # rows of python constants
    alias: str
    columns: list | None = None


@dataclass
class Join:
    """left <kind> JOIN right ON on (reference reads these via DataFusion;
    here joins execute host-side over columnar results)."""

    left: Any                      # TableRef | SubqueryRef | Join
    right: Any
    kind: str                      # inner|left|right|full|cross
    on: Optional[Expr] = None


@dataclass
class UnionStmt:
    """Set-operation chain (UNION/INTERSECT/EXCEPT [ALL]); ORDER BY/LIMIT
    apply to the combined result. `ops[i]` is the operator joining
    selects[i] and selects[i+1] (empty = all "union", the pre-set-op
    wire shape); INTERSECT binds tighter than UNION/EXCEPT, so an
    intersect chain nests as a UnionStmt inside `selects`."""

    selects: list                  # SelectStmt | UnionStmt (nested chain)
    alls: list = field(default_factory=list)   # per-operator ALL flags
    order_by: list = field(default_factory=list)
    limit: int | None = None
    offset: int | None = None
    ops: list = field(default_factory=list)    # union|intersect|except


@dataclass
class CreateDatabase:
    name: str
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)  # ttl/shard/vnode_duration/replica/precision


@dataclass
class AlterDatabase:
    name: str
    options: dict = field(default_factory=dict)


@dataclass
class DropDatabase:
    name: str
    if_exists: bool = False


@dataclass
class ColumnDef:
    name: str
    type_name: str
    codec: str | None = None


@dataclass
class CreateTable:
    name: str
    fields: list[ColumnDef]
    tags: list[str]
    if_not_exists: bool = False
    database: str | None = None      # qualified CREATE TABLE db.tbl


@dataclass
class DropTable:
    name: str
    if_exists: bool = False
    database: Optional[str] = None


@dataclass
class AlterTable:
    name: str
    action: str              # add_field/add_tag/drop/alter_codec/rename
    column: ColumnDef | None = None
    drop_name: str | None = None
    rename_to: str | None = None


@dataclass
class ShowStmt:
    kind: str                        # databases/tables/series/tag_values/queries
    table: str | None = None
    tag_key: str | None = None
    # SHOW TAG VALUES ... WITH KEY <op> — ("eq"|"ne"|"in"|"notin", [names])
    # (reference ast.rs:433 With::{Equal,UnEqual,In,NotIn}; Match/UnMatch
    # are NotImplemented upstream too)
    tag_with: tuple | None = None
    where: Optional[Expr] = None
    on_database: str | None = None
    limit: int | None = None
    offset: int | None = None
    order_by: list = field(default_factory=list)   # (output col, asc)


@dataclass
class DescribeStmt:
    kind: str                        # table/database
    name: str = ""
    database: str | None = None      # qualified DESCRIBE TABLE db.tbl


@dataclass
class InsertStmt:
    table: str
    columns: list[str]
    rows: list[list]                 # literal values per row
    select: SelectStmt | None = None
    database: str | None = None      # qualified INSERT INTO db.tbl


@dataclass
class DeleteStmt:
    table: str
    where: Optional[Expr] = None
    database: Optional[str] = None


@dataclass
class UpdateStmt:
    table: str
    assignments: dict[str, Expr]
    where: Optional[Expr] = None
    database: Optional[str] = None


@dataclass
class ExplainStmt:
    inner: Any
    analyze: bool = False
    verbose: bool = False


@dataclass
class CreateTenant:
    name: str
    if_not_exists: bool = False
    comment: str = ""
    drop_after: str | None = None
    # {group_name: {key: int}} from object_config / coord_* / http_*
    # option groups (reference limiter_config)
    limiter_groups: dict | None = None


@dataclass
class DropTenant:
    name: str
    if_exists: bool = False
    after: str | None = None   # DROP TENANT x AFTER '<duration>'


@dataclass
class CreateUser:
    name: str
    password: str = ""
    if_not_exists: bool = False
    comment: str = ""
    granted_admin: bool = False
    must_change_password: bool | None = None   # None = not specified


@dataclass
class DropUser:
    name: str
    if_exists: bool = False


@dataclass
class AlterUser:
    name: str
    # option changes: password/comment/granted_admin/must_change_password
    changes: dict = field(default_factory=dict)


@dataclass
class AlterTenantOpts:
    """ALTER TENANT t SET/UNSET comment/drop_after (None = unset)."""

    tenant: str
    changes: dict = field(default_factory=dict)


@dataclass
class CreateRole:
    """CREATE ROLE r [INHERIT member|owner] (reference ast.rs CreateRole)."""

    name: str
    inherit: str = "member"
    if_not_exists: bool = False


@dataclass
class DropRole:
    name: str
    if_exists: bool = False


@dataclass
class GrantRevoke:
    """GRANT/REVOKE READ|WRITE|ALL ON DATABASE db TO|FROM ROLE r
    (reference ast.rs GrantRevoke)."""

    grant: bool
    level: str          # read|write|all
    database: str
    role: str


@dataclass
class CopyStmt:
    """COPY INTO 'path' FROM table (export) | COPY INTO table FROM 'path'
    (import) (reference execution/ddl/copy.rs + COPY INTO in ast.rs)."""

    target: str
    source: str
    target_is_path: bool
    fmt: str = "csv"            # csv|parquet
    # CONNECTION = (...) credentials/endpoint for s3://, gcs://, azblob://
    # paths (reference parser.rs:1716, logical_planner.rs:835)
    options: dict = field(default_factory=dict)
    # COPY INTO t(col, ...): positional mapping of source columns
    columns: list | None = None


@dataclass
class CreateExternalTable:
    """CREATE EXTERNAL TABLE name STORED AS CSV|PARQUET [WITH HEADER ROW]
    LOCATION 'path' (reference create_external_table.rs:189)."""

    name: str
    path: str
    fmt: str = "csv"
    header: bool = True
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)   # object-store connection
    # declared column list [(name, sql_type)] — overrides inferred names
    # and coerces types (tpch.slt declares NUMERIC over CSV)
    columns: list = field(default_factory=list)


@dataclass
class VnodeAdmin:
    """MOVE|COPY|DROP|COMPACT VNODE <id> [TO NODE <n>] and REPLICA
    ADD|REMOVE|PROMOTE|DESTORY (reference spi ast.rs:56-73 vnode/replica
    admin)."""

    op: str                     # move|copy|drop|compact|replica_add|
    # replica_remove|replica_promote|replica_destory
    vnode_id: int = 0
    node_id: int = 0
    replica_set_id: int = 0


@dataclass
class RecoverStmt:
    """RECOVER TENANT|DATABASE|TABLE — undo a soft DROP (reference spi
    ast.rs:65-77 RecoverTenant/RecoverDatabase/RecoverTable)."""

    kind: str                   # tenant|database|table
    name: str
    database: Optional[str] = None


@dataclass
class BackupStmt:
    """BACKUP DATABASE <db> [INCREMENTAL] — consistent cluster-wide cut
    into the archive store (storage/backup.py; the reference ships
    `cnosdb-cli dump` / meta export instead, see PARITY.md)."""

    database: str
    incremental: bool = False


@dataclass
class RestoreStmt:
    """RESTORE DATABASE <db> [FROM '<backup_id>'] [TO TIMESTAMP <t>]
    [AS <new_name>] — point-in-time restore: newest backup at-or-before
    T plus archived-WAL replay up to T; without TO TIMESTAMP, roll
    forward to the latest archived write."""

    database: str
    backup_id: Optional[str] = None
    to_ts: Optional[int] = None         # ns since epoch
    new_name: Optional[str] = None


@dataclass
class AlterTenantMember:
    """ALTER TENANT t ADD USER u AS r | REMOVE USER u."""

    tenant: str
    user: str
    role: str | None = None     # None = REMOVE
    add: bool = True


@dataclass
class CreateStream:
    name: str
    target: str
    select: "SelectStmt"
    select_sql: str                 # raw text (persisted definition)
    interval_s: float = 10.0
    delay_ns: int = 0
    if_not_exists: bool = False


@dataclass
class CreateStreamTable:
    """CREATE STREAM TABLE name (cols) WITH (db=, table=,
    event_time_column=) engine = tskv — a readable stream source bound
    to an underlying tskv table (reference stream table providers,
    query_server/query/src/stream/)."""

    name: str
    columns: list                  # (name, sql_type)
    options: dict                  # db / table / event_time_column
    engine: str = "tskv"
    if_not_exists: bool = False


@dataclass
class DropStream:
    name: str
    if_exists: bool = False


@dataclass
class CreateMatView:
    """CREATE MATERIALIZED VIEW name [WATERMARK DELAY '...'] AS SELECT —
    a durable incremental rollup (sql/matview.py)."""

    name: str
    select: "SelectStmt"
    select_sql: str                 # raw text (persisted definition)
    delay_ns: int = 0
    if_not_exists: bool = False


@dataclass
class DropMatView:
    name: str
    if_exists: bool = False


@dataclass
class CompactStmt:
    database: str | None = None


@dataclass
class FlushStmt:
    database: str | None = None


@dataclass
class KillQuery:
    query_id: int


@dataclass
class IntervalValue:
    """INTERVAL literal. `ns` is the legacy fixed total (months 30d,
    years 365d — what bucketing consumes); `months`/`sub_ns` carry the
    calendar-true decomposition for date arithmetic."""

    ns: int
    months: int = 0
    sub_ns: int | None = None

    def __repr__(self):
        if self.months:
            return f"Interval({self.months}mo+{self.sub_ns or 0}ns)"
        return f"Interval({self.ns}ns)"
